"""VLM serving benchmarks on the current JAX backend (TPU when live).

BASELINE.md north star: camera → VLM (Qwen2-VL-2B shape) at >= 25 FPS
end-to-end on a v5e-1. Two modes:

  python bench_vlm.py model   # model-only: prefill, decode tok/s, MFU
  python bench_vlm.py e2e     # full dataflow FPS through the daemon

Prints one JSON line per metric; results are recorded in BENCHMARKS.md.
``bench.py`` (the driver entry point) remains the single-line latency
bench — this harness is the TPU-throughput counterpart.

MFU accounting: analytic matmul FLOPs from the config (weights 2*m*n per
token plus attention 4*T*dim per layer), against peak
``DORA_TPU_PEAK_TFLOPS`` (default 197, TPU v5e bf16). Embedding gathers
and normalizations are excluded — the estimate is a lower bound.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time
from pathlib import Path

PEAK_TFLOPS = float(os.environ.get("DORA_TPU_PEAK_TFLOPS", "197"))
PEAK_HBM_GBS = float(os.environ.get("DORA_TPU_PEAK_HBM_GBS", "819"))  # v5e


def _emit(metric: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 3),
                      "unit": unit, **extra}), flush=True)


# ---------------------------------------------------------------------------
# analytic FLOPs (lower bound: matmuls only)
# ---------------------------------------------------------------------------


def lm_matmul_flops_per_token(cfg) -> float:
    """Weight-matmul FLOPs for one LM token (no attention scores)."""
    hd = cfg.head_dim
    per_layer = 2 * (
        cfg.dim * cfg.heads * hd          # wq
        + 2 * cfg.dim * cfg.kv_heads * hd  # wk, wv
        + cfg.heads * hd * cfg.dim         # wo
        + 3 * cfg.dim * cfg.ffn            # gate, up, down
    )
    return cfg.layers * per_layer + 2 * cfg.dim * cfg.vocab  # + lm_head


def lm_attention_flops(cfg, context: int) -> float:
    """Score+value FLOPs for one token attending over ``context`` keys."""
    return cfg.layers * 4.0 * context * cfg.dim


def vision_matmul_flops(cfg) -> float:
    """Vision tower FLOPs for one image (all patches)."""
    p = cfg.n_patches
    patch_dim = cfg.patch_size * cfg.patch_size * 3
    per_layer = 2 * (4 * cfg.vision_dim**2 + 3 * cfg.vision_dim * cfg.vision_ffn)
    attn = 4.0 * p * cfg.vision_dim  # per patch, full self-attention
    return p * (
        2 * patch_dim * cfg.vision_dim
        + cfg.vision_layers * per_layer
        + cfg.vision_layers * attn
        + 2 * cfg.vision_dim * cfg.dim
    )


# ---------------------------------------------------------------------------
# model-only bench
# ---------------------------------------------------------------------------


def _tunnel_rtt_s() -> float:
    """Dispatch+fetch round-trip of an empty jit — on a tunneled backend
    (axon) this is ~100 ms and must be subtracted from wall timings.
    NOTE: ``block_until_ready`` does NOT synchronize on the axon tunnel;
    only fetching a value to host does, so every timing below reduces the
    workload to a scalar and times ``float(...)``."""
    import jax
    import jax.numpy as jnp

    empty = jax.jit(lambda: jnp.float32(0))
    float(empty())
    samples = []
    for _ in range(5):
        t = time.perf_counter()
        float(empty())
        samples.append(time.perf_counter() - t)
    return min(samples)


def _amortized_s(fn_scalar, n_iters: int, rtt_s: float, rounds: int = 3):
    """Median per-iteration seconds of a jit whose scalar output chains
    ``n_iters`` data-dependent repetitions of the workload."""
    float(fn_scalar())  # compile
    samples = []
    for _ in range(rounds):
        t = time.perf_counter()
        float(fn_scalar())
        samples.append(time.perf_counter() - t)
    return max(statistics.median(samples) - rtt_s, 1e-9) / n_iters


def bench_model(max_new: int = 64, prefill_iters: int = 16,
                generate_iters: int = 4) -> dict:
    import jax
    import jax.numpy as jnp

    from dora_tpu.models import vlm

    cfg = vlm.VLMConfig.bench_2b()
    backend = jax.default_backend()
    print(f"# backend={backend} devices={jax.devices()}", file=sys.stderr)
    rtt_s = _tunnel_rtt_s()
    print(f"# dispatch rtt {rtt_s*1e3:.1f} ms", file=sys.stderr)

    t0 = time.perf_counter()
    params = vlm.init_params(jax.random.PRNGKey(0), cfg)
    # Serving config: weights resident in bf16 (MXU-native), fp32 freed.
    cast = jax.jit(
        lambda p: jax.tree.map(lambda x: x.astype(jnp.bfloat16), p),
        donate_argnums=0,
    )
    params = cast(params)
    int8 = bool(os.environ.get("DORA_INT8_DECODE"))
    int4 = bool(os.environ.get("DORA_INT4_DECODE"))
    if int8 or int4:
        quantize = jax.jit(
            lambda p: vlm.quantize_decode(p), donate_argnums=0
        )
        params = quantize(params)
    n_params = vlm.param_count(params)
    print(f"# {n_params/1e9:.2f}B params in "
          f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)

    image = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
    prompt = jnp.arange(16, dtype=jnp.int32)[None] % cfg.vocab

    # Chain iterations with a data dependency (image perturbed by the
    # previous scalar) so XLA cannot hoist or CSE the repeated work.
    @jax.jit
    def prefill_chain(p, im, pr):
        def body(_, acc):
            logits, _, _ = vlm.prefill(p, cfg, im + acc * 1e-9, pr)
            return jnp.max(logits) * 1e-9
        return jax.lax.fori_loop(0, prefill_iters, body, jnp.float32(0))

    @jax.jit
    def generate_chain(p, im, pr):
        def body(_, acc):
            tokens = vlm.generate(p, cfg, im + acc * 1e-9, pr, max_new)
            return jnp.float32(jnp.max(tokens)) * 1e-9
        return jax.lax.fori_loop(0, generate_iters, body, jnp.float32(0))

    t0 = time.perf_counter()
    prefill_s = _amortized_s(
        lambda: prefill_chain(params, image, prompt), prefill_iters, rtt_s
    )
    print(f"# prefill bench (incl compile) {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    generate_s = _amortized_s(
        lambda: generate_chain(params, image, prompt), generate_iters, rtt_s
    )
    print(f"# generate bench (incl compile) {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    spec_s = spec_passes = None
    if os.environ.get("DORA_SPEC_DECODE"):
        # Speculative decode is a while_loop (iteration count is
        # data-dependent), so chain via Python: time one full generate
        # per fetch and subtract the RTT directly.
        def spec_once():
            # passes is an output of the decode while_loop, so fetching
            # it alone synchronizes the whole generation (one RTT).
            _, passes = vlm.generate_speculative(
                params, cfg, image, prompt, max_new
            )
            return float(passes)

        spec_passes = spec_once()  # compile + pass count
        samples = []
        for _ in range(3):
            t = time.perf_counter()
            spec_once()
            samples.append(time.perf_counter() - t)
        spec_s = max(statistics.median(samples) - rtt_s, 1e-9)
    decode_s = max(generate_s - prefill_s, 1e-9)
    tokens_per_s = max_new / decode_s

    # FLOPs: prefill processes image + patches+prompt tokens; each decode
    # token runs the full stack over a growing context.
    prefill_tokens = cfg.n_patches + int(prompt.shape[1])
    per_tok = lm_matmul_flops_per_token(cfg)
    prefill_flops = (
        vision_matmul_flops(cfg)
        + prefill_tokens * per_tok
        + sum(lm_attention_flops(cfg, t) for t in range(1, prefill_tokens + 1))
    )
    decode_flops = sum(
        per_tok + lm_attention_flops(cfg, prefill_tokens + i)
        for i in range(max_new)
    )
    peak = PEAK_TFLOPS * 1e12
    prefill_mfu = prefill_flops / prefill_s / peak
    decode_mfu = decode_flops / decode_s / peak
    fps = 1.0 / generate_s

    # Batch-1 decode is HBM-bandwidth-bound (every token streams the LM
    # weights once), so MBU — bytes of LM weights read per second against
    # peak HBM bandwidth — is the honest decode-efficiency number; MFU is
    # reported for completeness but ~0.3% is simply the batch-1 physics.
    # (embedding gather reads one row, not the table; lm_head is already
    # in the matmul count)
    bytes_per_param = 0.5 if int4 else (1.0 if int8 else 2.0)
    lm_param_bytes = bytes_per_param * (lm_matmul_flops_per_token(cfg) / 2)
    decode_mbu = lm_param_bytes * tokens_per_s / (PEAK_HBM_GBS * 1e9)

    tag = " int4" if int4 else (" int8" if int8 else "")
    _emit("vlm-2b prefill latency", prefill_s * 1e3, "ms",
          backend=backend, prefill_tokens=prefill_tokens)
    _emit(f"vlm-2b decode{tag} throughput", tokens_per_s, "tokens/s",
          backend=backend, max_new=max_new)
    _emit(f"vlm-2b decode{tag} MBU", decode_mbu * 100, "%",
          peak_hbm_gbs=PEAK_HBM_GBS)
    _emit("vlm-2b decode MFU", decode_mfu * 100, "%",
          peak_tflops=PEAK_TFLOPS)
    _emit("vlm-2b prefill MFU", prefill_mfu * 100, "%",
          peak_tflops=PEAK_TFLOPS)
    _emit(f"vlm-2b single-stream FPS ({max_new} new tokens)", fps, "fps",
          backend=backend)
    if spec_s is not None:
        spec_tok_s = max_new / max(spec_s - prefill_s, 1e-9)
        _emit(f"vlm-2b speculative decode{tag} throughput", spec_tok_s,
              "tokens/s", model_passes=spec_passes, max_new=max_new,
              note="greedy-exact prompt-lookup speculation")
    return {"fps": fps, "tokens_per_s": tokens_per_s,
            "decode_mfu": decode_mfu, "decode_mbu": decode_mbu,
            "prefill_ms": prefill_s * 1e3}


# ---------------------------------------------------------------------------
# end-to-end dataflow bench
# ---------------------------------------------------------------------------


def bench_e2e(tmp: Path, max_new: int = 4, frames: int = 100,
              size: str = "bench") -> dict:
    """camera -> VLM operator -> counting sink, through the real daemon.

    FPS = token outputs observed at the sink / wall time between first
    and last (excludes model compile, which gates the first output).
    """
    import textwrap

    import yaml

    from dora_tpu.daemon import run_dataflow

    sink = tmp / "fps_sink.py"
    sink.write_text(textwrap.dedent("""
        import json
        import statistics
        import time

        from dora_tpu.node import Node

        stamps = []
        with Node() as node:
            for event in node:
                if event["type"] != "INPUT":
                    continue
                stamps.append(time.perf_counter())
        assert len(stamps) >= 2, f"only {len(stamps)} outputs"
        # Steady state: the first outputs straddle the model's jit
        # compile (no persistent cache reaches the tunneled chip), so
        # measure after a warmup margin; keep the naive first->last
        # number for reference.
        warmup = min(5, len(stamps) - 2)
        window = stamps[warmup:]
        fps = (len(window) - 1) / (window[-1] - window[0])
        gaps = [b - a for a, b in zip(window, window[1:])]
        # Peak sustained rate: best sliding 50-output window. On the
        # tunneled chip the device->host fetch latency can degrade
        # mid-stream (KNOWN_ISSUES), dragging the whole-run mean below
        # what the pipeline sustains when the tunnel is healthy; the
        # peak window shows the capability alongside the honest mean.
        peak = fps
        w = 50
        for i in range(max(0, len(window) - w)):
            cand = (w - 1) / (window[i + w - 1] - window[i])
            peak = max(peak, cand)
        open("fps.json", "w").write(json.dumps({
            "fps": fps,
            "outputs": len(stamps),
            "measured_outputs": len(window),
            "p50_gap_ms": statistics.median(gaps) * 1e3,
            "peak_window_fps": peak,
            "fps_incl_warmup": (len(stamps) - 1) / (stamps[-1] - stamps[0]),
        }))
    """))
    spec = {
        "nodes": [
            {
                "id": "camera",
                "path": "module:dora_tpu.nodehub.camera",
                "inputs": {"tick": "dora/timer/millis/20"},
                "outputs": ["image"],
                "env": {
                    "IMAGE_WIDTH": "224",
                    "IMAGE_HEIGHT": "224",
                    "MAX_FRAMES": str(frames),
                },
            },
            {
                "id": "vlm",
                "operator": {
                    "jax": "dora_tpu.nodehub.ops:make_vlm",
                    "inputs": {
                        "image": {"source": "camera/image", "queue_size": 1}
                    },
                    "outputs": ["tokens"],
                },
                "env": {
                    "DORA_MODEL_SIZE": size,
                    "DORA_MAX_NEW_TOKENS": str(max_new),
                    "DORA_PARAM_DTYPE": "bfloat16",
                    # Fail loudly rather than silently falling back to a
                    # CPU grind if the chip is held by another process.
                    "JAX_PLATFORMS": "tpu",
                    # Serving levers under test ride through when set:
                    # int8 decode weights and async pipelined ticks.
                    **{
                        k: os.environ[k]
                        for k in (
                            "DORA_INT8_DECODE",
                            "DORA_INT8_PURE",
                            "DORA_PIPELINE_DEPTH",
                            "DORA_FETCH_EVERY",
                        )
                        if k in os.environ
                    },
                },
            },
            {
                "id": "sink",
                "path": "fps_sink.py",
                "inputs": {"tokens": "vlm/op/tokens"},
            },
        ]
    }
    df = tmp / "fps.yml"
    df.write_text(yaml.safe_dump(spec))
    result = run_dataflow(df, timeout_s=1800)
    if not result.is_ok():
        raise RuntimeError(f"e2e bench failed: {result.errors()}")
    data = json.loads((tmp / "fps.json").read_text())
    _emit(
        f"camera->vlm-{size} end-to-end FPS ({max_new} new tokens/frame)",
        data["fps"], "fps", outputs=data["outputs"],
        measured_outputs=data.get("measured_outputs"),
        p50_gap_ms=round(data.get("p50_gap_ms", 0), 1),
        peak_window_fps=round(data.get("peak_window_fps", 0), 1),
        vs_baseline=data["fps"] / 25.0,  # north star: 25 FPS
    )
    return data


def bench_batch(batches=(1, 4, 8), steps: int = 8, chains: int = 6) -> dict:
    """Continuous-batching decode throughput: B independent sequences
    through the batched fused kernels (ops/decode_block.
    attention_batch_step) — one LM weight stream serves every row, so
    aggregate tok/s should scale nearly linearly in B (round 5;
    requires DORA_INT8_DECODE/DORA_INT4_DECODE for the fused layout)."""
    import jax
    import jax.numpy as jnp

    from dora_tpu.models import vlm

    cfg = vlm.VLMConfig.bench_2b()
    rtt_s = _tunnel_rtt_s()
    print(f"# dispatch rtt {rtt_s*1e3:.1f} ms", file=sys.stderr)
    t0 = time.perf_counter()
    params = vlm.init_params(jax.random.PRNGKey(0), cfg)
    params = jax.jit(lambda p: vlm.quantize_decode(p), donate_argnums=0)(
        params
    )
    jax.block_until_ready(jax.tree.leaves(params)[0])
    print(f"# params ready {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    results = {}
    base = None
    for b in batches:
        caches = vlm.init_cache(cfg, b)
        positions = jnp.full((b,), 300, jnp.int32)
        tokens = jnp.arange(b, dtype=jnp.int32) + 5

        @jax.jit
        def chain(params, tokens, caches, positions):
            def body(carry, _):
                t, c, p = carry
                nt, c = vlm.decode_batch_fused(params, cfg, t, c, p)
                return (nt, c, p + 1), None
            (t, _, _), _ = jax.lax.scan(
                body, (tokens, caches, positions), None, length=steps
            )
            return t[0]

        def run_chains(chain=chain, tokens=tokens, caches=caches,
                       positions=positions):
            for _ in range(chains - 1):
                chain(params, tokens, caches, positions)
            return chain(params, tokens, caches, positions)

        per_chain = _amortized_s(run_chains, chains, rtt_s)
        tokps = b * steps / per_chain
        if base is None:
            base = tokps
        results[b] = tokps
        _emit(
            f"vlm-2b batched fused decode (batch {b})", tokps, "tokens/s",
            per_stream=round(tokps / b, 1),
            vs_batch1=round(tokps / base, 2),
            ms_per_step=round(per_chain / steps * 1e3, 2),
        )
    return results


def main() -> int:
    mode = sys.argv[1] if len(sys.argv) > 1 else "model"
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    if mode == "model":
        bench_model(max_new=int(os.environ.get("BENCH_MAX_NEW", "64")))
    elif mode == "batch":
        os.environ.setdefault("DORA_INT8_DECODE", "1")
        bench_batch()
    elif mode == "e2e":
        import tempfile

        with tempfile.TemporaryDirectory(prefix="dora-vlm-bench-") as tmp:
            bench_e2e(
                Path(tmp),
                max_new=int(os.environ.get("BENCH_MAX_NEW", "4")),
                frames=int(os.environ.get("BENCH_FRAMES", "100")),
                size=os.environ.get("DORA_MODEL_SIZE", "bench"),
            )
    else:
        raise SystemExit(f"unknown mode {mode!r} (model | batch | e2e)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
