"""Publish/subscribe communication layer.

Reference parity: libraries/communication-layer/pub-sub — a
CommunicationLayer/Publisher/Subscriber abstraction with a Zenoh backend
that the main path does not use (remote config only admits TCP,
libraries/core/src/config.rs:360-369). Here: the same abstraction with a
TCP broker backend that works out of the box (one process hosts the
broker; publishers/subscribers connect by topic); a zenoh backend slot is
gated on the optional ``zenoh`` package.
"""

from __future__ import annotations

import socket
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
from typing import Callable

from dora_tpu.transport.framing import (
    ConnectionClosed,
    recv_frame,
    send_frame,
)


class CommunicationLayer:
    """Abstract pub/sub layer."""

    def publisher(self, topic: str) -> "Publisher":
        raise NotImplementedError

    def subscribe(self, topic: str, callback: Callable[[bytes], None]) -> "Subscription":
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class Publisher:
    def publish(self, data: bytes) -> None:
        raise NotImplementedError


class Subscription:
    def close(self) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# TCP broker backend
# ---------------------------------------------------------------------------


class Broker:
    """Minimal topic broker: clients send [kind(1B)][topic][0][payload]
    frames; SUB registers interest, PUB fans out to subscribers."""

    def __init__(self, port: int = 0):
        self._server = socket.socket()
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(64)
        self.port = self._server.getsockname()[1]
        self._subs: dict[str, list[socket.socket]] = {}
        self._lock = tracked_lock("transport.broker")
        self._closing = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while not self._closing:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True
            ).start()

    def _client_loop(self, conn: socket.socket):
        try:
            while True:
                frame = recv_frame(conn)
                kind, topic, payload = _split(frame)
                if kind == b"S"[0]:
                    with self._lock:
                        self._subs.setdefault(topic, []).append(conn)
                elif kind == b"P"[0]:
                    with self._lock:
                        targets = list(self._subs.get(topic, ()))
                    dead = []
                    for t in targets:
                        try:
                            send_frame(t, b"M" + topic.encode() + b"\0" + payload)
                        except OSError:
                            dead.append(t)
                    if dead:
                        with self._lock:
                            for t in dead:
                                self._subs[topic].remove(t)
        except (ConnectionClosed, OSError):
            pass
        finally:
            with self._lock:
                for subs in self._subs.values():
                    if conn in subs:
                        subs.remove(conn)

    def close(self):
        self._closing = True
        self._server.close()


def _split(frame: bytes) -> tuple[int, str, bytes]:
    kind = frame[0]
    sep = frame.index(0, 1)
    return kind, frame[1:sep].decode(), frame[sep + 1 :]


class TcpPubSub(CommunicationLayer):
    def __init__(self, broker_addr: str):
        host, _, port = broker_addr.rpartition(":")
        self._addr = (host, int(port))
        self._pub_sock: socket.socket | None = None
        # One shared pub socket: holding across connect/send IS the
        # serialization that keeps frames un-interleaved.
        self._pub_lock = tracked_lock("transport.pubsub.pub", allow_blocking=True)
        self._subscriptions: list[_TcpSubscription] = []

    def publisher(self, topic: str) -> Publisher:
        layer = self

        class _Pub(Publisher):
            def publish(self, data: bytes) -> None:
                with layer._pub_lock:
                    if layer._pub_sock is None:
                        layer._pub_sock = socket.create_connection(layer._addr)
                    send_frame(
                        layer._pub_sock, b"P" + topic.encode() + b"\0" + data
                    )

        return _Pub()

    def subscribe(self, topic: str, callback) -> Subscription:
        sock = socket.create_connection(self._addr)
        send_frame(sock, b"S" + topic.encode() + b"\0")
        sub = _TcpSubscription(sock, callback)
        self._subscriptions.append(sub)
        return sub

    def close(self) -> None:
        with self._pub_lock:
            if self._pub_sock is not None:
                self._pub_sock.close()
        for sub in self._subscriptions:
            sub.close()


class _TcpSubscription(Subscription):
    def __init__(self, sock: socket.socket, callback):
        self._sock = sock
        self._callback = callback
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        try:
            while True:
                frame = recv_frame(self._sock)
                _, _, payload = _split(frame)
                self._callback(payload)
        except (ConnectionClosed, OSError):
            pass

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


def zenoh_layer(*args, **kwargs) -> CommunicationLayer:  # pragma: no cover
    """Zenoh backend slot (reference: pub-sub/src/zenoh.rs).

    Decision (documented here on purpose): the TCP broker above is this
    framework's *supported* pub-sub backend — it is wired, tested, and
    carries the OpenAI-server example. The reference ships a zenoh
    implementation of the same trait but nothing in its data plane uses
    it either (communication-layer/pub-sub is dead code upstream). We
    keep the slot so a zenoh backend can drop in behind the same
    CommunicationLayer trait if/when a deployment needs brokerless
    discovery, and fail with a clear message instead of half-working."""
    try:
        import zenoh  # noqa: F401
    except ImportError as e:
        raise RuntimeError(
            "the zenoh pub/sub backend requires the 'zenoh' package"
        ) from e
    raise NotImplementedError(
        "zenoh backend: not implemented — use the TCP broker "
        "(pubsub.tcp_layer), the supported backend"
    )
