"""Length-prefixed message framing over sockets (sync + asyncio).

Frame = 4-byte little-endian length + payload. Used for every TCP/UDS
channel: CLI<->coordinator, coordinator<->daemon, daemon<->daemon,
node<->daemon in tcp/uds mode.
"""

from __future__ import annotations

import asyncio
import socket
import struct

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30


class ConnectionClosed(ConnectionError):
    pass


# ---------------------------------------------------------------------------
# sync (used by node APIs — nodes are synchronous by design)
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionClosed("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} B exceeds limit")
    return _recv_exact(sock, length) if length else b""


# ---------------------------------------------------------------------------
# asyncio (used by daemon + coordinator event loops)
# ---------------------------------------------------------------------------


async def send_frame_async(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(_LEN.pack(len(payload)))
    writer.write(payload)
    await writer.drain()


async def recv_frame_async(reader: asyncio.StreamReader) -> bytes:
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError) as e:
        raise ConnectionClosed("peer closed connection") from e
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} B exceeds limit")
    if not length:
        return b""
    try:
        return await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError) as e:
        raise ConnectionClosed("peer closed mid-frame") from e
