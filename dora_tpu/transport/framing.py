"""Length-prefixed message framing over sockets (sync + asyncio).

Frame = 4-byte little-endian length + payload. Used for every TCP/UDS
channel: CLI<->coordinator, coordinator<->daemon, daemon<->daemon,
node<->daemon in tcp/uds mode.
"""

from __future__ import annotations

import asyncio
import socket
import struct

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30

#: Below this, header+payload are joined into ONE buffer before writing
#: (one syscall / transport.write); above, the copy would cost more than
#: the extra write it saves.
_JOIN_LIMIT = 1 << 16


class ConnectionClosed(ConnectionError):
    pass


# ---------------------------------------------------------------------------
# sync (used by node APIs — nodes are synchronous by design)
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def send_frames(sock: socket.socket, payloads: list[bytes]) -> None:
    """Coalesced send: every frame in one sendall (one syscall for the
    whole batch). The receiver's framed recv loop splits them back out —
    frame boundaries are length-prefixed, so batching is invisible on
    the wire."""
    if len(payloads) == 1:
        send_frame(sock, payloads[0])
        return
    buf = bytearray()
    for payload in payloads:
        buf += _LEN.pack(len(payload))
        buf += payload
    sock.sendall(buf)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionClosed("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} B exceeds limit")
    return _recv_exact(sock, length) if length else b""


# ---------------------------------------------------------------------------
# asyncio (used by daemon + coordinator event loops)
# ---------------------------------------------------------------------------


async def send_frame_async(writer: asyncio.StreamWriter, payload: bytes) -> None:
    if len(payload) < _JOIN_LIMIT:
        # One write call = one transport send attempt; two write calls on
        # an empty buffer can each hit the socket (two syscalls per reply
        # on the request/reply hot path).
        writer.write(_LEN.pack(len(payload)) + payload)
    else:
        writer.write(_LEN.pack(len(payload)))
        writer.write(payload)
    await writer.drain()


async def send_frames_async(
    writer: asyncio.StreamWriter, payloads: list[bytes]
) -> None:
    """Coalesced async send: all frames through one writelines + one
    drain (vectored into the transport buffer, flushed together)."""
    bufs: list[bytes] = []
    for payload in payloads:
        bufs.append(_LEN.pack(len(payload)))
        bufs.append(payload)
    writer.writelines(bufs)
    await writer.drain()


async def recv_frame_async(reader: asyncio.StreamReader) -> bytes:
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError) as e:
        raise ConnectionClosed("peer closed connection") from e
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} B exceeds limit")
    if not length:
        return b""
    try:
        return await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError) as e:
        raise ConnectionClosed("peer closed mid-frame") from e
