"""Transport primitives: length-prefixed socket framing and shmem RPC.

Reference parity: L0 of the reference — socket_stream_utils.rs /
tcp_utils.rs (length-prefixed framing) and shared-memory-server (the shmem
request-reply channel, implemented natively in native/shmem.cpp here).
"""
