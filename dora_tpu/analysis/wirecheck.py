"""Serde/wire coverage lint.

Every ``@message`` dataclass must (a) be in ``_REGISTRY`` with a
compiled codec in ``_PACK``/``_UNPACK`` — registration compiles these,
so a gap means the decorator half-ran — and (b) be constructible by the
golden test's ``_sample`` builder, so tests/test_serde_golden.py really
exercises it. A field annotation ``_sample`` cannot build (a new
container type, an unannotated Any-like) silently drops that class from
golden coverage; this lint turns that into an error.

Codes: ``serde-missing-codec``, ``serde-golden-uncoverable``,
``serde-registry-empty``.
"""

from __future__ import annotations

import importlib
import importlib.util
import pkgutil
from pathlib import Path

from dora_tpu.analysis import Finding


def _load_registry():
    import dora_tpu.message as message_pkg
    from dora_tpu.message import serde

    for mod in pkgutil.iter_modules(message_pkg.__path__):
        importlib.import_module(f"dora_tpu.message.{mod.name}")
    return serde


def _load_sample_builder(repo_root: Path):
    """Import the golden test module for its ``_sample`` builder, so the
    lint checks exactly what the tests exercise."""
    test_path = repo_root / "tests" / "test_serde_golden.py"
    if not test_path.exists():
        return None
    spec = importlib.util.spec_from_file_location(
        "_dora_serde_golden_for_lint", test_path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return getattr(mod, "_sample", None)


def lint(repo_root: str | Path = ".") -> list[Finding]:
    out: list[Finding] = []
    serde = _load_registry()
    registry = serde._REGISTRY
    if len(registry) < 50:
        out.append(Finding(
            "wirecheck", "serde-registry-empty", "error", "message/serde.py",
            f"only {len(registry)} registered message classes — the "
            "registry import sweep collapsed",
        ))
    for name in sorted(registry):
        cls = registry[name]
        if cls not in serde._PACK or name not in serde._UNPACK:
            out.append(Finding(
                "wirecheck", "serde-missing-codec", "error", name,
                "registered message class has no compiled pack/unpack "
                "codec — wire encode would fall back or fail",
            ))

    sample = _load_sample_builder(Path(repo_root))
    if sample is None:
        out.append(Finding(
            "wirecheck", "serde-golden-uncoverable", "error",
            "tests/test_serde_golden.py",
            "golden test module (or its _sample builder) not found — "
            "no golden coverage for any message class",
        ))
        return out
    for name in sorted(registry):
        try:
            obj = sample(registry[name])
            serde.decode(serde.encode(obj))
        except Exception as e:  # noqa: BLE001 - any failure is the finding
            out.append(Finding(
                "wirecheck", "serde-golden-uncoverable", "error", name,
                f"golden _sample cannot build/round-trip this class: {e}",
            ))
    return out
