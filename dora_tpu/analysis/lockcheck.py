"""Lock-order race detector (``DORA_LOCKCHECK=1``).

Every lock in the Python control/data plane is created through
:func:`tracked_lock` / :func:`tracked_rlock`. With ``DORA_LOCKCHECK``
unset the factory returns a plain ``threading.Lock`` / ``RLock`` — the
production hot path pays nothing beyond the one-time factory call, the
flight-recorder discipline. With it set, the factory returns a wrapper
that maintains a per-thread held-lock list and feeds a process-wide
lock-ORDER graph: an edge ``A -> B`` means some thread acquired B while
holding A. The detector reports:

* **order-graph cycles** — two locks ever taken in both orders by any
  threads is a potential ABBA deadlock, even if the run never actually
  deadlocked (the classic happened-before shadow of lockdep);
* **locks held across blocking calls** — ``queue`` waits, socket
  send/recv, ``time.sleep``, ``Event.wait``, shmem channel send/recv and
  ``jax.block_until_ready`` are probed; holding a lock across any of
  them serializes unrelated threads behind I/O. Locks that exist to
  serialize a blocking resource (a shared socket, a request-reply
  channel) opt out with ``allow_blocking=True`` — the suppression is at
  the lock, visible at its construction site;
* **long holds** — a hold beyond ``DORA_LOCKCHECK_HOLD_MS`` (default
  100) is recorded with its stack.

Findings land as flight-recorder instants (``lock_blocking``,
``lock_long_hold``) on the trace timeline and in an end-of-process
report; tier-1 runs with the detector on and fails on any unexplained
cycle (tests/conftest.py). Per-edge stacks are captured only on FIRST
observation, so the steady state allocates a tuple and a set lookup per
nested acquire and nothing per flat acquire.

Known limits (KNOWN_ISSUES round 17): the detector sees *executed*
orders only — an untaken branch hides its edge; ``asyncio.Lock``
(daemon/inter_daemon.py) is not tracked — coroutines interleave on one
thread and ABBA needs the wait graph, not the held set; blocking probes
see module-attribute calls only (``from time import sleep`` escapes).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from dora_tpu.analysis import Finding
from dora_tpu.telemetry import FLIGHT


class LockCheckState:
    """Process-wide detector switch (``DORA_LOCKCHECK=1``); mirrors
    :class:`dora_tpu.telemetry.TracingState` — one attribute check to
    know the detector is off."""

    __slots__ = ("active",)

    def __init__(self, active: bool = False):
        self.active = active

    def configure_from_env(self) -> None:
        self.active = os.environ.get("DORA_LOCKCHECK", "") not in ("", "0")


LOCKCHECK = LockCheckState(os.environ.get("DORA_LOCKCHECK", "") not in ("", "0"))

#: Hold-duration outlier threshold (ns), env-tunable for tests.
_HOLD_NS = int(
    float(os.environ.get("DORA_LOCKCHECK_HOLD_MS", "100") or "100") * 1e6
)

_STACK_LIMIT = 12

# ---------------------------------------------------------------------------
# global detector state (the meta lock is a RAW threading.Lock on purpose:
# the detector must not observe itself)
# ---------------------------------------------------------------------------

_meta = threading.Lock()
#: (held_name, acquired_name) -> {"count": int, "stack": str}
_edges: dict[tuple[str, str], dict] = {}
#: fast lock-free dedup shadow of _edges' keys (benign race: a miss only
#: costs one extra _meta acquisition)
_edge_seen: set[tuple[str, str]] = set()
#: (kind, lock_name, call) -> {"count": int, "stack": str, ...}
_events: dict[tuple[str, str, str], dict] = {}
_event_seen: set[tuple[str, str, str]] = set()

_tls = threading.local()


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack() -> str:
    # Skip the detector's own frames (the last two).
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-2])


def _note_edges(held: list, name: str) -> None:
    for rec in held:
        held_name = rec[0]
        if held_name == name:
            continue
        key = (held_name, name)
        if key in _edge_seen:
            with _meta:
                entry = _edges.get(key)
                if entry is not None:
                    entry["count"] += 1
                    continue
        stack = _stack()
        with _meta:
            entry = _edges.setdefault(key, {"count": 0, "stack": stack})
            entry["count"] += 1
            _edge_seen.add(key)


def _note_event(kind: str, lock_name: str, call: str, dur_ns: int = 0) -> None:
    FLIGHT.record(f"lock_{kind}", lock_name, call or None, dur_ns or None)
    key = (kind, lock_name, call)
    if key in _event_seen:
        with _meta:
            entry = _events.get(key)
            if entry is not None:
                entry["count"] += 1
                if dur_ns > entry["max_ns"]:
                    entry["max_ns"] = dur_ns
                return
    stack = _stack()
    with _meta:
        entry = _events.setdefault(
            key, {"count": 0, "stack": stack, "max_ns": 0}
        )
        entry["count"] += 1
        if dur_ns > entry["max_ns"]:
            entry["max_ns"] = dur_ns
        _event_seen.add(key)


# ---------------------------------------------------------------------------
# tracked lock wrappers
# ---------------------------------------------------------------------------


class TrackedLock:
    """``threading.Lock`` wrapper feeding the order graph. Only handed
    out when the detector is active — off-path code holds a plain lock.

    Held-list entries are mutable ``[name, allow_blocking, t0_ns, depth,
    lock_id]`` records; matching is by instance identity (two instances
    from one construction site can be held at once) while the order
    graph keys on the site ``name`` — order analysis is per-site, like
    lockdep classes."""

    __slots__ = ("name", "allow_blocking", "_inner")

    def __init__(self, name: str, allow_blocking: bool = False):
        self.name = name
        self.allow_blocking = allow_blocking
        self._inner = self._make_inner()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def _entry(self, held: list):
        me = id(self)
        for rec in reversed(held):
            if rec[4] == me:
                return rec
        return None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        _note_edges(held, self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            held.append(
                [self.name, self.allow_blocking, time.monotonic_ns(), 1,
                 id(self)]
            )
        return got

    def release(self) -> None:
        held = _held()
        rec = self._entry(held)
        if rec is not None:
            held.remove(rec)
            dur = time.monotonic_ns() - rec[2]
            if dur > _HOLD_NS and not self.allow_blocking:
                _note_event("long_hold", self.name, "", dur)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r} {self._inner!r}>"


class TrackedRLock(TrackedLock):
    """Reentrant variant: only the outermost acquire adds a held entry
    and order edges; inner levels bump the entry's depth, so recursion
    neither self-edges nor drops tracking early."""

    __slots__ = ()

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        rec = self._entry(held)
        if rec is not None:
            got = self._inner.acquire(blocking, timeout)
            if got:
                rec[3] += 1
            return got
        _note_edges(held, self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            held.append(
                [self.name, self.allow_blocking, time.monotonic_ns(), 1,
                 id(self)]
            )
        return got

    def release(self) -> None:
        held = _held()
        rec = self._entry(held)
        if rec is not None:
            rec[3] -= 1
            if rec[3] == 0:
                held.remove(rec)
                dur = time.monotonic_ns() - rec[2]
                if dur > _HOLD_NS and not self.allow_blocking:
                    _note_event("long_hold", self.name, "", dur)
        self._inner.release()

    def locked(self) -> bool:  # pragma: no cover - parity with RLock
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True


def tracked_lock(name: str, *, allow_blocking: bool = False):
    """A lock feeding the order graph under ``DORA_LOCKCHECK=1``; a plain
    ``threading.Lock`` otherwise. ``name`` identifies the construction
    site (all instances from one site share a graph node — order analysis
    is per-site, like lockdep classes). ``allow_blocking=True`` suppresses
    held-across-blocking-call and long-hold findings for locks whose JOB
    is to serialize a blocking resource."""
    if not LOCKCHECK.active:
        return threading.Lock()
    install_probes()
    return TrackedLock(name, allow_blocking)


def tracked_rlock(name: str, *, allow_blocking: bool = False):
    """Reentrant counterpart of :func:`tracked_lock`."""
    if not LOCKCHECK.active:
        return threading.RLock()
    install_probes()
    return TrackedRLock(name, allow_blocking)


# ---------------------------------------------------------------------------
# blocking-call probes
# ---------------------------------------------------------------------------

_probed: set[str] = set()


def _blocking_hit(call: str) -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    for rec in held:
        if not rec[1]:
            _note_event("blocking", rec[0], call)


def install_probes() -> None:
    """Patch the blocking primitives the data plane actually parks on so
    a held tracked lock across any of them becomes a finding. Idempotent
    per target; called from the factories so targets that import late
    (native, jax) get picked up by the next lock construction."""
    if "queue" not in _probed:
        _probed.add("queue")
        import queue as _queue

        def _probe_get(orig):
            def get(self, block=True, timeout=None):
                if block:
                    _blocking_hit("queue.Queue.get")
                return orig(self, block, timeout)

            return get

        def _probe_put(orig):
            def put(self, item, block=True, timeout=None):
                if block:
                    _blocking_hit("queue.Queue.put")
                return orig(self, item, block, timeout)

            return put

        _queue.Queue.get = _probe_get(_queue.Queue.get)
        _queue.Queue.put = _probe_put(_queue.Queue.put)

    if "socket" not in _probed:
        _probed.add("socket")
        import socket as _socket

        def _probe_sock(meth_name):
            orig = getattr(_socket.socket, meth_name)

            def probe(self, *args, **kwargs):
                if self.gettimeout() != 0:
                    _blocking_hit(f"socket.{meth_name}")
                return orig(self, *args, **kwargs)

            return probe

        for meth in ("send", "sendall", "recv", "accept", "connect"):
            setattr(_socket.socket, meth, _probe_sock(meth))

    if "time" not in _probed:
        _probed.add("time")
        _orig_sleep = time.sleep

        def sleep(secs):
            if secs > 0.001:
                _blocking_hit("time.sleep")
            return _orig_sleep(secs)

        time.sleep = sleep

    if "event" not in _probed:
        _probed.add("event")
        _orig_wait = threading.Event.wait

        def wait(self, timeout=None):
            if timeout is None or timeout > 0.001:
                _blocking_hit("threading.Event.wait")
            return _orig_wait(self, timeout)

        threading.Event.wait = wait

    if "native" not in _probed and "dora_tpu.native" in sys.modules:
        native = sys.modules["dora_tpu.native"]
        channel = getattr(native, "ShmemChannel", None)
        if channel is not None:
            _probed.add("native")

            def _probe_chan(meth_name):
                orig = getattr(channel, meth_name)

                def probe(self, *args, **kwargs):
                    _blocking_hit(f"ShmemChannel.{meth_name}")
                    return orig(self, *args, **kwargs)

                return probe

            for meth in ("send", "recv"):
                setattr(channel, meth, _probe_chan(meth))

    if "jax" not in _probed and "jax" in sys.modules:
        jax = sys.modules["jax"]
        orig_burt = getattr(jax, "block_until_ready", None)
        if orig_burt is not None:
            _probed.add("jax")

            def block_until_ready(x):
                _blocking_hit("jax.block_until_ready")
                return orig_burt(x)

            jax.block_until_ready = block_until_ready


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------


def _allowed_edges() -> set[tuple[str, str]]:
    """``DORA_LOCKCHECK_ALLOW="a>b,c>d"`` removes known-benign edges
    before cycle detection (the suppression story for false ABBAs from
    per-site granularity, README "Static analysis")."""
    out: set[tuple[str, str]] = set()
    for part in os.environ.get("DORA_LOCKCHECK_ALLOW", "").split(","):
        a, sep, b = part.strip().partition(">")
        if sep and a and b:
            out.add((a, b))
    return out


def order_graph() -> dict[tuple[str, str], dict]:
    with _meta:
        return {k: dict(v) for k, v in _edges.items()}


def order_cycles() -> list[list[str]]:
    """Elementary cycles in the lock-order graph (each reported once,
    rotated to start at its smallest name). A cycle means the involved
    locks were taken in incompatible orders by live code paths."""
    allow = _allowed_edges()
    with _meta:
        adj: dict[str, set[str]] = {}
        for a, b in _edges:
            if (a, b) in allow:
                continue
            adj.setdefault(a, set()).add(b)

    cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str], on_path: set[str]) -> None:
        for nxt in adj.get(node, ()):
            if nxt == start:
                cycles.add(tuple(path))
            elif nxt not in on_path and nxt > start:
                # Only walk names > start: every cycle is found from its
                # smallest member exactly once.
                on_path.add(nxt)
                dfs(start, nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return [list(c) for c in sorted(cycles)]


def findings() -> list[Finding]:
    """Everything the detector saw, as lint findings: cycles are errors,
    blocking/long-hold events are warnings (fix or opt the lock out)."""
    out: list[Finding] = []
    with _meta:
        edges = {k: dict(v) for k, v in _edges.items()}
        events = {k: dict(v) for k, v in _events.items()}
    for cycle in order_cycles():
        stacks = {
            f"{a}->{b}": edges[(a, b)]["stack"]
            for a, b in zip(cycle, cycle[1:] + cycle[:1])
            if (a, b) in edges
        }
        out.append(Finding(
            "lockcheck", "lock-cycle", "error", " -> ".join(cycle),
            "locks acquired in incompatible orders (potential ABBA deadlock)",
            {"cycle": cycle, "stacks": stacks},
        ))
    for (kind, lock_name, call), entry in sorted(events.items()):
        if kind == "blocking":
            out.append(Finding(
                "lockcheck", "lock-blocking", "warning", lock_name,
                f"held across blocking call {call} ({entry['count']}x)",
                {"call": call, "count": entry["count"],
                 "stack": entry["stack"]},
            ))
        else:
            out.append(Finding(
                "lockcheck", "lock-long-hold", "warning", lock_name,
                f"held {entry['max_ns'] / 1e6:.1f} ms "
                f"(threshold {_HOLD_NS / 1e6:.0f} ms, {entry['count']}x)",
                {"max_ns": entry["max_ns"], "count": entry["count"],
                 "stack": entry["stack"]},
            ))
    return out


def forget(prefix: str) -> None:
    """Drop edges/events whose lock names start with ``prefix`` — test
    fixtures seed violations under a ``test.`` prefix and clean up so the
    session-end zero-cycle gate only sees real code."""
    with _meta:
        for key in [k for k in _edges if k[0].startswith(prefix)
                    or k[1].startswith(prefix)]:
            del _edges[key]
            _edge_seen.discard(key)
        for key in [k for k in _events if k[1].startswith(prefix)]:
            del _events[key]
            _event_seen.discard(key)


def reset() -> None:
    with _meta:
        _edges.clear()
        _edge_seen.clear()
        _events.clear()
        _event_seen.clear()


def report(file=None) -> None:
    """End-of-process report (installed atexit when the detector is on;
    silent when nothing was found)."""
    found = findings()
    if not found:
        return
    file = file or sys.stderr
    print(f"--- lockcheck report ({len(found)} findings)", file=file)
    for f in found:
        print(f"  {f.render()}", file=file)
        stack = f.detail.get("stack")
        for key, s in (f.detail.get("stacks") or {}).items():
            print(f"    edge {key}:", file=file)
            print("      " + "      ".join(s.splitlines(True)), file=file)
        if stack:
            print("    " + "    ".join(stack.splitlines(True)), file=file)
    file.flush()


if LOCKCHECK.active and os.environ.get(
    "DORA_LOCKCHECK_REPORT", "1"
) not in ("", "0"):
    import atexit

    atexit.register(report)


# ---------------------------------------------------------------------------
# static wiring lint (part of `dora-tpu lint --self`)
# ---------------------------------------------------------------------------

#: Directories whose locks must go through the factories (the tentpole's
#: wiring contract); clock.py and native.py ride along as shared hot paths.
WIRED_DIRS = ("daemon", "node", "transport", "nodehub", "tpu", "ros2")
WIRED_FILES = ("clock.py", "native.py")


def lint_lock_wiring(package_root: str) -> list[Finding]:
    """Flag raw ``threading.Lock()``/``RLock()`` constructions inside the
    wired directories — every lock there must come from
    :func:`tracked_lock` so the detector's coverage cannot silently rot."""
    import ast
    from pathlib import Path

    root = Path(package_root)
    out: list[Finding] = []
    paths: list[Path] = []
    for d in WIRED_DIRS:
        paths.extend(sorted((root / d).rglob("*.py")))
    paths.extend(root / f for f in WIRED_FILES)
    for path in paths:
        if not path.exists():
            continue
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:  # pragma: no cover - repo parses
            out.append(Finding(
                "lockcheck", "lock-wiring-parse", "error",
                f"{path}:{e.lineno}", str(e)))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("Lock", "RLock")
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "threading"
            ):
                out.append(Finding(
                    "lockcheck", "lock-untracked", "error",
                    f"{path.relative_to(root.parent)}:{node.lineno}",
                    f"raw threading.{fn.attr}() in a wired directory — "
                    "use dora_tpu.analysis.lockcheck.tracked_lock()",
                ))
    return out
