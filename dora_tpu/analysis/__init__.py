"""Static/dynamic analysis plane (``dora-tpu lint`` / ``dora-tpu check``).

The native tier runs under ASan/TSan (tests/test_sanitizers.py); this
package is the correctness tooling for the Python control/data plane:

* :mod:`dora_tpu.analysis.lockcheck` — a lock-order race detector.
  ``tracked_lock()`` drop-ins record per-thread acquisition order into a
  process-wide graph when ``DORA_LOCKCHECK=1`` (a plain
  ``threading.Lock`` otherwise), reporting order-graph cycles (potential
  ABBA deadlocks), locks held across blocking calls, and long holds.
* :mod:`dora_tpu.analysis.graphcheck` — deploy-time dataflow descriptor
  checks (``dora-tpu check``): unbuffered cycles, dangling/duplicate
  edges, restart×p2p and qos/slo contradictions, promoted from runtime
  vetoes to machine-readable diagnostics.
* :mod:`dora_tpu.analysis.jaxlint` — AST lint over models/ and ops/ for
  recompile hazards: Python branches on traced values inside jit,
  unhashable static args, missing ``donate_argnums`` on pool-carrying
  jits, wall-clock/RNG calls under trace.
* :mod:`dora_tpu.analysis.envreg` — the central ``DORA_*`` env-var
  registry plus lints that every env read is declared and the README
  tables match.
* :mod:`dora_tpu.analysis.wirecheck` — serde coverage: every
  ``@message`` type has a compiled codec and golden-file coverage.

All passes emit :class:`Finding` so ``dora-tpu lint --json`` has one
machine-readable shape.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field


@dataclass
class Finding:
    """One diagnostic from any analysis pass.

    ``code`` is stable and machine-matchable (e.g. ``lock-cycle``,
    ``graph-unbuffered-cycle``, ``jax-tracer-branch``, ``env-undeclared``);
    ``level`` is ``error`` or ``warning``; ``where`` locates the finding
    (``path:line`` for source passes, a node/lock name for the others).
    """

    pass_name: str
    code: str
    level: str
    where: str
    message: str
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)

    def render(self) -> str:
        return f"[{self.pass_name}] {self.level} {self.code} {self.where}: {self.message}"


def errors(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if f.level == "error"]
