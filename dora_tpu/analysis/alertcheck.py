"""Deploy-time lint for the alerting plane (``dora-tpu check`` / ``lint``).

A bad alert rule is worse than no rule: it either never fires (typo'd
selector, percentile over a family that has no histogram) or fires on
noise (for-duration shorter than the sampling cadence evaluates a single
sample). These checks run over the resolved rule set — default pack with
the descriptor's ``alerts:`` block merged in — so a pack override is
linted exactly as the engine will run it, and over the sink environment,
so a webhook sink without an endpoint fails at check time instead of
silently dropping every notification.

Findings mirror :mod:`dora_tpu.analysis.graphcheck`'s shape; stable
codes: ``alert-unknown-metric``, ``alert-kind-mismatch``,
``alert-for-below-cadence``, ``alert-percentile-non-histogram``,
``alert-webhook-no-endpoint``.
"""

from __future__ import annotations

import os

from dora_tpu.alerts import (
    ENV_SINK,
    ENV_SINK_WEBHOOK,
    resolved_rules,
    selector_class,
)
from dora_tpu.analysis import Finding
from dora_tpu.metrics_history import history_interval_s

#: metric class each rule kind consumes: (numerator, denominator).
_KIND_CLASSES = {
    "gauge": ("gauge", None),
    "rate": ("counter", None),
    "ratio": ("counter", "counter"),
    "gauge_ratio": ("gauge", "gauge"),
    "percentile": ("hist", None),
}


def check_alerts(descriptor, interval_s: float | None = None) -> list[Finding]:
    """All alerting-plane diagnostics for one parsed descriptor."""
    out: list[Finding] = []
    interval = interval_s if interval_s is not None else history_interval_s()
    for rule in resolved_rules(descriptor.alerts):
        where = f"alerts/{rule.name}"
        if rule.kind != "burn":
            # burn selectors match node names, not series keys — every
            # other kind must name a known flattened metric family.
            for label, selector in (
                ("selector", rule.selector),
                ("denominator", rule.denominator),
            ):
                if selector is None:
                    continue
                cls = selector_class(selector)
                if cls is None:
                    out.append(Finding(
                        "alertcheck", "alert-unknown-metric", "error", where,
                        f"{label} {selector!r} matches no known metric "
                        "family (flatten_snapshot naming: 'srv:<node>:shed', "
                        "'queue:<node>/<input>', 'logerr:<node>', ...)",
                    ))
                    continue
                want = _KIND_CLASSES.get(rule.kind)
                want_cls = want and (want[1] if label == "denominator" else want[0])
                if want_cls and cls != want_cls:
                    code = (
                        "alert-percentile-non-histogram"
                        if rule.kind == "percentile"
                        else "alert-kind-mismatch"
                    )
                    out.append(Finding(
                        "alertcheck", code, "error", where,
                        f"kind {rule.kind!r} needs a {want_cls} {label}, but "
                        f"{selector!r} is a {cls} family",
                    ))
        if 0 < rule.for_s < interval and interval > 0:
            out.append(Finding(
                "alertcheck", "alert-for-below-cadence", "error", where,
                f"for_s={rule.for_s:g} is below the {interval:g}s sampling "
                "cadence — the predicate is evaluated once per sample, so "
                "this is for_s=0 with extra latency; use 0 or >= the cadence",
                detail={"for_s": rule.for_s, "interval_s": interval},
            ))
    out += check_alert_env()
    return out


def check_alert_env(env: dict | None = None) -> list[Finding]:
    """Sink-environment diagnostics (no descriptor needed)."""
    env = os.environ if env is None else env
    out: list[Finding] = []
    sinks = [s.strip() for s in env.get(ENV_SINK, "").split(",") if s.strip()]
    if "webhook" in sinks and not env.get(ENV_SINK_WEBHOOK):
        out.append(Finding(
            "alertcheck", "alert-webhook-no-endpoint", "error", ENV_SINK,
            f"{ENV_SINK} names the webhook sink but {ENV_SINK_WEBHOOK} "
            "is unset — every notification would be dropped",
        ))
    return out
