"""Central ``DORA_*`` environment-variable registry and its lints.

Every env var the runtime reads is declared here once, with its type,
default, and whether it belongs in the README tables. Two lints keep the
registry honest:

* ``env-undeclared`` — an ``os.environ`` / ``os.getenv`` read of a
  ``DORA_*`` name (literal, or via a module-level string constant like
  ``NODE_CONFIG_ENV``) that is not in :data:`REGISTRY`.
* ``env-unregistered-literal`` — any *other* full ``DORA_*`` string
  literal in the package (helper-call sites like
  ``_slo_env("DORA_SLO_TTFT_P99_MS")``, spawn-side injections) that is
  neither registered nor a registered-name prefix (f-string heads such
  as ``"DORA_SLO_"``) nor on the non-env allowlist (C enum identifiers
  embedded in native source).
* ``env-readme-unknown`` / ``env-readme-missing`` — the README env
  tables and the registry must agree: every ``DORA_*`` token in the
  README is registered (or allowlisted / a registered prefix), and every
  registry entry marked ``readme=True`` appears in the README.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path

from dora_tpu.analysis import Finding

_TOKEN = re.compile(r"DORA_[A-Z0-9_]*")


@dataclass(frozen=True)
class EnvVar:
    name: str
    kind: str          # "bool" | "int" | "float" | "str" | "path"
    default: str       # rendered default, "" when unset means off/absent
    desc: str
    readme: bool = False  # must appear in a README env table


def _e(name, kind, default, desc, readme=False):
    return name, EnvVar(name, kind, default, desc, readme)


#: The single source of truth for runtime-read ``DORA_*`` env vars.
REGISTRY: dict[str, EnvVar] = dict((
    # --- telemetry / observability -------------------------------------
    _e("DORA_LOG", "str", "info", "log level for the structured logger", True),
    _e("DORA_TRACING", "bool", "0", "enable span tracing", True),
    _e("DORA_JAEGER_TRACING", "str", "", "Jaeger agent addr for span export", True),
    _e("DORA_FLIGHT_RECORDER", "bool", "0", "enable the in-memory flight recorder", True),
    _e("DORA_FLIGHT_RECORDER_SIZE", "int", "65536", "flight recorder ring capacity", True),
    _e("DORA_NO_STACK_DUMP", "bool", "0", "suppress SIGUSR1 stack dumps"),
    _e("DORA_METRICS_HISTORY_S", "float", "900", "metrics history window seconds", True),
    _e("DORA_METRICS_HISTORY_LEN", "int", "1800", "metrics history ring length", True),
    _e("DORA_ALERTS", "bool", "1", "evaluate alert rules over the metrics history", True),
    _e("DORA_ALERT_SINK", "str", "", "comma list of alert sinks: log, jsonl, webhook", True),
    _e("DORA_ALERT_SINK_FILE", "path", "", "JSONL alert sink output file", True),
    _e("DORA_ALERT_SINK_WEBHOOK", "str", "", "webhook alert sink POST URL", True),
    _e("DORA_ALERT_WEBHOOK_RETRIES", "int", "2", "extra webhook delivery attempts per alert", True),
    _e("DORA_FLEET_DIGEST_S", "float", "2.0", "engine-state digest publish cadence (0 disables)", True),
    _e("DORA_FLEET_TOP_PREFIXES", "int", "32", "cached prefixes per engine digest", True),
    _e("DORA_PROM_PORT", "int", "", "coordinator Prometheus exporter port", True),
    _e("DORA_DEVICE_MONITOR", "bool", "1", "sample HBM/MFU device gauges", True),
    _e("DORA_DEVICE_PEAK_FLOPS", "float", "", "override device peak FLOP/s for MFU", True),
    _e("DORA_PROFILE_DIR", "path", "", "on-demand XLA profile output dir", True),
    # --- lockcheck (analysis plane) ------------------------------------
    _e("DORA_LOCKCHECK", "bool", "0", "enable the lock-order race detector", True),
    _e("DORA_LOCKCHECK_HOLD_MS", "float", "100", "long-hold warning threshold (ms)", True),
    _e("DORA_LOCKCHECK_ALLOW", "str", "", "comma list of suppressed order edges 'a>b'", True),
    _e("DORA_LOCKCHECK_REPORT", "bool", "1", "print the lockcheck report at exit", True),
    # --- daemon / routing / transport ----------------------------------
    _e("DORA_P2P", "bool", "1", "allow direct node-to-node routing", True),
    _e("DORA_SEND_COALESCE", "int", "0", "coalesce small sends (bytes)", True),
    _e("DORA_DAEMON_ADDR", "str", "", "daemon address override for hub nodes"),
    _e("DORA_NODE_CONFIG", "str", "", "spawn-injected node config (set by daemon)", True),
    _e("DORA_RUNTIME_NODE", "bool", "", "marks a runtime-managed operator process (set by daemon)"),
    _e("DORA_CHAOS_ID", "str", "", "dataflow:node tag for chaos targeting (set by daemon)"),
    _e("DORA_TEST_SESSION", "str", "", "test-session mark for orphan cleanup (set by conftest)"),
    _e("DORA_TPU_STATE_DIR", "path", "~/.dora-tpu", "coordinator/daemon state dir"),
    _e("DORA_TPU_CACHE", "path", "~/.cache/dora-tpu", "artifact download cache"),
    # --- ros2 / rtps bridge --------------------------------------------
    _e("DORA_RTPS_PEERS", "str", "", "static RTPS peer list"),
    _e("DORA_RTPS_LEASE_S", "float", "20", "RTPS liveliness lease seconds"),
    _e("DORA_RTPS_ANNOUNCE_S", "float", "5", "RTPS announce interval seconds"),
    # --- serving engine ------------------------------------------------
    _e("DORA_STUB_ENGINE", "bool", "0", "run the CPU stub engine", True),
    _e("DORA_STUB_CYCLE", "str", "", "stub engine canned-token cycle", True),
    _e("DORA_HF_CHECKPOINT", "path", "", "HF checkpoint dir for the real engine"),
    _e("DORA_CHECKPOINT", "path", "", "ops-node checkpoint path"),
    _e("DORA_CHECKPOINT_DIR", "path", "", "engine pool checkpoint/restore dir", True),
    _e("DORA_CHECKPOINT_EVERY", "int", "0", "checkpoint cadence (windows)", True),
    _e("DORA_CHECKPOINT_PAGES", "bool", "0", "include KV pages in checkpoints"),
    _e("DORA_MIGRATE_DIR", "path", "", "live-migration handoff dir", True),
    _e("DORA_BATCH_SLOTS", "int", "8", "continuous-batching slot count", True),
    _e("DORA_MAX_SEQ", "int", "1024", "max sequence length", True),
    _e("DORA_MAX_NEW_TOKENS", "int", "128", "default completion token budget", True),
    _e("DORA_MULTISTEP_K", "int", "8", "fused decode window size K", True),
    _e("DORA_STEP_DELAY_S", "float", "0", "artificial per-step delay (tests)"),
    _e("DORA_PREFILL_CHUNK", "int", "0", "chunked prefill size", True),
    _e("DORA_PAGED_KV", "bool", "0", "paged KV-cache pool", True),
    _e("DORA_PAGE_SIZE", "int", "64", "KV page size (tokens)", True),
    _e("DORA_PREFIX_CACHE", "bool", "0", "shared-prefix KV cache", True),
    _e("DORA_PREFIX_CACHE_PAGES", "int", "0", "prefix cache page budget", True),
    _e("DORA_OPENAI_CONCURRENT", "bool", "0", "concurrent OpenAI-server request handling", True),
    # --- qos / slo (descriptor blocks -> spawn env) --------------------
    _e("DORA_QOS_DEFAULT_CLASS", "str", "standard", "default admission QoS class", True),
    _e("DORA_QOS_DEPTH_INTERACTIVE", "int", "", "interactive-class backlog bound", True),
    _e("DORA_QOS_DEPTH_STANDARD", "int", "", "standard-class backlog bound"),
    _e("DORA_QOS_DEPTH_BATCH", "int", "", "batch-class backlog bound"),
    _e("DORA_QOS_SHED_WAIT_MS", "float", "", "shed requests queued longer than this", True),
    _e("DORA_QOS_AGING_S", "float", "", "class aging half-life for anti-starvation", True),
    _e("DORA_QOS_PREEMPT", "bool", "0", "allow higher-class preemption", True),
    _e("DORA_SLO_TTFT_P99_MS", "float", "", "SLO target: p99 time-to-first-token"),
    _e("DORA_SLO_TOKENS_PER_S_MIN", "float", "", "SLO target: min decode throughput"),
    _e("DORA_SLO_QUEUE_DEPTH_MAX", "int", "", "SLO target: max admission queue depth"),
    # --- slo autotuner -------------------------------------------------
    _e("DORA_AUTOTUNE_K", "bool", "0", "SLO-driven window autotuner", True),
    _e("DORA_AUTOTUNE_LADDER", "str", "", "autotuner K ladder", True),
    _e("DORA_AUTOTUNE_INTERVAL_S", "float", "", "autotuner decision interval", True),
    _e("DORA_AUTOTUNE_BURN_WINDOW_S", "float", "", "burn-rate window for autotune", True),
    _e("DORA_AUTOTUNE_HYSTERESIS", "float", "", "autotuner hysteresis factor", True),
    # --- models / ops --------------------------------------------------
    _e("DORA_MESH", "str", "", "device mesh spec for fused pipelines", True),
    _e("DORA_PIPELINE_DEPTH", "int", "2", "fuse pipeline depth", True),
    _e("DORA_FETCH_EVERY", "int", "1", "fused fetch cadence", True),
    _e("DORA_FETCH_LINGER_MS", "float", "0", "fused fetch linger window"),
    _e("DORA_FLASH_ATTENTION", "bool", "0", "flash-attention kernels"),
    _e("DORA_FUSED_DECODE", "bool", "0", "fused decode step"),
    _e("DORA_DECODE_UNROLL", "int", "1", "decode loop unroll factor"),
    _e("DORA_HEAD_BV", "int", "0", "decode-block head block size"),
    _e("DORA_INT8_DECODE", "bool", "0", "int8 weight quantized decode", True),
    _e("DORA_INT8_PURE", "bool", "0", "pure-int8 matmul path"),
    _e("DORA_INT4_DECODE", "bool", "0", "int4 weight quantized decode", True),
    _e("DORA_KV_INT8", "bool", "0", "int8 KV pages with per-page scales",
       True),
    _e("DORA_WEIGHT_BITS", "str", "", "decode weight bits (4 or 8)", True),
    _e("DORA_LORA_DIR", "path", "", "LoRA adapter catalog directory", True),
    _e("DORA_LORA_MAX_RESIDENT", "int", "8",
       "resident LoRA adapter slots", True),
    _e("DORA_LORA_RANK", "int", "", "LoRA pool rank override", True),
    _e("DORA_PARAM_DTYPE", "str", "", "parameter dtype override"),
    _e("DORA_SP_IMPL", "str", "", "sequence-parallel impl selector", True),
    _e("DORA_SPEC_DECODE", "bool", "0", "speculative decoding", True),
    _e("DORA_SPEC_K", "int", "4", "speculation depth", True),
    _e("DORA_SPEC_NGRAM", "int", "0", "n-gram draft order", True),
    _e("DORA_SPEC_BODY", "str", "", "draft body spec", True),
    _e("DORA_SPEC_ADAPTIVE", "bool", "0", "adaptive speculation length"),
    _e("DORA_SPEC_WORST_CASE", "bool", "0", "worst-case speculation accounting"),
    _e("DORA_MODEL_SIZE", "str", "", "ops-node model size preset"),
    _e("DORA_MAX_TILES", "int", "", "vision max image tiles"),
    _e("DORA_MAX_SRC", "int", "", "translator max source length"),
    _e("DORA_DETECT_THRESHOLD", "float", "", "detector score threshold"),
    _e("DORA_DETECT_TOPK", "int", "", "detector top-k"),
    _e("DORA_TOKENIZER", "path", "", "tokenizer path override"),
    _e("DORA_PROMPT", "str", "", "ops-node prompt override"),
    _e("DORA_TTS_STYLE", "str", "", "TTS style preset"),
    # --- distributed jax ----------------------------------------------
    _e("DORA_JAX_COORDINATOR", "str", "", "jax.distributed coordinator addr"),
    _e("DORA_JAX_NUM_PROCESSES", "int", "", "jax.distributed process count"),
    _e("DORA_JAX_PROCESS_ID", "int", "", "jax.distributed process id"),
    # --- bench ---------------------------------------------------------
    _e("DORA_BENCH_TRIALS", "int", "3", "bench_serving trial count"),
    _e("DORA_BENCH_QOS_STREAMS", "int", "", "bench_serving QoS stream mix"),
    _e("DORA_BENCH_PREFIX_STREAMS", "int", "", "bench_serving shared-prefix streams"),
))

#: Non-env ``DORA_`` identifiers that legitimately appear in docs/source:
#: C enum names in the embedded native source and README prose.
ALLOWED_NON_ENV_PREFIXES = ("DORA_EVENT_", "DORA_OP_")


def is_registered(name: str) -> bool:
    return name in REGISTRY


def _prefix_ok(token: str) -> bool:
    """A token like ``DORA_SLO_`` (an f-string head or README family
    shorthand) is fine when registered names extend it."""
    return token.endswith("_") and any(
        n.startswith(token) for n in REGISTRY
    )


def _allowlisted(token: str) -> bool:
    return any(token.startswith(p) for p in ALLOWED_NON_ENV_PREFIXES)


# ---------------------------------------------------------------------------
# lint: every DORA_* env read / literal is declared
# ---------------------------------------------------------------------------


def _env_read_name(node: ast.AST, consts: dict[str, str]) -> str | None:
    """Name read by ``os.environ.get/.pop/.setdefault``, ``os.environ[..]``
    or ``os.getenv`` — literal or via a module-level string constant."""
    def resolve(arg):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.Name):
            return consts.get(arg.id)
        return None

    if isinstance(node, ast.Call):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in ("get", "pop", "setdefault")
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "environ"
        ) or (isinstance(f, ast.Attribute) and f.attr == "getenv"):
            if node.args:
                return resolve(node.args[0])
    elif isinstance(node, ast.Subscript):
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "environ":
            return resolve(node.slice)
    return None


def lint_env_reads(package_root: str | Path = "dora_tpu") -> list[Finding]:
    out: list[Finding] = []
    for path in sorted(Path(package_root).rglob("*.py")):
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue
        consts = {
            t.id: s.value.value
            for s in tree.body
            if isinstance(s, ast.Assign) and isinstance(s.value, ast.Constant)
            and isinstance(s.value.value, str)
            for t in s.targets
            if isinstance(t, ast.Name)
        }
        read_nodes: set[int] = set()
        for node in ast.walk(tree):
            name = _env_read_name(node, consts)
            if name is None:
                continue
            # Remember the literal-arg node so the generic literal sweep
            # below doesn't double-report the same site.
            if isinstance(node, ast.Call) and node.args:
                read_nodes.add(id(node.args[0]))
            elif isinstance(node, ast.Subscript):
                read_nodes.add(id(node.slice))
            if name.startswith("DORA_") and not is_registered(name):
                out.append(Finding(
                    "envreg", "env-undeclared", "error",
                    f"{path}:{node.lineno}",
                    f"env read of {name!r} is not declared in "
                    "dora_tpu.analysis.envreg.REGISTRY",
                ))
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _TOKEN.fullmatch(node.value)
            ):
                continue
            if id(node) in read_nodes:
                continue
            tok = node.value
            if is_registered(tok) or _prefix_ok(tok) or _allowlisted(tok):
                continue
            out.append(Finding(
                "envreg", "env-unregistered-literal", "error",
                f"{path}:{node.lineno}",
                f"DORA_* literal {tok!r} is neither a registered env var "
                "nor an allowlisted identifier",
            ))
    return out


# ---------------------------------------------------------------------------
# lint: README env tables <-> registry
# ---------------------------------------------------------------------------


def lint_readme(readme_path: str | Path = "README.md") -> list[Finding]:
    out: list[Finding] = []
    path = Path(readme_path)
    if not path.exists():
        return [Finding("envreg", "env-readme-unknown", "error", str(path),
                        "README not found")]
    text = path.read_text()
    tokens = set(_TOKEN.findall(text))
    for tok in sorted(tokens):
        if is_registered(tok) or _prefix_ok(tok) or _allowlisted(tok):
            continue
        out.append(Finding(
            "envreg", "env-readme-unknown", "error", str(path),
            f"README mentions {tok!r}, which is not a registered env var",
        ))
    for var in REGISTRY.values():
        if var.readme and var.name not in tokens:
            out.append(Finding(
                "envreg", "env-readme-missing", "error", str(path),
                f"{var.name} is marked readme=True but absent from the "
                "README env tables",
            ))
    return out


def lint(package_root: str | Path = "dora_tpu",
         readme_path: str | Path = "README.md") -> list[Finding]:
    return lint_env_reads(package_root) + lint_readme(readme_path)
