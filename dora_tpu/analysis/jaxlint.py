"""JAX recompile-hazard lint (AST pass over models/ and ops/).

The serving engine's zero-steady-state-compile invariant (one XLA
program per closure, tests/test_paged_engine.py) dies by a thousand
cuts: a Python ``if`` on a traced value retraces per branch, an
unhashable static arg retraces per call, a missing ``donate_argnums``
on a pool-carrying jit doubles HBM, and a wall-clock/RNG call under
trace bakes one sample into the compiled program forever. This pass
catches all four shapes *before* runtime — the runtime compile-count
guard (telemetry.install_compile_listener) only fires after the damage.

Syntactic by design (KNOWN_ISSUES round 17): it sees functions defined
and jitted in the same module (decorator form ``@partial(jax.jit,
static_argnums=...)`` / ``@jax.jit``, and call form ``jax.jit(fn,
...)`` where ``fn`` is a module-local def or lambda). Closure-captured
tracers and dynamically built jits escape it; the runtime guard remains
the backstop.

Codes:

* ``jax-tracer-branch`` — ``if``/``while`` whose test uses a traced
  parameter's *value*. Shape/dtype/ndim/size access, ``len()``,
  ``isinstance()`` and ``is (not) None`` tests are concrete at trace
  time and exempt.
* ``jax-unhashable-static`` — a static argument whose default is a
  list/dict/set literal (retrace or TypeError per call).
* ``jax-missing-donate`` — a jitted function carrying a KV pool
  parameter (named ``pools``) without donating it: the old pool stays
  alive across the call, doubling page memory.
* ``jax-impure-call`` — ``time.*`` / ``random.*`` / ``np.random.*``
  inside a jitted body (``jax.random`` is the supported path).
"""

from __future__ import annotations

import ast
from pathlib import Path

from dora_tpu.analysis import Finding

#: Attribute reads on a tracer that are concrete at trace time.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "at"}

#: Parameter names that carry donated KV pools in this codebase.
_POOL_PARAMS = {"pools"}

_TIME_CALLS = {"time", "time_ns", "monotonic", "monotonic_ns",
               "perf_counter", "perf_counter_ns"}


def _is_jax_jit(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Attribute)
        and expr.attr == "jit"
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "jax"
    ) or (isinstance(expr, ast.Name) and expr.id == "jit")


def _const_int_tuple(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return out
    return []


def _const_str_tuple(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            elt.value for elt in node.elts
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
        ]
    return []


class _JitSite:
    """One jit application: the target function plus the jit kwargs."""

    def __init__(self, fn, call: ast.Call | None, lineno: int):
        self.fn = fn  # ast.FunctionDef | ast.Lambda
        self.lineno = lineno
        self.static_nums: list[int] = []
        self.static_names: list[str] = []
        self.donates = False
        if call is not None:
            for kw in call.keywords:
                if kw.arg == "static_argnums":
                    self.static_nums = _const_int_tuple(kw.value)
                elif kw.arg == "static_argnames":
                    self.static_names = _const_str_tuple(kw.value)
                elif kw.arg in ("donate_argnums", "donate_argnames"):
                    self.donates = True

    def params(self) -> list[ast.arg]:
        a = self.fn.args
        return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)

    def traced_params(self) -> set[str]:
        params = self.params()
        static = set(self.static_names)
        for i in self.static_nums:
            if 0 <= i < len(params):
                static.add(params[i].arg)
        return {p.arg for p in params} - static

    def static_params(self) -> set[str]:
        return {p.arg for p in self.params()} - self.traced_params()


def _collect_sites(tree: ast.Module) -> list[_JitSite]:
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    sites: list[_JitSite] = []
    jitted_defs: set[int] = set()

    # Decorator form.
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for dec in node.decorator_list:
            if _is_jax_jit(dec):
                sites.append(_JitSite(node, None, node.lineno))
                jitted_defs.add(id(node))
            elif isinstance(dec, ast.Call):
                target = None
                if _is_jax_jit(dec.func):
                    target = dec
                elif (
                    (isinstance(dec.func, ast.Name)
                     and dec.func.id == "partial")
                    or (isinstance(dec.func, ast.Attribute)
                        and dec.func.attr == "partial")
                ) and dec.args and _is_jax_jit(dec.args[0]):
                    target = dec
                if target is not None:
                    sites.append(_JitSite(node, target, node.lineno))
                    jitted_defs.add(id(node))

    # Call form: jax.jit(fn, ...) with fn a module-local def or lambda.
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jax_jit(node.func)
                and node.args):
            continue
        target = node.args[0]
        fn = None
        if isinstance(target, ast.Name):
            fn = defs.get(target.id)
            if fn is not None and id(fn) in jitted_defs:
                fn = None  # decorator form already covers it
        elif isinstance(target, ast.Lambda):
            fn = target
        if isinstance(fn, (ast.FunctionDef, ast.Lambda)):
            sites.append(_JitSite(fn, node, node.lineno))
    return sites


# ---------------------------------------------------------------------------
# per-site checks
# ---------------------------------------------------------------------------


def _value_uses(expr: ast.AST, traced: set[str]) -> list[ast.Name]:
    """Name nodes inside ``expr`` whose runtime *value* is a tracer.

    Prunes subtrees that are concrete at trace time: static attribute
    reads (``x.shape[0]``), ``len(x)``, ``isinstance(x, ...)``, and
    identity tests against None.
    """
    if isinstance(expr, ast.Name):
        return [expr] if expr.id in traced else []
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return []
        return _value_uses(expr.value, traced)
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Name) and expr.func.id in (
            "len", "isinstance", "hasattr", "getattr", "type",
        ):
            return []
        out = []
        for arg in list(expr.args) + [kw.value for kw in expr.keywords]:
            out.extend(_value_uses(arg, traced))
        # The callee itself (e.g. ``x.sum`` with x traced).
        out.extend(_value_uses(expr.func, traced))
        return out
    if isinstance(expr, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return []
        if any(isinstance(op, (ast.In, ast.NotIn)) for op in expr.ops):
            # Membership on a traced param is a dict-pytree key probe in
            # this codebase ("mid_pos" in params) — concrete at trace
            # time. `x in array` WOULD be a hazard; accepted blind spot
            # of the syntactic pass (module docstring).
            return []
        out = _value_uses(expr.left, traced)
        for comp in expr.comparators:
            out.extend(_value_uses(comp, traced))
        return out
    out = []
    for child in ast.iter_child_nodes(expr):
        out.extend(_value_uses(child, traced))
    return out


def _lint_site(site: _JitSite, rel: str) -> list[Finding]:
    out: list[Finding] = []
    traced = site.traced_params()
    fn_name = getattr(site.fn, "name", "<lambda>")

    # Shadowing: a param rebound in the body stops being the tracer we
    # reason about — drop it (syntactic pass, stay conservative).
    live = set(traced)
    for node in ast.walk(site.fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            targets = [node.target]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    live.discard(n.id)

    for node in ast.walk(site.fn):
        if isinstance(node, (ast.If, ast.While)):
            uses = _value_uses(node.test, live)
            if uses:
                names = sorted({u.id for u in uses})
                out.append(Finding(
                    "jaxlint", "jax-tracer-branch", "error",
                    f"{rel}:{node.test.lineno}",
                    f"{fn_name}: Python "
                    f"{'if' if isinstance(node, ast.If) else 'while'} "
                    f"branches on traced value(s) {', '.join(names)} — "
                    "retraces per branch; use lax.cond/select or "
                    "static_argnums",
                    {"fn": fn_name, "params": names},
                ))
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name
            ):
                mod = func.value.id
                if mod == "time" and func.attr in _TIME_CALLS:
                    out.append(Finding(
                        "jaxlint", "jax-impure-call", "error",
                        f"{rel}:{node.lineno}",
                        f"{fn_name}: time.{func.attr}() under jit is baked "
                        "into the compiled program",
                        {"fn": fn_name},
                    ))
                elif mod == "random":
                    out.append(Finding(
                        "jaxlint", "jax-impure-call", "error",
                        f"{rel}:{node.lineno}",
                        f"{fn_name}: stdlib random.{func.attr}() under jit "
                        "compiles one sample forever; use jax.random",
                        {"fn": fn_name},
                    ))
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Attribute)
                and func.value.attr == "random"
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id in ("np", "numpy")
            ):
                out.append(Finding(
                    "jaxlint", "jax-impure-call", "error",
                    f"{rel}:{node.lineno}",
                    f"{fn_name}: np.random.{func.attr}() under jit compiles "
                    "one sample forever; use jax.random",
                    {"fn": fn_name},
                ))

    params = site.params()
    static = site.static_params()
    defaults = list(site.fn.args.defaults)
    defaulted = params[len(params) - len(defaults):] if defaults else []
    for param, default in zip(defaulted, defaults):
        if param.arg in static and isinstance(
            default, (ast.List, ast.Dict, ast.Set)
        ):
            out.append(Finding(
                "jaxlint", "jax-unhashable-static", "error",
                f"{rel}:{default.lineno}",
                f"{fn_name}: static arg {param.arg!r} defaults to an "
                "unhashable literal — jit static args must hash",
                {"fn": fn_name, "param": param.arg},
            ))

    pool_params = sorted(
        p.arg for p in params if p.arg in _POOL_PARAMS and p.arg in traced
    )
    if pool_params and not site.donates:
        out.append(Finding(
            "jaxlint", "jax-missing-donate", "error",
            f"{rel}:{site.lineno}",
            f"{fn_name}: jit carries KV pool arg(s) "
            f"{', '.join(pool_params)} without donate_argnums — the stale "
            "pool stays alive across the call, doubling page HBM",
            {"fn": fn_name, "params": pool_params},
        ))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

#: The directories `dora-tpu lint --self` sweeps (jit lives here).
SELF_DIRS = ("models", "ops", "parallel", "tpu")


def lint_file(path: str | Path, rel: str | None = None) -> list[Finding]:
    path = Path(path)
    rel = rel or str(path)
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [Finding(
            "jaxlint", "jax-parse", "error", f"{rel}:{e.lineno}", str(e)
        )]
    out: list[Finding] = []
    for site in _collect_sites(tree):
        out.extend(_lint_site(site, rel))
    return out


def lint_paths(paths: list[str | Path]) -> list[Finding]:
    out: list[Finding] = []
    for raw in paths:
        p = Path(raw)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            out.extend(lint_file(f, str(f)))
    return out


def lint_self(package_root: str | Path) -> list[Finding]:
    """Sweep the repo's own jit-bearing trees (``dora-tpu lint --self``)."""
    root = Path(package_root)
    out: list[Finding] = []
    for d in SELF_DIRS:
        sub = root / d
        if sub.exists():
            for f in sorted(sub.rglob("*.py")):
                out.extend(lint_file(f, str(f.relative_to(root.parent))))
    return out
