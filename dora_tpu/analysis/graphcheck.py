"""Deploy-time dataflow graph checks (``dora-tpu check``).

Absorbs and extends :mod:`dora_tpu.core.validate`: the source/edge
validation stays (and still guards the runtime start path), while the
contradictions that used to be runtime vetoes or silent fallbacks become
deploy-time diagnostics with machine-readable codes:

* ``graph-invalid`` — anything :func:`core.validate.check_dataflow`
  rejects (unresolvable sources, inputs to undeclared outputs).
* ``graph-dangling-edge`` / ``graph-duplicate-edge`` /
  ``graph-duplicate-node`` — structural edge problems, ALL of them
  (validate raises on the first).
* ``graph-cycle-deadlock`` — a cycle of user-mapped edges with no timer
  input, no input from outside the cycle anywhere in its strongly
  connected component, and no node driven by events from outside the
  dataflow entirely (an HTTP front door, a keyboard, a sensor):
  nothing ever produces the first message, so the loop is deadlocked
  at startup. (Full queues cannot deadlock here — the daemon drops
  oldest — so the startup form is the real one.)
* ``graph-restart-p2p`` — a restartable node receiving p2p-eligible
  edges under an explicit ``DORA_P2P: "1"``. The daemon silently keeps
  such receivers daemon-routed (daemon/core.py ``_compute_p2p``: crash
  replay needs the daemon-held in-flight window); an explicit opt-in
  that cannot be honored is a descriptor contradiction.
* ``graph-slo-non-serving`` — ``slo:`` serving targets (ttft,
  tokens/s) on a node that reports no serving metrics; the burn-rate
  gauges would read forever-zero and the SLO silently never fires.
  An explicit ``serving: true``/``false`` node flag overrides the
  source-name heuristic for this and the qos check.
* ``graph-qos-non-serving`` — ``qos:`` on a node with no admission
  queue to shape.
* ``graph-qos-deadline-quantum`` — ``shed_wait_ms`` below the fused
  decode window quantum (``DORA_MULTISTEP_K`` steps): every queued
  request sheds before one window can complete.
* ``graph-fleet-duplicate-replica`` — two serving nodes with the same
  id: the merged fleet view (``dora-tpu fleet``) keys replicas by node
  id, so their engine digests would silently overwrite each other.
* ``graph-fleet-unrouted`` — several serving replicas share a
  model/config fingerprint (same model id, K, spec_k, kv dtype, weight
  bits — interchangeable placement targets) but no upstream node fans
  out to more than one of them, so nothing is positioned to consume
  the fleet state and steer requests by prefix affinity/occupancy
  (``dora_tpu.fleet.score_placement``). Each replica serves a private
  pipeline and the fleet plane is decorative.
"""

from __future__ import annotations

from pathlib import Path

from dora_tpu.analysis import Finding
from dora_tpu.core.config import TimerMapping, UserMapping
from dora_tpu.core.descriptor import CustomNode, Descriptor

#: Node-hub sources that run a serving engine and therefore report the
#: SERVING metrics the slo/qos planes consume.
SERVING_SOURCES = ("llm_server",)

#: Node-hub sources whose main loop is driven by events from OUTSIDE
#: the dataflow (HTTP requests, keystrokes, sensor frames, recorded
#: logs). Such a node produces output without first receiving a
#: dataflow input, so a cycle through one is not startup-deadlocked —
#: the external world injects the first message.
EXTERNAL_INGRESS_SOURCES = (
    "openai_server",
    "llm_server",
    "keyboard",
    "terminal_input",
    "microphone",
    "camera",
    "replay",
)

#: Floor for one fused decode window, per step (conservative: CPU stub
#: engines tick ~1 ms/step; real engines are slower).
_MS_PER_STEP_FLOOR = 1.0


def _is_serving(node) -> bool:
    # An explicit ``serving:`` declaration in the descriptor wins over
    # the source-name heuristic — a custom serving node under any
    # source name can opt in (and a node whose source merely mentions a
    # serving module can opt out with ``serving: false``).
    if getattr(node, "serving", None) is not None:
        return bool(node.serving)
    kind = node.kind
    return isinstance(kind, CustomNode) and any(
        s in str(kind.source) for s in SERVING_SOURCES
    )


def _has_external_ingress(node) -> bool:
    kind = node.kind
    return isinstance(kind, CustomNode) and any(
        s in str(kind.source) for s in EXTERNAL_INGRESS_SOURCES
    )


def _env_truthy(value) -> bool:
    return str(value) not in ("", "0", "None", "False", "false")


def check_descriptor(
    descriptor: Descriptor, working_dir: str | Path | None = None
) -> list[Finding]:
    """All deploy-time diagnostics for one parsed descriptor."""
    out: list[Finding] = []
    out += _structural(descriptor, working_dir)
    out += _cycle_deadlocks(descriptor)
    out += _restart_p2p(descriptor)
    out += _qos_slo(descriptor)
    out += _fleet(descriptor)
    return out


# ---------------------------------------------------------------------------
# structure
# ---------------------------------------------------------------------------


def _structural(descriptor, working_dir) -> list[Finding]:
    from dora_tpu.core.validate import ValidationError, check_dataflow

    out: list[Finding] = []
    try:
        check_dataflow(descriptor, working_dir)
    except ValidationError as e:
        out.append(Finding(
            "graphcheck", "graph-invalid", "error", "dataflow", str(e)
        ))

    seen_ids: set[str] = set()
    for node in descriptor.nodes:
        nid = str(node.id)
        if nid in seen_ids:
            out.append(Finding(
                "graphcheck", "graph-duplicate-node", "error", nid,
                f"node id {nid!r} declared more than once",
            ))
        seen_ids.add(nid)

    node_ids = {str(n.id) for n in descriptor.nodes}
    declared = descriptor.output_ids()
    for node in descriptor.nodes:
        by_source: dict[str, list[str]] = {}
        for input_id, inp in node.inputs.items():
            m = inp.mapping
            if isinstance(m, TimerMapping):
                continue
            if str(m.source) not in node_ids:
                out.append(Finding(
                    "graphcheck", "graph-dangling-edge", "error",
                    f"{node.id}/{input_id}",
                    f"source node {str(m.source)!r} does not exist",
                ))
            elif m.output_id not in declared:
                out.append(Finding(
                    "graphcheck", "graph-dangling-edge", "error",
                    f"{node.id}/{input_id}",
                    f"node {str(m.source)!r} has no output {str(m.output)!r}",
                ))
            by_source.setdefault(str(m), []).append(str(input_id))
        for source, inputs in by_source.items():
            if len(inputs) > 1:
                out.append(Finding(
                    "graphcheck", "graph-duplicate-edge", "warning",
                    f"{node.id}",
                    f"output {source!r} feeds {len(inputs)} inputs of the "
                    f"same node ({', '.join(sorted(inputs))}) — each message "
                    "is delivered twice",
                ))
    return out


# ---------------------------------------------------------------------------
# startup-deadlocked cycles
# ---------------------------------------------------------------------------


def _cycle_deadlocks(descriptor) -> list[Finding]:
    node_ids = {str(n.id) for n in descriptor.nodes}
    edges: dict[str, set[str]] = {nid: set() for nid in node_ids}
    has_timer: set[str] = set()
    external_ingress = {
        str(n.id) for n in descriptor.nodes if _has_external_ingress(n)
    }
    for node in descriptor.nodes:
        nid = str(node.id)
        for _input_id, inp in node.inputs.items():
            m = inp.mapping
            if isinstance(m, TimerMapping):
                has_timer.add(nid)
            elif isinstance(m, UserMapping) and str(m.source) in node_ids:
                edges[str(m.source)].add(nid)

    out: list[Finding] = []
    for scc in _tarjan_sccs(edges):
        internal = any(b in scc for a in scc for b in edges.get(a, ()))
        if not internal:
            continue  # not a cycle
        if any(n in has_timer for n in scc):
            continue  # a timer drives the loop
        if any(n in external_ingress for n in scc):
            continue  # an HTTP front door / sensor injects the first message
        fed_externally = False
        for node in descriptor.nodes:
            if str(node.id) not in scc:
                continue
            for inp in node.inputs.values():
                m = inp.mapping
                if isinstance(m, UserMapping) and str(m.source) not in scc:
                    fed_externally = True
        if fed_externally:
            continue
        members = sorted(scc)
        out.append(Finding(
            "graphcheck", "graph-cycle-deadlock", "error",
            " -> ".join(members),
            "cycle has no timer input and no input from outside the loop — "
            "no node can ever produce the first message",
            {"nodes": members},
        ))
    return out


def _tarjan_sccs(edges: dict[str, set[str]]) -> list[set[str]]:
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[set[str]] = []

    def strongconnect(v: str) -> None:
        # Iterative Tarjan: recursion would overflow on long chains.
        work = [(v, iter(sorted(edges.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(edges):
        if v not in index:
            strongconnect(v)
    return sccs


# ---------------------------------------------------------------------------
# restart × p2p
# ---------------------------------------------------------------------------


def _restart_p2p(descriptor) -> list[Finding]:
    global_env = (descriptor.raw or {}).get("env") or {}
    out: list[Finding] = []
    for node in descriptor.nodes:
        if node.restart is None:
            continue
        p2p_requested = None
        if "DORA_P2P" in node.env:
            p2p_requested = _env_truthy(node.env["DORA_P2P"])
        elif "DORA_P2P" in global_env:
            p2p_requested = _env_truthy(global_env["DORA_P2P"])
        if not p2p_requested:
            continue  # default-on p2p silently falls back; only an
            # EXPLICIT opt-in is a contradiction
        receives = [
            str(input_id)
            for input_id, inp in node.inputs.items()
            if isinstance(inp.mapping, UserMapping)
        ]
        if receives:
            out.append(Finding(
                "graphcheck", "graph-restart-p2p", "error", str(node.id),
                "restart: requires daemon-routed inputs (crash replay holds "
                "the un-acked window in the daemon), but the descriptor "
                "explicitly sets DORA_P2P=1 for this node — the opt-in "
                f"cannot be honored for inputs {', '.join(sorted(receives))}",
                {"inputs": sorted(receives)},
            ))
    return out


# ---------------------------------------------------------------------------
# qos / slo contradictions
# ---------------------------------------------------------------------------


def _qos_slo(descriptor) -> list[Finding]:
    global_env = (descriptor.raw or {}).get("env") or {}
    out: list[Finding] = []
    for node in descriptor.nodes:
        serving = _is_serving(node)
        slo = node.slo
        if slo is not None and not serving:
            targets = [
                k for k in ("ttft_p99_ms", "tokens_per_s_min")
                if getattr(slo, k) is not None
            ]
            if targets:
                out.append(Finding(
                    "graphcheck", "graph-slo-non-serving", "error",
                    str(node.id),
                    f"slo targets {', '.join(targets)} need SERVING metrics, "
                    "which this node never reports — the objective would "
                    "silently never fire",
                    {"targets": targets},
                ))
        qos = node.qos
        if qos is None:
            continue
        if not serving:
            out.append(Finding(
                "graphcheck", "graph-qos-non-serving", "error", str(node.id),
                "qos: shapes a serving admission queue, which this node "
                "does not run",
            ))
            continue
        if qos.shed_wait_ms is not None and qos.shed_wait_ms > 0:
            raw_k = node.env.get(
                "DORA_MULTISTEP_K", global_env.get("DORA_MULTISTEP_K", 8)
            )
            try:
                k = max(1, int(str(raw_k)))
            except ValueError:
                k = 8
            quantum_ms = k * _MS_PER_STEP_FLOOR
            if qos.shed_wait_ms < quantum_ms:
                out.append(Finding(
                    "graphcheck", "graph-qos-deadline-quantum", "error",
                    str(node.id),
                    f"shed_wait_ms={qos.shed_wait_ms:g} is below the fused "
                    f"decode window quantum (~{quantum_ms:g} ms at "
                    f"DORA_MULTISTEP_K={k}) — every queued request sheds "
                    "before one window completes",
                    {"shed_wait_ms": qos.shed_wait_ms,
                     "quantum_ms": quantum_ms, "k": k},
                ))
    return out


# ---------------------------------------------------------------------------
# fleet: replica identity and routability
# ---------------------------------------------------------------------------


def _node_fingerprint(node, global_env: dict) -> str:
    """Deploy-time prediction of the config fingerprint this node's
    engine will publish in its fleet digest — same fields as
    :func:`dora_tpu.fleet.config_fingerprint`, derived from descriptor
    env (node env over dataflow env over registry defaults)."""
    import os

    from dora_tpu import fleet

    def env(name, default=""):
        v = node.env.get(name, global_env.get(name, default))
        return str(v) if v is not None else default

    def env_int(name, default):
        try:
            return int(env(name, str(default)) or default)
        except ValueError:
            return default

    ckpt = env("DORA_HF_CHECKPOINT")
    model_id = os.path.basename(str(ckpt).rstrip("/")) if ckpt else "stub"
    if _env_truthy(env("DORA_INT4_DECODE", "0")):
        weight_bits = 4
    elif _env_truthy(env("DORA_INT8_DECODE", "0")):
        weight_bits = 8
    else:
        weight_bits = 16
    return fleet.config_fingerprint(
        model_id=model_id,
        window=env_int("DORA_MULTISTEP_K", 8),
        spec_k=env_int("DORA_SPEC_K", 0),
        kv_dtype="int8" if _env_truthy(env("DORA_KV_INT8", "0")) else "fp",
        weight_bits=weight_bits,
        page_size=env_int("DORA_PAGE_SIZE", 64),
    )


def _fleet(descriptor) -> list[Finding]:
    global_env = (descriptor.raw or {}).get("env") or {}
    serving = [n for n in descriptor.nodes if _is_serving(n)]
    out: list[Finding] = []

    seen: set[str] = set()
    for node in serving:
        nid = str(node.id)
        if nid in seen:
            out.append(Finding(
                "graphcheck", "graph-fleet-duplicate-replica", "error", nid,
                f"serving replica id {nid!r} declared more than once — the "
                "fleet view keys replicas by node id, so their engine "
                "digests would overwrite each other",
            ))
        seen.add(nid)

    if len(serving) < 2:
        return out

    # An upstream node that fans out to >=2 replicas of a fingerprint
    # group is positioned to route by fleet state; without one, the
    # "interchangeable" replicas can never actually trade traffic.
    by_fp: dict[str, list[str]] = {}
    for node in serving:
        by_fp.setdefault(_node_fingerprint(node, global_env), []).append(
            str(node.id)
        )
    upstreams: dict[str, set[str]] = {}  # source node -> replica ids fed
    for node in serving:
        for inp in node.inputs.values():
            m = inp.mapping
            if isinstance(m, UserMapping):
                upstreams.setdefault(str(m.source), set()).add(str(node.id))

    for fp, ids in sorted(by_fp.items()):
        if len(ids) < 2:
            continue
        group = set(ids)
        routed = any(len(fed & group) > 1 for fed in upstreams.values())
        if not routed:
            members = sorted(group)
            out.append(Finding(
                "graphcheck", "graph-fleet-unrouted", "warning",
                ", ".join(members),
                f"{len(members)} serving replicas share config fingerprint "
                f"{fp} (interchangeable placement targets) but no upstream "
                "node feeds more than one of them — nothing consumes the "
                "fleet state to steer requests (see `dora-tpu fleet` and "
                "fleet.score_placement)",
                {"fingerprint": fp, "replicas": members},
            ))
    return out
