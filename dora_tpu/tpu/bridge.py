"""Arrow ⇄ JAX device-array bridge.

Reference parity: the Arrow C-FFI / pyarrow boundary of the reference's
node APIs (SURVEY.md §2.9 "collective/comm backend" row: the TPU-native
equivalent is Arrow ⇄ DLPack into JAX device buffers).

Tensor convention on the wire: a 1-D Arrow primitive array plus metadata
parameters ``shape`` (list of ints) and ``dtype``; scalars and 1-D data
need no metadata. Host-side conversion is zero-copy (Arrow → numpy view);
the host→HBM transfer happens once per tick at the fused-subgraph ingress.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import pyarrow as pa

SHAPE_KEY = "shape"
DTYPE_KEY = "dtype"


def arrow_to_host(value: pa.Array, metadata: dict | None = None) -> np.ndarray:
    """Arrow array -> numpy (zero-copy when the type allows), reshaped per
    the ``shape`` metadata.

    String arrays (e.g. from terminal-input / keyboard) become the utf-8
    bytes of their joined entries, so text flows straight into byte-level
    tokenizing operators as a uint8 array.
    """
    if pa.types.is_string(value.type) or pa.types.is_large_string(value.type):
        text = " ".join(s for s in value.to_pylist() if s is not None)
        return np.frombuffer(text.encode(), dtype=np.uint8).copy()
    try:
        arr = value.to_numpy(zero_copy_only=True)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        arr = value.to_numpy(zero_copy_only=False)
    if metadata:
        shape = metadata.get(SHAPE_KEY)
        if shape is not None:
            arr = arr.reshape([int(s) for s in shape])
        dtype = metadata.get(DTYPE_KEY)
        if dtype is not None and str(arr.dtype) != dtype:
            arr = arr.astype(dtype)
    return arr


def arrow_to_device(value: pa.Array, metadata: dict | None = None):
    """Arrow array -> JAX device array (one host→HBM transfer)."""
    import jax.numpy as jnp

    return jnp.asarray(arrow_to_host(value, metadata))


def device_to_arrow(arr: Any) -> tuple[pa.Array, dict]:
    """JAX (or numpy) array -> (1-D Arrow array, tensor metadata).

    The device→host copy happens here — exactly once per externally
    consumed output per tick.
    """
    host = np.asarray(arr)
    metadata = {SHAPE_KEY: list(host.shape), DTYPE_KEY: str(host.dtype)}
    if host.dtype == np.dtype("bfloat16"):
        # Arrow has no bfloat16; widen on the wire, keep dtype metadata so
        # the receiver restores it.
        host = host.astype(np.float32)
    flat = np.ascontiguousarray(host).reshape(-1)
    return pa.array(flat), metadata


def is_tensor_metadata(metadata: dict | None) -> bool:
    return bool(metadata) and SHAPE_KEY in metadata
