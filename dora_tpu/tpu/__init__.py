"""The TPU execution tier — the part the reference does not have.

Operators declared with ``jax:`` sources are pure functions
``(state, inputs) -> (state, outputs)`` over JAX arrays. All jax operators
hosted in one runtime node are **fused into a single jit-compiled XLA
computation per tick**: edges between them become SSA values that never
leave device HBM (no Arrow materialization, no IPC), and operator state is
donated back to itself across ticks. Only edges crossing the node boundary
materialize to Arrow messages.

This is the TPU-first answer to the reference's operator runtime
(binaries/runtime), which hosts exactly one operator per process and moves
every edge through the daemon (SURVEY.md §2.2 dora-runtime row).
"""

from dora_tpu.tpu.api import DoraStatus, JaxOperator

__all__ = ["JaxOperator", "DoraStatus"]
