"""Fusion compiler: all jax operators of one runtime node become ONE
jit-compiled XLA computation per tick.

Graph lowering (SURVEY.md §7 step 5c): intra-node edges between jax
operators become SSA values inside the traced function — they never
materialize to Arrow, never cross a process boundary, and stay in device
HBM. Only inputs arriving from outside the node and outputs consumed
outside the node touch the Arrow data plane. Operator state is threaded
through the jit with donation, so it lives in HBM across ticks.

Tick semantics (the async-graph ↔ synchronous-XLA impedance match): timer
inputs are the tick triggers when present (the reference's vlm example
pattern — 20 ms camera timer, 100 ms model timer); otherwise every
external data input triggers. Non-trigger inputs are sampled latest-wins,
which is the reference's ``queue_size: 1`` idiom.
"""

from __future__ import annotations

from dora_tpu.analysis.lockcheck import tracked_lock

import logging
from dataclasses import dataclass, field
from typing import Any

from dora_tpu.core.config import TimerMapping, UserMapping
from dora_tpu.core.descriptor import (
    Descriptor,
    JaxSource,
    OperatorDefinition,
    ResolvedNode,
    RuntimeNode,
)
from dora_tpu.tpu.api import JaxOperator, load_jax_operator

logger = logging.getLogger(__name__)


@dataclass
class FusedGraph:
    """The static structure of one node's fused jax subgraph."""

    node_id: str
    operators: dict[str, JaxOperator]  # op id -> operator
    definitions: dict[str, OperatorDefinition]
    topo: list[str]  # op ids in dataflow order
    #: (op, input) -> (src op, src output): intra-node SSA edges
    intra_edges: dict[tuple[str, str], tuple[str, str]]
    #: event ids ("<op>/<input>") carrying data from outside the node
    external_inputs: set[str]
    #: event ids fed by daemon timers (trigger, no payload)
    timer_inputs: set[str]
    #: output ids ("<op>/<output>") consumed outside the node
    external_outputs: set[str]

    @property
    def trigger_inputs(self) -> set[str]:
        return self.timer_inputs or self.external_inputs

    # -- construction -------------------------------------------------------

    @classmethod
    def build(
        cls,
        node: ResolvedNode,
        descriptor: Descriptor | None = None,
        working_dir=None,
    ) -> "FusedGraph":
        assert isinstance(node.kind, RuntimeNode)
        jax_defs = {
            str(op.id): op
            for op in node.kind.operators
            if isinstance(op.source, JaxSource)
        }
        operators = {
            op_id: load_jax_operator(op.source.source, working_dir)
            for op_id, op in jax_defs.items()
        }

        intra: dict[tuple[str, str], tuple[str, str]] = {}
        external_inputs: set[str] = set()
        timer_inputs: set[str] = set()
        for op_id, op in jax_defs.items():
            for input_id, inp in op.inputs.items():
                if isinstance(inp.mapping, TimerMapping):
                    timer_inputs.add(f"{op_id}/{input_id}")
                    continue
                mapping: UserMapping = inp.mapping
                if str(mapping.source) == str(node.id):
                    # Sibling edge "<self>/<src_op>/<src_out>".
                    src_op, _, src_out = str(mapping.output).partition("/")
                    if src_op in jax_defs:
                        intra[(op_id, str(input_id))] = (src_op, src_out)
                        continue
                external_inputs.add(f"{op_id}/{input_id}")

        topo = _topo_sort(list(jax_defs), intra)

        # Outputs with consumers outside this fused subgraph (other nodes, or
        # python operators of the same node). Without a full descriptor we
        # conservatively export everything.
        external_outputs: set[str] = set()
        if descriptor is not None:
            for consumer in descriptor.nodes:
                for input_id, inp in consumer.inputs.items():
                    if isinstance(inp.mapping, TimerMapping):
                        continue
                    m: UserMapping = inp.mapping
                    if str(m.source) != str(node.id):
                        continue
                    out = str(m.output)  # "<op>/<output>"
                    src_op = out.partition("/")[0]
                    if src_op not in jax_defs:
                        continue
                    consumes_internally = (
                        str(consumer.id) == str(node.id)
                        and (str(input_id).partition("/")[0]) in jax_defs
                        and (
                            str(input_id).partition("/")[0],
                            str(input_id).partition("/")[2],
                        )
                        in intra
                    )
                    if not consumes_internally:
                        external_outputs.add(out)
        else:
            for op_id, op in jax_defs.items():
                external_outputs |= {f"{op_id}/{o}" for o in op.outputs}

        return cls(
            node_id=str(node.id),
            operators=operators,
            definitions=jax_defs,
            topo=topo,
            intra_edges=intra,
            external_inputs=external_inputs,
            timer_inputs=timer_inputs,
            external_outputs=external_outputs,
        )

    # -- the traced function ------------------------------------------------

    def step_fn(self, states: dict, ext_inputs: dict) -> tuple[dict, dict]:
        """The pure fused step: runs every operator in topo order with
        sibling edges as local SSA values. jit-compiled by the executor;
        unused outputs are dead-code-eliminated by XLA."""
        produced: dict[str, dict[str, Any]] = {}
        new_states: dict[str, Any] = {}
        for op_id in self.topo:
            operator = self.operators[op_id]
            definition = self.definitions[op_id]
            inputs: dict[str, Any] = {}
            for input_id in definition.inputs:
                iid = str(input_id)
                edge = self.intra_edges.get((op_id, iid))
                if edge is not None:
                    inputs[iid] = produced[edge[0]][edge[1]]
                else:
                    event_id = f"{op_id}/{iid}"
                    if event_id in ext_inputs:
                        inputs[iid] = ext_inputs[event_id]
            new_states[op_id], outputs = operator.step(states[op_id], inputs)
            produced[op_id] = outputs
        external = {
            out_id: produced[out_id.partition("/")[0]][out_id.partition("/")[2]]
            for out_id in sorted(self.external_outputs)
            if out_id.partition("/")[2] in produced.get(out_id.partition("/")[0], {})
        }
        return new_states, external


def _topo_sort(op_ids: list[str], intra: dict[tuple[str, str], tuple[str, str]]) -> list[str]:
    deps: dict[str, set[str]] = {op: set() for op in op_ids}
    for (dst, _), (src, _) in intra.items():
        deps[dst].add(src)
    order: list[str] = []
    ready = sorted(op for op, d in deps.items() if not d)
    while ready:
        op = ready.pop(0)
        order.append(op)
        for other, d in deps.items():
            if op in d:
                d.discard(op)
                if not d and other not in order and other not in ready:
                    ready.append(other)
                    ready.sort()
    if len(order) != len(op_ids):
        cyclic = sorted(set(op_ids) - set(order))
        raise ValueError(f"cycle among fused jax operators: {cyclic}")
    return order


def mesh_from_env():
    """Device mesh from ``DORA_MESH`` ("tp=4" / "dp=2,tp=2,sp=2"), or None.

    Multi-chip serving inside one runtime node (SURVEY §2.9 "pjit-sharded
    ops within a node"): the fused step jits over this mesh, operator
    states place per their sharding rules, and XLA inserts the
    collectives over ICI.
    """
    import os

    spec = os.environ.get("DORA_MESH", "").strip()
    if not spec:
        return None
    from dora_tpu.parallel.mesh import make_mesh

    # Unspecified dp absorbs the remaining devices, so "tp=4" just works
    # on any host (make_mesh resolves dp=-1).
    axes = {"dp": None, "tp": 1, "sp": 1}
    for part in spec.split(","):
        name, _, value = part.partition("=")
        name = name.strip()
        if name not in axes:
            raise ValueError(f"DORA_MESH: unknown axis {name!r} in {spec!r}")
        axes[name] = int(value)
    if axes["dp"] is None:
        axes["dp"] = -1
    return make_mesh(**axes)


def _fetch(value):
    """The one device→host transfer point of the pipelined executor —
    kept as a module hook so tests can inject tunnel latency."""
    import numpy as np

    return np.asarray(value)


def fetch_every_from_env() -> int:
    """Frames per device→host fetch (DORA_FETCH_EVERY, default 1).

    The round-4 drift analysis (KNOWN_ISSUES.md) showed serving FPS is
    hostage to fetch latency: every tick pays one device→host round
    trip, and concurrent fetches only amortize it to ~RTT/depth. With
    N > 1, tick outputs accumulate ON DEVICE (a jnp.stack ring) and one
    fetch moves N frames — per-frame fetch cost drops to ~RTT/N plus a
    few bytes of copy, decoupling steady-state FPS from the tunnel's
    latency term entirely. Outputs arrive in bursts of N (up to N-1
    frames of added latency): a serving-throughput config for
    continuous streams, not for request/response flows — hence opt-in.
    A partial group flushes after DORA_FETCH_LINGER_MS (default 100) so
    sporadic streams never stall."""
    import os

    return max(1, int(os.environ.get("DORA_FETCH_EVERY", "1")))


def pipeline_depth_from_env() -> int:
    """In-flight tick budget (DORA_PIPELINE_DEPTH). Default 4 on
    accelerators: JAX dispatch is asynchronous, so in-flight ticks
    overlap the device→host fetch with on-device compute of the next
    frames. Each fetch costs a full host round-trip even for a ready
    array (~116 ms measured on the axon-tunneled dev chip), but
    *concurrent* fetches from separate threads amortize it (~17 ms/item
    at 8-way, measured) — so the harvest fetches on a thread pool and
    the depth sets how many round-trips amortize. 0 = synchronous (the
    CPU/test default: interpret-mode ticks are host work and gain
    nothing)."""
    import os

    import jax

    value = os.environ.get("DORA_PIPELINE_DEPTH")
    if value is not None:
        return max(0, int(value))
    return 4 if jax.default_backend() in ("tpu", "gpu") else 0


class FusedExecutor:
    """Runtime driver of one fused graph: latest-wins input sampling, tick
    triggering, jit with state donation — over a device mesh when
    ``DORA_MESH`` is set (operator ``sharding`` rules place the state).

    With ``pipeline_depth`` > 0 ticks dispatch asynchronously: the jit
    call returns device futures immediately, the (states, outputs) pair
    is queued, and completed outputs are harvested in tick order — frames
    are pipelined, output order is preserved, and the serving loop never
    sits idle in a device→host fetch while the chip could be working on
    the next frame (BASELINE.md north star; the round-2 serial loop spent
    ~90 ms/frame of tunnel RTT doing exactly that)."""

    def __init__(self, graph: FusedGraph, mesh=None, pipeline_depth=None,
                 fetch_every=None):
        import jax

        self.graph = graph
        self.mesh = mesh if mesh is not None else mesh_from_env()
        #: ONE host operator (JaxOperator.host) opts the WHOLE node out of
        #: tracing: its step branches on data (data-dependent output
        #: shapes), so every sibling operator fused into this node also
        #: runs eagerly and never pipelines. To keep jit+pipelining for
        #: the rest of the graph, put host operators in their own node in
        #: the dataflow YAML — fusion is per-node by design.
        self.eager = any(op.host for op in graph.operators.values())
        #: optional zero-arg callback fired (from a fetch worker thread)
        #: whenever a pipelined tick's device→host fetch completes; the
        #: runtime points this at ``node.wake`` so its event loop parks in
        #: ``recv(None)`` instead of polling for completed ticks.
        self.on_fetch_done = None
        self.pipeline_depth = (
            pipeline_depth_from_env() if pipeline_depth is None
            else pipeline_depth
        )
        if self.eager:
            self.pipeline_depth = 0
        self.states = {}
        for op_id, op in graph.operators.items():
            if self.mesh is not None and op.sharding is not None:
                from dora_tpu.parallel.mesh import shard_params

                self.states[op_id] = shard_params(
                    op.init_state, self.mesh, op.sharding
                )
            else:
                self.states[op_id] = jax.device_put(op.init_state)
        #: latest device value per external data input (latest-wins sampling)
        self.latest: dict[str, Any] = {}
        #: in-flight tick emissions as (future, n_ticks) pairs, oldest
        #: first; each future resolves to a LIST of tick-output dicts
        #: (fetch groups). Guarded by _stage_lock (harvest/backpressure
        #: run on the event thread, submission on the linger timer's).
        self._in_flight: list[tuple[Any, int]] = []
        self._fetch_pool = None
        #: device-side output ring: tick outputs staged for the next
        #: grouped fetch (fetch_every > 1 — see fetch_every_from_env)
        self.fetch_every = (
            fetch_every_from_env() if fetch_every is None else fetch_every
        )
        if self.eager:
            self.fetch_every = 1
        self._staged: list[dict] = []
        self._linger_s = (
            float(__import__("os").environ.get("DORA_FETCH_LINGER_MS", "100"))
            / 1000.0
        )
        self._linger_timer = None
        # The linger timer flushes from its own thread; staging and
        # group submission must not race it.
        import threading

        self._stage_lock = tracked_lock("tpu.fuse.stage")
        if self.pipeline_depth > 0:
            from concurrent.futures import ThreadPoolExecutor

            # One worker per in-flight tick: every dispatched tick's
            # device→host fetch starts immediately on its own thread, so
            # the round-trips run concurrently instead of serializing on
            # the event loop (the fetch RPC cost is per-call, not
            # per-byte, on a tunneled chip). depth+1 workers: the
            # backpressure check runs after dispatch, so depth+1 ticks
            # can briefly be in flight and the newest one still needs a
            # free worker.
            self._fetch_pool = ThreadPoolExecutor(
                max_workers=self.pipeline_depth + 1,
                thread_name_prefix=f"dora-fetch-{graph.node_id}",
            )
        self._compiled_once = False
        # Donate state so it is updated in place in HBM; on CPU donation is
        # unimplemented and only produces warnings, so skip it there.
        donate = (0,) if jax.default_backend() in ("tpu", "gpu") else ()
        step_fn = graph.step_fn
        if self.mesh is not None:
            step_fn = self._meshed(step_fn)
        self._jit = (
            step_fn if self.eager else jax.jit(step_fn, donate_argnums=donate)
        )
        self._required = graph.external_inputs - graph.timer_inputs

    def _meshed(self, step_fn):
        """Run the step inside the mesh context so with_sharding_constraint
        in operator code resolves axis names."""
        import jax

        def run(states, latest):
            with self.mesh:
                return step_fn(states, latest)

        return run

    def observe(self, event_id: str, value, metadata: dict | None) -> None:
        """Record an input's latest value without ticking. Non-trigger
        inputs only update the sample the next tick will read (latest
        wins); backlog bounding itself is the queue layer's job
        (daemon drop-oldest + the node's bounded event buffer)."""
        from dora_tpu.tpu.bridge import arrow_to_device

        if event_id in self._required and value is not None:
            self.latest[event_id] = arrow_to_device(value, metadata)

    def tick_if_ready(self):
        """Run one tick when every required input has produced."""
        if not all(k in self.latest for k in self._required):
            return None  # warm-up: not every input has produced yet
        return self.tick()

    def on_event(self, event_id: str, value, metadata: dict | None):
        """Feed one arriving event; returns {output_id: (arrow, metadata)}
        when the event triggered a tick, else None."""
        self.observe(event_id, value, metadata)
        if event_id not in self.graph.trigger_inputs:
            return None
        return self.tick_if_ready()

    def tick(self):
        import logging
        import time

        from dora_tpu.tpu.bridge import device_to_arrow

        t0 = time.perf_counter()
        self.states, outputs = self._jit(self.states, dict(self.latest))
        if not self._compiled_once:
            self._compiled_once = True
            logging.getLogger(__name__).info(
                "fused step first tick (incl jit compile): %.1fs",
                time.perf_counter() - t0,
            )
        return {
            out_id: device_to_arrow(value) for out_id, value in outputs.items()
        }

    # -- pipelined dispatch (pipeline_depth > 0) ----------------------------

    def on_event_async(self, event_id: str, value, metadata: dict | None) -> None:
        """Pipelined on_event: dispatch the tick without fetching. The new
        state chains on-device behind the in-flight computation; results
        are picked up by :meth:`harvest`. With ``fetch_every`` > 1 the
        outputs stage in a device-side ring and N ticks share ONE
        device→host fetch."""
        self.observe(event_id, value, metadata)
        if event_id not in self.graph.trigger_inputs:
            return
        if not all(k in self.latest for k in self._required):
            return
        self.states, outputs = self._jit(self.states, dict(self.latest))
        self._compiled_once = True
        with self._stage_lock:
            self._staged.append(outputs)
            if len(self._staged) >= self.fetch_every:
                self._submit_group_locked()
            elif self._linger_timer is None:
                # Partial group: guarantee a flush even if no further
                # tick arrives (sporadic streams must not stall N-1
                # frames).
                import threading

                self._linger_timer = threading.Timer(
                    self._linger_s, self._linger_flush
                )
                self._linger_timer.daemon = True
                self._linger_timer.start()
        # Backpressure: bound in-flight TICKS (and their HBM) by waiting
        # out the oldest fetch. The bound is pipeline_depth ticks of
        # unfetched output plus the group currently staging (a resolved
        # future's buffers are already on host). The waited result is
        # not dropped — it stays queued for the next harvest in order.
        limit = self.pipeline_depth + self.fetch_every - 1
        while self._unfetched_ticks() > limit:
            with self._stage_lock:
                oldest = next(
                    (f for f, _ in self._in_flight if not f.done()), None
                )
            if oldest is None:
                break
            oldest.result()  # wait outside the lock

    def _unfetched_ticks(self) -> int:
        with self._stage_lock:
            pending = sum(
                n for f, n in self._in_flight if not f.done()
            )
            return pending + len(self._staged)

    def _submit_group(self) -> None:
        with self._stage_lock:
            self._submit_group_locked()

    def _submit_group_locked(self) -> None:
        """Move the staged ring into one fetch job. The per-output stack
        happens here (an async device op); the worker thread then pays a
        single device→host round trip for all staged ticks."""
        if not self._staged:
            return
        timer, self._linger_timer = self._linger_timer, None
        if timer is not None:
            timer.cancel()
        staged, self._staged = self._staged, []
        if len(staged) == 1:
            payload = staged[0]
        else:
            import jax.numpy as jnp

            payload = {
                key: jnp.stack([tick[key] for tick in staged])
                for key in staged[0]
            }
        # The tick count travels as a submit argument AND in the
        # in-flight pair — never attached to the future post-submit
        # (a worker could observe the future before the attribute).
        future = self._fetch_pool.submit(self._emit, payload, len(staged))
        self._in_flight.append((future, len(staged)))
        if self.on_fetch_done is not None:
            future.add_done_callback(lambda _f: self.on_fetch_done())

    def _linger_flush(self) -> None:
        with self._stage_lock:
            self._linger_timer = None
            self._submit_group_locked()

    def _emit(self, outputs: dict, n_ticks: int = 1) -> list[dict]:
        from dora_tpu.tpu.bridge import device_to_arrow

        # The device→host transfer goes through the module-level _fetch
        # hook (tests inject tunnel latency there); the Arrow conversion
        # below then runs on host arrays at zero device cost.
        host = {out_id: _fetch(v) for out_id, v in outputs.items()}
        if n_ticks == 1:
            return [
                {out_id: device_to_arrow(v) for out_id, v in host.items()}
            ]
        # ONE fetch per output id moved all n_ticks frames; the split
        # back into per-tick frames is host-side numpy slicing.
        return [
            {out_id: device_to_arrow(v[i]) for out_id, v in host.items()}
            for i in range(n_ticks)
        ]

    @property
    def has_in_flight(self) -> bool:
        with self._stage_lock:
            return bool(self._in_flight) or bool(self._staged)

    def harvest(self, block: bool = False) -> list[dict]:
        """Completed tick outputs in dispatch order. Non-blocking by
        default: drains the queue head while its fetch has finished.
        ``block`` waits for everything (stream-end flush), including a
        partially filled fetch group."""
        if block:
            self._submit_group()
        done: list[dict] = []
        while True:
            with self._stage_lock:
                if not self._in_flight:
                    break
                future, _ = self._in_flight[0]
                if not (block or future.done()):
                    break
                self._in_flight.pop(0)
            done.extend(future.result())  # may wait: outside the lock
        return done

    def close(self) -> None:
        """Release the fetch pool. Call after the stream-end flush
        (``harvest(block=True)``); any still-queued fetches are drained
        so their device buffers are not abandoned mid-copy."""
        with self._stage_lock:
            timer, self._linger_timer = self._linger_timer, None
            in_flight, self._in_flight = self._in_flight, []
        if timer is not None:
            timer.cancel()
        if self._fetch_pool is not None:
            for future, _ in in_flight:
                try:
                    future.result()
                except Exception:
                    pass
            self._fetch_pool.shutdown(wait=True)
            self._fetch_pool = None
