"""Operator APIs: the JAX (TPU-tier) operator ABI and the Python operator
status codes.

Reference parity: apis/rust/operator (DoraOperator::on_event + DoraStatus,
src/lib.rs:41-69) and the Python ``Operator.on_event(event, send_output)``
convention (binaries/runtime/src/operator/python.rs:93-107). The JAX
operator is this framework's TPU-native addition: a pure traced function
instead of a callback, so adjacent operators fuse into one XLA program.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable


class DoraStatus(enum.IntEnum):
    """Return value of a Python operator's on_event
    (reference: DoraStatus{Continue,Stop,StopAll})."""

    CONTINUE = 0
    STOP = 1
    STOP_ALL = 2


@dataclass
class JaxOperator:
    """A TPU-tier operator: a pure function over JAX pytrees.

    ``step(state, inputs) -> (new_state, outputs)`` where ``inputs`` /
    ``outputs`` are dicts keyed by the operator's declared input/output
    names and values are JAX arrays (or pytrees). The function must be
    traceable: no side effects, no data-dependent Python control flow.

    The runtime jits the fused graph with the state donated, so ``state``
    lives in device HBM across ticks; weights belong in ``init_state``.

    ``input_shapes`` optionally pins {input: (shape, dtype)} so the fused
    computation can warm-compile before the first tick; unset inputs
    compile on first arrival.

    ``sharding`` optionally names a mesh-axis layout for the operator's
    state (applied via jax.sharding when the runtime runs on a mesh; see
    dora_tpu.parallel).

    ``host=True`` marks a host-orchestrated operator: its step runs
    eagerly outside the fused jit (it may inspect values, branch on
    data, and call its own jits internally). Needed for models whose
    output shapes are data-dependent — e.g. VITS TTS, where the frame
    count comes from predicted durations. NOTE the blast radius: one
    host operator switches its ENTIRE node to eager execution — every
    sibling operator fused into the same node loses jit fusion and
    pipelining too (fusion is per-node). Put host operators in their own
    node in the dataflow YAML to keep the fused path for the rest;
    everything else about the contract (state threading, Arrow I/O) is
    identical.
    """

    step: Callable[[Any, dict[str, Any]], tuple[Any, dict[str, Any]]]
    init_state: Any = ()
    input_shapes: dict[str, tuple] = field(default_factory=dict)
    sharding: Any = None
    host: bool = False


def load_jax_operator(source: str, working_dir=None) -> JaxOperator:
    """Resolve a ``jax:`` operator source — ``module.path:factory`` or
    ``file.py:factory`` (factory defaults to ``make_operator``)."""
    import importlib
    import importlib.util
    from pathlib import Path

    mod_path, sep, factory_name = source.partition(":")
    factory_name = factory_name if sep else "make_operator"
    if mod_path.endswith(".py"):
        path = Path(mod_path)
        if working_dir is not None and not path.is_absolute():
            path = Path(working_dir) / path
        spec = importlib.util.spec_from_file_location(
            f"dora_tpu_op_{path.stem}", path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_path)
    factory = getattr(module, factory_name)
    operator = factory()
    if not isinstance(operator, JaxOperator):
        raise TypeError(
            f"{source}: factory returned {type(operator).__name__}, "
            f"expected JaxOperator"
        )
    return operator
