"""Dataflow metrics plane: counters + latency histograms, snapshot/merge.

The daemon keeps one :class:`DataflowMetrics` per dataflow and feeds it
from the routing hot path (``daemon/core.py``), the per-node queues
(``daemon/queues.py``), and the wire fast path (``message/fastroute.py``):

* per-(sender, output) routed message/byte counters,
* per-(node, input) drop-oldest counters and live queue depth,
* fastroute hit/fallback counters (wire-splice vs reflective route),
* send→deliver latency histograms computed from the HLC timestamps every
  ``Timestamped`` frame already carries (physical ns, same machine, so
  the difference is a real wall-clock latency including queue wait).

Everything is plain dicts and ints so the hot-path cost is one dict get
and one add; ``snapshot()`` produces a JSON-able dict the control plane
ships daemon → coordinator → CLI, and :func:`merge_snapshots` aggregates
across machines (histogram bucket counts add; percentiles recompute).
"""

from __future__ import annotations

from typing import Any

#: Histogram buckets are powers of two in microseconds: bucket ``i``
#: holds values in [2^(i-1), 2^i) µs; bucket 0 holds < 1 µs. 27 buckets
#: span 1 µs .. ~67 s, which covers everything from a shmem splice to a
#: wedged queue.
HISTOGRAM_BUCKETS = 27


class Histogram:
    """Fixed-bucket log2 latency histogram (microseconds)."""

    __slots__ = ("counts", "count", "sum_us")

    def __init__(self):
        self.counts = [0] * HISTOGRAM_BUCKETS
        self.count = 0
        self.sum_us = 0.0

    def observe(self, value_us: float) -> None:
        if value_us < 0:
            value_us = 0.0  # HLC logical ticks can run ahead of wall time
        bucket = min(int(value_us).bit_length(), HISTOGRAM_BUCKETS - 1)
        self.counts[bucket] += 1
        self.count += 1
        self.sum_us += value_us

    def snapshot(self) -> dict:
        out = {
            "count": self.count,
            "sum_us": round(self.sum_us, 1),
            "counts": list(self.counts),
        }
        for p in (50, 90, 99):
            out[f"p{p}_us"] = percentile_from_counts(self.counts, p)
        return out


def bucket_upper_us(i: int) -> float:
    """Upper bound of bucket ``i`` in µs (reported percentile value)."""
    return float(1 << i)


def percentile_from_counts(counts: list[int], p: float) -> float | None:
    """The p-th percentile latency from histogram bucket counts — the
    upper bound of the bucket the rank falls in (pessimistic by at most
    one octave, which is the histogram's stated resolution)."""
    total = sum(counts)
    if total == 0:
        return None
    rank = total * p / 100.0
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            return bucket_upper_us(i)
    return bucket_upper_us(len(counts) - 1)


class DataflowMetrics:
    """Hot-path counters for one dataflow (daemon side)."""

    __slots__ = (
        "links",
        "drops",
        "latency",
        "fastroute_hits",
        "fastroute_fallbacks",
        "respawns",
        "replayed_inputs",
    )

    def __init__(self):
        #: (sender, output) -> [msgs, bytes]
        self.links: dict[tuple[str, str], list] = {}
        #: (node, input) -> dropped-oldest count
        self.drops: dict[tuple[str, str], int] = {}
        #: (node, input) -> send→deliver Histogram
        self.latency: dict[tuple[str, str], Histogram] = {}
        self.fastroute_hits = 0
        self.fastroute_fallbacks = 0
        #: node -> times the daemon respawned it (restart policy)
        self.respawns: dict[str, int] = {}
        #: node -> un-acked inputs requeued to it across respawns
        self.replayed_inputs: dict[str, int] = {}

    # -- hot-path feeders ---------------------------------------------------

    def count_link(self, sender: str, output: str, nbytes: int) -> None:
        entry = self.links.get((sender, output))
        if entry is None:
            entry = self.links[(sender, output)] = [0, 0]
        entry[0] += 1
        entry[1] += nbytes

    def count_drop(self, node: str, input_id: str) -> None:
        key = (node, input_id)
        self.drops[key] = self.drops.get(key, 0) + 1

    def observe_latency(self, node: str, input_id: str, us: float) -> None:
        hist = self.latency.get((node, input_id))
        if hist is None:
            hist = self.latency[(node, input_id)] = Histogram()
        hist.observe(us)

    def count_respawn(self, node: str) -> None:
        self.respawns[node] = self.respawns.get(node, 0) + 1

    def count_replayed(self, node: str, n: int) -> None:
        if n > 0:
            self.replayed_inputs[node] = self.replayed_inputs.get(node, 0) + n

    # -- export -------------------------------------------------------------

    def snapshot(self, queue_depths: dict[str, int] | None = None) -> dict:
        hits, falls = self.fastroute_hits, self.fastroute_fallbacks
        routed = hits + falls
        out = {
            "links": {
                f"{s}/{o}": {"msgs": v[0], "bytes": v[1]}
                for (s, o), v in self.links.items()
            },
            "drops": {f"{n}/{i}": c for (n, i), c in self.drops.items()},
            "queue_depth": dict(queue_depths or {}),
            "fastroute": {
                "hits": hits,
                "fallbacks": falls,
                "hit_ratio": round(hits / routed, 4) if routed else None,
            },
            "latency_us": {
                f"{n}/{i}": h.snapshot() for (n, i), h in self.latency.items()
            },
        }
        if self.respawns or self.replayed_inputs:
            out["recovery"] = {
                "respawns": dict(self.respawns),
                "replayed_inputs": dict(self.replayed_inputs),
            }
        return out


class ServingMetrics:
    """Node-side serving counters (the LLM server's view of its engine).

    Lives in the serving node's process, shipped to its daemon as a
    fire-and-forget ``n2d.ReportServing`` snapshot (same plane as
    ReportTrace) and surfaced through the coordinator's metrics fan-out
    next to the dataflow counters — ``dora-tpu metrics [--watch]`` shows
    slots, pages, backlog, decode tokens/s and the TTFT histogram.

    Counters are cumulative (the CLI derives rates from consecutive
    snapshots in watch mode); gauges are set just before ``snapshot``.
    """

    __slots__ = (
        "ttft", "dispatch_gap", "fetch_latency", "backlog_wait",
        "grant_pages", "decode_tokens", "prefill_chunks",
        "requests", "rejected", "slots_active", "slots_total",
        "free_pages", "total_pages", "used_pages", "peak_used_pages",
        "largest_contig_free", "backlog_depth", "host_dispatches",
        "host_fetches", "compiles", "engine",
        "checkpoints", "last_checkpoint_unix", "restored_streams",
        "migrated_out", "migrated_in",
        "spec_drafted", "spec_accepted", "spec_accept_len",
        "shed", "preempted", "resumed", "qos_depth",
        "autotune_k", "retunes",
        "prefix_hits", "prefix_misses", "prefix_hit_tokens",
        "prefix_cached_pages", "prefix_shared_pages",
        "prefix_cow_copies", "prefix_evictions",
        "device_compute_ns", "host_dispatch_ns", "device_fetch_ns",
        "dispatched_flops", "useful_flops",
        "hbm_used_bytes", "hbm_limit_bytes", "hbm_peak_bytes",
        "mfu", "device_busy_fraction",
        "kv_dtype", "kv_pool_bytes", "kv_quant_err",
        "lora_resident", "lora_max_resident", "lora_resident_bytes",
        "lora_loads", "lora_evictions", "adapter_streams",
        "adapter_stalls",
    )

    def __init__(self, engine: str = "dense"):
        self.ttft = Histogram()
        #: host time between consecutive engine dispatches while decode
        #: is active — the per-step host overhead the multi-step window
        #: amortizes (each gap now buys up to K tokens, not 1).
        #: Split from fetch_latency on purpose: the gap is pure
        #: host/scheduler time, the fetch is the blocking device->host
        #: transfer — tunnel drift moves the fetch track, a host-side
        #: regression moves the gap track (KNOWN_ISSUES round 4).
        self.dispatch_gap = Histogram()
        #: blocking device->host fetch durations (the sync points:
        #: chunk greedy reads, the [B, K+1] window matrix), observed by
        #: the engine via its ``serving_metrics`` hook
        self.fetch_latency = Histogram()
        #: time requests spent parked in the admission backlog before
        #: their slot/page grant (AdmissionQueue on_admit)
        self.backlog_wait = Histogram()
        #: per-admission page-grant size -> count (exact — grant sizes
        #: are small ints; fed by the paged engine at submit)
        self.grant_pages: dict[int, int] = {}
        self.decode_tokens = 0
        self.prefill_chunks = 0
        self.requests = 0
        self.rejected = 0
        self.slots_active = 0
        self.slots_total = 0
        self.free_pages = 0
        self.total_pages = 0
        self.used_pages = 0
        #: high-water mark of pages in use (allocator-tracked)
        self.peak_used_pages = 0
        #: longest run of physically-adjacent free pages — the
        #: fragmentation gauge (how large a contiguous grant could be)
        self.largest_contig_free = 0
        self.backlog_depth = 0
        #: device program launches / device->host fetches (engine
        #: counters, set just before snapshot like the gauges)
        self.host_dispatches = 0
        self.host_fetches = 0
        #: XLA compiles observed process-wide (telemetry.compile_count,
        #: runtime listener) — a nonzero delta at steady state is a
        #: recompile regression, now visible outside pytest
        self.compiles = 0
        self.engine = engine
        #: serving-state checkpoints written (DORA_CHECKPOINT_EVERY /
        #: SIGTERM), and the wall time of the last one — snapshot()
        #: derives checkpoint_age_s from it so the staleness of the
        #: recovery point is visible in `dora-tpu metrics`
        self.checkpoints = 0
        self.last_checkpoint_unix = 0.0
        #: streams resumed mid-generation from a checkpoint on respawn
        self.restored_streams = 0
        #: live streams drained to / admitted from a migration handoff
        self.migrated_out = 0
        self.migrated_in = 0
        #: prompt-lookup speculation (paged engine, DORA_SPEC_K):
        #: drafts proposed vs drafts the verification pass accepted —
        #: the acceptance rate is the lever behind tokens_per_dispatch
        self.spec_drafted = 0
        self.spec_accepted = 0
        #: tokens emitted per verification pass (accepted + the bonus
        #: token, 1..spec_k+1) as a log2 histogram — the accepted-length
        #: distribution, reusing the octave buckets (values are token
        #: counts here, not µs)
        self.spec_accept_len = Histogram()
        #: traffic shaping (QoS): requests shed on overload (bounded
        #: class depth or queue-wait deadline -> retriable "overloaded"
        #: chunk), streams evicted by page preemption, and preempted
        #: streams re-admitted (recompute-on-resume)
        self.shed = 0
        self.preempted = 0
        self.resumed = 0
        #: per-class admission-queue depth gauge (set before snapshot)
        self.qos_depth: dict[str, int] = {}
        #: live fused-window K (gauge) and autotuner retunes applied —
        #: 0 autotune_k means "engine exposes no window" (dense)
        self.autotune_k = 0
        self.retunes = 0
        #: shared-prefix KV cache (paged engine, DORA_PREFIX_CACHE):
        #: admission lookups that mapped cached pages (hits) vs cold
        #: prefills (misses), tokens served from cache, pages the radix
        #: cache holds / currently mapped shared into live streams
        #: (gauges), copy-on-write boundary pages re-materialized, and
        #: cached pages evicted back to the pool under admission
        #: pressure
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0
        self.prefix_cached_pages = 0
        self.prefix_shared_pages = 0
        self.prefix_cow_copies = 0
        self.prefix_evictions = 0
        #: device utilization plane (dora_tpu.profiling, DORA_DEVICE_MONITOR):
        #: cumulative window/chunk wall time attributed by the engine to
        #: host dispatch vs device compute vs the device->host fetch (ns
        #: counters fed on the step path), plus the FLOPs ledger — work
        #: dispatched to the device vs work behind EMITTED tokens (the
        #: two differ by speculation's rejected tails)
        self.device_compute_ns = 0
        self.host_dispatch_ns = 0
        self.device_fetch_ns = 0
        self.dispatched_flops = 0
        self.useful_flops = 0
        #: HBM gauges sampled off device.memory_stats() just before
        #: snapshot; None when the backend exposes no allocator stats
        #: (CPU) — the CLI renders dashes, prom exports 0
        self.hbm_used_bytes: int | None = None
        self.hbm_limit_bytes: int | None = None
        self.hbm_peak_bytes: int | None = None
        #: model FLOPs utilization over the last report interval
        #: (useful_flops delta / wall / peak; None without a known peak)
        #: and the fraction of wall time the device was computing
        self.mfu: float | None = None
        self.device_busy_fraction: float | None = None
        #: quantized-serving plane: KV pool number format ("fp" or
        #: "int8"), total pool HBM bytes (values + scale planes), and
        #: the per-page quantization-error gauge (mean relative
        #: quantization step over sampled allocated pages —
        #: PagedBatchEngine.kv_quant_error; None on fp pools)
        self.kv_dtype = "fp"
        self.kv_pool_bytes: int | None = None
        self.kv_quant_err: float | None = None
        #: multi-tenant LoRA plane (paged engine, DORA_LORA_DIR):
        #: resident adapters vs pool capacity, their HBM bytes, and the
        #: cumulative load/eviction churn (a high eviction rate against
        #: a small resident pool is the swap-thrash signature — see
        #: KNOWN_ISSUES round 19). ``adapter_streams`` is a dict gauge:
        #: live streams pinned per resident adapter (tenant name keys,
        #: the qos_depth idiom).
        self.lora_resident = 0
        self.lora_max_resident = 0
        self.lora_resident_bytes = 0
        self.lora_loads = 0
        self.lora_evictions = 0
        self.adapter_streams: dict[str, int] = {}
        #: backlog entries shed (or admitted late) because the N+1-th
        #: tenant's adapter could not evict — every resident adapter
        #: pinned by a live stream. Split from plain queue overload so
        #: the two are distinguishable (KNOWN_ISSUES round 19); the
        #: wire chunk carries the same attribution as
        #: ``stall_reason="adapter_residency"``.
        self.adapter_stalls = 0

    def snapshot(self) -> dict:
        import time

        return {
            "engine": self.engine,
            "requests": self.requests,
            "rejected": self.rejected,
            "decode_tokens": self.decode_tokens,
            "prefill_chunks": self.prefill_chunks,
            "slots_active": self.slots_active,
            "slots_total": self.slots_total,
            "free_pages": self.free_pages,
            "total_pages": self.total_pages,
            "used_pages": self.used_pages,
            "peak_used_pages": self.peak_used_pages,
            "largest_contig_free": self.largest_contig_free,
            "backlog_depth": self.backlog_depth,
            "host_dispatches": self.host_dispatches,
            "host_fetches": self.host_fetches,
            "compiles": self.compiles,
            "tokens_per_dispatch": (
                round(self.decode_tokens / self.host_dispatches, 2)
                if self.host_dispatches
                else None
            ),
            "grant_pages": {
                str(k): v for k, v in sorted(self.grant_pages.items())
            },
            "ttft_us": self.ttft.snapshot(),
            "dispatch_gap_us": self.dispatch_gap.snapshot(),
            "fetch_us": self.fetch_latency.snapshot(),
            "backlog_wait_us": self.backlog_wait.snapshot(),
            "checkpoints": self.checkpoints,
            "checkpoint_age_s": (
                round(time.time() - self.last_checkpoint_unix, 3)
                if self.last_checkpoint_unix
                else None
            ),
            "restored_streams": self.restored_streams,
            "migrated_out": self.migrated_out,
            "migrated_in": self.migrated_in,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_acceptance": (
                round(self.spec_accepted / self.spec_drafted, 4)
                if self.spec_drafted
                else None
            ),
            "spec_accept_len": self.spec_accept_len.snapshot(),
            "shed": self.shed,
            "preempted": self.preempted,
            "resumed": self.resumed,
            "qos_depth": dict(self.qos_depth),
            "autotune_k": self.autotune_k,
            "retunes": self.retunes,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": (
                round(
                    self.prefix_hits
                    / (self.prefix_hits + self.prefix_misses),
                    4,
                )
                if (self.prefix_hits + self.prefix_misses)
                else None
            ),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_cached_pages": self.prefix_cached_pages,
            "prefix_shared_pages": self.prefix_shared_pages,
            "prefix_cow_copies": self.prefix_cow_copies,
            "prefix_evictions": self.prefix_evictions,
            "device_compute_ns": self.device_compute_ns,
            "host_dispatch_ns": self.host_dispatch_ns,
            "device_fetch_ns": self.device_fetch_ns,
            "dispatched_flops": self.dispatched_flops,
            "useful_flops": self.useful_flops,
            "hbm_used_bytes": self.hbm_used_bytes,
            "hbm_limit_bytes": self.hbm_limit_bytes,
            "hbm_peak_bytes": self.hbm_peak_bytes,
            "mfu": self.mfu,
            "device_busy_fraction": self.device_busy_fraction,
            "kv_dtype": self.kv_dtype,
            "kv_pool_bytes": self.kv_pool_bytes,
            "kv_quant_err": self.kv_quant_err,
            "lora_resident": self.lora_resident,
            "lora_max_resident": self.lora_max_resident,
            "lora_resident_bytes": self.lora_resident_bytes,
            "lora_loads": self.lora_loads,
            "lora_evictions": self.lora_evictions,
            "adapter_streams": dict(self.adapter_streams),
            "adapter_stalls": self.adapter_stalls,
        }


def merge_snapshots(snapshots: list[dict]) -> dict:
    """Aggregate per-daemon snapshots into one cluster view (coordinator).

    Counters add; queue depths union (each input queue lives on exactly
    one machine); histogram bucket counts add and percentiles recompute
    from the merged buckets."""
    links: dict[str, dict] = {}
    drops: dict[str, int] = {}
    depth: dict[str, int] = {}
    hits = falls = 0
    lat_counts: dict[str, list[int]] = {}
    lat_sum: dict[str, float] = {}
    serving: dict[str, dict] = {}
    respawns: dict[str, int] = {}
    replayed: dict[str, int] = {}
    slo: dict[str, dict] = {}
    logs: dict[str, dict] = {}
    trace_drops: dict[str, int] = {}
    alert_statuses: list[dict] = []
    for snap in snapshots:
        if not snap:
            continue
        # Each serving node lives on exactly one machine: union. Same
        # for the SLO burn block — objectives attach to a node, and the
        # node's daemon evaluates them against its own history ring —
        # and the per-node log counters. Alert engines run per daemon;
        # their statuses merge instance-wise (dora_tpu.alerts).
        serving.update(snap.get("serving", {}))
        slo.update(snap.get("slo", {}))
        logs.update(snap.get("logs", {}))
        for node, c in (snap.get("trace") or {}).get("drops", {}).items():
            trace_drops[node] = trace_drops.get(node, 0) + c
        if snap.get("alerts"):
            alert_statuses.append(snap["alerts"])
        recovery = snap.get("recovery") or {}
        for key, c in recovery.get("respawns", {}).items():
            respawns[key] = respawns.get(key, 0) + c
        for key, c in recovery.get("replayed_inputs", {}).items():
            replayed[key] = replayed.get(key, 0) + c
        for key, v in snap.get("links", {}).items():
            entry = links.setdefault(key, {"msgs": 0, "bytes": 0})
            entry["msgs"] += v.get("msgs", 0)
            entry["bytes"] += v.get("bytes", 0)
        for key, c in snap.get("drops", {}).items():
            drops[key] = drops.get(key, 0) + c
        depth.update(snap.get("queue_depth", {}))
        fr = snap.get("fastroute", {})
        hits += fr.get("hits", 0)
        falls += fr.get("fallbacks", 0)
        for key, h in snap.get("latency_us", {}).items():
            counts = lat_counts.setdefault(key, [0] * HISTOGRAM_BUCKETS)
            for i, c in enumerate(h.get("counts", [])[:HISTOGRAM_BUCKETS]):
                counts[i] += c
            lat_sum[key] = lat_sum.get(key, 0.0) + h.get("sum_us", 0.0)
    routed = hits + falls
    latency = {}
    for key, counts in lat_counts.items():
        entry = {
            "count": sum(counts),
            "sum_us": round(lat_sum[key], 1),
            "counts": counts,
        }
        for p in (50, 90, 99):
            entry[f"p{p}_us"] = percentile_from_counts(counts, p)
        latency[key] = entry
    out = {
        "links": links,
        "drops": drops,
        "queue_depth": depth,
        "fastroute": {
            "hits": hits,
            "fallbacks": falls,
            "hit_ratio": round(hits / routed, 4) if routed else None,
        },
        "latency_us": latency,
    }
    if serving:
        out["serving"] = serving
    if slo:
        out["slo"] = slo
    if logs:
        out["logs"] = logs
    if trace_drops:
        out["trace"] = {"drops": trace_drops}
    if alert_statuses:
        from dora_tpu.alerts import merge_alert_status

        out["alerts"] = merge_alert_status(alert_statuses)
    if respawns or replayed:
        out["recovery"] = {
            "respawns": respawns,
            "replayed_inputs": replayed,
        }
    return out
