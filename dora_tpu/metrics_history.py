"""Retained metrics time series: per-dataflow history rings + merge.

The snapshot plane (``dora_tpu.metrics``) answers "what are the counters
now"; this module answers "what happened over the last hour". Each daemon
samples its merged dataflow snapshot (``Daemon.metrics_snapshot``) on a
fixed cadence (``DORA_METRICS_HISTORY_S``, default 5 s) into a bounded
:class:`MetricsHistoryRing` — fixed capacity, oldest-overwritten, wrap
losses counted, the allocation discipline of ``telemetry.FlightRecorder``.

Samples are **delta encoded**: cumulative counters and histogram bucket
counts are differenced against the previous sample, so a ring slot holds
only what changed in that interval and rate/percentile math downstream is
a division, not a diff of two snapshots the caller happens to hold.
Counter resets (a respawned node re-reporting from zero) are detected
here — a negative delta stores the new cumulative value as the delta and
bumps a per-key reset counter — so consumers never see negative rates.

``merge_history_snapshots`` aligns per-machine rings onto the cluster
timeline using the same HLC-offset trick as the trace merge
(``tracing.merge_trace_snapshots``): each ring snapshot carries a
``(wall_ns, hlc_ns)`` pair captured together; ``hlc_ns - wall_ns`` is the
machine's clock offset and shifting every sample's wall stamp by it puts
all machines on one comparable axis. It also derives the server-side
series the CLI/autotuner consume: per-key rates, windowed histogram
percentiles, and SLO burn.

SLO targets (descriptor ``slo:`` block, ``core.descriptor.SloPolicy``)
are evaluated per sample against the interval's deltas; a violation is
flagged in the slot and surfaced as burn-rate gauges — the fraction of
the error budget (every sample in the window being in-target) consumed
over 1 m / 10 m windows.
"""

from __future__ import annotations

import os
from typing import Any

from dora_tpu.metrics import HISTOGRAM_BUCKETS, percentile_from_counts

#: Default sampling cadence (seconds); 0 disables sampling entirely.
DEFAULT_INTERVAL_S = 5.0
#: Default ring capacity: 720 samples = 1 h at the default 5 s cadence.
DEFAULT_CAPACITY = 720
#: Derived rates/percentiles are computed over a trailing window of at
#: most this many seconds of aligned samples (matches the 1 m burn window).
RATE_WINDOW_S = 60.0

#: SLO objective names (descriptor keys, burn-gauge labels).
SLO_OBJECTIVES = ("ttft_p99_ms", "tokens_per_s_min", "queue_depth_max")


def history_interval_s() -> float:
    """``DORA_METRICS_HISTORY_S`` (seconds between samples; <=0 disables)."""
    raw = os.environ.get("DORA_METRICS_HISTORY_S", "")
    if raw == "":
        return DEFAULT_INTERVAL_S
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_INTERVAL_S


def history_capacity() -> int:
    """``DORA_METRICS_HISTORY_LEN`` (ring slots; default 720 ≈ 1 h @ 5 s)."""
    try:
        return max(2, int(os.environ.get("DORA_METRICS_HISTORY_LEN", "")
                          or DEFAULT_CAPACITY))
    except ValueError:
        return DEFAULT_CAPACITY


def flatten_snapshot(snap: dict) -> tuple[dict, dict, dict]:
    """Flatten a ``metrics_snapshot`` dict into flat series keys.

    Returns ``(counters, gauges, hists)``:

    * counters — cumulative monotonic values (``link:a/out:msgs``,
      ``drop:b/in``, ``fastroute:hits``, ``respawn:a``,
      ``srv:llm:decode_tokens`` …),
    * gauges — instantaneous values (``queue:b/in``,
      ``srv:llm:used_pages`` …),
    * hists — cumulative histogram bucket-count lists (``lat:b/in``,
      ``srv:llm:ttft_us``).
    """
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    hists: dict[str, list[int]] = {}
    for key, v in snap.get("links", {}).items():
        counters[f"link:{key}:msgs"] = v.get("msgs", 0)
        counters[f"link:{key}:bytes"] = v.get("bytes", 0)
    for key, c in snap.get("drops", {}).items():
        counters[f"drop:{key}"] = c
    fr = snap.get("fastroute", {})
    counters["fastroute:hits"] = fr.get("hits", 0)
    counters["fastroute:fallbacks"] = fr.get("fallbacks", 0)
    recovery = snap.get("recovery") or {}
    for node, c in recovery.get("respawns", {}).items():
        counters[f"respawn:{node}"] = c
    for node, c in recovery.get("replayed_inputs", {}).items():
        counters[f"replay:{node}"] = c
    for key, d in snap.get("queue_depth", {}).items():
        gauges[f"queue:{key}"] = d
    for key, h in snap.get("latency_us", {}).items():
        hists[f"lat:{key}"] = list(h.get("counts", []))
    # Structured log severity: per-node stderr/stdout ERROR and WARN
    # line counts (daemon-side parse; alerting's log-error-rate rule).
    for node, c in snap.get("logs", {}).items():
        counters[f"logerr:{node}"] = c.get("errors", 0)
        counters[f"logwarn:{node}"] = c.get("warns", 0)
    # Trace-plane truncation: node events the daemon-side buffer cap
    # trimmed (the trace-truncated alert watches this rate).
    for node, c in (snap.get("trace") or {}).get("drops", {}).items():
        counters[f"tracedrop:{node}"] = c
    for node, s in snap.get("serving", {}).items():
        for name in ("decode_tokens", "requests", "rejected",
                     "prefill_chunks", "host_dispatches", "compiles",
                     "spec_drafted", "spec_accepted",
                     "shed", "preempted", "resumed", "retunes",
                     "prefix_hits", "prefix_misses", "prefix_hit_tokens",
                     "prefix_cow_copies", "prefix_evictions",
                     "device_compute_ns", "host_dispatch_ns",
                     "device_fetch_ns", "dispatched_flops",
                     "useful_flops", "lora_loads", "lora_evictions",
                     "adapter_stalls"):
            counters[f"srv:{node}:{name}"] = s.get(name, 0)
        for name in ("slots_active", "slots_total", "used_pages",
                     "total_pages", "free_pages", "backlog_depth",
                     "autotune_k", "prefix_cached_pages",
                     "prefix_shared_pages", "lora_resident",
                     "lora_max_resident", "lora_resident_bytes"):
            gauges[f"srv:{node}:{name}"] = s.get(name, 0)
        # Device utilization gauges are None when unknown (CPU backend,
        # monitor off, pre-round-16 snapshot): recorded only when real,
        # so history series never fabricate a zero-MFU sample.
        # checkpoint_age_s rides along: derived (non-monotonic) but a
        # gauge like the rest, None until the first checkpoint lands —
        # the checkpoint-stale alert reads it from here.
        for name in ("mfu", "device_busy_fraction", "hbm_used_bytes",
                     "hbm_limit_bytes", "hbm_peak_bytes",
                     "kv_pool_bytes", "kv_quant_err", "checkpoint_age_s"):
            if s.get(name) is not None:
                gauges[f"srv:{node}:{name}"] = s[name]
        # kv_dtype is a string gauge; series store its 0/1 projection
        # (same encoding as the dora_serving_kv_int8 prom family).
        if s.get("kv_dtype") is not None:
            gauges[f"srv:{node}:kv_int8"] = (
                1 if s["kv_dtype"] == "int8" else 0
            )
        for cls, d in (s.get("qos_depth") or {}).items():
            gauges[f"srv:{node}:qos_depth:{cls}"] = d
        for name, n in (s.get("adapter_streams") or {}).items():
            gauges[f"srv:{node}:adapter_streams:{name}"] = n
        ttft = s.get("ttft_us") or {}
        hists[f"srv:{node}:ttft_us"] = list(ttft.get("counts", []))
    # Fleet plane: the per-replica digest-derived gauge block
    # (daemon metrics_snapshot["fleet"], dora_tpu.fleet.fleet_gauges).
    # The `fleet-digest-stale` default alert rule watches digest_age_s.
    for node, f in snap.get("fleet", {}).items():
        for name in ("digest_age_s", "free_streams", "used_pages",
                     "total_pages", "occupancy", "prefix_pages"):
            if f.get(name) is not None:
                gauges[f"fleet:{node}:{name}"] = f[name]
    return counters, gauges, hists


def burn_window_complete(n_samples: int, window_s: float,
                         interval_s: float) -> bool:
    """Does ``n_samples`` retained samples cover a full ``window_s``
    burn window at ``interval_s`` cadence? Burn gauges computed over a
    PARTIAL window are noisy (KNOWN_ISSUES round 9: a freshly started
    dataflow reports burn over a 3-sample prefix) — consumers that act
    on burn (the llm_server K autotuner) and the
    ``dora_slo_burn_window_complete`` prom gauge gate on this."""
    if interval_s <= 0:
        return False
    return n_samples >= max(1, round(window_s / interval_s))


class MetricsHistoryRing:
    """Bounded per-dataflow time series of delta-encoded samples.

    Slots are preallocated and overwritten in place on wrap (wrap losses
    counted in ``dropped``), mirroring ``FlightRecorder``. ``sample()``
    is called from the daemon's sampler task; everything else reads.
    """

    # slot layout (parallel to FlightRecorder's positional slots)
    WALL, HLC, COUNTERS, GAUGES, HIST, SLO = range(6)

    __slots__ = (
        "capacity", "interval_s", "slo_targets", "_slots", "_idx",
        "dropped", "resets", "_last_counters", "_last_hists",
        "_last_wall_ns", "violation_total",
    )

    def __init__(
        self,
        capacity: int | None = None,
        interval_s: float | None = None,
        slo_targets: dict[str, dict] | None = None,
    ):
        self.capacity = capacity if capacity is not None else history_capacity()
        self.interval_s = (
            interval_s if interval_s is not None else history_interval_s()
        )
        #: node id -> {objective: target} (descriptor ``slo:`` blocks)
        self.slo_targets = dict(slo_targets or {})
        self._slots: list[list] = [
            [0, 0, None, None, None, None] for _ in range(self.capacity)
        ]
        self._idx = 0
        self.dropped = 0
        #: series key -> counter-reset count (respawn re-reports, …)
        self.resets: dict[str, int] = {}
        self._last_counters: dict[str, float] = {}
        self._last_hists: dict[str, list[int]] = {}
        self._last_wall_ns = 0
        #: (node, objective) -> total violating samples since spawn
        self.violation_total: dict[tuple[str, str], int] = {}

    def __len__(self) -> int:
        return min(self._idx, self.capacity)

    # -- write --------------------------------------------------------------

    def sample(
        self, snap: dict, wall_ns: int, hlc_ns: int
    ) -> list[tuple[str, str, float, float]]:
        """Delta-encode one snapshot into the ring.

        Returns newly-detected SLO violations as
        ``(node, objective, observed, target)`` tuples — the caller
        records them as flight-recorder instants."""
        counters, gauges, hists = flatten_snapshot(snap)
        dt_s = (
            (wall_ns - self._last_wall_ns) / 1e9
            if self._last_wall_ns
            else self.interval_s
        )
        c_delta: dict[str, float] = {}
        for key, cur in counters.items():
            d = cur - self._last_counters.get(key, 0)
            if d < 0:  # counter reset: treat the new cumulative as the delta
                self.resets[key] = self.resets.get(key, 0) + 1
                d = cur
            if d:
                c_delta[key] = d
        h_delta: dict[str, list[int]] = {}
        for key, cur_counts in hists.items():
            prev = self._last_hists.get(key)
            if prev is None or len(prev) != len(cur_counts):
                d = list(cur_counts)
            else:
                d = [c - p for c, p in zip(cur_counts, prev)]
                if any(x < 0 for x in d):
                    self.resets[key] = self.resets.get(key, 0) + 1
                    d = list(cur_counts)
            if any(d):
                h_delta[key] = d
        slo_flags, events = self._evaluate_slo(c_delta, gauges, h_delta, dt_s)

        if self._idx >= self.capacity:
            self.dropped += 1
        slot = self._slots[self._idx % self.capacity]
        slot[self.WALL] = wall_ns
        slot[self.HLC] = hlc_ns
        slot[self.COUNTERS] = c_delta
        slot[self.GAUGES] = gauges
        slot[self.HIST] = h_delta
        slot[self.SLO] = slo_flags or None
        self._idx += 1
        self._last_counters = counters
        self._last_hists = hists
        self._last_wall_ns = wall_ns
        return events

    def _evaluate_slo(
        self,
        c_delta: dict[str, float],
        gauges: dict[str, float],
        h_delta: dict[str, list[int]],
        dt_s: float,
    ) -> tuple[dict, list[tuple[str, str, float, float]]]:
        """Check this interval's deltas against the targets.

        Returns ``({node: {objective: observed}}, [(node, objective,
        observed, target), ...])`` for the violating objectives only."""
        flags: dict[str, dict[str, float]] = {}
        events: list[tuple[str, str, float, float]] = []

        def _hit(node: str, objective: str, observed: float, target: float):
            flags.setdefault(node, {})[objective] = observed
            key = (node, objective)
            self.violation_total[key] = self.violation_total.get(key, 0) + 1
            events.append((node, objective, observed, target))

        for node, targets in self.slo_targets.items():
            target = targets.get("ttft_p99_ms")
            if target is not None:
                counts = h_delta.get(f"srv:{node}:ttft_us")
                if counts:
                    p99 = percentile_from_counts(counts, 99)
                    if p99 is not None and p99 > target * 1000.0:
                        _hit(node, "ttft_p99_ms", round(p99 / 1000.0, 3),
                             target)
            target = targets.get("tokens_per_s_min")
            if target is not None and dt_s > 0:
                toks = c_delta.get(f"srv:{node}:decode_tokens", 0)
                active = gauges.get(f"srv:{node}:slots_active", 0)
                # Only a floor while the engine is actually decoding —
                # an idle server is not "missing" its throughput target.
                if toks or active:
                    rate = toks / dt_s
                    if rate < target:
                        _hit(node, "tokens_per_s_min", round(rate, 2), target)
            target = targets.get("queue_depth_max")
            if target is not None:
                prefix = f"queue:{node}/"
                depth = max(
                    (v for k, v in gauges.items() if k.startswith(prefix)),
                    default=None,
                )
                backlog = gauges.get(f"srv:{node}:backlog_depth")
                if backlog is not None:
                    depth = max(depth or 0, backlog)
                if depth is not None and depth > target:
                    _hit(node, "queue_depth_max", depth, target)
        return flags, events

    # -- read ---------------------------------------------------------------

    def samples(self) -> list[list]:
        """Filled slots, oldest first (slot lists, not copies)."""
        start = max(0, self._idx - self.capacity)
        return [self._slots[i % self.capacity] for i in range(start, self._idx)]

    def slo_status(self) -> dict:
        """Burn-rate gauges per node: fraction of the error budget
        consumed over the trailing 1 m / 10 m windows (1.0 = every sample
        in the window violated at least one objective)."""
        if not self.slo_targets:
            return {}
        samples = self.samples()
        interval = self.interval_s or DEFAULT_INTERVAL_S
        out: dict[str, dict] = {}
        for node, targets in self.slo_targets.items():
            entry: dict[str, Any] = {"targets": dict(targets)}
            for label, window_s in (("burn_1m", 60.0), ("burn_10m", 600.0)):
                n = max(1, round(window_s / interval))
                window = samples[-n:]
                # Partial windows still report burn (over the prefix)
                # but flag incompleteness so consumers — the autotuner,
                # alerting off dora_slo_burn_rate — can ignore the
                # noisy early gauges (KNOWN_ISSUES round 9).
                entry[f"{label}_complete"] = burn_window_complete(
                    len(window), window_s, interval
                )
                if not window:
                    entry[label] = 0.0
                    continue
                bad = sum(
                    1 for s in window
                    if s[self.SLO] and node in s[self.SLO]
                )
                entry[label] = round(bad / len(window), 4)
            entry["violations"] = sum(
                c for (n_, _), c in self.violation_total.items() if n_ == node
            )
            last = next(
                (s[self.SLO][node] for s in reversed(samples)
                 if s[self.SLO] and node in s[self.SLO]),
                None,
            )
            if last:
                entry["last"] = dict(last)
            out[node] = entry
        return out

    def snapshot(self) -> dict:
        """JSON-able ring export (one daemon's view; the coordinator adds
        the machine id and the ``(wall_ns, hlc_ns)`` alignment pair is
        captured by the daemon at export time)."""
        return {
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "dropped": self.dropped,
            "resets": dict(self.resets),
            "samples": [
                {
                    "wall_ns": s[self.WALL],
                    "hlc_ns": s[self.HLC],
                    "counters": s[self.COUNTERS] or {},
                    "gauges": s[self.GAUGES] or {},
                    "hist": s[self.HIST] or {},
                    **({"slo": s[self.SLO]} if s[self.SLO] else {}),
                }
                for s in self.samples()
            ],
            "slo": self.slo_status(),
        }


# ---------------------------------------------------------------------------
# cluster merge (coordinator side)
# ---------------------------------------------------------------------------


def merge_history_snapshots(snapshots: list[dict]) -> dict:
    """Merge per-daemon ring snapshots onto one cluster timeline.

    Clock alignment is the trace merge's: each snapshot carries a
    ``(wall_ns, hlc_ns)`` pair captured together at export; the
    difference is that machine's offset from the cluster HLC timeline
    and every sample's wall stamp is shifted by it (``t_ns``). Samples
    are tagged with their machine and sorted; derived series (rates,
    windowed percentiles, SLO burn) are computed over the aligned tail.
    """
    samples: list[dict] = []
    resets: dict[str, int] = {}
    dropped = 0
    machines: list[str] = []
    slo: dict[str, dict] = {}
    interval_s = None
    for snap in snapshots:
        if not snap or not snap.get("samples") and not snap.get("slo"):
            if snap:
                interval_s = interval_s or snap.get("interval_s")
            continue
        machine = str(snap.get("machine_id", ""))
        if machine not in machines:
            machines.append(machine)
        offset = int(snap.get("hlc_ns", 0)) - int(snap.get("wall_ns", 0))
        if interval_s is None:
            interval_s = snap.get("interval_s")
        dropped += snap.get("dropped", 0)
        for key, c in snap.get("resets", {}).items():
            resets[key] = resets.get(key, 0) + c
        # Each node lives on exactly one machine: SLO status unions.
        slo.update(snap.get("slo", {}))
        for s in snap.get("samples", []):
            samples.append({
                "t_ns": int(s.get("wall_ns", 0)) + offset,
                "machine": machine,
                "counters": s.get("counters", {}),
                "gauges": s.get("gauges", {}),
                "hist": s.get("hist", {}),
                **({"slo": s["slo"]} if s.get("slo") else {}),
            })
    samples.sort(key=lambda s: s["t_ns"])
    out = {
        "interval_s": interval_s or DEFAULT_INTERVAL_S,
        "machines": machines,
        "samples": samples,
        "resets": resets,
        "dropped": dropped,
        "rates": derive_rates(samples),
        "percentiles": derive_percentiles(samples),
        "util": derive_util(samples),
    }
    if slo:
        out["slo"] = slo
    return out


_UTIL_GAUGES = ("mfu", "device_busy_fraction", "hbm_used_bytes",
                "hbm_limit_bytes", "hbm_peak_bytes",
                "kv_int8", "kv_pool_bytes", "kv_quant_err")


def derive_util(samples: list[dict]) -> dict:
    """Latest device-utilization gauges per serving node — the explicit
    UTIL panel of ``dora-tpu top --json``. ``{node: {mfu: …, …}}``;
    nodes (or whole histories) recorded before round 16 simply don't
    appear — consumers render dashes, never zeros."""
    util: dict[str, dict] = {}
    for s in reversed(samples):
        for key, val in s.get("gauges", {}).items():
            if not key.startswith("srv:"):
                continue
            _, node, name = key.split(":", 2)
            if name in _UTIL_GAUGES:
                util.setdefault(node, {}).setdefault(name, val)
    return util


def _window(samples: list[dict], window_s: float = RATE_WINDOW_S) -> list[dict]:
    if not samples:
        return []
    cutoff = samples[-1]["t_ns"] - int(window_s * 1e9)
    return [s for s in samples if s["t_ns"] >= cutoff]


def _window_span_s(window: list[dict], interval_s: float) -> float:
    """Wall seconds the window covers. Each sample represents one
    interval of deltas, so a single sample still spans ``interval_s``."""
    if not window:
        return 0.0
    span = (window[-1]["t_ns"] - window[0]["t_ns"]) / 1e9
    return span + interval_s if span >= 0 else interval_s


def derive_rates(
    samples: list[dict], window_s: float = RATE_WINDOW_S
) -> dict:
    """Per-second rates over the trailing window, plus the headline
    derived series (total msgs/s, per-node tok/s, respawns/min)."""
    window = _window(samples, window_s)
    if not window:
        return {"per_key": {}, "msgs_per_s": 0.0, "tokens_per_s": {},
                "respawns_per_min": 0.0, "window_s": 0.0}
    # All machines share one cadence; infer it from the densest machine.
    by_machine: dict[str, int] = {}
    for s in window:
        by_machine[s["machine"]] = by_machine.get(s["machine"], 0) + 1
    n_per_machine = max(by_machine.values())
    span = (window[-1]["t_ns"] - window[0]["t_ns"]) / 1e9
    interval = span / (n_per_machine - 1) if n_per_machine > 1 else span or 1.0
    span_s = span + interval if span > 0 else interval
    totals: dict[str, float] = {}
    for s in window:
        for key, d in s["counters"].items():
            totals[key] = totals.get(key, 0) + d
    per_key = {k: round(v / span_s, 3) for k, v in totals.items()}
    msgs = sum(
        v for k, v in totals.items()
        if k.startswith("link:") and k.endswith(":msgs")
    )
    tokens = {
        k[len("srv:"):-len(":decode_tokens")]: round(v / span_s, 2)
        for k, v in totals.items()
        if k.startswith("srv:") and k.endswith(":decode_tokens")
    }
    respawns = sum(v for k, v in totals.items() if k.startswith("respawn:"))
    return {
        "per_key": per_key,
        "msgs_per_s": round(msgs / span_s, 2),
        "tokens_per_s": tokens,
        "respawns_per_min": round(respawns / span_s * 60.0, 3),
        "window_s": round(span_s, 3),
    }


def derive_percentiles(
    samples: list[dict], window_s: float = RATE_WINDOW_S
) -> dict:
    """Windowed percentiles from histogram deltas: what the p50/p99 *was
    over the last minute*, not since dataflow start."""
    window = _window(samples, window_s)
    sums: dict[str, list[int]] = {}
    for s in window:
        for key, d in s["hist"].items():
            counts = sums.setdefault(key, [0] * HISTOGRAM_BUCKETS)
            for i, c in enumerate(d[:HISTOGRAM_BUCKETS]):
                counts[i] += c
    out = {}
    for key, counts in sums.items():
        total = sum(counts)
        if not total:
            continue
        out[key] = {
            "count": total,
            "p50_us": percentile_from_counts(counts, 50),
            "p99_us": percentile_from_counts(counts, 99),
        }
    return out


# ---------------------------------------------------------------------------
# series extraction (sparkline feeds for `top` / `--watch`)
# ---------------------------------------------------------------------------


def counter_series(
    merged: dict, key: str, points: int = 30
) -> list[float]:
    """Trailing per-second rates of one counter key, one value per
    sample interval (cluster-summed per time bucket), oldest first."""
    samples = merged.get("samples", [])
    interval = merged.get("interval_s") or DEFAULT_INTERVAL_S
    if not samples or interval <= 0:
        return []
    # Bucket cluster samples onto the shared cadence so two machines'
    # same-tick samples add instead of interleaving as zigzag.
    buckets: dict[int, float] = {}
    for s in samples:
        b = int(s["t_ns"] / (interval * 1e9))
        buckets[b] = buckets.get(b, 0.0) + s["counters"].get(key, 0)
    ordered = [buckets[b] / interval for b in sorted(buckets)]
    return ordered[-points:]


def gauge_series(merged: dict, key: str, points: int = 30) -> list[float]:
    """Trailing values of one gauge key (cluster max per time bucket —
    gauges live on one machine, max is union), oldest first."""
    samples = merged.get("samples", [])
    interval = merged.get("interval_s") or DEFAULT_INTERVAL_S
    if not samples or interval <= 0:
        return []
    buckets: dict[int, float] = {}
    for s in samples:
        if key not in s["gauges"]:
            continue
        b = int(s["t_ns"] / (interval * 1e9))
        buckets[b] = max(buckets.get(b, 0.0), s["gauges"][key])
    ordered = [buckets[b] for b in sorted(buckets)]
    return ordered[-points:]
