"""ROS2 interoperability.

Reference parity: libraries/extensions/ros2-bridge (+msg-gen, +python) —
compilation-free ROS2 interop: message definitions (.msg/.srv/.action)
are parsed at runtime into typed schemas, converted to/from Arrow, and
bridged over DDS. Here:

  * ``msg_parser`` — the IDL parser + schema model (mirrors msg-gen's
    parser, which the reference unit-tests; so do we);
  * ``arrow_convert`` — schema-driven dict ⇄ Arrow struct conversion
    (mirrors ros2-bridge/python's typed serialize/deserialize);
  * ``bridge`` — the transport; requires ``rclpy`` (a ROS2 install) and
    degrades to a clear error without it, like the reference's
    feature-gated builds.
"""

from dora_tpu.ros2.msg_parser import (
    ActionSpec,
    Field,
    MessageSpec,
    ServiceSpec,
    TypeRef,
    find_interface,
    parse_action,
    parse_message,
    parse_service,
)

__all__ = [
    "ActionSpec",
    "Field",
    "MessageSpec",
    "ServiceSpec",
    "TypeRef",
    "find_interface",
    "parse_action",
    "parse_message",
    "parse_service",
]
