"""Real-socket DDS transport for the ROS2 bridge — no ROS2 install.

``activate()`` installs the same minimal rclpy surface the loopback
provides (init/shutdown/create_node, SingleThreadedExecutor,
publishers/subscriptions, ``<pkg>.msg`` classes synthesized from parsed
specs) — but publishers and subscriptions ride a real RTPS participant
(ros2/rtps.py): SPDP/SEDP discovery over UDP multicast + well-known
unicast ports, CDR-LE payload frames to matched readers. This restores
the reference bridge's key property — DDS interop without sourcing a
ROS2 distribution (Cargo.toml links rustdds directly; here the RTPS
stack is ~500 lines of Python) — with the caveat that no second DDS
vendor exists in this image to interop-test against (PARITY.md).

Selection (ros2 bridge tests / Ros2Context callers)::

    from dora_tpu.ros2.rtps_transport import activate
    activate()          # installs rtps-backed rclpy unless real one exists
    ctx = Ros2Context() # bridge code, unchanged

Delivery semantics mirror rclpy: subscription callbacks run on the
executor's spin thread (frames arrive on the participant's rx threads
and are posted to the executor queue).
"""

from __future__ import annotations

import sys
import types

from dora_tpu.ros2 import find_interface
from dora_tpu.ros2 import loopback as _lb
from dora_tpu.ros2.cdr import decode as cdr_decode
from dora_tpu.ros2.cdr import encode as cdr_encode

_PARTICIPANT = None


def _participant():
    global _PARTICIPANT
    if _PARTICIPANT is None:
        from dora_tpu.ros2.rtps import RtpsParticipant

        _PARTICIPANT = RtpsParticipant()
    return _PARTICIPANT


def _resolve(name: str):
    return find_interface(name)


def _msg_to_dict(msg, spec) -> dict:
    out = {}
    for f in spec.fields:
        value = getattr(msg, f.name, None)
        if f.type.is_primitive:
            out[f.name] = value
        elif f.type.is_array:
            nested = _resolve(f.type.base)
            out[f.name] = [
                v if isinstance(v, dict) else _msg_to_dict(v, nested)
                for v in (value or [])
            ]
        else:
            nested = _resolve(f.type.base)
            if value is None:
                out[f.name] = {}
            elif isinstance(value, dict):
                out[f.name] = value
            else:
                out[f.name] = _msg_to_dict(value, nested)
    return out


class _Publisher:
    def __init__(self, topic: str, msg_cls):
        spec = msg_cls._spec
        self._spec = spec
        self._writer = _participant().create_writer(topic, spec.full_name)

    def publish(self, msg) -> None:
        values = _msg_to_dict(msg, self._spec)
        self._writer.publish_cdr(cdr_encode(self._spec, values, _resolve))


class _Node(_lb._Node):
    """Loopback node surface with RTPS-backed endpoints."""

    def create_publisher(self, msg_cls, topic: str, qos_depth: int = 10):
        return _Publisher(topic, msg_cls)

    def create_subscription(self, msg_cls, topic: str, callback, qos_depth=10):
        spec = msg_cls._spec
        executor = self._executor

        def on_frame(raw: bytes) -> None:
            try:
                values = cdr_decode(spec, raw, _resolve)
            except Exception:
                return
            msg = msg_cls()
            for key, val in values.items():
                setattr(msg, key, val)
            executor._post(lambda cb=callback, m=msg: cb(m))

        reader = _participant().create_reader(
            topic, spec.full_name, callback=on_frame
        )
        self._subscriptions.append((topic, reader))
        return reader

    def destroy_node(self) -> None:
        self._subscriptions.clear()


def _build_rclpy_module():
    rclpy = types.ModuleType("rclpy")
    rclpy.__dora_tpu_loopback__ = True  # bridge gates accept either fake
    rclpy.__dora_tpu_rtps__ = True

    def init(args=None):
        _participant()

    def shutdown():
        global _PARTICIPANT
        if _PARTICIPANT is not None:
            _PARTICIPANT.close()
            _PARTICIPANT = None

    def create_node(name, namespace="/"):
        return _Node(name, namespace)

    executors = types.ModuleType("rclpy.executors")
    executors.SingleThreadedExecutor = _lb._Executor

    rclpy.init = init
    rclpy.shutdown = shutdown
    rclpy.create_node = create_node
    rclpy.executors = executors
    return rclpy, executors


def activate() -> None:
    """Install the RTPS-backed rclpy (and on-demand ``<pkg>.msg``
    modules). A real rclpy, or an already-installed fake, wins."""
    try:
        import rclpy  # noqa: F401

        return
    except ImportError:
        pass
    rclpy, executors = _build_rclpy_module()
    sys.modules["rclpy"] = rclpy
    sys.modules["rclpy.executors"] = executors
    sys.meta_path.append(_lb._MsgFinder())
