"""Schema-driven ROS2 message ⇄ Arrow conversion.

Reference parity: libraries/extensions/ros2-bridge/python/src/typed/
{serialize,deserialize} — ROS2 messages become Arrow struct arrays keyed
by field name, recursively for nested message types.
"""

from __future__ import annotations

from typing import Any, Callable

import pyarrow as pa

from dora_tpu.ros2.msg_parser import MessageSpec, TypeRef

_PRIMITIVE_ARROW = {
    "bool": pa.bool_(),
    "byte": pa.uint8(),
    "char": pa.uint8(),
    "int8": pa.int8(),
    "uint8": pa.uint8(),
    "int16": pa.int16(),
    "uint16": pa.uint16(),
    "int32": pa.int32(),
    "uint32": pa.uint32(),
    "int64": pa.int64(),
    "uint64": pa.uint64(),
    "float32": pa.float32(),
    "float64": pa.float64(),
    "string": pa.string(),
    "wstring": pa.string(),
}


def arrow_type(
    spec: MessageSpec, resolve: Callable[[str], MessageSpec] | None = None
) -> pa.StructType:
    """The Arrow struct type for one message spec; nested message types are
    resolved through ``resolve`` (e.g. ros2.find_interface)."""

    def field_type(t: TypeRef) -> pa.DataType:
        if t.is_primitive:
            base = _PRIMITIVE_ARROW[t.base]
        else:
            if resolve is None:
                raise ValueError(f"cannot resolve nested type {t.base!r}")
            base = arrow_type(resolve(t.base), resolve)
        if t.is_array:
            if t.array_size is not None:
                return pa.list_(base, t.array_size)
            return pa.list_(base)
        return base

    return pa.struct(
        [pa.field(f.name, field_type(f.type)) for f in spec.fields]
    )


def to_arrow(
    messages: list[dict],
    spec: MessageSpec,
    resolve: Callable[[str], MessageSpec] | None = None,
) -> pa.Array:
    """List of message dicts -> Arrow struct array (defaults filled in)."""
    typed = arrow_type(spec, resolve)
    filled = [_fill_defaults(m, spec) for m in messages]
    return pa.array(filled, type=typed)


def from_arrow(array: pa.Array) -> list[dict]:
    """Arrow struct array -> list of message dicts."""
    return array.to_pylist()


def _fill_defaults(message: dict, spec: MessageSpec) -> dict:
    out = {}
    for f in spec.fields:
        if f.name in message:
            out[f.name] = message[f.name]
        elif f.default is not None:
            out[f.name] = f.default
        else:
            out[f.name] = _zero(f.type)
    return out


def _zero(t: TypeRef) -> Any:
    if t.is_array:
        if t.array_size is not None:
            return [_zero_scalar(t)] * t.array_size
        return []
    return _zero_scalar(t)


def _zero_scalar(t: TypeRef) -> Any:
    if t.base == "bool":
        return False
    if t.base in ("string", "wstring"):
        return ""
    if t.base.startswith("float"):
        return 0.0
    if t.is_primitive:
        return 0
    return {}
