"""DDS-less loopback transport for the ROS2 bridge.

Environments without a ROS2 installation cannot import rclpy, which left
``ros2/bridge.py`` (the runtime half of the bridge — reference:
libraries/extensions/ros2-bridge linking rustdds) unexecuted outside
ROS2 machines. This module fakes the minimal rclpy surface the bridge
uses — ``init``/``shutdown``/``create_node``, the single-threaded
executor, publishers/subscriptions over an in-process topic bus, and
message classes synthesized from the parsed ``.msg`` specs — so the
*same* bridge code paths (publish conversion, subscription event-merge
queue, executor threading) run end to end without DDS.

Usage (tests do this when rclpy is absent)::

    from dora_tpu.ros2.loopback import activate
    activate()                      # installs fake rclpy + msg modules
    ctx = Ros2Context()             # bridge code, unchanged

Delivery semantics mirror rclpy: subscription callbacks run on the
executor's spin thread, not the publisher's.
"""

from __future__ import annotations

import queue
import sys
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
import types
from collections import defaultdict

from dora_tpu.ros2 import find_interface

#: topic -> list of (msg_cls, callback, executor)
_BUS: dict[str, list] = defaultdict(list)
_BUS_LOCK = tracked_lock("ros2.loopback.bus")


_PRIMITIVE_DEFAULTS = {
    "bool": False,
    "byte": 0,
    "char": 0,
    "float32": 0.0,
    "float64": 0.0,
    "string": "",
    "wstring": "",
}


def _default_for(type_ref) -> object:
    if type_ref.is_array:
        return []
    if type_ref.is_primitive:
        return _PRIMITIVE_DEFAULTS.get(type_ref.base, 0)
    return None  # nested message: left to the caller


def _make_msg_class(package: str, name: str):
    spec = find_interface(f"{package}/{name}")
    fields = spec.fields

    def __init__(self):
        for f in fields:
            setattr(self, f.name, f.default if f.default is not None
                    else _default_for(f.type))

    return type(name, (), {"__init__": __init__, "_spec": spec})


class _MsgModule(types.ModuleType):
    """``<pkg>.msg`` module that synthesizes message classes on demand
    from the parsed interface specs."""

    def __init__(self, package: str):
        super().__init__(f"{package}.msg")
        self._package = package
        self._classes: dict[str, type] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._classes:
            self._classes[name] = _make_msg_class(self._package, name)
        return self._classes[name]


class _Executor:
    """SingleThreadedExecutor lookalike: spin() drains a callback queue
    until shutdown — callbacks run on the spin thread, as in rclpy."""

    def __init__(self):
        self._work: queue.Queue = queue.Queue()
        self._shutdown = threading.Event()
        self._nodes: list = []

    def add_node(self, node) -> None:
        self._nodes.append(node)
        node._executor = self

    def spin(self) -> None:
        while not self._shutdown.is_set():
            try:
                fn = self._work.get(timeout=0.05)
            except queue.Empty:
                continue
            fn()

    def shutdown(self) -> None:
        self._shutdown.set()

    def _post(self, fn) -> None:
        self._work.put(fn)


class _Publisher:
    def __init__(self, topic: str):
        self._topic = topic

    def publish(self, msg) -> None:
        with _BUS_LOCK:
            targets = list(_BUS[self._topic])
        for msg_cls, callback, executor in targets:
            # Copy field-by-field: subscribers must not alias the
            # publisher's message object (DDS serializes; we mimic).
            copy = msg_cls()
            for key, value in vars(msg).items():
                setattr(copy, key, value)
            executor._post(lambda cb=callback, m=copy: cb(m))


class _Node:
    def __init__(self, name: str, namespace: str = "/"):
        self._name = name
        self._namespace = namespace
        self._executor: _Executor | None = None
        self._subscriptions: list[tuple[str, object]] = []

    def create_publisher(self, msg_cls, topic: str, qos_depth: int = 10):
        return _Publisher(topic)

    def create_subscription(self, msg_cls, topic: str, callback, qos_depth=10):
        entry = (msg_cls, callback, self._executor)
        with _BUS_LOCK:
            _BUS[topic].append(entry)
        self._subscriptions.append((topic, entry))
        return entry

    def destroy_node(self) -> None:
        with _BUS_LOCK:
            for topic, entry in self._subscriptions:
                if entry in _BUS[topic]:
                    _BUS[topic].remove(entry)


def _build_rclpy_module():
    rclpy = types.ModuleType("rclpy")
    rclpy.__dora_tpu_loopback__ = True

    def init(args=None):
        pass

    def shutdown():
        with _BUS_LOCK:
            _BUS.clear()

    def create_node(name, namespace="/"):
        return _Node(name, namespace)

    executors = types.ModuleType("rclpy.executors")
    executors.SingleThreadedExecutor = _Executor

    rclpy.init = init
    rclpy.shutdown = shutdown
    rclpy.create_node = create_node
    rclpy.executors = executors
    return rclpy, executors


def activate() -> None:
    """Install the loopback rclpy (and on-demand ``<pkg>.msg`` modules)
    into sys.modules. No-op when a real rclpy is importable — the real
    DDS transport always wins."""
    try:
        import rclpy  # noqa: F401

        # Idempotent: a real rclpy always wins, and a loopback that is
        # already installed stays — rebuilding would strand existing
        # imports on a stale module object and stack duplicate
        # _MsgFinder entries on sys.meta_path.
        return
    except ImportError:
        pass
    rclpy, executors = _build_rclpy_module()
    sys.modules["rclpy"] = rclpy
    sys.modules["rclpy.executors"] = executors
    sys.meta_path.append(_MsgFinder())


class _MsgFinder:
    """Meta-path finder for ``<pkg>.msg`` of packages visible under
    AMENT_PREFIX_PATH (the bridge does ``__import__("std_msgs.msg")``)."""

    @staticmethod
    def _ament_has(package: str) -> bool:
        import os
        from pathlib import Path

        for prefix in filter(
            None, os.environ.get("AMENT_PREFIX_PATH", "").split(os.pathsep)
        ):
            if (Path(prefix) / "share" / package / "msg").is_dir():
                return True
        return False

    def find_spec(self, fullname: str, path=None, target=None):
        from importlib.machinery import ModuleSpec

        package, _, tail = fullname.partition(".")
        if tail not in ("", "msg") or not self._ament_has(package):
            return None
        return ModuleSpec(
            fullname, _MsgLoader(), is_package=(tail == "")
        )


class _MsgLoader:
    def create_module(self, spec):
        package, _, tail = spec.name.partition(".")
        if tail == "msg":
            return _MsgModule(package)
        module = types.ModuleType(spec.name)
        module.__path__ = []  # namespace package holding .msg
        return module

    def exec_module(self, module) -> None:
        pass
