"""CDR (OMG Common Data Representation, XCDR1 little-endian) for ROS2
message specs.

Drives encode/decode from the runtime-parsed ``MessageSpec`` objects
(ros2/msg_parser.py) — the same specs that drive Arrow conversion — so
any ``.msg`` the parser understands can ride the RTPS wire without
generated code. Reference parity: the reference bridge serializes
through rustdds' CDR (libraries/extensions/ros2-bridge); this is the
dependency-free Python counterpart.

Encapsulation: the RTPS serialized payload prepends a 4-byte header
(0x00 0x01 = CDR_LE, two option bytes); alignment is relative to the
byte after that header, which is how both are implemented here (offset
0 = first payload byte).
"""

from __future__ import annotations

import struct
from typing import Callable

_PRIM = {
    "bool": ("?", 1),
    "byte": ("B", 1),
    "char": ("B", 1),
    "int8": ("b", 1),
    "uint8": ("B", 1),
    "int16": ("<h", 2),
    "uint16": ("<H", 2),
    "int32": ("<i", 4),
    "uint32": ("<I", 4),
    "int64": ("<q", 8),
    "uint64": ("<Q", 8),
    "float32": ("<f", 4),
    "float64": ("<d", 8),
}

CDR_LE = b"\x00\x01\x00\x00"
PL_CDR_LE = b"\x00\x03\x00\x00"


class _Writer:
    def __init__(self):
        self.buf = bytearray()

    def align(self, n: int) -> None:
        pad = (-len(self.buf)) % n
        self.buf += b"\x00" * pad

    def prim(self, kind: str, value) -> None:
        fmt, size = _PRIM[kind]
        self.align(size)
        if kind == "bool":
            self.buf += b"\x01" if value else b"\x00"
        else:
            self.buf += struct.pack(fmt, value)

    def string(self, value: str) -> None:
        raw = str(value).encode("utf-8") + b"\x00"
        self.align(4)
        self.buf += struct.pack("<I", len(raw))
        self.buf += raw

    def u32(self, value: int) -> None:
        self.prim("uint32", value)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def align(self, n: int) -> None:
        self.pos += (-self.pos) % n

    def prim(self, kind: str):
        fmt, size = _PRIM[kind]
        self.align(size)
        raw = self.data[self.pos : self.pos + size]
        self.pos += size
        if kind == "bool":
            return raw != b"\x00"
        return struct.unpack(fmt, raw)[0]

    def string(self) -> str:
        self.align(4)
        (n,) = struct.unpack_from("<I", self.data, self.pos)
        self.pos += 4
        raw = self.data[self.pos : self.pos + n]
        self.pos += n
        return raw.rstrip(b"\x00").decode("utf-8", errors="replace")

    def u32(self) -> int:
        return self.prim("uint32")


def _encode_value(w: _Writer, tref, value, resolve: Callable) -> None:
    if tref.is_array:
        items = list(value if value is not None else [])
        if tref.array_size is not None:
            items = (items + [_zero(tref, resolve)] * tref.array_size)[
                : tref.array_size
            ]
        else:
            w.u32(len(items))
        for item in items:
            _encode_scalar(w, tref, item, resolve)
    else:
        _encode_scalar(w, tref, value, resolve)


def _encode_scalar(w: _Writer, tref, value, resolve: Callable) -> None:
    if tref.base == "string":
        w.string(value if value is not None else "")
    elif tref.base == "wstring":
        raise NotImplementedError("wstring CDR is not supported")
    elif tref.is_primitive:
        w.prim(tref.base, value if value is not None else 0)
    else:
        spec = resolve(tref.base)
        encode_into(w, spec, value or {}, resolve)


def _zero(tref, resolve: Callable):
    if tref.base == "string":
        return ""
    if tref.is_primitive:
        return 0
    return {}


def encode_into(w: _Writer, spec, values: dict, resolve: Callable) -> None:
    for f in spec.fields:
        _encode_value(w, f.type, values.get(f.name), resolve)


def encode(spec, values: dict, resolve: Callable) -> bytes:
    """dict -> CDR bytes (without the 4-byte encapsulation header)."""
    w = _Writer()
    encode_into(w, spec, values, resolve)
    # RTPS serialized payloads are padded to a 4-byte boundary.
    w.align(4)
    return bytes(w.buf)


def _decode_value(r: _Reader, tref, resolve: Callable):
    if tref.is_array:
        n = tref.array_size if tref.array_size is not None else r.u32()
        return [_decode_scalar(r, tref, resolve) for _ in range(n)]
    return _decode_scalar(r, tref, resolve)


def _decode_scalar(r: _Reader, tref, resolve: Callable):
    if tref.base == "string":
        return r.string()
    if tref.base == "wstring":
        raise NotImplementedError("wstring CDR is not supported")
    if tref.is_primitive:
        return r.prim(tref.base)
    spec = resolve(tref.base)
    return decode_from(r, spec, resolve)


def decode_from(r: _Reader, spec, resolve: Callable) -> dict:
    return {f.name: _decode_value(r, f.type, resolve) for f in spec.fields}


def decode(spec, data: bytes, resolve: Callable) -> dict:
    """CDR bytes (no encapsulation header) -> dict."""
    return decode_from(_Reader(data), spec, resolve)


def roundtrip_check(spec, values: dict, resolve: Callable) -> dict:
    return decode(spec, encode(spec, values, resolve), resolve)
