"""Minimal RTPS 2.3 participant — DDS interop without a ROS2 install.

The reference bridge's selling point is that it speaks DDS directly
(ros2-client + rustdds, libraries/extensions/ros2-bridge/Cargo.toml) —
no ROS2 environment needed. This is the Python-native counterpart: a
self-contained RTPS participant over real UDP sockets implementing the
discovery + best-effort data subset the bridge needs:

* **SPDP** — participant discovery: periodic ``DATA(p)`` announcements
  (PL_CDR_LE parameter lists) on the well-known multicast group
  (239.255.0.1, port ``7400 + 250*domain``) AND, for environments
  where multicast is filtered, on the spec's well-known unicast ports
  (``7410 + 2*participant_id``) of every address in
  ``DORA_RTPS_PEERS`` (comma-separated, default 127.0.0.1).
* **SEDP** — endpoint discovery: ``DATA(w)`` / ``DATA(r)`` publication
  and subscription announcements (topic, type, user-traffic locator)
  unicast to each discovered participant's metatraffic locator.
* **User data** — ``DATA`` submessages with CDR_LE payloads sent
  straight to every matched reader's user locator.
* **Reliable QoS** (round 5) — writers opened with ``reliable=True``
  keep a keep-last history and advertise RELIABLE reliability in SEDP;
  they append a piggyback ``HEARTBEAT`` to every DATA and repeat it
  from the announce loop. Reliable readers deliver IN ORDER per remote
  writer, buffer out-of-sequence arrivals, answer heartbeats with
  ``ACKNACK`` bitmaps of the missing sequence numbers, and honor
  ``GAP`` (a writer's statement that evicted-from-history sequences
  will never arrive). Loss recovery is asserted under an injected-loss
  socket shim dropping every k-th DATA (tests/test_ros2_rtps.py).
* **Lease expiry** — peers advertise their SPDP lease duration
  (``DORA_RTPS_LEASE_S``); a participant that stops announcing is
  dropped — with its endpoints — once its lease runs out, matching the
  reference stack's participant liveliness semantics.

Messages use the standard ROS2 mangling (topic ``rt/<name>``, type
``pkg::msg::dds_::Type_``) so the frames are what any DDS stack
expects; cross-vendor interop cannot be exercised in this offline
image (no other DDS exists here) and is documented as such in
PARITY.md. The wire format is validated by two independent
participants in separate processes exchanging over real sockets
(tests/test_ros2_rtps.py).
"""

from __future__ import annotations

import os
import random
import socket
import struct
import threading

from dora_tpu.analysis.lockcheck import tracked_rlock
import time
from dataclasses import dataclass, field

PROTOCOL = b"RTPS"
VERSION = (2, 3)
VENDOR = b"\x01\x21"  # unassigned range; parsers must accept any vendor

# Submessage ids
_INFO_TS = 0x09
_DATA = 0x15
_ACKNACK = 0x06
_HEARTBEAT = 0x07
_GAP = 0x08

# Builtin entity ids (RTPS 2.3 table 9.2)
ENT_SPDP_W = 0x000100C2
ENT_SPDP_R = 0x000100C7
ENT_SEDP_PUB_W = 0x000003C2
ENT_SEDP_PUB_R = 0x000003C7
ENT_SEDP_SUB_W = 0x000004C2
ENT_SEDP_SUB_R = 0x000004C7

# Parameter ids
PID_SENTINEL = 0x0001
PID_LEASE = 0x0002
PID_TOPIC_NAME = 0x0005
PID_TYPE_NAME = 0x0007
PID_PROTOCOL_VERSION = 0x0015
PID_VENDORID = 0x0016
PID_UNICAST_LOCATOR = 0x002F
PID_DEFAULT_UNICAST_LOCATOR = 0x0031
PID_METATRAFFIC_UNICAST_LOCATOR = 0x0032
PID_PARTICIPANT_GUID = 0x0050
PID_ENDPOINT_GUID = 0x005A
PID_BUILTIN_ENDPOINTS = 0x0058
PID_RELIABILITY = 0x001A

LOCATOR_UDPV4 = 1

MULTICAST_GROUP = "239.255.0.1"


def _ports(domain: int) -> tuple[int, int]:
    """(multicast discovery port, unicast discovery port base)."""
    base = 7400 + 250 * domain
    return base, base + 10


def _mangle_topic(topic: str) -> str:
    return "rt" + (topic if topic.startswith("/") else "/" + topic)


def _mangle_type(msg_type: str) -> str:
    pkg, _, name = msg_type.partition("/")
    return f"{pkg}::msg::dds_::{name}_"


def _locator(addr: str, port: int) -> bytes:
    ip = socket.inet_aton(addr)
    return struct.pack("<iI", LOCATOR_UDPV4, port) + b"\x00" * 12 + ip


def _parse_locator(raw: bytes) -> tuple[str, int] | None:
    kind, port = struct.unpack_from("<iI", raw, 0)
    if kind != LOCATOR_UDPV4:
        return None
    return socket.inet_ntoa(raw[20:24]), port


def _param(pid: int, value: bytes) -> bytes:
    pad = (-len(value)) % 4
    return struct.pack("<HH", pid, len(value) + pad) + value + b"\x00" * pad


def _param_string(pid: int, s: str) -> bytes:
    raw = s.encode() + b"\x00"
    return _param(pid, struct.pack("<I", len(raw)) + raw)


def _parse_params(data: bytes) -> list[tuple[int, bytes]]:
    out, pos = [], 0
    while pos + 4 <= len(data):
        pid, length = struct.unpack_from("<HH", data, pos)
        pos += 4
        if pid == PID_SENTINEL:
            break
        out.append((pid, data[pos : pos + length]))
        pos += length
    return out


def _param_str_value(raw: bytes) -> str:
    (n,) = struct.unpack_from("<I", raw, 0)
    return raw[4 : 4 + n].rstrip(b"\x00").decode(errors="replace")


@dataclass
class _Peer:
    guid_prefix: bytes
    meta: tuple[str, int]
    seen: float = 0.0
    sedp_sent: bool = False
    lease_s: float = 100.0


@dataclass
class _RemoteEndpoint:
    guid: bytes
    topic: str
    type_name: str
    locator: tuple[str, int] | None
    reliable: bool = False


@dataclass
class _Writer:
    entity_id: int
    topic: str
    type_name: str
    seq: int = 0
    reliable: bool = False
    #: keep-last history for reliable resend: seq -> encapsulated payload
    store: dict = field(default_factory=dict)
    depth: int = 32
    hb_count: int = 0
    #: per-reader-guid last processed ACKNACK count (stale-drop)
    acked: dict = field(default_factory=dict)


@dataclass
class _WriterProxy:
    """Reliable reception state for one remote writer."""

    next_seq: int = 1  # next sequence to deliver in order
    pending: dict = field(default_factory=dict)  # seq -> payload | None(gap)
    last_hb_count: int = -1
    acknack_count: int = 0


@dataclass
class _Reader:
    entity_id: int
    topic: str
    type_name: str
    callback: object = None
    history: list = field(default_factory=list)
    reliable: bool = False
    proxies: dict = field(default_factory=dict)  # writer guid -> _WriterProxy


class RtpsParticipant:
    """One DDS participant: discovery threads + best-effort data plane."""

    ANNOUNCE_PERIOD_S = float(os.environ.get("DORA_RTPS_ANNOUNCE_S", "0.25"))

    def __init__(self, domain_id: int = 0, name: str = "dora_tpu"):
        self.domain = domain_id
        self.name = name
        self.guid_prefix = (
            VENDOR + os.getpid().to_bytes(4, "big")
            + random.randbytes(6)
        )
        self._writers: dict[int, _Writer] = {}
        self._readers: dict[int, _Reader] = {}
        self._remote_writers: dict[bytes, _RemoteEndpoint] = {}
        self._remote_readers: dict[bytes, _RemoteEndpoint] = {}
        self._peers: dict[bytes, _Peer] = {}
        self._next_entity = 1
        self._lock = tracked_rlock("ros2.rtps")
        self._closed = threading.Event()
        #: advertised SPDP lease (peers drop us this long after our last
        #: announcement); tests shrink it to exercise expiry.
        self.lease_s = float(os.environ.get("DORA_RTPS_LEASE_S", "100"))
        #: optional (dest, submsgs) -> bool keep hook — the loss-injection
        #: shim of the reliable-protocol tests.
        self.send_filter = None

        mcast_port, ucast_base = _ports(domain_id)
        # Metatraffic unicast: the spec's well-known ports so unicast
        # initial-peers discovery works without any out-of-band channel.
        self._meta_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.participant_id = 0
        for pid in range(64):
            try:
                self._meta_sock.bind(("0.0.0.0", ucast_base + 2 * pid))
                self.participant_id = pid
                break
            except OSError:
                continue
        else:
            self._meta_sock.bind(("0.0.0.0", 0))
        self.meta_port = self._meta_sock.getsockname()[1]

        # User traffic: ephemeral, advertised through discovery.
        self._user_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._user_sock.bind(("0.0.0.0", 0))
        self.user_port = self._user_sock.getsockname()[1]

        # SPDP multicast receive (best effort — may be filtered).
        self._mcast_port = mcast_port
        self._mcast_rx = None
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if hasattr(socket, "SO_REUSEPORT"):
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind(("0.0.0.0", mcast_port))
            mreq = socket.inet_aton(MULTICAST_GROUP) + socket.inet_aton(
                "0.0.0.0"
            )
            s.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
            s.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1)
            self._mcast_rx = s
        except OSError:
            self._mcast_rx = None

        self._send_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            self._send_sock.setsockopt(
                socket.IPPROTO_IP, socket.IP_MULTICAST_TTL, 1
            )
            self._send_sock.setsockopt(
                socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1
            )
        except OSError:
            pass

        self.peers_hint = [
            p.strip()
            for p in os.environ.get("DORA_RTPS_PEERS", "127.0.0.1").split(",")
            if p.strip()
        ]

        self._threads = [
            threading.Thread(target=self._announce_loop, daemon=True,
                             name="rtps-announce"),
            threading.Thread(target=self._rx_loop, daemon=True,
                             args=(self._meta_sock,), name="rtps-meta"),
            threading.Thread(target=self._rx_loop, daemon=True,
                             args=(self._user_sock,), name="rtps-user"),
        ]
        if self._mcast_rx is not None:
            self._threads.append(
                threading.Thread(target=self._rx_loop, daemon=True,
                                 args=(self._mcast_rx,), name="rtps-mcast")
            )
        for t in self._threads:
            t.start()

    # -- wire helpers -------------------------------------------------------

    def _header(self) -> bytes:
        return (
            PROTOCOL + bytes(VERSION) + VENDOR + self.guid_prefix
        )

    def _data_submsg(self, reader_ent: int, writer_ent: int, seq: int,
                     payload: bytes) -> bytes:
        flags = 0x01 | 0x04  # little-endian | data present
        # Entity ids are 4-octet arrays (key+kind), never endian-swapped;
        # everything else honors the E flag (little-endian).
        body = (
            struct.pack("<HH", 0, 16)
            + struct.pack(">II", reader_ent, writer_ent)
            + struct.pack("<iI", seq >> 32, seq & 0xFFFFFFFF)
            + payload
        )
        return struct.pack("<BBH", _DATA, flags, len(body)) + body

    def _send(self, dest: tuple[str, int], submsgs: bytes) -> None:
        if self.send_filter is not None and not self.send_filter(
            dest, submsgs
        ):
            return  # test shim: injected packet loss
        try:
            self._send_sock.sendto(self._header() + submsgs, dest)
        except OSError:
            pass

    @staticmethod
    def _sn(seq: int) -> bytes:
        return struct.pack("<iI", seq >> 32, seq & 0xFFFFFFFF)

    @staticmethod
    def _parse_sn(body: bytes, off: int) -> int:
        high, low = struct.unpack_from("<iI", body, off)
        return (high << 32) | low

    def _heartbeat_submsg(self, reader_ent: int, writer: "_Writer",
                          final: bool = False) -> bytes:
        # Called from both the app thread (publish piggyback) and the
        # announce thread (periodic sweep): the store read and count
        # bump must not race publish_cdr's locked history mutation.
        with self._lock:
            writer.hb_count += 1
            first = min(writer.store) if writer.store else max(writer.seq, 1)
            last = writer.seq
        flags = 0x01 | (0x02 if final else 0)
        body = (
            struct.pack(">II", reader_ent, writer.entity_id)
            + self._sn(first)
            + self._sn(last)
            + struct.pack("<i", writer.hb_count)
        )
        return struct.pack("<BBH", _HEARTBEAT, flags, len(body)) + body

    def _acknack_submsg(self, reader_ent: int, writer_ent: int, base: int,
                        missing: list[int], count: int) -> bytes:
        num_bits = (max(missing) - base + 1) if missing else 0
        words = [0] * ((num_bits + 31) // 32)
        for s in missing:
            i = s - base
            words[i // 32] |= 1 << (31 - i % 32)  # RTPS bitmap: MSB first
        body = (
            struct.pack(">II", reader_ent, writer_ent)
            + self._sn(base)
            + struct.pack("<I", num_bits)
            + b"".join(struct.pack("<I", w) for w in words)
            + struct.pack("<i", count)
        )
        flags = 0x01 | (0x00 if missing else 0x02)  # final when nothing asked
        return struct.pack("<BBH", _ACKNACK, flags, len(body)) + body

    def _gap_submsg(self, reader_ent: int, writer_ent: int,
                    start: int, end: int) -> bytes:
        """GAP covering [start, end] (irrelevant sequences)."""
        body = (
            struct.pack(">II", reader_ent, writer_ent)
            + self._sn(start)
            + self._sn(end + 1)  # gapList base: first seq NOT in the gap
            + struct.pack("<I", 0)  # numBits 0: no extra bits
        )
        return struct.pack("<BBH", _GAP, 0x01, len(body)) + body

    # -- announcements ------------------------------------------------------

    def _spdp_payload(self) -> bytes:
        guid = self.guid_prefix + struct.pack(">I", 0x000001C1)
        params = b"".join(
            [
                _param(PID_PROTOCOL_VERSION, bytes(VERSION) + b"\x00\x00"),
                _param(PID_VENDORID, VENDOR + b"\x00\x00"),
                _param(PID_PARTICIPANT_GUID, guid),
                _param(
                    PID_METATRAFFIC_UNICAST_LOCATOR,
                    _locator(self._local_addr(), self.meta_port),
                ),
                _param(
                    PID_DEFAULT_UNICAST_LOCATOR,
                    _locator(self._local_addr(), self.user_port),
                ),
                _param(PID_BUILTIN_ENDPOINTS, struct.pack("<I", 0x0000000F)),
                _param(
                    PID_LEASE,
                    struct.pack(
                        "<iI", int(self.lease_s),
                        int((self.lease_s % 1) * (1 << 32)),
                    ),
                ),
                _param(PID_SENTINEL, b""),
            ]
        )
        from dora_tpu.ros2.cdr import PL_CDR_LE

        return PL_CDR_LE + params

    def _local_addr(self) -> str:
        return "127.0.0.1"

    def _sedp_payload(self, topic: str, type_name: str, guid_ent: int,
                      locator_port: int, reliable: bool = False) -> bytes:
        from dora_tpu.ros2.cdr import PL_CDR_LE

        guid = self.guid_prefix + struct.pack(">I", guid_ent)
        # Reliability kind: 1 = BEST_EFFORT, 2 = RELIABLE (+100 ms
        # max_blocking_time, the common DDS default).
        kind = 2 if reliable else 1
        params = b"".join(
            [
                _param_string(PID_TOPIC_NAME, topic),
                _param_string(PID_TYPE_NAME, type_name),
                _param(PID_ENDPOINT_GUID, guid),
                _param(
                    PID_UNICAST_LOCATOR,
                    _locator(self._local_addr(), locator_port),
                ),
                _param(
                    PID_RELIABILITY,
                    struct.pack("<iiI", kind, 0, 100_000_000),
                ),
                _param(PID_SENTINEL, b""),
            ]
        )
        return PL_CDR_LE + params

    def _announce_loop(self) -> None:
        seq = 0
        while not self._closed.is_set():
            seq += 1
            spdp = self._data_submsg(
                ENT_SPDP_R, ENT_SPDP_W, seq, self._spdp_payload()
            )
            dests = [(MULTICAST_GROUP, self._mcast_port)]
            _, ucast_base = _ports(self.domain)
            for host in self.peers_hint:
                for pid in range(8):
                    port = ucast_base + 2 * pid
                    if host in ("127.0.0.1", "localhost") and (
                        port == self.meta_port
                    ):
                        continue
                    dests.append((host, port))
            for dest in dests:
                self._send(dest, spdp)
            self._sedp_announce()
            self._expire_peers()
            self._heartbeat_sweep()
            self._closed.wait(self.ANNOUNCE_PERIOD_S)

    def _expire_peers(self) -> None:
        """Drop peers (and their endpoints) whose SPDP lease ran out —
        the participant-liveliness semantics of the reference's DDS
        stack (a crashed peer's endpoints must unmatch)."""
        now = time.monotonic()
        with self._lock:
            dead = [
                guid for guid, p in self._peers.items()
                if p.seen and now - p.seen > p.lease_s
            ]
            for guid in dead:
                del self._peers[guid]
                for table in (self._remote_writers, self._remote_readers):
                    for ep_guid in [g for g in table if g[:12] == guid]:
                        del table[ep_guid]
                # Reliable-protocol state keyed by the dead peer's
                # endpoints must go too (peer churn must not leak
                # buffered payloads or acknack bookkeeping).
                for r in self._readers.values():
                    for wg in [g for g in r.proxies if g[:12] == guid]:
                        del r.proxies[wg]
                for w in self._writers.values():
                    for rg in [g for g in w.acked if g[:12] == guid]:
                        del w.acked[rg]

    def _heartbeat_sweep(self) -> None:
        """Periodic HEARTBEAT for every reliable writer with history —
        the retransmission clock: a reader that missed a DATA (and its
        piggyback heartbeat) learns what it lacks from this."""
        with self._lock:
            writers = [w for w in self._writers.values()
                       if w.reliable and w.seq]
        for w in writers:
            hb = self._heartbeat_submsg(0, w)
            for ep in self.matched_readers(w.topic):
                if ep.reliable:
                    self._send(ep.locator, hb)

    def _sedp_announce(self) -> None:
        with self._lock:
            peers = [p for p in self._peers.values()]
            writers = list(self._writers.values())
            readers = list(self._readers.values())
        for peer in peers:
            msgs = b""
            for i, w in enumerate(writers):
                payload = self._sedp_payload(
                    w.topic, w.type_name, w.entity_id, self.user_port,
                    reliable=w.reliable,
                )
                msgs += self._data_submsg(
                    ENT_SEDP_PUB_R, ENT_SEDP_PUB_W, i + 1, payload
                )
            for i, r in enumerate(readers):
                payload = self._sedp_payload(
                    r.topic, r.type_name, r.entity_id, self.user_port,
                    reliable=r.reliable,
                )
                msgs += self._data_submsg(
                    ENT_SEDP_SUB_R, ENT_SEDP_SUB_W, i + 1, payload
                )
            if msgs:
                self._send(peer.meta, msgs)

    # -- receive path -------------------------------------------------------

    def _rx_loop(self, sock: socket.socket) -> None:
        sock.settimeout(0.2)
        while not self._closed.is_set():
            try:
                data, _addr = sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle(data)
            except Exception:
                continue  # malformed frames must never kill the pump

    def _handle(self, data: bytes) -> None:
        if len(data) < 20 or data[:4] != PROTOCOL:
            return
        src_prefix = data[8:20]
        if src_prefix == self.guid_prefix:
            return
        pos = 20
        while pos + 4 <= len(data):
            sub_id, flags, length = struct.unpack_from("<BBH", data, pos)
            if not flags & 0x01:
                return  # big-endian peers unsupported (none in practice)
            body = data[pos + 4 : pos + 4 + length]
            pos += 4 + length
            if sub_id == _HEARTBEAT and len(body) >= 28:
                self._on_heartbeat(src_prefix, body)
                continue
            if sub_id == _ACKNACK and len(body) >= 24:
                self._on_acknack(src_prefix, body)
                continue
            if sub_id == _GAP and len(body) >= 28:
                self._on_gap(src_prefix, body)
                continue
            if sub_id != _DATA or len(body) < 24:
                continue
            _extra, to_qos = struct.unpack_from("<HH", body, 0)
            reader_ent, writer_ent = struct.unpack_from(">II", body, 4)
            seq = self._parse_sn(body, 12)
            # octetsToInlineQos counts from the octet after itself
            # (i.e. from body offset 4) to the inline-qos/payload.
            payload = body[4 + to_qos :]
            if writer_ent == ENT_SPDP_W:
                self._on_spdp(payload)
            elif writer_ent == ENT_SEDP_PUB_W:
                self._on_sedp(src_prefix, payload, is_writer=True)
            elif writer_ent == ENT_SEDP_SUB_W:
                self._on_sedp(src_prefix, payload, is_writer=False)
            else:
                self._on_user_data(src_prefix, writer_ent, reader_ent,
                                   payload, seq)

    def _on_spdp(self, payload: bytes) -> None:
        if len(payload) < 4:
            return
        params = _parse_params(payload[4:])
        guid = meta = None
        lease_s = 100.0
        for pid, value in params:
            if pid == PID_PARTICIPANT_GUID and len(value) >= 12:
                guid = value[:12]
            elif pid == PID_METATRAFFIC_UNICAST_LOCATOR and len(value) >= 24:
                meta = _parse_locator(value)
            elif pid == PID_LEASE and len(value) >= 8:
                sec, frac = struct.unpack_from("<iI", value, 0)
                lease_s = sec + frac / (1 << 32)
        if guid is None or meta is None or guid == self.guid_prefix:
            return
        with self._lock:
            peer = self._peers.get(guid)
            if peer is None:
                self._peers[guid] = _Peer(
                    guid, meta, time.monotonic(), lease_s=lease_s
                )
            else:
                peer.meta = meta
                peer.seen = time.monotonic()
                peer.lease_s = lease_s

    def _on_sedp(self, src_prefix: bytes, payload: bytes,
                 is_writer: bool) -> None:
        if len(payload) < 4:
            return
        params = _parse_params(payload[4:])
        topic = type_name = None
        guid = None
        locator = None
        reliable = False
        for pid, value in params:
            if pid == PID_TOPIC_NAME:
                topic = _param_str_value(value)
            elif pid == PID_TYPE_NAME:
                type_name = _param_str_value(value)
            elif pid == PID_ENDPOINT_GUID and len(value) >= 16:
                guid = value
            elif pid in (PID_UNICAST_LOCATOR, PID_DEFAULT_UNICAST_LOCATOR):
                locator = _parse_locator(value) or locator
            elif pid == PID_RELIABILITY and len(value) >= 4:
                reliable = struct.unpack_from("<i", value, 0)[0] >= 2
        if not topic or guid is None:
            return
        ep = _RemoteEndpoint(guid, topic, type_name or "", locator,
                             reliable=reliable)
        with self._lock:
            if is_writer:
                self._remote_writers[guid] = ep
            else:
                self._remote_readers[guid] = ep

    def _on_user_data(self, src_prefix: bytes, writer_ent: int,
                      reader_ent: int, payload: bytes, seq: int = 0) -> None:
        """Route a user DATA to local readers on the writer's topic.
        Reliable readers deliver IN ORDER per remote writer: early
        arrivals buffer until the gap fills (retransmission) or a GAP
        declares it irrelevant."""
        writer_guid = src_prefix + struct.pack(">I", writer_ent)
        with self._lock:
            ep = self._remote_writers.get(writer_guid)
            readers = list(self._readers.values())
        if ep is None:
            return
        if len(payload) < 4:
            return
        body = payload[4:]  # strip encapsulation header
        for r in readers:
            if r.topic != ep.topic:
                continue
            if not (r.reliable and ep.reliable):
                self._deliver(r, body)
                continue
            with self._lock:
                proxy = r.proxies.setdefault(writer_guid, _WriterProxy())
                if seq < proxy.next_seq or seq in proxy.pending:
                    continue  # duplicate (retransmission overlap)
                proxy.pending[seq] = body
                ready = self._drain_proxy(proxy)
            # Deliver OUTSIDE the participant lock (like the best-effort
            # path above): a reader callback that re-enters this
            # participant would otherwise deadlock.
            for deliverable in ready:
                self._deliver(r, deliverable)

    def _deliver(self, reader: "_Reader", body: bytes) -> None:
        if reader.callback is not None:
            reader.callback(body)
        else:
            reader.history.append(body)

    def _drain_proxy(self, proxy: "_WriterProxy") -> list[bytes]:
        """Pop the contiguous run at the head of the pending buffer and
        return its deliverable bodies (None entries are GAP-declared
        irrelevant sequences). Caller holds the lock and must deliver
        only after releasing it."""
        ready: list[bytes] = []
        while proxy.next_seq in proxy.pending:
            body = proxy.pending.pop(proxy.next_seq)
            proxy.next_seq += 1
            if body is not None:
                ready.append(body)
        return ready

    # -- reliable protocol ---------------------------------------------------

    def _on_heartbeat(self, src_prefix: bytes, body: bytes) -> None:
        """Answer a writer's HEARTBEAT with an ACKNACK naming exactly
        the sequences this reader still lacks in [first, last]."""
        reader_ent, writer_ent = struct.unpack_from(">II", body, 0)
        first = self._parse_sn(body, 8)
        last = self._parse_sn(body, 16)
        (count,) = struct.unpack_from("<i", body, 24)
        writer_guid = src_prefix + struct.pack(">I", writer_ent)
        deliveries: list[tuple["_Reader", bytes]] = []
        acks: list[bytes] = []
        with self._lock:
            ep = self._remote_writers.get(writer_guid)
            if ep is None or not ep.reliable or ep.locator is None:
                return
            locator = ep.locator
            targets = [
                r for r in self._readers.values()
                if r.topic == ep.topic and r.reliable
            ]
            for r in targets:
                proxy = r.proxies.setdefault(writer_guid, _WriterProxy())
                if count <= proxy.last_hb_count:
                    continue  # stale repeat
                proxy.last_hb_count = count
                # Sequences below `first` left the writer's history:
                # the truly-missing ones are unrecoverable (skip), but
                # anything already buffered out-of-order DID arrive and
                # must still be delivered, in order.
                while proxy.next_seq < first:
                    buffered = proxy.pending.pop(proxy.next_seq, None)
                    proxy.next_seq += 1
                    if buffered is not None:
                        deliveries.append((r, buffered))
                deliveries.extend((r, b) for b in self._drain_proxy(proxy))
                missing = [
                    s for s in range(proxy.next_seq, last + 1)
                    if s not in proxy.pending
                ]
                proxy.acknack_count += 1
                acks.append(self._acknack_submsg(
                    r.entity_id, writer_ent,
                    missing[0] if missing else last + 1,
                    missing, proxy.acknack_count,
                ))
        # Callbacks and socket sends happen outside the lock: a callback
        # re-entering the participant (or a blocking send) must never
        # hold up discovery/delivery on other threads.
        for r, deliverable in deliveries:
            self._deliver(r, deliverable)
        for ack in acks:
            self._send(locator, ack)

    def _on_acknack(self, src_prefix: bytes, body: bytes) -> None:
        """Resend requested sequences from history; GAP the evicted."""
        reader_ent, writer_ent = struct.unpack_from(">II", body, 0)
        base = self._parse_sn(body, 8)
        (num_bits,) = struct.unpack_from("<I", body, 16)
        words = [
            struct.unpack_from("<I", body, 20 + 4 * i)[0]
            for i in range((num_bits + 31) // 32)
        ]
        (count,) = struct.unpack_from(
            "<i", body, 20 + 4 * len(words)
        )
        requested = [
            base + i
            for i in range(num_bits)
            if words[i // 32] & (1 << (31 - i % 32))
        ]
        reader_guid = src_prefix + struct.pack(">I", reader_ent)
        with self._lock:
            w = self._writers.get(writer_ent)
            if w is None or not w.reliable:
                return
            if count <= w.acked.get(reader_guid, -1):
                return  # stale repeat
            w.acked[reader_guid] = count
            ep = self._remote_readers.get(reader_guid)
            store = dict(w.store)
        if ep is None or ep.locator is None:
            return
        for s in requested:
            payload = store.get(s)
            if payload is not None:
                self._send(
                    ep.locator,
                    self._data_submsg(reader_ent, writer_ent, s, payload),
                )
            else:
                # Evicted from keep-last history: tell the reader to
                # stop waiting for it.
                self._send(
                    ep.locator,
                    self._gap_submsg(reader_ent, writer_ent, s, s),
                )

    def _on_gap(self, src_prefix: bytes, body: bytes) -> None:
        """Mark [gapStart, gapListBase) as irrelevant for this writer."""
        reader_ent, writer_ent = struct.unpack_from(">II", body, 0)
        start = self._parse_sn(body, 8)
        list_base = self._parse_sn(body, 16)
        writer_guid = src_prefix + struct.pack(">I", writer_ent)
        deliveries: list[tuple["_Reader", bytes]] = []
        with self._lock:
            ep = self._remote_writers.get(writer_guid)
            if ep is None:
                return
            for r in self._readers.values():
                if r.topic != ep.topic or not r.reliable:
                    continue
                proxy = r.proxies.setdefault(writer_guid, _WriterProxy())
                for s in range(max(start, proxy.next_seq), list_base):
                    proxy.pending.setdefault(s, None)
                deliveries.extend((r, b) for b in self._drain_proxy(proxy))
        for r, deliverable in deliveries:
            self._deliver(r, deliverable)

    # -- public API ---------------------------------------------------------

    def create_writer(self, topic: str, msg_type: str,
                      reliable: bool = False,
                      history_depth: int = 32) -> "RtpsWriter":
        with self._lock:
            ent = (self._next_entity << 8) | 0x03  # user writer, no key
            self._next_entity += 1
            w = _Writer(ent, _mangle_topic(topic), _mangle_type(msg_type),
                        reliable=reliable, depth=history_depth)
            self._writers[ent] = w
        self._sedp_announce()
        return RtpsWriter(self, w)

    def create_reader(self, topic: str, msg_type: str,
                      callback=None, reliable: bool = False) -> "_Reader":
        with self._lock:
            ent = (self._next_entity << 8) | 0x04  # user reader, no key
            self._next_entity += 1
            r = _Reader(ent, _mangle_topic(topic), _mangle_type(msg_type),
                        callback, reliable=reliable)
            self._readers[ent] = r
        self._sedp_announce()
        return r

    def matched_readers(self, topic: str) -> list[_RemoteEndpoint]:
        with self._lock:
            return [
                ep for ep in self._remote_readers.values()
                if ep.topic == topic and ep.locator is not None
            ]

    def wait_for_match(self, topic: str, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        mangled = _mangle_topic(topic)
        while time.monotonic() < deadline:
            if self.matched_readers(mangled):
                return True
            time.sleep(0.05)
        return False

    def close(self) -> None:
        self._closed.set()
        for t in self._threads:
            t.join(timeout=1)
        for s in (self._meta_sock, self._user_sock, self._send_sock,
                  self._mcast_rx):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass


class RtpsWriter:
    def __init__(self, participant: RtpsParticipant, writer: _Writer):
        self._p = participant
        self._w = writer

    def publish_cdr(self, cdr_bytes: bytes) -> None:
        """Send an already-CDR-encoded payload to every matched reader.
        Reliable writers store the sample in keep-last history and
        piggyback a HEARTBEAT so readers detect loss immediately."""
        from dora_tpu.ros2.cdr import CDR_LE

        w = self._w
        payload = CDR_LE + cdr_bytes
        with self._p._lock:
            w.seq += 1
            seq = w.seq
            if w.reliable:
                w.store[seq] = payload
                while len(w.store) > w.depth:
                    del w.store[min(w.store)]
        submsg = self._p._data_submsg(0, w.entity_id, seq, payload)
        hb = self._p._heartbeat_submsg(0, w) if w.reliable else b""
        for ep in self._p.matched_readers(w.topic):
            if w.reliable and ep.reliable:
                self._p._send(ep.locator, submsg + hb)
            else:
                self._p._send(ep.locator, submsg)
