"""ROS2 interface-definition parser (.msg / .srv / .action).

Reference parity: libraries/extensions/ros2-bridge/msg-gen/src/parser —
the reference generates Rust types at build time; we parse at runtime
into schema objects that drive Arrow conversion. Grammar covered:
primitive and namespaced types, fixed/bounded/unbounded arrays, bounded
strings, default values, constants, comments.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from pathlib import Path

PRIMITIVES = {
    "bool", "byte", "char",
    "int8", "uint8", "int16", "uint16", "int32", "uint32", "int64", "uint64",
    "float32", "float64", "string", "wstring",
}

_TYPE_RE = re.compile(
    r"^(?P<base>[A-Za-z0-9_/]+)"
    r"(?:<=(?P<strbound>\d+))?"
    r"(?P<array>\[(?:(?P<size>\d+)|<=(?P<bound>\d+))?\])?$"
)
_CONST_RE = re.compile(
    r"^(?P<type>\S+)\s+(?P<name>[A-Z][A-Z0-9_]*)\s*=\s*(?P<value>.+)$"
)
_FIELD_RE = re.compile(
    r"^(?P<type>\S+)\s+(?P<name>[a-zA-Z][a-zA-Z0-9_]*)(?:\s+(?P<default>.+))?$"
)


@dataclass(frozen=True)
class TypeRef:
    """A (possibly array) field type."""

    base: str  # primitive name or "pkg/Type"
    is_array: bool = False
    array_size: int | None = None  # fixed size
    array_bound: int | None = None  # bounded (<=N)
    string_bound: int | None = None  # bounded string payload

    @property
    def is_primitive(self) -> bool:
        return self.base in PRIMITIVES

    @property
    def package(self) -> str | None:
        return self.base.split("/")[0] if "/" in self.base else None

    @classmethod
    def parse(cls, raw: str, package: str | None = None) -> "TypeRef":
        m = _TYPE_RE.match(raw)
        if not m:
            raise ValueError(f"invalid type {raw!r}")
        base = m.group("base")
        if base not in PRIMITIVES and "/" not in base and package:
            # Relative reference to a message in the same package.
            base = f"{package}/{base}"
        return cls(
            base=base,
            is_array=m.group("array") is not None,
            array_size=int(m.group("size")) if m.group("size") else None,
            array_bound=int(m.group("bound")) if m.group("bound") else None,
            string_bound=int(m.group("strbound")) if m.group("strbound") else None,
        )


@dataclass(frozen=True)
class Field:
    type: TypeRef
    name: str
    default: object = None


@dataclass(frozen=True)
class Constant:
    type: str
    name: str
    value: object


@dataclass(frozen=True)
class MessageSpec:
    package: str
    name: str
    fields: tuple[Field, ...] = ()
    constants: tuple[Constant, ...] = ()

    @property
    def full_name(self) -> str:
        return f"{self.package}/{self.name}"


@dataclass(frozen=True)
class ServiceSpec:
    package: str
    name: str
    request: MessageSpec = None
    response: MessageSpec = None


@dataclass(frozen=True)
class ActionSpec:
    package: str
    name: str
    goal: MessageSpec = None
    result: MessageSpec = None
    feedback: MessageSpec = None


def _parse_value(type_name: str, raw: str):
    raw = raw.strip()
    if type_name == "bool":
        return raw.lower() in ("true", "1")
    if type_name in ("string", "wstring") or raw.startswith(("'", '"')):
        try:
            return ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            return raw
    if raw.startswith("["):
        return ast.literal_eval(raw)
    try:
        return ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        return raw


def _strip_comment(line: str) -> str:
    # A '#' inside a quoted string is not a comment.
    out = []
    quote = None
    for c in line:
        if quote:
            out.append(c)
            if c == quote:
                quote = None
        elif c in "'\"":
            quote = c
            out.append(c)
        elif c == "#":
            break
        else:
            out.append(c)
    return "".join(out).strip()


def parse_message(text: str, package: str = "", name: str = "Msg") -> MessageSpec:
    fields: list[Field] = []
    constants: list[Constant] = []
    for raw_line in text.splitlines():
        line = _strip_comment(raw_line)
        if not line:
            continue
        const = _CONST_RE.match(line)
        if const:
            constants.append(
                Constant(
                    type=const.group("type"),
                    name=const.group("name"),
                    value=_parse_value(const.group("type"), const.group("value")),
                )
            )
            continue
        m = _FIELD_RE.match(line)
        if not m:
            raise ValueError(f"cannot parse line: {raw_line!r}")
        type_ref = TypeRef.parse(m.group("type"), package)
        default = m.group("default")
        fields.append(
            Field(
                type=type_ref,
                name=m.group("name"),
                default=_parse_value(type_ref.base, default) if default else None,
            )
        )
    return MessageSpec(
        package=package, name=name, fields=tuple(fields), constants=tuple(constants)
    )


def parse_service(text: str, package: str = "", name: str = "Srv") -> ServiceSpec:
    parts = _split_sections(text, 2)
    return ServiceSpec(
        package=package,
        name=name,
        request=parse_message(parts[0], package, f"{name}_Request"),
        response=parse_message(parts[1], package, f"{name}_Response"),
    )


def parse_action(text: str, package: str = "", name: str = "Action") -> ActionSpec:
    parts = _split_sections(text, 3)
    return ActionSpec(
        package=package,
        name=name,
        goal=parse_message(parts[0], package, f"{name}_Goal"),
        result=parse_message(parts[1], package, f"{name}_Result"),
        feedback=parse_message(parts[2], package, f"{name}_Feedback"),
    )


def _split_sections(text: str, n: int) -> list[str]:
    parts = re.split(r"^---\s*$", text, flags=re.MULTILINE)
    if len(parts) != n:
        raise ValueError(f"expected {n} sections separated by '---', got {len(parts)}")
    return parts


# ---------------------------------------------------------------------------
# interface discovery (reference: scan $AMENT_PREFIX_PATH)
# ---------------------------------------------------------------------------


def find_interface(full_name: str, ament_prefix_path: str | None = None):
    """Locate and parse ``pkg/Type`` under $AMENT_PREFIX_PATH
    (``<prefix>/share/<pkg>/{msg,srv,action}/<Type>.{msg,srv,action}``)."""
    package, _, name = full_name.partition("/")
    prefixes = (ament_prefix_path or os.environ.get("AMENT_PREFIX_PATH", "")).split(
        os.pathsep
    )
    for prefix in filter(None, prefixes):
        share = Path(prefix) / "share" / package
        for kind, ext, parser in (
            ("msg", ".msg", parse_message),
            ("srv", ".srv", parse_service),
            ("action", ".action", parse_action),
        ):
            path = share / kind / f"{name}{ext}"
            if path.exists():
                return parser(path.read_text(), package, name)
    raise FileNotFoundError(
        f"interface {full_name!r} not found under AMENT_PREFIX_PATH"
    )
