"""ROS2 DDS transport bridge (requires a ROS2 installation with rclpy).

Reference parity: the ros2-bridge runtime half — Ros2Node/publisher/
subscription with subscriptions mergeable into a dora node's event
stream (apis/python/node/src/lib.rs:209-239). The reference links rustdds
directly; the Python-native equivalent rides rclpy. Without rclpy this
module still imports (the parser/Arrow layers work standalone) but
constructing a context raises with a clear message.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

from dora_tpu.ros2 import find_interface
from dora_tpu.ros2.arrow_convert import from_arrow, to_arrow


def _require_rclpy():
    try:
        import rclpy  # noqa: F401

        return rclpy
    except ImportError as e:
        raise RuntimeError(
            "the ROS2 bridge transport requires rclpy (source a ROS2 "
            "installation); the message parser and Arrow conversion work "
            "without it"
        ) from e


class Ros2Context:
    """Owns the rclpy init + a background spin thread."""

    def __init__(self, args=None):
        self._rclpy = _require_rclpy()
        self._rclpy.init(args=args)
        self._nodes: list[Any] = []

    def node(self, name: str, namespace: str = "/") -> "Ros2Node":
        node = Ros2Node(self, name, namespace)
        self._nodes.append(node)
        return node

    def close(self) -> None:
        for node in self._nodes:
            node.close()
        self._rclpy.shutdown()


class Ros2Node:
    def __init__(self, context: Ros2Context, name: str, namespace: str):
        rclpy = context._rclpy
        self._node = rclpy.create_node(name, namespace=namespace)
        self._executor = rclpy.executors.SingleThreadedExecutor()
        self._executor.add_node(self._node)
        self._thread = threading.Thread(target=self._executor.spin, daemon=True)
        self._thread.start()

    def publisher(self, topic: str, msg_type: str, qos_depth: int = 10):
        msg_cls = _import_msg(msg_type)
        pub = self._node.create_publisher(msg_cls, topic, qos_depth)
        spec = find_interface(msg_type)

        class _Publisher:
            def publish(self, value):
                """value: dict, or an Arrow struct array of one element."""
                import pyarrow as pa

                if isinstance(value, pa.Array):
                    value = from_arrow(value)[0]
                msg = msg_cls()
                for k, v in value.items():
                    setattr(msg, k, v)
                pub.publish(msg)

        return _Publisher()

    def subscription(self, topic: str, msg_type: str, qos_depth: int = 10):
        """A subscription whose ``recv``/queue yields Arrow struct arrays —
        merge it into a dora node loop."""
        msg_cls = _import_msg(msg_type)
        spec = find_interface(msg_type)
        out: queue.Queue = queue.Queue()

        def on_msg(msg):
            value = {f.name: getattr(msg, f.name) for f in spec.fields}
            out.put(to_arrow([value], spec, resolve=find_interface))

        self._node.create_subscription(msg_cls, topic, on_msg, qos_depth)

        class _Subscription:
            queue = out

            def recv(self, timeout: float | None = None):
                try:
                    return out.get(timeout=timeout)
                except queue.Empty:
                    return None

        return _Subscription()

    def close(self) -> None:
        self._executor.shutdown()


def _import_msg(full_name: str):
    """'std_msgs/String' -> the rclpy message class."""
    package, _, name = full_name.partition("/")
    module = __import__(f"{package}.msg", fromlist=[name])
    return getattr(module, name)
