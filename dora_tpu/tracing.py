"""Trace plane: cluster-wide timeline assembly and Chrome-trace export.

The raw material is the flight-recorder rings (telemetry.FlightRecorder):
every process records per-message span events — ``t_send`` (node publish),
``t_route`` (daemon route, decoded or fastroute wire path), ``t_deliver``
(daemon -> receiver queue delivery), ``t_recv`` (event-stream receive) —
each slot carrying both ``monotonic_ns`` and the HLC wall clock. Nodes
stream ring growth to their daemon (node_to_daemon.ReportTrace); the
coordinator fans ``TraceRequest`` out to every machine and merges the
per-machine snapshots here.

Clock alignment: monotonic clocks have per-process epochs, so cross-process
ordering uses the wall stamps. Each daemon snapshot carries a
``(wall_ns, hlc_ns)`` pair captured back to back; the HLC physical
component advances to the maximum clock observed anywhere in the cluster
(clock.py), so ``hlc_ns - wall_ns`` is that machine's offset from the
cluster's shared timeline and adding it aligns every machine's wall stamps
onto one axis.

Export is the Chrome trace event format (the ``traceEvents`` JSON that
Perfetto and chrome://tracing load): one ``pid`` per (machine, process)
track, ``ph:"X"`` complete spans for the per-message records (linked by
the W3C trace id in ``args``), ``ph:"i"`` instants for drops, coalesce
flushes, and fastroute fallbacks.
"""

from __future__ import annotations

from typing import Any

from dora_tpu.telemetry import trace_id_of

# FlightRecorder slot indices (see telemetry.FlightRecorder docstring).
MONO, WALL, KIND, A, B, C = range(6)

#: Trace-plane span kinds -> Chrome-trace span name prefix. ``b`` holds
#: the serialized trace context (t_deliver has none — the daemon doesn't
#: decode metadata on the wire path at delivery time), ``c`` the span
#: duration in ns.
SPAN_KINDS = {
    "t_send": "send",
    "t_route": "route",
    "t_deliver": "deliver",
    "t_recv": "recv",
}

#: Serving-engine request-lifecycle span kinds (telemetry.ServingTracer
#: + models/batch_engine) -> span name prefix. Same slot discipline as
#: SPAN_KINDS (``b`` = per-request trace context, ``c`` = dur ns) but
#: exported on the per-process ENGINE track (tid 1) in cat "serving":
#: queued(backlog wait) → admitted(page grant) → prefill_chunk[i] →
#: decode_window[j] → finish(reason).
SERVING_SPAN_KINDS = {
    "s_queued": "queued",
    "s_admitted": "admitted",
    "s_prefill_chunk": "prefill_chunk",
    "s_decode_window": "decode_window",
    "s_finish": "finish",
    # Elastic recovery: checkpoint write / restore-on-respawn, and the
    # two halves of a drain-and-migrate handoff. migrate_out/migrate_in
    # share the request's trace context, so a migrated stream shows ONE
    # contiguous trace id across both engines' tracks.
    "s_checkpoint": "checkpoint",
    "s_restore": "restore",
    "s_migrate_out": "migrate_out",
    "s_migrate_in": "migrate_in",
    # Traffic shaping: a lower-class stream evicted by page preemption
    # (its grant freed for a higher-class request) and its later
    # re-admission (recompute-on-resume). Both carry the stream's trace
    # context, so a preempted request shows one contiguous chain:
    # … decode_window → preempt → queued → resume → prefill_chunk …
    "s_preempt": "preempt",
    "s_resume": "resume",
    # Shared-prefix cache: admission mapped cached KV pages into the new
    # stream's block table (prefill starts at the divergence point).
    # Emitted just before s_admitted, with the same trace context.
    "s_prefix_hit": "prefix_hit",
    # Device-time attribution (dora_tpu.profiling): each fused window /
    # final prefill chunk splits its wall time into host-dispatch →
    # device-compute → device-fetch child spans, emitted per boundary
    # (keyed "window"/"chunk", no request context — one dispatch serves
    # every stream). Retires the round-4 tunnel-vs-compute guesswork:
    # the drift is now measured, not inferred.
    "s_dev_dispatch": "dev_dispatch",
    "s_dev_compute": "dev_compute",
    "s_dev_fetch": "dev_fetch",
}

#: Hot-path flight events surfaced as instants (everything else recorded
#: in the ring also exports as an instant, generically named).
INSTANT_NAMES = {
    "drop_oldest": "drop oldest",
    "coalesce_flush": "coalesce flush",
    "fastroute_fallback": "fastroute fallback",
    "s_reject": "admission reject",
    "s_page_wait": "page wait",
    "xla_compile": "xla compile",
    "trace_truncated": "trace truncated",
    "node_respawn": "node respawn",
    "replay_inputs": "replay inputs",
    "daemon_reconnect": "daemon reconnect",
    "slo_violation": "SLO violation",
    "s_shed": "load shed",
    "k_retune": "window retune",
    "alert_pending": "alert pending",
    "alert_firing": "alert firing",
    "alert_resolved": "alert resolved",
    "fleet_digest": "fleet digest",
}

#: Instants that belong on the engine track and may carry a request
#: trace context in ``b`` (linked into the lifecycle chain by args).
_ENGINE_INSTANTS = {"s_reject", "s_page_wait", "xla_compile", "s_shed",
                    "k_retune"}

#: Chrome-trace tid of the serving-engine track within a process (tid 0
#: is the message plane).
ENGINE_TID = 1

_VALID_PH = {"X", "i", "M"}
_VALID_SCOPES = {"g", "p", "t"}
_VALID_SPAN_CATS = {"message", "serving"}


def merge_trace_snapshots(snapshots: list[dict | None]) -> dict:
    """Merge per-machine daemon snapshots onto one clock-aligned timeline.

    Each snapshot is ``Daemon.trace_snapshot`` output::

        {"machine": str, "wall_ns": int, "hlc_ns": int,
         "processes": {process_name: [[mono, wall, kind, a, b, c], ...]},
         "dropped_events": {process_name: int}}   # optional

    Returns ``{"processes": [{"machine", "process", "events",
    "dropped_events"}, ...]}`` with every event's wall stamp shifted by
    that machine's ``hlc_ns - wall_ns`` offset onto the cluster HLC
    timeline. ``dropped_events`` (events the daemon's per-node buffer
    cap trimmed before this snapshot; ring-level drops ride along as
    ``trace_truncated`` events) is carried per process so the export
    can mark truncated tracks.
    """
    processes: list[dict] = []
    for snap in snapshots:
        if not snap or not snap.get("processes"):
            continue
        offset = int(snap.get("hlc_ns", 0)) - int(snap.get("wall_ns", 0))
        machine = str(snap.get("machine", "?"))
        dropped = snap.get("dropped_events") or {}
        for process, events in sorted(snap["processes"].items()):
            aligned = []
            for e in events:
                if len(e) < 6 or not e[KIND]:
                    continue  # torn/foreign slot shipped by an old node
                e = list(e)
                e[WALL] = int(e[WALL]) + offset
                aligned.append(e)
            aligned.sort(key=lambda e: e[WALL])
            processes.append(
                {
                    "machine": machine,
                    "process": process,
                    "events": aligned,
                    "dropped_events": int(dropped.get(process, 0)),
                }
            )
    processes.sort(key=lambda p: (p["machine"], p["process"]))
    return {"processes": processes}


def _span_args(ctx) -> dict:
    args: dict[str, Any] = {}
    if ctx:
        args["ctx"] = str(ctx)
        trace_id = trace_id_of(str(ctx))
        if trace_id:
            args["trace_id"] = trace_id
    return args


def to_chrome_trace(merged: dict) -> dict:
    """Chrome trace event JSON (Perfetto-loadable) from a merged trace.

    One pid per (machine, process) with an ``M`` process_name record; a
    ``ph:"X"`` complete span per message-plane record whose ``ts`` is the
    span start (wall stamp is taken at record time = span end, so start =
    wall - dur); ``ph:"i"`` instants for everything else. Serving-engine
    lifecycle records (SERVING_SPAN_KINDS + engine instants) land on a
    separate ENGINE track (tid 1, named via a thread_name meta) inside
    the same process pid, cat "serving", so Perfetto shows the request
    chain under the process that served it. A process whose events were
    truncated (daemon buffer cap, ``dropped_events`` from the merge)
    opens with a ``trace truncated`` instant. Timestamps are
    microseconds (floats), rebased to the earliest event so Perfetto's
    axis starts near zero.
    """
    events: list[dict] = []
    processes = merged.get("processes", [])
    base_ns = min(
        (e[WALL] for p in processes for e in p["events"]), default=0
    )
    for pid, proc in enumerate(processes, start=1):
        machine = proc["machine"]
        track = f"{machine}/{proc['process']}" if machine else proc["process"]
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": track},
            }
        )
        if any(
            e[KIND] in SERVING_SPAN_KINDS or e[KIND] in _ENGINE_INSTANTS
            for e in proc["events"]
        ):
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": ENGINE_TID,
                    "args": {"name": "engine"},
                }
            )
        dropped = int(proc.get("dropped_events", 0) or 0)
        if dropped > 0:
            first_us = (
                (proc["events"][0][WALL] - base_ns) / 1000.0
                if proc["events"]
                else 0.0
            )
            events.append(
                {
                    "name": f"trace truncated ({dropped} events lost)",
                    "ph": "i",
                    "ts": max(0.0, first_us),
                    "pid": pid,
                    "tid": 0,
                    "s": "p",
                    "cat": "flight",
                }
            )
        for e in proc["events"]:
            kind = e[KIND]
            wall_us = (e[WALL] - base_ns) / 1000.0
            if kind in SPAN_KINDS or kind in SERVING_SPAN_KINDS:
                serving = kind in SERVING_SPAN_KINDS
                name = (SERVING_SPAN_KINDS if serving else SPAN_KINDS)[kind]
                dur_us = max(0, int(e[C] or 0)) / 1000.0
                events.append(
                    {
                        "name": f"{name} {e[A]}",
                        "ph": "X",
                        "ts": max(0.0, wall_us - dur_us),
                        "dur": dur_us,
                        "pid": pid,
                        "tid": ENGINE_TID if serving else 0,
                        "cat": "serving" if serving else "message",
                        "args": _span_args(e[B]),
                    }
                )
            else:
                name = INSTANT_NAMES.get(kind, kind)
                if kind in _ENGINE_INSTANTS:
                    # Engine instants carry the request context in b:
                    # link them into the lifecycle chain, not the label.
                    extra = str(e[A]) if e[A] is not None else ""
                    ev = {
                        "name": f"{name} {extra}".rstrip(),
                        "ph": "i",
                        "ts": max(0.0, wall_us),
                        "pid": pid,
                        "tid": ENGINE_TID,
                        "s": "p",
                        "cat": "serving",
                    }
                    args = _span_args(e[B])
                    if args:
                        ev["args"] = args
                    events.append(ev)
                    continue
                extra = " ".join(str(x) for x in (e[A], e[B]) if x is not None)
                events.append(
                    {
                        "name": f"{name} {extra}".rstrip(),
                        "ph": "i",
                        "ts": max(0.0, wall_us),
                        "pid": pid,
                        "tid": 0,
                        "s": "p",
                        "cat": "flight",
                    }
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(trace: Any) -> list[str]:
    """Schema self-check for the exporter: every field Perfetto relies on
    is present and well-typed. Returns a list of problems (empty = OK) —
    wired into tier-1 and ``dora-tpu trace --check`` so a malformed field
    fails the suite, not the user's Perfetto session."""
    errors: list[str] = []
    if not isinstance(trace, dict):
        return ["trace is not an object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: name missing or not a string")
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            errors.append(f"{where}: ph {ph!r} not one of {sorted(_VALID_PH)}")
            continue
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int) or isinstance(ev.get(key), bool):
                errors.append(f"{where}: {key} missing or not an int")
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool) or ts < 0:
            errors.append(f"{where}: ts missing, non-numeric, or negative")
        if ph == "X":
            dur = ev.get("dur")
            if (
                not isinstance(dur, (int, float))
                or isinstance(dur, bool)
                or dur < 0
            ):
                errors.append(f"{where}: dur missing, non-numeric, or negative")
            cat = ev.get("cat")
            if cat not in _VALID_SPAN_CATS:
                errors.append(
                    f"{where}: span cat {cat!r} not one of "
                    f"{sorted(_VALID_SPAN_CATS)}"
                )
            elif cat == "serving":
                # Engine lifecycle spans: engine track, known taxonomy.
                if ev.get("tid") != ENGINE_TID:
                    errors.append(
                        f"{where}: serving span on tid {ev.get('tid')!r}, "
                        f"expected engine tid {ENGINE_TID}"
                    )
                prefix = str(ev.get("name", "")).split(" ", 1)[0]
                if prefix not in SERVING_SPAN_KINDS.values():
                    errors.append(
                        f"{where}: serving span name {ev.get('name')!r} "
                        "outside the lifecycle taxonomy"
                    )
        if ph == "i" and ev.get("s") not in _VALID_SCOPES:
            errors.append(f"{where}: instant scope s {ev.get('s')!r} invalid")
    return errors


def _sample_snapshots() -> list[dict]:
    """Two synthetic machine snapshots with deliberate clock skew — the
    offline input for :func:`self_check`. Machine B also hosts a
    serving process with a full request-lifecycle chain (one request
    context), an engine instant, a ring ``trace_truncated`` event, and
    a daemon-side ``dropped_events`` count, so the self-check covers
    the engine track end to end."""
    ctx = "traceparent:00-000102030405060708090a0b0c0d0e0f-0001020304050607-01;"
    base = 1_700_000_000_000_000_000
    # Machine A's wall clock lags the cluster HLC by 5 ms.
    a = {
        "machine": "A",
        "wall_ns": base,
        "hlc_ns": base + 5_000_000,
        "processes": {
            "(daemon)": [
                [10, base + 1_200_000, "t_route", "sender/data", ctx, 150_000],
                [11, base + 1_500_000, "t_deliver", "receiver/in", None, 400_000],
                [12, base + 1_600_000, "drop_oldest", "receiver/in", 3, None],
                # Alert engine transitions land on the daemon track
                # (dora_tpu.alerts via Daemon.sample_history).
                [13, base + 1_700_000, "alert_pending",
                 "queue-depth:receiver/in", "value=300 threshold=256", None],
                [14, base + 1_800_000, "alert_firing",
                 "queue-depth:receiver/in", "value=310 threshold=256", None],
            ],
            "sender": [
                [20, base + 1_000_000, "t_send", "data", ctx, 90_000],
                [21, base + 1_050_000, "coalesce_flush", 4, 4096, None],
            ],
        },
    }
    # Machine B's wall clock runs 2 ms ahead of the cluster HLC. The
    # serving chain shares the message chain's trace id (the tracer
    # derives the request context from the delivered message).
    rctx = "traceparent:00-000102030405060708090a0b0c0d0e0f-1111020304050607-01;"
    b = {
        "machine": "B",
        "wall_ns": base + 2_000_000,
        "hlc_ns": base,
        "processes": {
            # Raw wall base+8.5ms = cluster base+6.5ms — after the sender's
            # aligned base+6ms even though A's raw stamps lag B's.
            "receiver": [
                [30, base + 8_500_000, "t_recv", "in", ctx, 0],
                [31, base + 8_600_000, "fastroute_fallback", "decode", None, None],
            ],
            "llm": [
                [40, base + 8_700_000, "trace_truncated", 17, None, None],
                [41, base + 8_900_000, "s_queued", "req-1", rctx, 100_000],
                [52, base + 8_990_000, "s_prefix_hit", "req-1 tokens=16/24 pages=2", rctx, 0],
                [42, base + 9_000_000, "s_admitted", "req-1 pages=2 shared=2", rctx, 20_000],
                [43, base + 9_300_000, "s_prefill_chunk", "req-1 base=0", rctx, 200_000],
                [53, base + 9_500_000, "s_dev_dispatch", "window", rctx, 30_000],
                [54, base + 9_700_000, "s_dev_compute", "window", rctx, 180_000],
                [55, base + 9_750_000, "s_dev_fetch", "window", rctx, 40_000],
                [44, base + 9_800_000, "s_decode_window", "req-1 k=8 n=5", rctx, 400_000],
                [45, base + 9_850_000, "xla_compile", "window", None, 3_000_000],
                [48, base + 9_860_000, "s_preempt", "req-1 pages=2", rctx, 0],
                [49, base + 9_880_000, "s_resume", "req-1 emitted=5", rctx, 0],
                [46, base + 9_900_000, "s_finish", "req-1 stop", rctx, 0],
                [47, base + 9_950_000, "s_reject", "req-2 length", None, None],
                [50, base + 9_960_000, "s_shed", "req-4 queue_wait", None, None],
                [51, base + 9_970_000, "k_retune", "K 8->4 spec=0", None, None],
            ],
        },
        "dropped_events": {"llm": 23},
    }
    return [a, b, None]


def self_check() -> list[str]:
    """Offline end-to-end check of merge + export + schema: build sample
    snapshots (with clock skew), merge, export, validate — plus a few
    semantic assertions the schema validator can't express. Returns
    problems (empty = OK)."""
    merged = merge_trace_snapshots(_sample_snapshots())
    errors = validate_chrome_trace(to_chrome_trace(merged))
    tracks = {(p["machine"], p["process"]) for p in merged["processes"]}
    if len(tracks) != 4:
        errors.append(f"expected 4 process tracks, got {sorted(tracks)}")
    # Clock alignment: B's recv must land after A's send on the merged
    # axis even though B's raw wall clock ran ahead.
    walls = {
        (p["process"], e[KIND]): e[WALL]
        for p in merged["processes"]
        for e in p["events"]
    }
    send = walls.get(("sender", "t_send"))
    recv = walls.get(("receiver", "t_recv"))
    if send is None or recv is None or recv <= send:
        errors.append(f"alignment broken: send={send} recv={recv}")
    trace = to_chrome_trace(merged)
    ids = {
        ev["args"].get("trace_id")
        for ev in trace["traceEvents"]
        if ev["ph"] == "X" and ev.get("args", {}).get("trace_id")
    }
    if len(ids) != 1:
        errors.append(f"expected one linked trace id, got {ids}")
    # Engine track: the request-lifecycle chain must export in order on
    # tid 1 with its thread_name meta, linked by the same trace id as
    # the message chain that carried the request in.
    engine_spans = [
        ev for ev in trace["traceEvents"]
        if ev["ph"] == "X" and ev.get("cat") == "serving"
    ]
    chain = [ev["name"].split(" ", 1)[0] for ev in engine_spans]
    want = ["queued", "prefix_hit", "admitted", "prefill_chunk",
            "dev_dispatch", "dev_compute", "dev_fetch",
            "decode_window", "preempt", "resume", "finish"]
    if chain != want:
        errors.append(f"lifecycle chain broken: {chain}")
    if any(ev.get("args", {}).get("trace_id") not in ids for ev in engine_spans):
        errors.append("serving spans not linked to the message trace id")
    metas = {
        (ev["pid"], ev["tid"]): ev["args"]["name"]
        for ev in trace["traceEvents"]
        if ev["ph"] == "M" and ev["name"] == "thread_name"
    }
    if "engine" not in metas.values():
        errors.append("engine thread_name meta missing")
    truncated = [
        ev["name"] for ev in trace["traceEvents"]
        if ev["ph"] == "i" and ev["name"].startswith("trace truncated")
    ]
    # One from the ring-shipped event, one from the daemon-cap count.
    if len(truncated) != 2:
        errors.append(f"expected 2 trace-truncated instants, got {truncated}")
    return errors
