"""Daemon ⇄ daemon data-plane forwarding (multi-machine dataflows).

Reference parity: binaries/daemon/src/inter_daemon.rs — persistent lazy TCP
connections, length-prefixed frames; shared memory never crosses machines
(payloads are copied out before forwarding, daemon/src/lib.rs:1361-1376).
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from dora_tpu.message import coordinator as cm
from dora_tpu.message.serde import decode_timestamped, encode_timestamped
from dora_tpu.transport.framing import (
    ConnectionClosed,
    recv_frame_async,
    send_frame_async,
)

if TYPE_CHECKING:
    from dora_tpu.daemon.core import Daemon

logger = logging.getLogger(__name__)


async def start_server(daemon: "Daemon", port: int = 0) -> tuple[asyncio.AbstractServer, int]:
    """Listen for events from other machines' daemons."""

    async def on_peer(reader, writer):
        try:
            while True:
                frame = await recv_frame_async(reader)
                event = decode_timestamped(frame, daemon.clock).inner
                df = daemon.dataflows.get(getattr(event, "dataflow_id", None))
                if df is None:
                    continue
                if isinstance(event, cm.InterDaemonOutput):
                    daemon.deliver_remote_output(
                        df, event.output_id, event.metadata, event.data
                    )
                elif isinstance(event, cm.InterDaemonInputsClosed):
                    daemon.close_remote_inputs(df, event.inputs)
        except (ConnectionClosed, ConnectionError):
            pass
        except Exception:
            logger.exception("inter-daemon connection failed")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(on_peer, host="0.0.0.0", port=port)
    return server, server.sockets[0].getsockname()[1]


class InterDaemonClient:
    """Lazy persistent connections to peer daemons, keyed by address."""

    def __init__(self, clock):
        self._clock = clock
        self._writers: dict[str, asyncio.StreamWriter] = {}
        self._locks: dict[str, asyncio.Lock] = {}

    async def send(self, addr: str, event) -> None:
        lock = self._locks.setdefault(addr, asyncio.Lock())
        async with lock:
            writer = self._writers.get(addr)
            payload = encode_timestamped(event, self._clock)
            for attempt in (1, 2):
                if writer is None:
                    host, _, port = addr.rpartition(":")
                    _, writer = await asyncio.open_connection(host, int(port))
                    self._writers[addr] = writer
                try:
                    await send_frame_async(writer, payload)
                    return
                except (ConnectionError, ConnectionClosed):
                    self._writers.pop(addr, None)
                    writer = None
                    if attempt == 2:
                        raise

    def close(self) -> None:
        for writer in self._writers.values():
            try:
                writer.close()
            except Exception:
                pass
        self._writers.clear()
