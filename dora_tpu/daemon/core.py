"""The daemon: spawn dataflows, route messages, own timers and buffers.

Reference parity: binaries/daemon/src/lib.rs — per-machine data plane with
a start barrier (pending.rs), output routing with bounded per-input queues,
shared-memory drop-token lifecycle (§2.8 of SURVEY.md), stop with grace
kill, and failure classification (grace_duration / cascading / other).

Two modes, like the reference (lib.rs:93-224):
  * attached: `Daemon.run(coordinator_addr, machine_id)` — register with a
    coordinator, serve Spawn/Stop/… events (dora_tpu.daemon.coordinator_conn);
  * standalone: `run_dataflow(descriptor)` — run one dataflow to completion
    in-process (CLI `dora daemon --run-dataflow`, tests, examples).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from dora_tpu import PROTOCOL_VERSION
from dora_tpu.clock import HLC
from dora_tpu.core.config import TimerMapping, UserMapping
from dora_tpu.core.descriptor import CustomNode, Descriptor, new_dataflow_uuid
from dora_tpu.daemon import spawn as spawn_mod
from dora_tpu.daemon.connection import (
    NodeConnection,
    ShmemConnection,
    serve_stream,
)
from dora_tpu.transport.framing import ConnectionClosed
from dora_tpu.daemon.queues import DropQueue, NodeEventQueue, QueueEntry
from dora_tpu.daemon.replay_buffer import ReplayBuffer
from dora_tpu.ids import DataId, InputId, NodeId, OutputId
from dora_tpu.message import daemon_to_node as d2n
from dora_tpu.message import node_to_daemon as n2d
from dora_tpu.message.common import (
    InlineData,
    Metadata,
    NodeError,
    NodeErrorCause,
    NodeExitStatus,
    NodeResult,
    DataflowResult,
    SharedMemoryData,
    TypeInfo,
    ENCODING_RAW,
)
from dora_tpu.message import fastroute
from dora_tpu import fleet
from dora_tpu.alerts import AlertEngine, engine_for
from dora_tpu.metrics import DataflowMetrics
from dora_tpu.metrics_history import MetricsHistoryRing, history_interval_s
from dora_tpu.telemetry import FLIGHT, OTEL_CTX_KEY, TRACING
from dora_tpu.message.serde import (
    Timestamped,
    decode_timestamped,
    encode,
    encode_timestamped,
)
from dora_tpu.native import ShmemChannel, ShmemRegion

logger = logging.getLogger(__name__)

#: Default stop grace period before leftover nodes are killed
#: (reference: binaries/daemon/src/lib.rs:1616).
DEFAULT_GRACE_S = 15.0

#: Control-channel shmem capacity. Payloads ≥ the zero-copy threshold travel
#: in their own regions; the channel only carries control messages and
#: inline payloads.
SHMEM_CHANNEL_CAPACITY = 1 << 20

#: Trace plane: cap on buffered ReportTrace events per node (oldest
#: dropped first — same recency-wins policy as the ring itself).
MAX_NODE_TRACE_EVENTS = 20_000


def _extend_trace_buffer(df, node_id: str, events: list) -> None:
    """Append a node's ReportTrace chunk to its bounded daemon-side
    buffer. Trimming is COUNTED (``node_trace_drops``), not silent: the
    count rides the trace snapshot so QueryTrace replies and the Chrome
    export can say how many events this second truncation point lost
    (the ring's own wrap losses are already ``trace_truncated`` events
    inside the stream)."""
    buf = df.node_traces.setdefault(node_id, [])
    buf.extend(events)
    if len(buf) > MAX_NODE_TRACE_EVENTS:
        trim = len(buf) - MAX_NODE_TRACE_EVENTS
        df.node_trace_drops[node_id] = (
            df.node_trace_drops.get(node_id, 0) + trim
        )
        del buf[:trim]


@dataclass
class TokenState:
    """One shared-memory region in flight: who owns it, how many receivers
    still reference it."""

    owner: str  # node id
    pending: int = 0


@dataclass
class RunningNode:
    node_id: str
    process: Any = None  # asyncio.subprocess.Process | None (dynamic)
    finished: bool = False
    dynamic: bool = False


@dataclass
class DataflowState:
    id: str
    descriptor: Descriptor
    working_dir: Path
    local_nodes: set[str]  # node ids this machine runs
    #: OutputId -> receiver InputIds (local and remote alike)
    mappings: dict[OutputId, set[InputId]] = field(default_factory=dict)
    open_outputs: set[OutputId] = field(default_factory=set)
    #: receiver node -> its user (non-timer) inputs that are still open
    open_inputs: dict[str, set[str]] = field(default_factory=dict)
    #: interval_ns -> receiver InputIds
    timers: dict[int, set[InputId]] = field(default_factory=dict)
    timer_tasks: list[asyncio.Task] = field(default_factory=list)
    queues: dict[str, NodeEventQueue] = field(default_factory=dict)
    drop_queues: dict[str, DropQueue] = field(default_factory=dict)
    #: shmem drop tokens still referenced by receivers
    tokens: dict[str, TokenState] = field(default_factory=dict)
    #: per-receiver tokens delivered in a NextEvents batch but not yet acked
    delivered_tokens: dict[str, set[str]] = field(default_factory=dict)
    #: (sender, output_id) -> (OutputId, [(receiver node, input id)]) —
    #: the wire fast path's view of ``mappings`` with the id parsing and
    #: stringification done once (mappings are fixed after spawn; the
    #: mutable open_outputs/open_inputs/p2p_edges are re-checked per send)
    route_cache: dict[tuple[str, str], Any] = field(default_factory=dict)
    running_nodes: dict[str, RunningNode] = field(default_factory=dict)
    node_results: dict[str, NodeResult] = field(default_factory=dict)
    stderr_rings: dict[str, list[str]] = field(default_factory=dict)
    #: start barrier
    pending_nodes: set[str] = field(default_factory=set)
    started: asyncio.Event = field(default_factory=asyncio.Event)
    barrier_error: str | None = None
    #: node whose pre-subscribe exit poisoned the barrier (structured
    #: cascading-cause attribution; never recovered from the message text)
    barrier_failed_node: str | None = None
    #: failure bookkeeping
    failed_nodes: list[str] = field(default_factory=list)
    grace_kills: set[str] = field(default_factory=set)
    stop_sent: bool = False
    done: asyncio.Future = field(default_factory=lambda: asyncio.get_event_loop().create_future())
    #: regions this daemon mapped for routing (closed on finish)
    mapped_regions: dict[str, ShmemRegion] = field(default_factory=dict)
    #: shmem node-channel connections created for this dataflow
    shmem_conns: list[Any] = field(default_factory=list)
    #: multi-machine: machine id -> daemon listen addr (inter-daemon data)
    machine_listen_ports: dict[str, str] = field(default_factory=dict)
    #: node id -> set when its control-channel connection has fully drained;
    #: exit handling waits on this so in-flight SendMessages are not lost
    control_done: dict[str, asyncio.Event] = field(default_factory=dict)
    #: peer-to-peer: node -> {input_id: shmem channel name} announced
    #: pre-barrier (the announcement marks sender capability too)
    p2p_listeners: dict[str, dict[str, str]] = field(default_factory=dict)
    #: edges assigned p2p at barrier release; send_out skips these
    #: (sender, output, receiver, input)
    p2p_edges: set = field(default_factory=set)
    #: hot-path counters + latency histograms (dora_tpu.metrics)
    metrics: DataflowMetrics = field(default_factory=DataflowMetrics)
    #: trace plane: node id -> flight-recorder events the node shipped
    #: via ReportTrace (bounded; see MAX_NODE_TRACE_EVENTS)
    node_traces: dict[str, list] = field(default_factory=dict)
    #: trace plane: node id -> events the daemon-side cap trimmed away
    #: (the ring's own wrap losses arrive as trace_truncated events;
    #: this counts the second truncation point, the buffer here)
    node_trace_drops: dict[str, int] = field(default_factory=dict)
    #: serving plane: node id -> latest ServingMetrics snapshot the node
    #: shipped via ReportServing (latest-wins; snapshots are cumulative)
    node_serving: dict[str, dict] = field(default_factory=dict)
    #: fleet plane: node id -> {"digest": dict, "recv_wall_ns": int} —
    #: the latest EngineStateDigest shipped via ReportEngineState with
    #: its receive stamp (digest age is measured from the stamp, so a
    #: wedged exporter shows as a growing age, not silence)
    node_fleet: dict[str, dict] = field(default_factory=dict)
    #: elastic recovery: node id -> respawn attempts consumed so far
    respawn_attempts: dict[str, int] = field(default_factory=dict)
    #: nodes between death and respawn — the finish check treats them
    #: as still running, so the dataflow cannot conclude under them
    respawning: set[str] = field(default_factory=set)
    #: node id -> un-acked delivered-input window, redelivered on
    #: respawn (nodes with a ``restart`` policy only)
    replay_buffers: dict[str, ReplayBuffer] = field(default_factory=dict)
    #: node id -> the asyncio task consuming its event queue. Respawn
    #: cancels the dead incarnation's task BEFORE replaying: a loop
    #: parked in next_batch cannot see its socket die, and waking it
    #: with the replayed entries would hand them to a dead connection.
    event_tasks: dict[str, asyncio.Task] = field(default_factory=dict)
    #: metrics time series: bounded ring of delta-encoded samples
    #: (dora_tpu.metrics_history; None when DORA_METRICS_HISTORY_S <= 0).
    #: Retained after finish so QueryMetricsHistory covers archived runs.
    history: MetricsHistoryRing | None = None
    #: the sampler task feeding ``history`` (cancelled on finish)
    history_task: asyncio.Task | None = None
    #: alerting plane: rules engine evaluated on the sampler tick over
    #: ``history`` (dora_tpu.alerts; None when history is off or
    #: DORA_ALERTS=0). Retained after finish like the ring, so
    #: QueryAlerts covers archived runs.
    alerts: AlertEngine | None = None
    #: structured log severity: node id -> [error lines, warn lines]
    #: counted by on_node_log from the parsed level prefixes
    log_counts: dict[str, list[int]] = field(default_factory=dict)

    def node_machine(self, node_id: str) -> str:
        return self.descriptor.node(node_id).deploy.machine or ""


class Daemon:
    """One data-plane daemon (per machine)."""

    def __init__(
        self,
        machine_id: str = "",
        local_comm: str = "tcp",
        uds_dir: str | None = None,
    ):
        self.machine_id = machine_id
        self.local_comm = local_comm
        self.uds_dir = uds_dir
        # Re-read the flight-recorder/tracing env knobs: the daemon may
        # be constructed long after module import (bench A/B legs, tests).
        FLIGHT.configure_from_env()
        TRACING.configure_from_env()
        self.clock = HLC()
        self.dataflows: dict[str, DataflowState] = {}
        self._server: asyncio.AbstractServer | None = None
        self._server_addr: str | None = None
        self._dynamic_server: asyncio.AbstractServer | None = None
        self.dynamic_port: int | None = None
        #: hook for attached mode: send InterDaemonEvent to another machine
        self.inter_daemon_send: Callable[..., Any] | None = None
        #: hook for attached mode: notify coordinator (ReadyOnMachine, logs, …)
        self.coordinator_notify: Callable[..., Any] | None = None
        #: optional sink for log lines (LogSubscribe streaming)
        self.log_sink: Callable[..., Any] | None = None
        #: hook for attached mode: forward a node's finished deep-capture
        #: artifact (n2d.ReportProfile) to the coordinator's waiting
        #: StartProfile/StopProfile reply
        self.profile_sink: Callable[..., Any] | None = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self, dynamic_port: int | None = 0) -> None:
        """Start the node-channel accept loop (tcp/uds) and the dynamic-node
        bootstrap listener."""
        if self.local_comm == "uds":
            import tempfile

            d = self.uds_dir or tempfile.mkdtemp(prefix="dora-tpu-")
            path = str(Path(d) / f"daemon-{id(self):x}.sock")
            self._server, self._server_addr = await serve_stream(
                self._handle_connection, uds_path=path
            )
        else:
            self._server, self._server_addr = await serve_stream(
                self._handle_connection
            )
        if dynamic_port is not None:
            self._dynamic_server = await asyncio.start_server(
                self._handle_dynamic_client, host="127.0.0.1", port=dynamic_port
            )
            self.dynamic_port = self._dynamic_server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        for server in (self._server, self._dynamic_server):
            if server is not None:
                server.close()
                try:
                    await server.wait_closed()
                except Exception:
                    pass
        for df in list(self.dataflows.values()):
            for t in df.timer_tasks:
                t.cancel()
            if df.history_task is not None:
                df.history_task.cancel()
                df.history_task = None
            # Teardown reaper: node processes must never outlive the
            # daemon (an aborted/timed-out dataflow otherwise leaks
            # wedged nodes holding mapped shmem — observed as orphaned
            # checker.py processes in round 2). The graceful path
            # (stop_dataflow + grace kill) has already run by the time a
            # healthy dataflow gets here, so these are stragglers: kill.
            self._kill_stragglers(df)
            self._close_shmem_conns(df)
            for region in df.mapped_regions.values():
                try:
                    region.close(unlink=False, force=True)
                except Exception:
                    pass

    async def run(
        self,
        coordinator_addr: str,
        machine_id: str = "",
        register_timeout_s: float = 30.0,
    ) -> None:
        """Attached mode: register with a coordinator and serve its events
        until destroyed (reference: Daemon::run, daemon/src/lib.rs:93-155)."""
        from dora_tpu.daemon.coordinator_conn import run_attached

        await run_attached(self, coordinator_addr, machine_id, register_timeout_s)

    # ------------------------------------------------------------------
    # dataflow spawn
    # ------------------------------------------------------------------

    async def spawn_dataflow(
        self,
        descriptor: Descriptor,
        dataflow_id: str | None = None,
        working_dir: str | Path | None = None,
        local_nodes: set[str] | None = None,
        machine_listen_ports: dict[str, str] | None = None,
    ) -> DataflowState:
        """Build routing tables and spawn this machine's (non-dynamic) nodes."""
        dataflow_id = dataflow_id or new_dataflow_uuid()
        working_dir = Path(working_dir or Path.cwd()).resolve()
        if local_nodes is None:
            local_nodes = {
                str(n.id)
                for n in descriptor.nodes
                if (n.deploy.machine or "") == self.machine_id
            }

        df = DataflowState(
            id=dataflow_id,
            descriptor=descriptor,
            working_dir=working_dir,
            local_nodes=local_nodes,
            machine_listen_ports=dict(machine_listen_ports or {}),
        )
        self.dataflows[dataflow_id] = df

        # Metrics history ring + sampler (DORA_METRICS_HISTORY_S <= 0
        # disables). SLO targets come from the descriptor's per-node
        # ``slo:`` blocks; violations flag ring samples and land in the
        # flight recorder as instants on the trace timeline.
        interval = history_interval_s()
        if interval > 0:
            slo_targets = {
                str(n.id): n.slo.as_targets()
                for n in descriptor.nodes
                if n.slo is not None
            }
            df.history = MetricsHistoryRing(
                interval_s=interval, slo_targets=slo_targets
            )
            # Alert engine rides the same cadence: default rule pack
            # merged under the descriptor's ``alerts:`` block, sinks
            # from DORA_ALERT_SINK (dora_tpu.alerts; DORA_ALERTS=0
            # disables evaluation while keeping the ring).
            df.alerts = engine_for(descriptor.alerts, interval_s=interval)
            df.history_task = asyncio.create_task(self._history_sampler(df))

        # Routing tables (reference: daemon/src/lib.rs:628-660).
        for node in descriptor.nodes:
            for output in node.outputs:
                df.open_outputs.add(OutputId(node.id, output))
        for node in descriptor.nodes:
            nid = str(node.id)
            fused_internal = node.fused_internal_inputs()
            for input_id, inp in node.inputs.items():
                if input_id in fused_internal:
                    # Edge between two fused jax operators: an SSA value
                    # inside the node's XLA computation, not a routed input.
                    continue
                target = InputId(node.id, input_id)
                if isinstance(inp.mapping, TimerMapping):
                    df.timers.setdefault(inp.mapping.interval_ns, set()).add(target)
                else:
                    mapping: UserMapping = inp.mapping
                    df.mappings.setdefault(mapping.output_id, set()).add(target)
                    df.open_inputs.setdefault(nid, set()).add(str(input_id))

        # Per-local-node queues + barrier membership.
        for node in descriptor.nodes:
            nid = str(node.id)
            if nid not in local_nodes:
                continue
            queue_sizes = {
                str(iid): inp.queue_size for iid, inp in node.inputs.items()
            }
            df.queues[nid] = NodeEventQueue(
                node_id=nid,
                queue_sizes=queue_sizes,
                on_token_unref=lambda token, df=df: self._unref_token(df, token),
                metrics=df.metrics,
            )
            df.drop_queues[nid] = DropQueue()
            df.control_done[nid] = asyncio.Event()
            if node.restart is not None:
                df.replay_buffers[nid] = ReplayBuffer(
                    nid, spill_dir=working_dir / ".dora-replay" / dataflow_id
                )
            dynamic = isinstance(node.kind, CustomNode) and node.kind.is_dynamic
            df.running_nodes[nid] = RunningNode(node_id=nid, dynamic=dynamic)
            if not dynamic:
                df.pending_nodes.add(nid)

        # Spawn processes.
        for node in descriptor.nodes:
            nid = str(node.id)
            if nid not in local_nodes or df.running_nodes[nid].dynamic:
                continue
            node_config = self._make_node_config(df, nid)
            try:
                process = await spawn_mod.spawn_node(self, df, node, node_config)
            except RuntimeError as e:
                self.handle_node_exit(df, node.id, None, error=str(e))
                continue
            df.running_nodes[nid].process = process

        if not df.pending_nodes:
            self._release_barrier(df)
        return df

    def _make_node_config(self, df: DataflowState, node_id: str) -> d2n.NodeConfig:
        node = df.descriptor.node(node_id)
        run_config = d2n.RunConfig(
            inputs={str(i): inp.queue_size for i, inp in node.inputs.items()},
            outputs=[str(o) for o in node.outputs],
        )
        if self.local_comm == "shmem":
            import uuid as uuid_mod

            # Random component: uuid7 time prefixes repeat across nearby
            # runs, and a crashed run's leaked segments must never collide
            # with a new one (shm_open O_EXCL would fail).
            prefix = f"dtp-{df.id[:8]}-{uuid_mod.uuid4().hex[:8]}-{node_id}"
            comm: Any = d2n.ShmemCommunication(
                control_region_id=f"{prefix}-ctl",
                events_region_id=f"{prefix}-evt",
                drop_region_id=f"{prefix}-drop",
            )
            for name in (comm.control_region_id, comm.events_region_id,
                         comm.drop_region_id):
                channel = ShmemChannel.create(name, SHMEM_CHANNEL_CAPACITY)
                conn = ShmemConnection(channel)
                df.shmem_conns.append(conn)
                asyncio.create_task(self._handle_connection(conn))
        elif self.local_comm == "uds":
            comm = d2n.UnixDomainCommunication(socket_file=self._server_addr)
        else:
            comm = d2n.TcpCommunication(socket_addr=self._server_addr)
        return d2n.NodeConfig(
            dataflow_id=df.id,
            node_id=node_id,
            run_config=run_config,
            daemon_communication=comm,
            dataflow_descriptor=dict(df.descriptor.raw),
            dynamic=df.running_nodes.get(node_id, RunningNode(node_id)).dynamic,
        )

    # ------------------------------------------------------------------
    # start barrier (reference: binaries/daemon/src/pending.rs)
    # ------------------------------------------------------------------

    def _node_subscribed(self, df: DataflowState, node_id: str) -> None:
        if node_id in df.pending_nodes:
            df.pending_nodes.discard(node_id)
            if not df.pending_nodes:
                if self._is_multi_machine(df):
                    # Multi-machine: coordinator aggregates ReadyOnMachine and
                    # broadcasts AllNodesReady (coordinator/src/lib.rs:221-267).
                    self.coordinator_notify("ready", df, [])
                else:
                    self._release_barrier(df)

    def _release_barrier(
        self,
        df: DataflowState,
        error: str | None = None,
        failed_node: str | None = None,
    ) -> None:
        df.barrier_error = error
        df.barrier_failed_node = failed_node
        if error is None:
            self._compute_p2p(df)
        df.started.set()
        if error is None:
            self._start_timers(df)

    def _compute_p2p(self, df: DataflowState) -> None:
        """Assign peer-to-peer edges (TPU-build extension): an edge goes
        direct when both endpoints are local, both announced (python
        clients that will serve/query the channels), the receiver serves
        that input, and the output is produced by the node itself (a
        send_stdout_as output is published by the daemon's stdout pump,
        which must keep routing it). Assigned edges are skipped by
        send_out — the sender publishes into the receiver's channel."""
        import os

        if os.environ.get("DORA_P2P", "1") in ("", "0"):
            return
        for oid, targets in df.mappings.items():
            sender = str(oid.node)
            if sender not in df.local_nodes or sender not in df.p2p_listeners:
                continue
            node = df.descriptor.node(sender)
            if node.send_stdout_as == str(oid.output):
                continue
            for target in targets:
                rnode = str(target.node)
                listeners = df.p2p_listeners.get(rnode)
                if (
                    rnode in df.local_nodes
                    and listeners is not None
                    and str(target.input) in listeners
                    # A restartable receiver's inputs stay daemon-routed:
                    # crash replay needs the daemon to hold the un-acked
                    # in-flight window (ReplayBuffer), and p2p events
                    # bypass it entirely.
                    and df.descriptor.node(rnode).restart is None
                ):
                    df.p2p_edges.add(
                        (sender, str(oid.output), rnode, str(target.input))
                    )

    def _p2p_edges_reply(self, df: DataflowState, node_id: str) -> Any:
        outputs: dict[str, Any] = {}
        for oid, targets in df.mappings.items():
            if str(oid.node) != node_id:
                continue
            edges = []
            daemon_route = False
            for target in targets:
                rnode = str(target.node)
                key = (node_id, str(oid.output), rnode, str(target.input))
                if key in df.p2p_edges:
                    edges.append(
                        d2n.P2PEdge(
                            channel=df.p2p_listeners[rnode][str(target.input)],
                            input_id=str(target.input),
                            receiver=rnode,
                        )
                    )
                else:
                    daemon_route = True
            if edges:
                outputs[str(oid.output)] = d2n.P2POutput(
                    edges=edges, daemon_route=daemon_route
                )
        return d2n.P2PEdgesReply(outputs=outputs)

    def release_barrier(self, df: DataflowState) -> None:
        """Coordinator broadcast AllNodesReady: release the start barrier."""
        if not df.started.is_set():
            self._release_barrier(df)

    def _is_multi_machine(self, df: DataflowState) -> bool:
        return self.coordinator_notify is not None and len(df.descriptor.machines()) > 1

    def poison_barrier(self, df: DataflowState, failed_node: str) -> None:
        """A node exited before subscribing: fail the whole start barrier
        (reference: pending.rs:160-190)."""
        if not df.started.is_set():
            self._release_barrier(
                df,
                error=f"node {failed_node!r} exited before subscribing",
                failed_node=failed_node,
            )

    # ------------------------------------------------------------------
    # timers (reference: daemon/src/lib.rs:1539-1592)
    # ------------------------------------------------------------------

    def _start_timers(self, df: DataflowState) -> None:
        for interval_ns, targets in df.timers.items():
            df.timer_tasks.append(
                asyncio.create_task(self._timer_loop(df, interval_ns, targets))
            )

    async def _timer_loop(self, df, interval_ns: int, targets: set[InputId]):
        period = interval_ns / 1e9
        timer_id = str(TimerMapping(interval_ns=interval_ns).data_id)
        next_tick = time.monotonic() + period
        while True:
            delay = next_tick - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            next_tick += period
            metadata = Metadata(
                type_info=TypeInfo(encoding=ENCODING_RAW, len=0),
                parameters={"timer": timer_id},
            )
            for target in targets:
                queue = df.queues.get(str(target.node))
                if queue is None:
                    continue
                event = d2n.Input(id=str(target.input), metadata=metadata, data=None)
                ts = self.clock.new_timestamp()
                queue.push(
                    Timestamped(inner=event, timestamp=ts),
                    input_id=str(target.input),
                    send_ns=ts.physical_ns,
                )

    # ------------------------------------------------------------------
    # routing (reference: daemon/src/lib.rs:955-1003, 1314-1390)
    # ------------------------------------------------------------------

    def send_out(
        self,
        df: DataflowState,
        sender: str,
        output_id: str,
        metadata: Metadata,
        data: Any,
        send_ns: int = 0,
    ) -> None:
        """Route one output to all local receiver queues and remote machines.

        ``send_ns`` is the sender's HLC physical timestamp (from the
        Timestamped frame); it seeds the send→deliver latency histograms.
        0 means unknown — the routed events fall back to route time."""
        oid = OutputId(NodeId(sender), DataId(output_id))
        token = data.drop_token if isinstance(data, SharedMemoryData) else None
        if oid not in df.open_outputs:
            if token:
                self._notify_owner(df, sender, token)
            return
        receivers = df.mappings.get(oid, ())
        if token is not None:
            df.tokens[token] = TokenState(owner=sender)
        nbytes = metadata.type_info.len
        df.metrics.count_link(sender, output_id, nbytes)
        if FLIGHT.enabled:
            FLIGHT.record("route", f"{sender}/{output_id}", nbytes)
        if TRACING.active:
            FLIGHT.record(
                "t_route",
                f"{sender}/{output_id}",
                str(metadata.parameters.get(OTEL_CTX_KEY, "")),
                max(0, time.time_ns() - send_ns) if send_ns else 0,
            )

        remote_machines: set[str] = set()
        for target in receivers:
            rnode = str(target.node)
            if (sender, output_id, rnode, str(target.input)) in df.p2p_edges:
                continue  # the sender published this edge peer-to-peer
            if rnode in df.local_nodes:
                queue = df.queues.get(rnode)
                open_inputs = df.open_inputs.get(rnode, set())
                if queue is None or str(target.input) not in open_inputs:
                    continue
                if token is not None:
                    df.tokens[token].pending += 1
                event = d2n.Input(
                    id=str(target.input), metadata=metadata, data=data
                )
                ts = self.clock.new_timestamp()
                queue.push(
                    Timestamped(inner=event, timestamp=ts),
                    input_id=str(target.input),
                    drop_token=token,
                    send_ns=send_ns or ts.physical_ns,
                )
            else:
                remote_machines.add(df.node_machine(rnode))

        if remote_machines and self.inter_daemon_send is not None:
            # Shared memory never crosses machines: copy payload to bytes.
            payload = self._payload_bytes(df, data)
            for machine in remote_machines:
                self.inter_daemon_send(df, machine, str(oid), metadata, payload)

        # The token can already be gone: a push into a closed/dropping
        # queue (receiver died mid-dataflow) releases synchronously and
        # deletes it before we get here.
        token_state = df.tokens.get(token) if token is not None else None
        if token_state is not None and token_state.pending == 0:
            del df.tokens[token]
            self._notify_owner(df, sender, token)

    def send_out_wire(
        self, df: DataflowState, sender: str, fast: "fastroute.FastSend"
    ) -> bool:
        """Route a shallow-parsed inline SendMessage by splicing wire
        bytes — no metadata/data object trees, no re-encode on delivery.

        Returns False (nothing pushed) when any receiver is remote: the
        inter-daemon path needs the decoded metadata, so the caller
        falls back to the reflective route for the whole frame.
        """
        key = (sender, fast.output_id)
        cached = df.route_cache.get(key)
        if cached is None:
            oid = OutputId(NodeId(sender), DataId(fast.output_id))
            cached = (
                oid,
                [(str(t.node), str(t.input)) for t in df.mappings.get(oid, ())],
                f"{sender}/{fast.output_id}",  # flight label, built once
            )
            df.route_cache[key] = cached
        oid, receivers, label = cached
        if oid not in df.open_outputs:
            return True  # dropped, like send_out on a closed output
        if any(rnode not in df.local_nodes for rnode, _ in receivers):
            return False
        df.metrics.count_link(sender, fast.output_id, fast.payload_len)
        if FLIGHT.enabled:
            FLIGHT.record("fastroute_hit", label, fast.payload_len)
        send_ns = fast.timestamp.physical_ns
        if TRACING.active:
            # Context spliced off the wire by parse_send_message (no
            # metadata object tree exists on this path).
            FLIGHT.record(
                "t_route", label, fast.ctx, max(0, time.time_ns() - send_ns)
            )
        for rnode, input_id in receivers:
            if (sender, fast.output_id, rnode, input_id) in df.p2p_edges:
                continue  # the sender published this edge peer-to-peer
            queue = df.queues.get(rnode)
            if queue is None or input_id not in df.open_inputs.get(rnode, set()):
                continue
            queue.push(
                None,
                input_id=input_id,
                wire=fastroute.build_input_event(
                    input_id, fast.body, self.clock.new_timestamp()
                ),
                send_ns=send_ns,
            )
        return True

    def deliver_remote_output(
        self, df: DataflowState, output_id: str, metadata: Metadata, payload: bytes | None
    ) -> None:
        """An output forwarded from another machine's daemon."""
        oid = OutputId.parse(output_id)
        data = InlineData(data=payload) if payload is not None else None
        nbytes = metadata.type_info.len
        df.metrics.count_link(str(oid.node), str(oid.output), nbytes)
        if FLIGHT.enabled:
            FLIGHT.record("route_remote", output_id, nbytes)
        for target in df.mappings.get(oid, ()):  # local receivers only
            rnode = str(target.node)
            if rnode not in df.local_nodes:
                continue
            queue = df.queues.get(rnode)
            open_inputs = df.open_inputs.get(rnode, set())
            if queue is None or str(target.input) not in open_inputs:
                continue
            event = d2n.Input(id=str(target.input), metadata=metadata, data=data)
            # Latency measured from local arrival time: remote HLC
            # physical clocks are not comparable across machines.
            ts = self.clock.new_timestamp()
            queue.push(
                Timestamped(inner=event, timestamp=ts),
                input_id=str(target.input),
                send_ns=ts.physical_ns,
            )

    def metrics_snapshot(self, df: DataflowState) -> dict:
        """JSON-able metrics snapshot for one dataflow on this machine —
        the payload of a MetricsRequest reply (daemon → coordinator)."""
        depths: dict[str, int] = {}
        for nid, queue in df.queues.items():
            for input_id, count in queue.input_counts.items():
                if count:
                    depths[f"{nid}/{input_id}"] = count
        snap = df.metrics.snapshot(depths)
        snap["fastroute"]["fallback_reasons"] = dict(fastroute.FALLBACKS)
        if df.node_serving:
            snap["serving"] = {
                nid: dict(s) for nid, s in df.node_serving.items()
            }
        if df.node_fleet:
            now_ns = time.time_ns()
            snap["fleet"] = {
                nid: fleet.fleet_gauges(
                    e["digest"], (now_ns - e["recv_wall_ns"]) / 1e9
                )
                for nid, e in df.node_fleet.items()
            }
        if df.history is not None and df.history.slo_targets:
            snap["slo"] = df.history.slo_status()
        if df.log_counts:
            snap["logs"] = {
                nid: {"errors": c[0], "warns": c[1]}
                for nid, c in df.log_counts.items()
            }
        if df.node_trace_drops:
            snap["trace"] = {"drops": dict(df.node_trace_drops)}
        if df.alerts is not None:
            snap["alerts"] = df.alerts.status()
        return snap

    async def _history_sampler(self, df: DataflowState) -> None:
        """Feed the dataflow's history ring on the configured cadence.
        SLO violations detected by the ring are recorded as flight
        instants so they show up on the `dora-tpu trace` timeline."""
        interval = df.history.interval_s
        while True:
            await asyncio.sleep(interval)
            try:
                self.sample_history(df)
            except Exception:
                logger.exception("history sample failed (%s)", df.id)

    def sample_history(self, df: DataflowState) -> None:
        """Take one history sample now (sampler tick / final flush)."""
        if df.history is None:
            return
        snap = self.metrics_snapshot(df)
        wall_ns = time.time_ns()
        hlc_ns = self.clock.new_timestamp().physical_ns
        events = df.history.sample(snap, wall_ns, hlc_ns)
        for node, objective, observed, target in events:
            FLIGHT.record(
                "slo_violation", f"{node}:{objective}",
                f"observed={observed} target={target}", None,
            )
        # Alert evaluation rides the sampler tick: transitions become
        # flight instants on this daemon's trace track (and fan out to
        # the configured sinks inside the engine).
        if df.alerts is not None:
            for ev in df.alerts.evaluate_ring(df.history, wall_ns):
                FLIGHT.record(
                    f"alert_{ev['phase']}",
                    f"{ev['rule']}:{ev['instance']}",
                    f"value={ev['value']} threshold={ev['threshold']}",
                    None,
                )

    def history_snapshot(self, df: DataflowState) -> dict:
        """Per-machine history-ring snapshot — the payload of a
        MetricsHistoryRequest reply. Carries a ``(wall_ns, hlc_ns)``
        pair captured back to back so the merge
        (dora_tpu.metrics_history) can align this machine's sample
        stamps onto the cluster HLC timeline, exactly like the trace
        merge."""
        if df.history is None:
            return {}
        out = df.history.snapshot()
        out["machine_id"] = self.machine_id
        out["hlc_ns"] = self.clock.new_timestamp().physical_ns
        out["wall_ns"] = time.time_ns()
        return out

    def fleet_snapshot(self, df: DataflowState) -> dict:
        """Per-machine fleet snapshot — the payload of a FleetRequest
        reply. Latest digest per replica with its receive stamp, plus
        the back-to-back ``(wall_ns, hlc_ns)`` pair so the merge
        (dora_tpu.fleet.merge_fleet_snapshots) can align receive stamps
        onto the cluster HLC timeline, exactly like metrics history."""
        if not df.node_fleet:
            return {}
        return {
            "machine_id": self.machine_id,
            "hlc_ns": self.clock.new_timestamp().physical_ns,
            "wall_ns": time.time_ns(),
            "replicas": {
                nid: {**e["digest"], "recv_wall_ns": e["recv_wall_ns"]}
                for nid, e in df.node_fleet.items()
            },
        }

    def alerts_snapshot(self, df: DataflowState) -> dict:
        """Per-machine alert-engine status — the payload of an
        AlertsRequest reply. No clock alignment needed (states, not
        samples); the machine id lets the coordinator's merge attribute
        instances."""
        if df.alerts is None:
            return {}
        out = df.alerts.status()
        out["machine_id"] = self.machine_id
        return out

    def trace_snapshot(self, df: DataflowState) -> dict:
        """Per-machine trace snapshot for one dataflow — the payload of a
        TraceRequest reply. Carries this daemon's own ring plus every
        ring chunk its nodes shipped via ReportTrace, and a
        ``(wall_ns, hlc_ns)`` pair captured back to back so the merge
        (dora_tpu.tracing) can align this machine's wall stamps onto the
        cluster HLC timeline. The daemon ring is process-wide, so
        concurrent dataflows share its events."""
        processes: dict[str, list] = {
            nid: [list(e) for e in events]
            for nid, events in df.node_traces.items()
        }
        daemon_events = [list(e) for e in FLIGHT.events()]
        if daemon_events:
            processes["(daemon)"] = daemon_events
        hlc_ns = self.clock.new_timestamp().physical_ns
        out = {
            "machine": self.machine_id,
            "wall_ns": time.time_ns(),
            "hlc_ns": hlc_ns,
            "processes": processes,
        }
        if df.node_trace_drops:
            out["dropped_events"] = dict(df.node_trace_drops)
        return out

    def _payload_bytes(self, df: DataflowState, data: Any) -> bytes | None:
        if data is None:
            return None
        if isinstance(data, InlineData):
            return bytes(data.data)
        region = self._map_region(df, data.shmem_id)
        return bytes(region.buf[: data.len])

    def _map_region(self, df: DataflowState, shmem_id: str) -> ShmemRegion:
        region = df.mapped_regions.get(shmem_id)
        if region is None:
            region = ShmemRegion.open(shmem_id)
            df.mapped_regions[shmem_id] = region
        return region

    def publish_stdout_line(
        self, df: DataflowState, node_id: NodeId, output: str, line: str
    ) -> None:
        """Re-publish a stdout line as a dataflow output (``send_stdout_as``,
        reference: daemon/src/lib.rs:1174-1220). Payload is an Arrow string
        array in IPC format so receivers decode it like any other input."""
        from dora_tpu.node.arrow import ipc_bytes_str

        payload = ipc_bytes_str(line)
        metadata = Metadata(
            type_info=TypeInfo(encoding="arrow-ipc", len=len(payload)),
            parameters={},
        )
        self.send_out(df, str(node_id), output, metadata, InlineData(data=payload))

    # ------------------------------------------------------------------
    # drop tokens (reference: SURVEY.md §2.8)
    # ------------------------------------------------------------------

    def _unref_token(self, df: DataflowState, token: str) -> None:
        state = df.tokens.get(token)
        if state is None:
            return
        state.pending -= 1
        if state.pending <= 0:
            del df.tokens[token]
            self._notify_owner(df, state.owner, token)

    def _notify_owner(self, df: DataflowState, owner: str, token: str) -> None:
        drop_queue = df.drop_queues.get(owner)
        if drop_queue is not None:
            drop_queue.push(token)

    def ack_tokens(self, df: DataflowState, node_id: str, tokens: list[str]) -> None:
        delivered = df.delivered_tokens.get(node_id)
        for token in tokens:
            if delivered is not None:
                delivered.discard(token)
            self._unref_token(df, token)

    # ------------------------------------------------------------------
    # output closing / node exit
    # ------------------------------------------------------------------

    def close_outputs(self, df: DataflowState, node_id: str, outputs: list[str]) -> None:
        """Close outputs; propagate InputClosed/AllInputsClosed downstream
        (and InputsClosed to remote machines)."""
        remote_closed: dict[str, list[str]] = {}
        for output in outputs:
            oid = OutputId(NodeId(node_id), DataId(output))
            if oid not in df.open_outputs:
                continue
            df.open_outputs.discard(oid)
            for target in df.mappings.get(oid, ()):
                rnode = str(target.node)
                if rnode not in df.local_nodes:
                    remote_closed.setdefault(
                        df.node_machine(rnode), []
                    ).append(str(target))
                    continue
                self._close_local_input(df, rnode, str(target.input))
        if remote_closed and self.inter_daemon_send is not None:
            for machine, inputs in remote_closed.items():
                self.inter_daemon_send(df, machine, None, None, None, closed=inputs)

    def _close_local_input(self, df: DataflowState, rnode: str, input_id: str) -> None:
        open_inputs = df.open_inputs.get(rnode)
        if open_inputs is None or input_id not in open_inputs:
            return
        open_inputs.discard(input_id)
        queue = df.queues.get(rnode)
        if queue is None:
            return
        queue.push(
            Timestamped(
                inner=d2n.InputClosed(id=input_id),
                timestamp=self.clock.new_timestamp(),
            )
        )
        if not open_inputs and not self._has_timer_inputs(df, rnode):
            queue.push(
                Timestamped(
                    inner=d2n.AllInputsClosed(),
                    timestamp=self.clock.new_timestamp(),
                )
            )
            queue.close()

    def close_remote_inputs(self, df: DataflowState, inputs: list[str]) -> None:
        """InputsClosed forwarded from another machine."""
        for s in inputs:
            node, _, input_id = s.partition("/")
            self._close_local_input(df, node, input_id)

    def _has_timer_inputs(self, df: DataflowState, node_id: str) -> bool:
        return any(
            str(t.node) == node_id for targets in df.timers.values() for t in targets
        )

    def handle_node_exit(
        self,
        df: DataflowState,
        node_id: NodeId | str,
        returncode: int | None,
        error: str | None = None,
    ) -> None:
        nid = str(node_id)
        running = df.running_nodes.get(nid)
        if running is None or running.finished:
            return
        running.finished = True

        if error is not None:
            status = NodeExitStatus(success=False, error=error)
        elif returncode == 0:
            status = NodeExitStatus(success=True, code=0)
        elif returncode is not None and returncode < 0:
            status = NodeExitStatus(success=False, signal=-returncode)
        else:
            status = NodeExitStatus(success=False, code=returncode)

        # Elastic recovery: a failed node with remaining restart budget
        # respawns instead of failing the dataflow. Decided BEFORE any
        # failure bookkeeping — recording the failure would cascade the
        # rest of the dataflow, and closing the queue would propagate
        # AllInputsClosed downstream and finish the run under us.
        if not status.success and self._should_respawn(df, nid):
            attempt = df.respawn_attempts.get(nid, 0) + 1
            df.respawn_attempts[nid] = attempt
            df.respawning.add(nid)
            df.metrics.count_respawn(nid)
            if FLIGHT.enabled:
                FLIGHT.record("node_respawn", nid, attempt)
            logger.warning(
                "node %s/%s failed (%s); respawn attempt %d",
                df.id, nid, error or f"code {returncode}", attempt,
            )
            asyncio.create_task(self._respawn_node(df, nid, attempt, status))
            return

        self._record_exit_result(df, nid, status)

        # Barrier poison: node died before subscribing. In multi-machine
        # mode the coordinator must learn about it so the other machines'
        # barriers fail too (reference: pending.rs ReadyOnMachine with
        # exited_before_subscribe).
        if nid in df.pending_nodes:
            df.pending_nodes.discard(nid)
            if not status.success:
                if self._is_multi_machine(df):
                    self.coordinator_notify("ready", df, [nid])
                self.poison_barrier(df, nid)
            elif not df.pending_nodes:
                if self._is_multi_machine(df):
                    self.coordinator_notify("ready", df, [])
                else:
                    self._release_barrier(df)

        # Release buffers the dead node still referenced.
        queue = df.queues.get(nid)
        if queue is not None:
            queue.release_all_tokens()
            queue.close()
        for token in df.delivered_tokens.pop(nid, set()):
            self._unref_token(df, token)
        drop_queue = df.drop_queues.get(nid)
        if drop_queue is not None:
            drop_queue.close()
        buffer = df.replay_buffers.get(nid)
        if buffer is not None:
            buffer.close()

        # Output closing + finish-check are deferred until the node's control
        # connection has drained: SendMessages still in the socket buffer at
        # exit time must route before the outputs close.
        asyncio.create_task(self._finalize_node_exit(df, nid))

    def _record_exit_result(self, df: DataflowState, nid: str,
                            status: NodeExitStatus) -> None:
        """Classify an exit (grace_duration / cascading / other) and
        record the NodeResult + failure bookkeeping."""
        if status.success:
            result = NodeResult(error=None)
        else:
            if nid in df.grace_kills:
                cause = NodeErrorCause(kind="grace_duration")
            elif df.failed_nodes:
                cause = NodeErrorCause(
                    kind="cascading", caused_by_node=df.failed_nodes[0]
                )
            elif df.barrier_error is not None and nid != df.barrier_failed_node:
                cause = NodeErrorCause(
                    kind="cascading", caused_by_node=df.barrier_failed_node
                )
            else:
                stderr = "\n".join(df.stderr_rings.get(nid, [])) or None
                cause = NodeErrorCause(kind="other", stderr=stderr)
            result = NodeResult(error=NodeError(exit_status=status, cause=cause))
            df.failed_nodes.append(nid)
        df.node_results[nid] = result

    def _should_respawn(self, df: DataflowState, nid: str) -> bool:
        """A failed exit respawns only while the dataflow is otherwise
        healthy: barrier released cleanly, no stop in flight, the node was
        not grace-killed, no other node has already failed (that failure
        is about to end the run anyway), and restart budget remains."""
        node = df.descriptor.node(nid)
        if node.restart is None:
            return False
        if df.stop_sent or nid in df.grace_kills or df.done.done():
            return False
        if not df.started.is_set() or df.barrier_error is not None:
            return False
        if df.failed_nodes:
            return False
        return df.respawn_attempts.get(nid, 0) < node.restart.max_attempts

    async def _respawn_node(
        self,
        df: DataflowState,
        nid: str,
        attempt: int,
        status: NodeExitStatus,
    ) -> None:
        """Backoff, replay the un-acked input window, spawn a fresh
        incarnation. If the dataflow stopped during the backoff, fall back
        to recording the original failure like a normal exit."""
        node = df.descriptor.node(nid)
        policy = node.restart
        delay = min(
            policy.backoff_base_s * (2 ** (attempt - 1)), policy.backoff_max_s
        )
        # Jitter decorrelates simultaneous respawns across a machine.
        await asyncio.sleep(delay * (0.75 + 0.5 * random.random()))

        if df.stop_sent or df.done.done():
            df.respawning.discard(nid)
            self._record_exit_result(df, nid, status)
            queue = df.queues.get(nid)
            if queue is not None:
                queue.release_all_tokens()
                queue.close()
            for token in df.delivered_tokens.pop(nid, set()):
                self._unref_token(df, token)
            dq = df.drop_queues.get(nid)
            if dq is not None:
                dq.close()
            buffer = df.replay_buffers.get(nid)
            if buffer is not None:
                buffer.close()
            await self._finalize_node_exit(df, nid)
            return

        # Fresh control-drain latch for the new incarnation (the old one
        # is set — its connection is gone).
        df.control_done[nid] = asyncio.Event()

        # The dead incarnation's events loop can still be parked in
        # queue.next_batch (a coroutine awaiting the queue never sees its
        # socket drop). Left alive, the replay below would WAKE it: it
        # would consume the requeued entries and send them to the dead
        # connection. Cancel it before touching the queue — next_batch
        # is cancellation-safe (a cancel while parked consumes nothing).
        stale = df.event_tasks.pop(nid, None)
        if stale is not None and not stale.done():
            stale.cancel()
            try:
                await stale
            except (asyncio.CancelledError, Exception):
                pass

        # Replay: un-acked in-flight inputs go back to the FRONT of the
        # queue, ahead of anything routed while the node was down.
        buffer = df.replay_buffers.get(nid)
        if buffer is not None and len(buffer):
            entries = buffer.drain()
            queue = df.queues.get(nid)
            if queue is not None:
                queue.requeue_front(entries)
            df.metrics.count_replayed(nid, len(entries))
            if FLIGHT.enabled:
                FLIGHT.record("replay_inputs", nid, len(entries))
            logger.info(
                "node %s/%s: replaying %d un-acked input(s) on respawn",
                df.id, nid, len(entries),
            )

        was_dynamic = df.running_nodes[nid].dynamic
        df.running_nodes[nid] = RunningNode(node_id=nid, dynamic=was_dynamic)
        df.respawning.discard(nid)
        node_config = self._make_node_config(df, nid)
        try:
            process = await spawn_mod.spawn_node(self, df, node, node_config)
        except RuntimeError as e:
            self.handle_node_exit(df, nid, None, error=str(e))
            return
        df.running_nodes[nid].process = process

    async def _finalize_node_exit(self, df: DataflowState, nid: str) -> None:
        done = df.control_done.get(nid)
        if done is not None and not done.is_set():
            try:
                await asyncio.wait_for(done.wait(), timeout=2)
            except asyncio.TimeoutError:
                pass
        node = df.descriptor.node(nid)
        self.close_outputs(df, nid, [str(o) for o in node.outputs])
        self._check_dataflow_finished(df)

    def _check_dataflow_finished(self, df: DataflowState) -> None:
        pending = [
            r
            for r in df.running_nodes.values()
            if (not r.finished or r.node_id in df.respawning) and not r.dynamic
        ]
        if pending:
            return
        for t in df.timer_tasks:
            t.cancel()
        df.timer_tasks.clear()
        if df.history_task is not None:
            df.history_task.cancel()
            df.history_task = None
            # Final flush: the ring keeps serving archived
            # QueryMetricsHistory, so capture the tail of the run.
            try:
                self.sample_history(df)
            except Exception:
                pass
        for queue in df.queues.values():
            queue.release_all_tokens()
            queue.close()
        for dq in df.drop_queues.values():
            dq.close()
        for buffer in df.replay_buffers.values():
            buffer.close()
        for region in df.mapped_regions.values():
            try:
                region.close(unlink=False, force=True)
            except Exception:
                pass
        df.mapped_regions.clear()
        # Deferred close (never block the live loop); the conns stay in
        # df.shmem_conns so Daemon.close() can still force the unlink
        # synchronously before process exit (close_sync is close-once
        # safe against this deferred path).
        for conn in df.shmem_conns:
            conn.close()
        # Safety net: unlink announced p2p edge channels a SIGKILLed node
        # may have leaked (nodes normally unlink their own on close).
        from dora_tpu.native import unlink_region

        for listeners in df.p2p_listeners.values():
            for name in listeners.values():
                for victim in (name, name + "-a"):  # data + ack channels
                    try:
                        unlink_region(victim)
                    except Exception:
                        pass
        df.p2p_listeners.clear()
        result = DataflowResult(
            uuid=df.id,
            node_results={
                nid: df.node_results.get(nid, NodeResult(error=None))
                for nid, r in df.running_nodes.items()
                if not r.dynamic or nid in df.node_results
            },
        )
        if not df.done.done():
            df.done.set_result(result)
        if self.coordinator_notify is not None:
            self.coordinator_notify("finished", df, result)

    # ------------------------------------------------------------------
    # stop (reference: daemon/src/lib.rs:1594-1636)
    # ------------------------------------------------------------------

    def stop_dataflow(self, df: DataflowState, grace_s: float | None = None) -> None:
        if df.stop_sent:
            return
        df.stop_sent = True
        if not df.started.is_set():
            self._release_barrier(df, error="dataflow stopped before start")
        for nid, queue in df.queues.items():
            running = df.running_nodes.get(nid)
            if running is not None and running.finished:
                continue
            queue.push(
                Timestamped(inner=d2n.Stop(), timestamp=self.clock.new_timestamp())
            )
            queue.close()
        asyncio.create_task(self._grace_kill(df, grace_s or DEFAULT_GRACE_S))

    async def _grace_kill(self, df: DataflowState, grace_s: float) -> None:
        await asyncio.sleep(grace_s)
        self._kill_stragglers(df, record_grace=True)

    @staticmethod
    def _kill_stragglers(df: DataflowState, record_grace: bool = False) -> None:
        for nid, running in df.running_nodes.items():
            if running.finished or running.process is None:
                continue
            if record_grace:
                df.grace_kills.add(nid)
            try:
                running.process.kill()
            except ProcessLookupError:
                pass

    @staticmethod
    def _close_shmem_conns(df: DataflowState) -> None:
        """Synchronous close + unlink (teardown path — must not outlive
        the process; see ShmemConnection.close_sync)."""
        for conn in df.shmem_conns:
            conn.close_sync()
        df.shmem_conns.clear()

    def reload_node(self, df: DataflowState, node_id: str, operator_id: str | None) -> None:
        queue = df.queues.get(node_id)
        if queue is not None:
            queue.push(
                Timestamped(
                    inner=d2n.Reload(operator_id=operator_id),
                    timestamp=self.clock.new_timestamp(),
                )
            )

    def migrate_node(self, df: DataflowState, node_id: str, handoff_dir: str) -> None:
        """Ask a serving node to drain its live streams into
        ``handoff_dir`` at the next window boundary (cm.MigrateNode)."""
        queue = df.queues.get(node_id)
        if queue is not None:
            queue.push(
                Timestamped(
                    inner=d2n.Migrate(handoff_dir=handoff_dir),
                    timestamp=self.clock.new_timestamp(),
                )
            )

    def profile_node(self, df: DataflowState, node_id: str, action: str,
                     seconds: float) -> None:
        """Ask a serving node to start/stop an on-demand deep profile
        capture (cm.StartProfile/StopProfile)."""
        queue = df.queues.get(node_id)
        if queue is not None:
            queue.push(
                Timestamped(
                    inner=d2n.Profile(action=action, seconds=seconds),
                    timestamp=self.clock.new_timestamp(),
                )
            )

    # ------------------------------------------------------------------
    # logging
    # ------------------------------------------------------------------

    def on_node_log(self, df: DataflowState, node_id: str, level: str, text: str) -> None:
        # Structured severity: feed the per-node error/warn counters the
        # metrics plane exports (prom, history series, log-errors alert).
        if level in ("error", "warn"):
            counts = df.log_counts.get(node_id)
            if counts is None:
                counts = df.log_counts[node_id] = [0, 0]
            counts[0 if level == "error" else 1] += 1
        if self.log_sink is not None:
            from dora_tpu.message.common import LogMessage

            self.log_sink(
                LogMessage(
                    dataflow_id=df.id,
                    level=level,
                    message=text,
                    node_id=node_id,
                    machine_id=self.machine_id,
                )
            )

    # ------------------------------------------------------------------
    # node-channel listeners
    # ------------------------------------------------------------------

    async def _handle_connection(self, conn: NodeConnection) -> None:
        try:
            frame = await conn.recv()
            if frame is None:
                return
            ts = decode_timestamped(frame, self.clock)
            register = ts.inner
            if not isinstance(register, n2d.Register):
                await self._reply(conn, d2n.ReplyResult(error="expected Register"))
                return
            error = self._check_register(register)
            await self._reply(conn, d2n.ReplyResult(error=error))
            if error is not None:
                return
            df = self.dataflows[register.dataflow_id]
            node_id = register.node_id
            if register.channel == n2d.CHANNEL_CONTROL:
                await self._control_loop(df, node_id, conn)
            elif register.channel == n2d.CHANNEL_EVENTS:
                await self._events_loop(df, node_id, conn)
            elif register.channel == n2d.CHANNEL_DROP:
                await self._drop_loop(df, node_id, conn)
        except (ConnectionError, ConnectionClosed):
            pass  # node went away mid-reply; its exit watcher reports it
        except Exception:
            logger.exception("node connection failed")
        finally:
            conn.close()

    def _check_register(self, register: n2d.Register) -> str | None:
        ours = PROTOCOL_VERSION.split(".")[:2]
        theirs = register.protocol_version.split(".")[:2]
        if ours != theirs:
            return (
                f"incompatible protocol version {register.protocol_version} "
                f"(daemon speaks {PROTOCOL_VERSION})"
            )
        df = self.dataflows.get(register.dataflow_id)
        if df is None:
            return f"unknown dataflow {register.dataflow_id!r}"
        if register.node_id not in df.queues:
            return f"unknown node {register.node_id!r} on this machine"
        return None

    async def _reply(self, conn: NodeConnection, msg: Any) -> None:
        await conn.send(encode_timestamped(msg, self.clock))

    async def _control_loop(self, df: DataflowState, node_id: str, conn) -> None:
        try:
            await self._control_loop_inner(df, node_id, conn)
        finally:
            done = df.control_done.get(node_id)
            if done is not None:
                done.set()

    async def _control_loop_inner(self, df: DataflowState, node_id: str, conn) -> None:
        while True:
            frame = await conn.recv()
            if frame is None:
                return
            # Hot path: inline-payload SendMessage frames route as wire
            # bytes (message/fastroute.py) — the metadata/data subtrees
            # are never built as objects. Anything the fast path cannot
            # prove routable takes the reflective decode below.
            fast = fastroute.parse_send_message(frame)
            if fast is not None:
                # Clock first: the routed events' fresh timestamps must
                # be causally after the sender's.
                self.clock.update_with_timestamp(fast.timestamp)
                if self.send_out_wire(df, node_id, fast):
                    df.metrics.fastroute_hits += 1
                    continue
                # Remote receivers: re-decode below (the second clock
                # update is harmless — HLC updates are monotone).
            tsd = decode_timestamped(frame, self.clock)
            msg = tsd.inner
            if isinstance(msg, n2d.SendMessage):
                df.metrics.fastroute_fallbacks += 1
                self.send_out(
                    df, node_id, msg.output_id, msg.metadata, msg.data,
                    send_ns=tsd.timestamp.physical_ns,
                )
            elif isinstance(msg, n2d.ReportDropTokens):
                self.ack_tokens(df, node_id, msg.drop_tokens)
            elif isinstance(msg, n2d.ReportTrace):
                _extend_trace_buffer(df, node_id, msg.events)
            elif isinstance(msg, n2d.ReportServing):
                df.node_serving[node_id] = msg.snapshot
            elif isinstance(msg, n2d.ReportEngineState):
                df.node_fleet[node_id] = {
                    "digest": fleet.digest_as_dict(msg.digest),
                    "recv_wall_ns": time.time_ns(),
                }
            elif isinstance(msg, n2d.ReportProfile):
                if self.profile_sink is not None:
                    self.profile_sink(df.id, node_id, msg.artifact, msg.error)
            elif isinstance(msg, n2d.P2PAnnounce):
                df.p2p_listeners[node_id] = dict(msg.listeners)
                await self._reply(conn, d2n.ReplyResult())
            elif isinstance(msg, n2d.P2PEdgesRequest):
                await self._reply(conn, self._p2p_edges_reply(df, node_id))
            elif isinstance(msg, n2d.CloseOutputs):
                self.close_outputs(df, node_id, msg.outputs)
                await self._reply(conn, d2n.ReplyResult())
            elif isinstance(msg, n2d.OutputsDone):
                node = df.descriptor.node(node_id)
                # The send_stdout_as output is produced by the daemon-side
                # stdout pump, not the node's control channel — it closes at
                # exit-finalize time, after the pump drained (otherwise the
                # node's own close() races its final stdout lines away).
                stdout_output = node.send_stdout_as
                self.close_outputs(
                    df,
                    node_id,
                    [str(o) for o in node.outputs if str(o) != stdout_output],
                )
                await self._reply(conn, d2n.ReplyResult())
            else:
                await self._reply(
                    conn,
                    d2n.ReplyResult(error=f"unexpected control request {type(msg).__name__}"),
                )

    async def _events_loop(self, df: DataflowState, node_id: str, conn) -> None:
        frame = await conn.recv()
        if frame is None:
            return
        msg = decode_timestamped(frame, self.clock).inner
        if not isinstance(msg, n2d.Subscribe):
            await self._reply(conn, d2n.ReplyResult(error="expected Subscribe"))
            return
        # Start barrier: withhold the reply until all nodes subscribed.
        self._node_subscribed(df, node_id)
        await df.started.wait()
        await self._reply(conn, d2n.ReplyResult(error=df.barrier_error))
        if df.barrier_error is not None:
            return

        queue = df.queues[node_id]
        delivered = df.delivered_tokens.setdefault(node_id, set())
        replay = df.replay_buffers.get(node_id)
        df.event_tasks[node_id] = asyncio.current_task()
        first_poll = True
        while True:
            frame = await conn.recv()
            if frame is None:
                return
            msg = decode_timestamped(frame, self.clock).inner
            if isinstance(msg, n2d.NextEvent):
                self.ack_tokens(df, node_id, msg.drop_tokens)
                if replay is not None and not first_poll:
                    # The poll is the ack seam: batch k+1 is requested
                    # only after batch k was consumed — but only on THIS
                    # connection. A fresh incarnation's first poll has
                    # consumed nothing and must not ack the window the
                    # dead incarnation left behind.
                    replay.ack()
                first_poll = False
                batch = await queue.next_batch()
                if replay is not None:
                    replay.remember(batch)
                wires = []
                deliver_ns = time.time_ns()
                for entry in batch:
                    if entry.drop_token is not None:
                        delivered.add(entry.drop_token)
                    if entry.send_ns and entry.input_id is not None:
                        # HLC physical time is time_ns-based, so on one
                        # machine the difference is real send→deliver
                        # latency (including queue wait).
                        df.metrics.observe_latency(
                            node_id, entry.input_id,
                            (deliver_ns - entry.send_ns) / 1000.0,
                        )
                        if TRACING.active:
                            # Daemon-side span covering queue wait: no
                            # ctx (the wire path never decodes metadata
                            # at delivery); the timeline still lines up
                            # via the wall stamps.
                            FLIGHT.record(
                                "t_deliver",
                                f"{node_id}/{entry.input_id}",
                                None,
                                max(0, deliver_ns - entry.send_ns),
                            )
                    # Fast-path entries carry their wire image; others
                    # (timers, close events, shmem inputs) encode here.
                    wires.append(
                        entry.wire if entry.wire is not None
                        else encode(entry.event)
                    )
                await conn.send(
                    fastroute.build_next_events_frame(
                        wires, self.clock.new_timestamp()
                    )
                )
            elif isinstance(msg, n2d.EventStreamDropped):
                queue.release_all_tokens()
                queue.close()
                await self._reply(conn, d2n.ReplyResult())
            else:
                await self._reply(
                    conn,
                    d2n.ReplyResult(error=f"unexpected event request {type(msg).__name__}"),
                )

    async def _drop_loop(self, df: DataflowState, node_id: str, conn) -> None:
        frame = await conn.recv()
        if frame is None:
            return
        msg = decode_timestamped(frame, self.clock).inner
        if not isinstance(msg, n2d.SubscribeDrop):
            await self._reply(conn, d2n.ReplyResult(error="expected SubscribeDrop"))
            return
        await self._reply(conn, d2n.ReplyResult())
        drop_queue = df.drop_queues[node_id]
        while True:
            frame = await conn.recv()
            if frame is None:
                return
            msg = decode_timestamped(frame, self.clock).inner
            if isinstance(msg, n2d.NextDropEvents):
                tokens = await drop_queue.next_batch()
                await self._reply(conn, d2n.DropEvents(drop_tokens=tokens))
            elif isinstance(msg, n2d.ReportDropTokens):
                self.ack_tokens(df, node_id, msg.drop_tokens)
            else:
                await self._reply(
                    conn,
                    d2n.ReplyResult(error=f"unexpected drop request {type(msg).__name__}"),
                )

    # ------------------------------------------------------------------
    # dynamic-node bootstrap (reference: daemon/src/local_listener.rs)
    # ------------------------------------------------------------------

    async def _handle_dynamic_client(self, reader, writer) -> None:
        from dora_tpu.transport.framing import recv_frame_async, send_frame_async

        try:
            frame = await recv_frame_async(reader)
            msg = decode_timestamped(frame, self.clock).inner
            if not isinstance(msg, n2d.NodeConfigRequest):
                reply = d2n.NodeConfigReply(error="expected NodeConfigRequest")
            else:
                reply = self._dynamic_node_config(msg.node_id)
            await send_frame_async(
                writer, encode_timestamped(reply, self.clock)
            )
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _dynamic_node_config(self, node_id: str) -> d2n.NodeConfigReply:
        matches = []
        for df in self.dataflows.values():
            running = df.running_nodes.get(node_id)
            if running is not None and running.dynamic and not running.finished:
                matches.append(df)
        if not matches:
            return d2n.NodeConfigReply(
                error=f"no running dataflow has a dynamic node {node_id!r}"
            )
        if len(matches) > 1:
            return d2n.NodeConfigReply(
                error=f"multiple running dataflows have a dynamic node {node_id!r}; "
                f"cannot disambiguate"
            )
        df = matches[0]
        return d2n.NodeConfigReply(node_config=self._make_node_config(df, node_id))


# ---------------------------------------------------------------------------
# standalone mode (reference: daemon/src/lib.rs:157-224)
# ---------------------------------------------------------------------------


async def run_dataflow_async(
    dataflow: str | Path | Descriptor,
    working_dir: str | Path | None = None,
    local_comm: str | None = None,
    timeout_s: float | None = None,
) -> DataflowResult:
    """Run one dataflow to completion with an in-process daemon.

    ``local_comm=None`` (default) means "use the YAML's
    ``communication: {local: uds|shmem|tcp}`` block (or the reference's
    ``_unstable_local`` spelling), else tcp" — the dataflow_socket.yml
    idiom (reference examples/rust-dataflow/dataflow_socket.yml). Any
    explicit string — including ``"tcp"`` — overrides the YAML."""
    if isinstance(dataflow, Descriptor):
        descriptor = dataflow
        working_dir = Path(working_dir or Path.cwd())
    else:
        path = Path(dataflow)
        descriptor = Descriptor.read(path)
        working_dir = Path(working_dir or path.parent)
    descriptor.check(working_dir)
    if local_comm is None:  # any explicit choice wins over YAML
        local_comm = descriptor.communication.local.kind

    from dora_tpu.telemetry import install_task_dump, remove_task_dump

    loop = asyncio.get_running_loop()
    install_task_dump(loop)
    daemon = Daemon(local_comm=local_comm)
    await daemon.start()
    try:
        df = await daemon.spawn_dataflow(
            descriptor,
            working_dir=working_dir,
            local_nodes={str(n.id) for n in descriptor.nodes},
        )
        if timeout_s is not None:
            return await asyncio.wait_for(asyncio.shield(df.done), timeout_s)
        return await df.done
    finally:
        await daemon.close()
        remove_task_dump(loop)


def run_dataflow(
    dataflow: str | Path | Descriptor,
    working_dir: str | Path | None = None,
    local_comm: str | None = None,
    timeout_s: float | None = None,
) -> DataflowResult:
    return asyncio.run(
        run_dataflow_async(dataflow, working_dir, local_comm, timeout_s)
    )
