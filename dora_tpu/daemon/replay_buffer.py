"""Daemon-side replay buffer: un-acked in-flight inputs of a restartable
node, redelivered on respawn.

Delivery is the ack seam: a node polls ``NextEvent`` for batch *k+1*
only after it consumed batch *k*, so every entry of the batches handed
out since the last poll is exactly the node's un-acked in-flight input
set. The events loop ``remember()``s each delivered batch and
``ack()``s on the next poll; when the node dies mid-batch the daemon
``drain()``s the buffer back to the FRONT of the node's event queue
before respawning, so the new incarnation sees the same inputs again in
order — at-least-once semantics (consumers dedup by request id, see
``nodehub/llm_server``).

The in-memory window is bounded: beyond ``max_entries`` the oldest
entries spill to a Parquet file with the ``nodehub/record.py`` schema
(timestamp / trace / value / metadata, zstd) under the dataflow's
working dir — crash forensics stay readable with the standard replay
tooling even when the spill is never redelivered. Spilled rows hold the
pre-framed wire image, so redelivery rebuilds :class:`QueueEntry`
objects without re-encoding.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

from dora_tpu.daemon.queues import QueueEntry

logger = logging.getLogger(__name__)

#: In-memory un-acked window per node. A node's NextEvent batch is at
#: most MAX_BATCH=64 entries; several batches can be outstanding only
#: briefly, so 256 covers the normal case without spilling.
DEFAULT_MAX_ENTRIES = 256

#: Hard cap on spilled rows — the buffer is bounded end to end; beyond
#: this, the oldest spilled rows are forgotten (counted, not silent).
MAX_SPILL_ROWS = 4096


class ReplayBuffer:
    """Un-acked delivered inputs of one restartable node."""

    def __init__(self, node_id: str, spill_dir: str | Path | None = None,
                 max_entries: int = DEFAULT_MAX_ENTRIES):
        self.node_id = node_id
        self.max_entries = max_entries
        self.spill_dir = Path(spill_dir) if spill_dir else None
        self._entries: list[QueueEntry] = []
        self._spilled: list[dict[str, Any]] = []
        self._writer = None
        self._spill_path: Path | None = None
        #: entries dropped past the spill cap (observability, not silence)
        self.overflow_dropped = 0
        #: total entries redelivered across respawns
        self.replayed_total = 0

    def __len__(self) -> int:
        return len(self._entries) + len(self._spilled)

    # -- feed (events loop) --------------------------------------------------

    def remember(self, entries: list[QueueEntry]) -> None:
        """Record a just-delivered batch as un-acked."""
        for entry in entries:
            if entry.input_id is None:
                continue  # Stop/Closed markers are regenerated, not replayed
            self._entries.append(entry)
        while len(self._entries) > self.max_entries:
            self._spill(self._entries.pop(0))

    def ack(self) -> None:
        """The node polled again: everything delivered before this poll
        was consumed."""
        self._entries.clear()
        self._spilled.clear()

    # -- spill (Parquet, record.py schema) -----------------------------------

    def _spill(self, entry: QueueEntry) -> None:
        if len(self._spilled) >= MAX_SPILL_ROWS:
            self._spilled.pop(0)
            self.overflow_dropped += 1
        wire = entry.wire
        if wire is None and entry.event is not None:
            from dora_tpu.message.serde import encode

            wire = encode(entry.event)
        row = {
            "timestamp_utc_ns": int(entry.send_ns or 0),
            "trace": "",
            "value": wire,  # pre-framed wire image (see module doc)
            "metadata": json.dumps(
                {"input_id": entry.input_id, "drop_token": entry.drop_token}
            ),
        }
        self._spilled.append(row)
        if self.spill_dir is not None:
            try:
                self._write_spill_row(row)
            except Exception as e:  # pragma: no cover - disk-full etc.
                logger.warning("replay spill write failed for %s: %s",
                               self.node_id, e)

    def _write_spill_row(self, row: dict[str, Any]) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        if self._writer is None:
            self.spill_dir.mkdir(parents=True, exist_ok=True)
            self._spill_path = self.spill_dir / f"replay_{self.node_id}.parquet"
            schema = pa.schema(
                [
                    pa.field("timestamp_utc_ns", pa.int64()),
                    pa.field("trace", pa.string()),
                    pa.field("value", pa.binary()),
                    pa.field("metadata", pa.string()),
                ]
            )
            self._writer = pq.ParquetWriter(
                self._spill_path, schema, compression="zstd"
            )
        self._writer.write_table(
            pa.table(
                {
                    "timestamp_utc_ns": [row["timestamp_utc_ns"]],
                    "trace": [row["trace"]],
                    "value": [row["value"]],
                    "metadata": [row["metadata"]],
                },
                schema=self._writer.schema,
            )
        )

    # -- drain (respawn path) ------------------------------------------------

    def drain(self) -> list[QueueEntry]:
        """All un-acked entries in original delivery order (spilled rows
        first — they are the oldest), cleared from the buffer."""
        out: list[QueueEntry] = []
        for row in self._spilled:
            meta = json.loads(row["metadata"]) if row["metadata"] else {}
            out.append(
                QueueEntry(
                    event=None,
                    input_id=meta.get("input_id"),
                    drop_token=meta.get("drop_token"),
                    wire=row["value"],
                    send_ns=row["timestamp_utc_ns"],
                )
            )
        out.extend(self._entries)
        self._spilled = []
        self._entries = []
        self.replayed_total += len(out)
        return out

    def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
