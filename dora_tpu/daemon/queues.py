"""Daemon-side per-node event queues with bounded per-input backlog.

Reference parity: binaries/daemon/src/node_communication/mod.rs:192-359 —
each (node, input) has a bounded queue (YAML ``queue_size``, default 10);
overflow drops the *oldest* queued event of that input and immediately
releases its shared-memory drop token so the sender can reuse the region.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from dora_tpu.core.config import DEFAULT_QUEUE_SIZE
from dora_tpu.message import daemon_to_node as d2n
from dora_tpu.message.common import SharedMemoryData
from dora_tpu.message.serde import Timestamped
from dora_tpu.telemetry import FLIGHT

logger = logging.getLogger(__name__)


@dataclass
class QueueEntry:
    #: decoded event, or None for fast-path entries that only ever exist
    #: as wire bytes (message/fastroute.py routes without object trees)
    event: Timestamped | None
    input_id: str | None = None  # set for Input events (drop-oldest scope)
    drop_token: str | None = None
    #: pre-encoded ``Timestamped(event)`` wire image; the events loop
    #: splices it into the NextEvents reply instead of re-encoding
    wire: bytes | None = None
    #: sender-side HLC physical ns (send→deliver latency histograms);
    #: 0 = unknown (close/stop events, which are never measured)
    send_ns: int = 0


@dataclass
class NodeEventQueue:
    """Events awaiting one node's next blocking NextEvent poll."""

    node_id: str
    queue_sizes: dict[str, int]  # input id -> bound
    on_token_unref: Callable[[str], None]  # release a dropped event's token
    entries: deque[QueueEntry] = field(default_factory=deque)
    input_counts: dict[str, int] = field(default_factory=dict)
    waiter: asyncio.Future | None = None
    closed: bool = False  # no more events will ever arrive
    #: DataflowMetrics hook (dora_tpu.metrics); None = unmetered (tests)
    metrics: Any = None
    #: input id -> "node/input" flight-recorder label (computed once, so
    #: the enabled hot path allocates no strings per event)
    flight_labels: dict[str, str] = field(default_factory=dict)

    def _flight_label(self, input_id: str) -> str:
        label = self.flight_labels.get(input_id)
        if label is None:
            label = self.flight_labels[input_id] = (
                f"{self.node_id}/{input_id}"
            )
        return label

    def push(self, event: Timestamped | None, input_id: str | None = None,
             drop_token: str | None = None, wire: bytes | None = None,
             send_ns: int = 0) -> None:
        if self.closed:
            if drop_token is not None:
                self.on_token_unref(drop_token)
            return
        if input_id is not None:
            bound = self.queue_sizes.get(input_id, DEFAULT_QUEUE_SIZE)
            count = self.input_counts.get(input_id, 0)
            if count >= bound:
                self._drop_oldest(input_id)
            self.input_counts[input_id] = self.input_counts.get(input_id, 0) + 1
            if FLIGHT.enabled:
                FLIGHT.record("enqueue", self._flight_label(input_id),
                              self.input_counts[input_id])
        self.entries.append(QueueEntry(event, input_id, drop_token, wire,
                                       send_ns))
        self._wake()

    def _drop_oldest(self, input_id: str) -> None:
        for i, entry in enumerate(self.entries):
            if entry.input_id == input_id:
                del self.entries[i]
                self.input_counts[input_id] -= 1
                if entry.drop_token is not None:
                    self.on_token_unref(entry.drop_token)
                depth = self.input_counts[input_id]
                # Overflow shedding is a YAML contract, not an error — but
                # it must never be invisible: the metrics plane counts it
                # and debug logging names the victim.
                logger.debug(
                    "queue overflow: dropped oldest event of %s/%s "
                    "(depth %d)", self.node_id, input_id, depth,
                )
                if FLIGHT.enabled:
                    FLIGHT.record("drop_oldest",
                                  self._flight_label(input_id), depth)
                if self.metrics is not None:
                    self.metrics.count_drop(self.node_id, input_id)
                return

    def requeue_front(self, entries: list[QueueEntry]) -> None:
        """Put already-delivered entries back at the FRONT of the queue,
        in their original order — the replay path for a respawned node's
        un-acked in-flight inputs. Skips the per-input bound on purpose:
        these entries were inside the bound when first delivered, and
        dropping them here would turn a crash into silent input loss.
        A ``closed`` queue still accepts the replay: closed means the
        end-of-stream marker is queued, and pending entries drain before
        polls report end of stream — upstream finishing while the node
        was down must not eat the replay window."""
        for entry in reversed(entries):
            self.entries.appendleft(entry)
            if entry.input_id is not None:
                self.input_counts[entry.input_id] = (
                    self.input_counts.get(entry.input_id, 0) + 1
                )
        if entries:
            self._wake()

    def close(self) -> None:
        """Mark the stream closed: pending entries still drain, then polls
        return empty (= end of stream)."""
        self.closed = True
        self._wake()

    def release_all_tokens(self) -> None:
        """Stream abandoned (node died): ack every queued shmem token."""
        for entry in self.entries:
            if entry.drop_token is not None:
                self.on_token_unref(entry.drop_token)
        self.entries.clear()
        self.input_counts.clear()

    #: Events handed out per NextEvent poll — the frame-size/fairness
    #: ceiling on coalesced delivery, NOT the staleness bound. An event
    #: delivered to the node has left the drop-oldest domain, but the
    #: per-input exposure is already capped at push time: the queue never
    #: holds more than ``queue_size`` entries per input, so one batch
    #: cannot hand out more of an input than the YAML contract allows
    #: (a queue_size=1 camera input still yields at most 1 per poll).
    #: Raised 4 -> 64 in round 6: at 4, a 1 KiB-message stream paid one
    #: node<->daemon round trip per 4 events, which capped the daemon
    #: route at a fraction of its wire capacity (see BENCHMARKS.md
    #: small-message axis).
    MAX_BATCH = 64

    async def next_batch(self) -> list[QueueEntry]:
        """Block until events are available (or the stream closes); hand
        out up to MAX_BATCH entries. Empty list = stream closed."""
        while not self.entries:
            if self.closed:
                return []
            if self.waiter is None or self.waiter.done():
                self.waiter = asyncio.get_running_loop().create_future()
            try:
                await self.waiter
            except asyncio.CancelledError:
                raise
        out = []
        while self.entries and len(out) < self.MAX_BATCH:
            entry = self.entries.popleft()
            if entry.input_id is not None:
                self.input_counts[entry.input_id] -= 1
            out.append(entry)
        return out

    def _wake(self) -> None:
        if self.waiter is not None and not self.waiter.done():
            self.waiter.set_result(None)


@dataclass
class DropQueue:
    """Released drop tokens awaiting the owning node's NextDropEvents poll."""

    tokens: list[str] = field(default_factory=list)
    waiter: asyncio.Future | None = None
    closed: bool = False

    def push(self, token: str) -> None:
        if self.closed:
            return
        self.tokens.append(token)
        self._wake()

    def close(self) -> None:
        self.closed = True
        self._wake()

    async def next_batch(self) -> list[str]:
        while not self.tokens:
            if self.closed:
                return []
            if self.waiter is None or self.waiter.done():
                self.waiter = asyncio.get_running_loop().create_future()
            await self.waiter
        out, self.tokens = self.tokens, []
        return out

    def _wake(self) -> None:
        if self.waiter is not None and not self.waiter.done():
            self.waiter.set_result(None)


def event_input_id(event: Any) -> str | None:
    return event.id if isinstance(event, d2n.Input) else None


def event_drop_token(event: Any) -> str | None:
    if isinstance(event, d2n.Input) and isinstance(event.data, SharedMemoryData):
        return event.data.drop_token
    return None
