"""Daemon-side node-channel connections over TCP, UDS, and shared memory.

A ``NodeConnection`` is one request-reply channel to one node (control,
events, or drop). TCP/UDS connections ride one asyncio accept loop; the
node identifies itself (and the channel kind) with its first Register
message. Shmem channels block in native code, so each is pumped by an
executor thread that re-enters the asyncio loop per request.

Reference parity: binaries/daemon/src/node_communication/{mod,tcp}.rs.
"""

from __future__ import annotations

import asyncio
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
from typing import Awaitable, Callable

from dora_tpu.native import Disconnected, ShmemChannel
from dora_tpu.transport.framing import (
    ConnectionClosed,
    recv_frame_async,
    send_frame_async,
    send_frames_async,
)


class NodeConnection:
    """One request-reply channel; recv() returns raw frames (None = closed)."""

    async def recv(self) -> bytes | None:
        raise NotImplementedError

    async def send(self, payload: bytes) -> None:
        raise NotImplementedError

    async def send_many(self, payloads: list[bytes]) -> None:
        """Coalesced send: deliver every frame, amortizing the per-send
        cost where the transport allows (vectored write on streams)."""
        for payload in payloads:
            await self.send(payload)

    def close(self) -> None:
        raise NotImplementedError


class StreamConnection(NodeConnection):
    """TCP or UDS connection (asyncio streams)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    async def recv(self) -> bytes | None:
        try:
            return await recv_frame_async(self.reader)
        except (ConnectionClosed, ConnectionError):
            return None

    async def send(self, payload: bytes) -> None:
        await send_frame_async(self.writer, payload)

    async def send_many(self, payloads: list[bytes]) -> None:
        await send_frames_async(self.writer, payloads)

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass


class ShmemConnection(NodeConnection):
    """Server side of one native shmem request-reply channel.

    The blocking native recv runs on a dedicated pump thread (one per
    channel) that re-enters the asyncio loop per request — executor slots
    stay free for short-lived work.
    """

    RECV_TICK_S = 0.5

    def __init__(self, channel: ShmemChannel):
        self.channel = channel
        self._closing = False
        self._close_lock = tracked_lock("daemon.connection.close")
        self._channel_closed = False
        self._loop = asyncio.get_running_loop()
        self._incoming: asyncio.Queue[bytes | None] = asyncio.Queue()
        self._thread = threading.Thread(
            target=self._pump, name=f"shmem-pump-{channel.name}", daemon=True
        )
        self._thread.start()

    def _pump(self) -> None:
        try:
            while not self._closing:
                try:
                    data = self.channel.recv(self.RECV_TICK_S)
                except (Disconnected, Exception):
                    break
                if data is not None:
                    self._loop.call_soon_threadsafe(self._incoming.put_nowait, data)
            self._loop.call_soon_threadsafe(self._incoming.put_nowait, None)
        except RuntimeError:
            pass  # event loop closed during teardown

    async def recv(self) -> bytes | None:
        return await self._incoming.get()

    async def send(self, payload: bytes) -> None:
        # Fast path: under request-reply discipline the requester is parked
        # in recv, so the reply slot is free — send inline (memcpy + futex
        # wake, a few µs) instead of paying an executor-thread hop. Fall
        # back to a blocking send off-loop only if the slot is occupied
        # (pipelined fire-and-forget peer or stuck client).
        try:
            if self.channel.try_send(payload):
                return
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self.channel.send, payload)
        except Disconnected:
            raise ConnectionClosed("shmem peer disconnected") from None

    def close(self) -> None:
        """Disconnect and free the channel. The native handle is freed only
        after the pump thread exits (freeing under a blocked recv would be a
        use-after-free); reply sends always complete before the listener
        calls close(), so no send can race the free either."""
        if self._closing:
            return
        self._closing = True
        self._disconnect_once()

        def _finish(thread=self._thread):
            thread.join(timeout=5)
            self._close_channel_once()

        threading.Thread(target=_finish, daemon=True).start()

    def _disconnect_once(self) -> None:
        """Disconnect under the close lock: close() (deferred helper) and
        close_sync() (daemon teardown) can overlap, and a disconnect
        racing the native free would touch a handle mid-free."""
        with self._close_lock:
            if self._channel_closed:
                return
            try:
                self.channel.disconnect()
            except Exception:
                pass

    def _close_channel_once(self) -> None:
        """Free + unlink the native channel exactly once (the deferred
        close() helper and the synchronous teardown path can both reach
        here; a double native close would be a double munmap). The lock
        is held across the native close so a concurrent
        ``_disconnect_once`` can never observe the handle mid-free."""
        with self._close_lock:
            if self._channel_closed:
                return
            self._channel_closed = True
            try:
                self.channel.close()
            except Exception:
                pass

    def close_sync(self, timeout: float = 2.0) -> None:
        """Close and unlink before returning — the daemon-teardown path.
        The deferred close() is right for per-connection teardown during a
        live run (never block the loop), but at process exit the helper
        thread would be killed before shm_unlink runs, leaking segments.
        Disconnect wakes the pump's blocked recv immediately, so the join
        is bounded by one recv tick in practice. Safe after close():
        whichever path reaches the native free first wins."""
        self._closing = True
        self._disconnect_once()
        self._thread.join(timeout=timeout)
        self._close_channel_once()


async def serve_stream(
    host_listener: Callable[[NodeConnection], Awaitable[None]],
    *,
    tcp_host: str | None = None,
    uds_path: str | None = None,
) -> tuple[asyncio.AbstractServer, str]:
    """Start one accept loop; every accepted connection is handed to
    ``host_listener`` as a StreamConnection. Returns (server, address)."""

    async def on_client(reader, writer):
        await host_listener(StreamConnection(reader, writer))

    if uds_path is not None:
        server = await asyncio.start_unix_server(on_client, path=uds_path)
        return server, uds_path
    server = await asyncio.start_server(on_client, host=tcp_host or "127.0.0.1", port=0)
    addr = server.sockets[0].getsockname()
    return server, f"{addr[0]}:{addr[1]}"
