"""Node process spawning: source resolution, config injection, log pumps.

Reference parity: binaries/daemon/src/spawn.rs:42-462 — resolve the node
source (dynamic / shell / path / .py), inject ``DORA_NODE_CONFIG``, pipe
stdout/stderr to a per-node log file, keep a small stderr ring buffer for
error reports, re-publish stdout as a dataflow output when
``send_stdout_as`` is set, and watch for process exit.
"""

from __future__ import annotations

import asyncio
import base64
import os
import shlex
import sys
from pathlib import Path
from typing import TYPE_CHECKING

from dora_tpu.core.descriptor import (
    DYNAMIC_SOURCE,
    SHELL_SOURCE,
    CustomNode,
    ResolvedNode,
    RuntimeNode,
)
from dora_tpu.message.common import parse_level_prefix
from dora_tpu.message.daemon_to_node import NodeConfig
from dora_tpu.message.serde import decode, encode

if TYPE_CHECKING:
    from dora_tpu.daemon.core import Daemon, DataflowState

#: Last-N stderr lines kept for failure reports
#: (reference: binaries/daemon/src/lib.rs:69).
STDERR_RING_LINES = 10

NODE_CONFIG_ENV = "DORA_NODE_CONFIG"


def encode_node_config(cfg: NodeConfig) -> str:
    """NodeConfig -> env-var-safe string (base64 of the wire encoding)."""
    return base64.b64encode(encode(cfg)).decode("ascii")


def decode_node_config(value: str) -> NodeConfig:
    cfg = decode(base64.b64decode(value.encode("ascii")))
    if not isinstance(cfg, NodeConfig):
        raise ValueError("DORA_NODE_CONFIG does not contain a NodeConfig")
    return cfg


def log_file_path(working_dir: Path, dataflow_id: str, node_id: str) -> Path:
    """out/<dataflow-id>/log_<node>.txt (reference: daemon/src/log.rs)."""
    return working_dir / "out" / dataflow_id / f"log_{node_id}.txt"


def resolve_command(node: ResolvedNode, working_dir: Path) -> list[str] | str:
    """Resolve a node's source to an argv list (or a shell string).

    - ``path: shell`` runs ``args`` through the shell;
    - ``*.py`` sources run under the current Python interpreter;
    - runtime nodes (operators) run the operator-runtime module;
    - anything else is an executable path or $PATH name.
    """
    if isinstance(node.kind, RuntimeNode):
        return [sys.executable, "-m", "dora_tpu.runtime"]
    custom: CustomNode = node.kind
    source = custom.source
    args = shlex.split(custom.args) if custom.args else []
    if source == SHELL_SOURCE:
        return custom.args or ""
    if "://" in source:
        # URL-sourced node: fetch once into the cache, then run it
        # (reference: daemon/src/spawn.rs resolves url sources via
        # dora-download).
        from dora_tpu.download import download_file

        local = download_file(source)
        if local.suffix == ".py":
            return [sys.executable, str(local)] + args
        return [str(local)] + args
    if source.startswith("module:"):
        # TPU-build addition: run an installed Python module as the node
        # (equivalent of the reference node-hub's console-script entries).
        return [sys.executable, "-m", source[len("module:"):]] + args
    if source.endswith(".py"):
        path = Path(source)
        if not path.is_absolute():
            path = working_dir / path
        return [sys.executable, str(path)] + args
    path = Path(source)
    if not path.is_absolute():
        local = working_dir / path
        if local.exists():
            return [str(local)] + args
    return [source] + args


async def spawn_node(
    daemon: "Daemon",
    df: "DataflowState",
    node: ResolvedNode,
    node_config: NodeConfig,
) -> asyncio.subprocess.Process:
    """Spawn one node process with its config injected via the environment."""
    working_dir = df.working_dir
    cmd = resolve_command(node, working_dir)

    env = dict(os.environ)
    # Chaos marker BEFORE node.env: fault-injection tooling
    # (dora_tpu.tools.chaos) finds victim pids by scanning /proc/*/environ
    # for this id; a descriptor env entry may override it.
    env["DORA_CHAOS_ID"] = f"{df.id}:{node.id}"
    # SLO targets BEFORE node.env so a descriptor env entry can override:
    # serving nodes (nodehub/llm_server) self-check these in their report
    # loop and record slo_violation instants on their own trace track.
    if node.slo is not None:
        for key, target in node.slo.as_targets().items():
            env[f"DORA_SLO_{key.upper()}"] = str(target)
    if node.qos is not None:
        for key, val in node.qos.as_env().items():
            env[f"DORA_QOS_{key}"] = val
    env.update({str(k): str(v) for k, v in node.env.items()})
    env[NODE_CONFIG_ENV] = encode_node_config(node_config)
    # Nodes importing dora_tpu from a source checkout need the repo root.
    repo_root = str(Path(__file__).resolve().parent.parent.parent)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if isinstance(node.kind, RuntimeNode):
        env["DORA_RUNTIME_NODE"] = "1"

    kwargs = dict(
        cwd=str(working_dir),
        env=env,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE,
    )
    if isinstance(cmd, str):
        process = await asyncio.create_subprocess_shell(cmd, **kwargs)
    else:
        try:
            process = await asyncio.create_subprocess_exec(*cmd, **kwargs)
        except FileNotFoundError as e:
            raise RuntimeError(f"node {node.id!r}: cannot spawn {cmd[0]!r}: {e}") from e

    log_path = log_file_path(working_dir, df.id, str(node.id))
    log_path.parent.mkdir(parents=True, exist_ok=True)
    log_file = open(log_path, "ab")

    pumps = [
        asyncio.create_task(
            _pump_stream(daemon, df, node, process.stdout, log_file, is_stderr=False)
        ),
        asyncio.create_task(
            _pump_stream(daemon, df, node, process.stderr, log_file, is_stderr=True)
        ),
    ]
    asyncio.create_task(_watch_exit(daemon, df, node, process, log_file, pumps))
    return process


async def _pump_stream(daemon, df, node, stream, log_file, *, is_stderr: bool):
    send_as = node.send_stdout_as
    while True:
        try:
            line = await stream.readline()
        except (ValueError, ConnectionError):
            # Over-long line without newline: fall back to raw chunks.
            try:
                line = await stream.read(1 << 16)
            except Exception:
                break
        if not line:
            break
        try:
            log_file.write(line)
            log_file.flush()
        except ValueError:
            break  # log file closed during shutdown
        text = line.decode(errors="replace").rstrip("\n")
        if is_stderr:
            ring = df.stderr_rings.setdefault(str(node.id), [])
            ring.append(text)
            del ring[:-STDERR_RING_LINES]
        # Structured severity: a recognizable level prefix on the line
        # wins over the stream-based default (stderr is where Python
        # logging sends EVERYTHING, so "stderr == error" over-counted;
        # conversely an `ERROR:` line on stdout was invisible). Feeds
        # the per-node log_errors/log_warns counters and `logs --level`.
        level = parse_level_prefix(text)
        if level is None:
            level = "error" if is_stderr else "info"
        daemon.on_node_log(df, str(node.id), level, text)
        if not is_stderr and send_as:
            daemon.publish_stdout_line(df, node.id, send_as, text)


async def _watch_exit(daemon, df, node, process, log_file, pumps):
    returncode = await process.wait()
    # Drain stdout/stderr fully before the result is classified (the stderr
    # ring and send_stdout_as republishing must see every line).
    try:
        await asyncio.wait_for(asyncio.gather(*pumps), timeout=10)
    except (asyncio.TimeoutError, Exception):
        pass
    try:
        log_file.close()
    except Exception:
        pass
    daemon.handle_node_exit(df, node.id, returncode)
