"""The per-machine data-plane daemon.

Reference parity: binaries/daemon — one daemon per machine; spawns node
processes, routes outputs to subscriber inputs over shmem/TCP/UDS, owns
timers, tracks shared-memory lifetime via drop tokens, enforces the
cluster-wide start barrier, classifies node failures, and stops dataflows
with a grace-kill.

Design difference: the reference is a tokio actor loop
(binaries/daemon/src/lib.rs:274-337); here the daemon is a single asyncio
event loop where listener coroutines mutate daemon state directly (safe:
cooperative scheduling, no preemption between awaits). Shared-memory
channels — whose recv blocks in native code — are pumped by executor
threads that re-enter the loop via run_coroutine_threadsafe.
"""

from dora_tpu.daemon.core import Daemon, run_dataflow

__all__ = ["Daemon", "run_dataflow"]
