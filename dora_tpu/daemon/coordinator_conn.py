"""Attached-mode daemon: connection to the coordinator.

Reference parity: binaries/daemon/src/coordinator.rs (register with
retry, event/reply pump) and the coordinator-event handling arm of the
daemon main loop (daemon/src/lib.rs:364-407). Heartbeat constants match
the reference: daemon→coordinator every 5 s, bail after 20 s of silence
(daemon/src/lib.rs:262-268,308-324).

A dropped coordinator connection is NOT fatal: the daemon keeps its
dataflows running and re-registers with exponential backoff + jitter.
The reconnect budget stays under the coordinator's 30 s heartbeat-drop
window so the machine slot is still listed when the daemon comes back.
The outbox outlives individual connections — notifications queued while
disconnected (AllNodesFinished, logs, …) flush after re-register.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import TYPE_CHECKING

from dora_tpu import PROTOCOL_VERSION
from dora_tpu.core.descriptor import Descriptor
from dora_tpu.daemon import inter_daemon
from dora_tpu.daemon.spawn import log_file_path
from dora_tpu.message import coordinator as cm
from dora_tpu.message.serde import decode_timestamped, encode_timestamped
from dora_tpu.telemetry import FLIGHT
from dora_tpu.transport.framing import (
    ConnectionClosed,
    recv_frame_async,
    send_frame_async,
)

if TYPE_CHECKING:
    from dora_tpu.daemon.core import Daemon

logger = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_S = 5.0
COORDINATOR_SILENCE_BAIL_S = 20.0
REGISTER_RETRY_S = 1.0  # kept for back-compat; backoff starts here

#: Reconnect backoff: base * 2^attempt with ±25 % jitter, capped.
RECONNECT_BACKOFF_BASE_S = 0.5
RECONNECT_BACKOFF_MAX_S = 5.0
#: Total budget for re-registering after a dropped connection. Must stay
#: under the coordinator's HEARTBEAT_DROP_S (30 s) so the machine is
#: still registered when the daemon comes back.
RECONNECT_WINDOW_S = 25.0


async def run_attached(
    daemon: "Daemon",
    coordinator_addr: str,
    machine_id: str,
    register_timeout_s: float = 30.0,
) -> None:
    """Register with the coordinator and serve its events until destroyed.

    Connection losses inside that lifetime trigger re-register with
    backoff (see module docstring); only DestroyDaemon — or exhausting
    the reconnect window — tears the daemon down."""
    daemon.machine_id = machine_id
    await daemon.start()
    # SIGUSR2 forensics for attached daemons too (run_dataflow_async has
    # its own) — `dora-tpu up`-spawned daemons are the common wedge case.
    from dora_tpu.telemetry import install_task_dump, remove_task_dump

    loop = asyncio.get_running_loop()
    install_task_dump(loop)
    inter_server, inter_port = await inter_daemon.start_server(daemon)
    inter_client = inter_daemon.InterDaemonClient(daemon.clock)

    host, _, port = coordinator_addr.rpartition(":")

    # The outbox outlives connections: messages queued while disconnected
    # are flushed after re-register instead of being lost.
    outbox: asyncio.Queue = asyncio.Queue()

    def notify(kind: str, df, payload) -> None:
        if kind == "ready":
            outbox.put_nowait(
                cm.ReadyOnMachine(dataflow_id=df.id, exited_before_subscribe=payload)
            )
        elif kind == "finished":
            outbox.put_nowait(cm.AllNodesFinished(dataflow_id=df.id, result=payload))

    daemon.coordinator_notify = notify
    daemon.log_sink = lambda log: outbox.put_nowait(cm.DaemonLog(log=log))
    daemon.profile_sink = lambda df_id, node_id, artifact, error: (
        outbox.put_nowait(
            cm.ProfileReplyFromDaemon(
                dataflow_id=df_id, node_id=node_id,
                artifact=artifact, error=error,
            )
        )
    )

    def send_inter(df, machine, output_id, metadata, payload, closed=None):
        addr = df.machine_listen_ports.get(machine)
        if addr is None:
            logger.warning("no listen addr for machine %r", machine)
            return
        if closed is not None:
            event = cm.InterDaemonInputsClosed(dataflow_id=df.id, inputs=closed)
        else:
            event = cm.InterDaemonOutput(
                dataflow_id=df.id,
                output_id=output_id,
                metadata=metadata,
                data=payload,
            )
        asyncio.create_task(inter_client.send(addr, event))

    daemon.inter_daemon_send = send_inter

    first = True
    try:
        while True:
            try:
                reader, writer = await _connect_register(
                    daemon,
                    host,
                    int(port),
                    machine_id,
                    inter_port,
                    timeout_s=register_timeout_s if first else RECONNECT_WINDOW_S,
                )
            except (ConnectionError, RuntimeError):
                if first:
                    raise
                logger.error(
                    "could not re-register with coordinator within %ss; giving up",
                    RECONNECT_WINDOW_S,
                )
                return
            if not first:
                logger.info("re-registered with coordinator")
                if FLIGHT.enabled:
                    FLIGHT.record("daemon_reconnect", machine_id, 0)
            first = False
            destroyed = await _serve_connection(
                daemon, reader, writer, outbox, machine_id
            )
            if destroyed:
                return
            logger.error("lost coordinator connection; reconnecting")
    finally:
        remove_task_dump(loop)
        inter_client.close()
        inter_server.close()
        await daemon.close()


async def _connect_register(
    daemon: "Daemon",
    host: str,
    port: int,
    machine_id: str,
    inter_port: str,
    timeout_s: float,
):
    """Connect + RegisterDaemon with exponential backoff + jitter until
    ``timeout_s`` elapses. A registration *rejection* raises immediately
    (retrying cannot change the coordinator's answer)."""
    deadline = time.monotonic() + timeout_s
    attempt = 0
    while True:
        writer = None
        try:
            reader, writer = await asyncio.open_connection(host, port)
            await send_frame_async(
                writer,
                encode_timestamped(
                    cm.RegisterDaemon(
                        machine_id=machine_id,
                        protocol_version=PROTOCOL_VERSION,
                        listen_port=inter_port,
                    ),
                    daemon.clock,
                ),
            )
            reply = decode_timestamped(
                await recv_frame_async(reader), daemon.clock
            ).inner
            if not isinstance(reply, cm.RegisterDaemonReply) or reply.error:
                raise RuntimeError(
                    f"daemon register failed: {getattr(reply, 'error', reply)}"
                )
            return reader, writer
        except (ConnectionError, ConnectionClosed, OSError) as e:
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass
            if time.monotonic() > deadline:
                raise ConnectionError(f"coordinator unreachable: {e}") from e
            attempt += 1
            delay = min(
                RECONNECT_BACKOFF_BASE_S * (2 ** (attempt - 1)),
                RECONNECT_BACKOFF_MAX_S,
            )
            await asyncio.sleep(delay * (0.75 + 0.5 * random.random()))


async def _serve_connection(
    daemon: "Daemon", reader, writer, outbox: asyncio.Queue, machine_id: str
) -> bool:
    """Pump one coordinator connection. Returns True on DestroyDaemon
    (clean teardown), False when the connection dropped (caller
    reconnects)."""
    last_contact = time.monotonic()

    async def sender():
        while True:
            msg = await outbox.get()
            try:
                await send_frame_async(
                    writer, encode_timestamped(msg, daemon.clock)
                )
            except (ConnectionError, ConnectionClosed, OSError):
                # Keep the message: it retransmits after reconnect.
                outbox.put_nowait(msg)
                return

    async def heartbeat():
        while True:
            await asyncio.sleep(HEARTBEAT_INTERVAL_S)
            if time.monotonic() - last_contact > COORDINATOR_SILENCE_BAIL_S:
                logger.error(
                    "coordinator silent for >%ss; dropping connection",
                    COORDINATOR_SILENCE_BAIL_S,
                )
                writer.close()
                return
            outbox.put_nowait(cm.DaemonHeartbeat())

    tasks = [asyncio.create_task(sender()), asyncio.create_task(heartbeat())]
    try:
        while True:
            frame = await recv_frame_async(reader)
            last_contact = time.monotonic()
            event = decode_timestamped(frame, daemon.clock).inner
            if isinstance(event, cm.Heartbeat):
                continue
            if isinstance(event, cm.SpawnDataflowNodes):
                await _handle_spawn(daemon, outbox, event)
            elif isinstance(event, cm.AllNodesReady):
                df = daemon.dataflows.get(event.dataflow_id)
                if df is None:
                    continue
                if event.exited_before_subscribe:
                    daemon.poison_barrier(df, event.exited_before_subscribe[0])
                else:
                    daemon.release_barrier(df)
            elif isinstance(event, cm.StopDataflow):
                df = daemon.dataflows.get(event.dataflow_id)
                if df is not None:
                    daemon.stop_dataflow(df, event.grace_duration_s)
            elif isinstance(event, cm.ReloadDataflow):
                df = daemon.dataflows.get(event.dataflow_id)
                if df is not None:
                    daemon.reload_node(df, event.node_id, event.operator_id)
            elif isinstance(event, cm.MigrateDataflowNode):
                df = daemon.dataflows.get(event.dataflow_id)
                if df is not None:
                    daemon.migrate_node(df, event.node_id, event.handoff_dir)
            elif isinstance(event, cm.ProfileDataflowNode):
                df = daemon.dataflows.get(event.dataflow_id)
                if df is not None:
                    daemon.profile_node(
                        df, event.node_id, event.action, event.seconds
                    )
            elif isinstance(event, cm.LogsRequest):
                df = daemon.dataflows.get(event.dataflow_id)
                logs = b""
                if df is not None:
                    path = log_file_path(df.working_dir, df.id, event.node_id)
                    if path.exists():
                        logs = path.read_bytes()
                outbox.put_nowait(
                    cm.LogsReplyFromDaemon(
                        dataflow_id=event.dataflow_id,
                        node_id=event.node_id,
                        logs=logs,
                    )
                )
            elif isinstance(event, cm.MetricsRequest):
                df = daemon.dataflows.get(event.dataflow_id)
                outbox.put_nowait(
                    cm.MetricsReplyFromDaemon(
                        dataflow_id=event.dataflow_id,
                        machine_id=machine_id,
                        metrics=(
                            daemon.metrics_snapshot(df) if df is not None else {}
                        ),
                    )
                )
            elif isinstance(event, cm.TraceRequest):
                df = daemon.dataflows.get(event.dataflow_id)
                outbox.put_nowait(
                    cm.TraceReplyFromDaemon(
                        dataflow_id=event.dataflow_id,
                        machine_id=machine_id,
                        trace=(
                            daemon.trace_snapshot(df) if df is not None else {}
                        ),
                    )
                )
            elif isinstance(event, cm.MetricsHistoryRequest):
                df = daemon.dataflows.get(event.dataflow_id)
                outbox.put_nowait(
                    cm.MetricsHistoryReplyFromDaemon(
                        dataflow_id=event.dataflow_id,
                        machine_id=machine_id,
                        history=(
                            daemon.history_snapshot(df) if df is not None else {}
                        ),
                    )
                )
            elif isinstance(event, cm.AlertsRequest):
                df = daemon.dataflows.get(event.dataflow_id)
                outbox.put_nowait(
                    cm.AlertsReplyFromDaemon(
                        dataflow_id=event.dataflow_id,
                        machine_id=machine_id,
                        alerts=(
                            daemon.alerts_snapshot(df) if df is not None else {}
                        ),
                    )
                )
            elif isinstance(event, cm.FleetRequest):
                df = daemon.dataflows.get(event.dataflow_id)
                outbox.put_nowait(
                    cm.FleetReplyFromDaemon(
                        dataflow_id=event.dataflow_id,
                        machine_id=machine_id,
                        fleet=(
                            daemon.fleet_snapshot(df) if df is not None else {}
                        ),
                    )
                )
            elif isinstance(event, cm.DestroyDaemon):
                return True
            else:
                logger.warning("unexpected coordinator event %s", type(event).__name__)
    except (ConnectionClosed, ConnectionError, OSError):
        return False
    finally:
        for t in tasks:
            t.cancel()
        try:
            writer.close()
        except Exception:
            pass


async def _handle_spawn(daemon: "Daemon", outbox, event: cm.SpawnDataflowNodes) -> None:
    error = None
    try:
        descriptor = Descriptor.parse(event.dataflow_descriptor)
        await daemon.spawn_dataflow(
            descriptor,
            dataflow_id=event.dataflow_id,
            working_dir=event.working_dir,
            local_nodes=set(event.nodes),
            machine_listen_ports=event.machine_listen_ports,
        )
    except Exception as e:
        logger.exception("spawn failed")
        error = str(e)
    outbox.put_nowait(
        cm.SpawnDataflowResult(dataflow_id=event.dataflow_id, error=error)
    )
