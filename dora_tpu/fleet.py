"""Fleet state plane: per-replica engine digests and placement scoring.

The ROADMAP's multi-replica router needs to answer "which replica should
serve this prompt?" without inspecting any data-plane internals. This
module is the observability half of that answer — replicas *export*
state, the control plane aggregates it, and a pure function ranks
candidates:

* every serving engine publishes an ``EngineStateDigest``
  (message/common.py) on the ``DORA_FLEET_DIGEST_S`` cadence — a
  bounded radix-cache digest (top-N cached prefixes as incremental
  ``(hash_chain, token_len, pages)`` tuples, see
  models/prefix_cache.py), live page/HBM occupancy, the ``fits()``-
  derived free-stream capacity, the resident adapter set, and a config
  fingerprint that makes interchangeable replicas comparable;
* the plane mirrors the metrics plane wire-for-wire:
  ``n2d.ReportEngineState`` (fire-and-forget) -> daemon keeps
  latest-per-node with a receive stamp -> ``cm.QueryFleet`` fans out
  ``FleetRequest`` per machine and merges the per-daemon snapshots with
  :func:`merge_fleet_snapshots` (HLC-offset alignment, exactly like
  metrics_history);
* :func:`score_placement` is the deterministic placement function the
  future router calls — longest cached prefix wins, occupancy breaks
  ties, and a digest older than the staleness bound is discounted
  toward zero (a stale cache claim is a guess, not a fact).

Staleness bound: placement decisions can lag true cache state by up to
one publish cadence (see KNOWN_ISSUES round 21) — the discount makes
that lag degrade placement *quality*, never correctness, because a
mis-placed request only re-prefills what a hit would have skipped.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any

from dora_tpu.message.common import EngineStateDigest
from dora_tpu.models.prefix_cache import prompt_hash_chain

#: publish cadence in seconds; 0 disables the exporter entirely
DIGEST_INTERVAL_ENV = "DORA_FLEET_DIGEST_S"
DEFAULT_DIGEST_INTERVAL_S = 2.0
#: cached prefixes shipped per digest (bound the wire, not the tree)
TOP_PREFIXES_ENV = "DORA_FLEET_TOP_PREFIXES"
DEFAULT_TOP_PREFIXES = 32
#: a digest older than STALE_FACTOR cadences scores as no information
#: (and trips the `fleet-digest-stale` default alert rule)
STALE_FACTOR = 3.0


def digest_interval_s() -> float:
    try:
        return float(
            os.environ.get(DIGEST_INTERVAL_ENV, DEFAULT_DIGEST_INTERVAL_S)
        )
    except ValueError:
        return DEFAULT_DIGEST_INTERVAL_S


def digest_top_n() -> int:
    try:
        return int(os.environ.get(TOP_PREFIXES_ENV, DEFAULT_TOP_PREFIXES))
    except ValueError:
        return DEFAULT_TOP_PREFIXES


def stale_after_s(interval_s: float | None = None) -> float:
    """Age past which a digest carries no placement signal (and the
    default alert pack considers the exporter wedged)."""
    base = digest_interval_s() if interval_s is None else interval_s
    return STALE_FACTOR * base


def weight_bits_from_env() -> int:
    """Weight precision of the serving process, from the same env knobs
    the engine builders read (int4 wins when both are set, matching the
    builder's precedence)."""
    if os.environ.get("DORA_INT4_DECODE", "0") == "1":
        return 4
    if os.environ.get("DORA_INT8_DECODE", "0") == "1":
        return 8
    return 16


def model_id_from_env() -> str:
    ckpt = os.environ.get("DORA_HF_CHECKPOINT", "")
    return os.path.basename(ckpt.rstrip("/")) or "stub"


def config_fingerprint(*, model_id: str, window: int, spec_k: int,
                       kv_dtype: str, weight_bits: int,
                       page_size: int) -> str:
    """Replicas with equal fingerprints are interchangeable targets:
    same model, same decode window K, same speculation width, same KV
    dtype / weight precision, same page geometry. Deterministic across
    processes (blake2b, never the salted builtin hash)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(
        f"{model_id}|K={window}|spec={spec_k}|kv={kv_dtype}"
        f"|w={weight_bits}|ps={page_size}".encode()
    )
    return h.hexdigest()


# ---------------------------------------------------------------------------
# digest construction (replica side)
# ---------------------------------------------------------------------------


def free_stream_capacity(engine, *, prompt_len: int | None = None,
                         max_new: int = 16) -> int:
    """Streams the engine could admit RIGHT NOW, derived from the same
    ``fits``/``pages_needed`` math admission uses: free slots capped by
    the pages a typical stream (one prefill chunk + ``max_new`` decode
    rows) would claim from the free pool plus evictable cached pages.
    Conservative by construction — a router acting on it may under-fill
    a replica, never overload one."""
    free_slots = int(getattr(engine, "free_slots", 0))
    if not hasattr(engine, "free_pages"):
        # slot engine: capacity is slots, gated on the request ever fitting
        return free_slots if engine.fits(prompt_len or 1, max_new) else 0
    if prompt_len is None:
        prompt_len = int(getattr(engine, "chunk", 0)) or 1
    if free_slots == 0 or not engine.fits(prompt_len, max_new):
        return 0
    avail = engine.free_pages
    cache = getattr(engine, "prefix_cache", None)
    if cache is not None:
        avail += cache.evictable_pages()
    per_stream = max(1, engine.pages_needed(prompt_len, max_new))
    return min(free_slots, avail // per_stream)


def build_digest(
    engine,
    *,
    model_id: str | None = None,
    seq: int = 0,
    top_n: int | None = None,
    hbm_used_bytes: int = 0,
    hbm_limit_bytes: int = 0,
    unix_ts: float | None = None,
) -> EngineStateDigest:
    """Snapshot one engine into the wire digest. Pure reads off the
    scheduler thread's own state — bounded work (top-N walk of the
    radix tree), no device sync, so publishing on a cadence stays off
    the decode critical path."""
    if model_id is None:
        model_id = model_id_from_env()
    window = int(getattr(engine, "window", 0) or 0)
    spec_k = int(getattr(engine, "spec_k", 0) or 0)
    kv_dtype = str(getattr(engine, "kv_dtype", "fp") or "fp")
    weight_bits = weight_bits_from_env()
    page_size = int(getattr(engine, "page_size", 0) or 0)
    alloc = getattr(engine, "allocator", None)
    if alloc is not None:
        # page 0 is the allocator's reserved null page — mirror the
        # metrics plane's total_pages convention.
        total_pages = alloc.num_pages - 1
        used_pages = alloc.in_use
        free_pages = alloc.free_pages
    else:
        total_pages = used_pages = free_pages = 0
    cache = getattr(engine, "prefix_cache", None)
    if cache is not None:
        prefixes = [
            [chain, token_len, pages]
            for chain, token_len, pages in cache.digest(
                digest_top_n() if top_n is None else top_n
            )
        ]
        prefix_pages = cache.size
    else:
        prefixes = []
        prefix_pages = 0
    lora = getattr(engine, "lora", None)
    adapters = (
        sorted(lora.streams_by_adapter()) if lora is not None else []
    )
    return EngineStateDigest(
        model_id=model_id,
        fingerprint=config_fingerprint(
            model_id=model_id, window=window, spec_k=spec_k,
            kv_dtype=kv_dtype, weight_bits=weight_bits, page_size=page_size,
        ),
        page_size=page_size,
        window=window,
        spec_k=spec_k,
        kv_dtype=kv_dtype,
        weight_bits=weight_bits,
        max_slots=int(getattr(engine, "max_slots", 0) or 0),
        free_streams=free_stream_capacity(engine),
        used_pages=used_pages,
        free_pages=free_pages,
        total_pages=total_pages,
        prefix_pages=prefix_pages,
        hbm_used_bytes=int(hbm_used_bytes or 0),
        hbm_limit_bytes=int(hbm_limit_bytes or 0),
        adapters=adapters,
        prefixes=prefixes,
        seq=seq,
        unix_ts=time.time() if unix_ts is None else unix_ts,
    )


class DigestPublisher:
    """Owns one serving node's publish cadence: ``tick(now)`` from the
    serving loop's per-second report path; publishes (fire-and-forget)
    when ``DORA_FLEET_DIGEST_S`` elapsed since the last digest. A
    cadence of 0 disables the plane — the A/B bench's "off" arm."""

    def __init__(self, node, engine, *, model_id: str | None = None,
                 interval_s: float | None = None, tracer=None,
                 hbm=None, clock=time.monotonic):
        self.node = node
        self.engine = engine
        self.model_id = model_id
        self.interval_s = (
            digest_interval_s() if interval_s is None else interval_s
        )
        self.tracer = tracer
        #: optional () -> (used_bytes, limit_bytes) from the device monitor
        self.hbm = hbm
        self.clock = clock
        self.seq = 0
        self._last: float | None = None
        self.enabled = (
            self.interval_s > 0 and hasattr(node, "report_engine_state")
        )

    def tick(self, now: float | None = None) -> bool:
        if not self.enabled:
            return False
        now = self.clock() if now is None else now
        if self._last is not None and now - self._last < self.interval_s:
            return False
        self._last = now
        self.seq += 1
        used = limit = 0
        if self.hbm is not None:
            try:
                used, limit = self.hbm()
            except Exception:
                used = limit = 0
        digest = build_digest(
            self.engine, model_id=self.model_id, seq=self.seq,
            hbm_used_bytes=used, hbm_limit_bytes=limit,
        )
        try:
            self.node.report_engine_state(digest)
        except Exception:
            return False  # fleet state is best-effort, like metrics
        if self.tracer is not None:
            self.tracer.instant(
                "fleet_digest", "(engine)",
                f"seq={self.seq} prefixes={len(digest.prefixes)} "
                f"free_streams={digest.free_streams}",
            )
        return True


# ---------------------------------------------------------------------------
# daemon side
# ---------------------------------------------------------------------------


def digest_as_dict(digest) -> dict[str, Any]:
    """The wire dataclass as the plain dict the daemon stores and the
    snapshot/merge plumbing ships (control-plane payloads are dicts so
    old CLIs tolerate new fields)."""
    import dataclasses

    return dataclasses.asdict(digest)


def fleet_gauges(digest: dict, age_s: float) -> dict[str, Any]:
    """The per-replica gauge block spliced into the daemon's metrics
    snapshot (``snap["fleet"][node]``) — what the history ring flattens
    to ``fleet:<node>:*`` series, the alert pack watches, and prom
    exports as ``dora_fleet_*``."""
    total = int(digest.get("total_pages", 0) or 0)
    used = int(digest.get("used_pages", 0) or 0)
    return {
        "digest_age_s": round(max(0.0, age_s), 3),
        "free_streams": int(digest.get("free_streams", 0) or 0),
        "used_pages": used,
        "total_pages": total,
        "occupancy": round(used / total, 4) if total else 0.0,
        "prefix_pages": int(digest.get("prefix_pages", 0) or 0),
        "seq": int(digest.get("seq", 0) or 0),
    }


# ---------------------------------------------------------------------------
# merge (coordinator side)
# ---------------------------------------------------------------------------


def merge_fleet_snapshots(snapshots: list[dict]) -> dict[str, Any]:
    """Merge per-daemon fleet snapshots (Daemon.fleet_snapshot) into
    one cluster view.

    Each snapshot stamps its machine's wall and HLC clocks back to
    back; the difference is that machine's offset from the cluster HLC
    axis, so per-replica receive stamps land on one comparable ``t_ns``
    axis regardless of wall-clock skew (the metrics_history idiom).
    Digest ages are computed against the *local* wall pair — same
    clock, skew-free — so a skewed machine never reads as stale."""
    replicas: dict[str, dict] = {}
    machines: list[str] = []
    cluster_now = 0
    for snap in snapshots:
        if not isinstance(snap, dict) or not snap:
            continue
        offset = int(snap.get("hlc_ns", 0)) - int(snap.get("wall_ns", 0))
        cluster_now = max(cluster_now, int(snap.get("wall_ns", 0)) + offset)
        machine = str(snap.get("machine_id", ""))
        if machine not in machines:
            machines.append(machine)
        wall_ns = int(snap.get("wall_ns", 0))
        for node, entry in (snap.get("replicas") or {}).items():
            recv_ns = int(entry.get("recv_wall_ns", 0))
            merged = {
                k: v for k, v in entry.items() if k != "recv_wall_ns"
            }
            merged["machine"] = machine
            merged["t_ns"] = recv_ns + offset
            merged["age_s"] = round(max(0, wall_ns - recv_ns) / 1e9, 3)
            prev = replicas.get(node)
            if prev is None or merged["t_ns"] >= prev["t_ns"]:
                replicas[node] = merged
    return {
        "replicas": replicas,
        "machines": sorted(machines),
        "t_ns": cluster_now,
    }


# ---------------------------------------------------------------------------
# placement scoring (router side)
# ---------------------------------------------------------------------------


def score_placement(
    prompt_tokens,
    adapter: str | None,
    replicas: dict[str, dict],
    *,
    stale_after: float | None = None,
) -> list[dict[str, Any]]:
    """Rank replicas for one prompt, best first. Deterministic: the
    same inputs always produce the same order, so a router fleet makes
    consistent decisions without coordination.

    ``replicas`` is the ``merge_fleet_snapshots`` ``"replicas"``
    mapping (digest fields + ``age_s``). Ordering:

    1. score — longest cached prefix (token count) matched by hashing
       the prompt with :func:`prompt_hash_chain` at each replica's own
       page size, discounted linearly to 0 as the digest age approaches
       ``stale_after`` (default 3x the publish cadence);
    2. occupancy — lower used/total page fraction wins ties;
    3. free streams (more is better), then replica id.
    """
    if stale_after is None:
        stale_after = stale_after_s()
    chains_by_ps: dict[int, dict[str, int]] = {}
    ranked: list[dict[str, Any]] = []
    for rid in sorted(replicas):
        d = replicas[rid]
        ps = int(d.get("page_size", 0) or 0)
        if ps > 0 and ps not in chains_by_ps:
            chains_by_ps[ps] = {
                chain: token_len
                for chain, token_len in prompt_hash_chain(
                    prompt_tokens, ps, adapter
                )
            }
        chains = chains_by_ps.get(ps, {})
        matched = 0
        for entry in d.get("prefixes") or []:
            chain, token_len = str(entry[0]), int(entry[1])
            if chains.get(chain) == token_len and token_len > matched:
                matched = token_len
        total = int(d.get("total_pages", 0) or 0)
        used = int(d.get("used_pages", 0) or 0)
        occupancy = round(used / total, 4) if total else 0.0
        age = float(d.get("age_s", 0.0) or 0.0)
        discount = (
            max(0.0, 1.0 - age / stale_after) if stale_after > 0 else 1.0
        )
        ranked.append({
            "replica": rid,
            "matched_tokens": matched,
            "score": round(matched * discount, 3),
            "occupancy": occupancy,
            "age_s": age,
            "free_streams": int(d.get("free_streams", 0) or 0),
            "fingerprint": d.get("fingerprint", ""),
        })
    ranked.sort(key=lambda e: (
        -e["score"], e["occupancy"], -e["free_streams"], e["replica"],
    ))
    return ranked
