"""Token-ids → text decoder node.

The TPU-tier model operators emit token ids (device arrays); the
reference's nodes emit ready-made strings because decoding happens inside
their torch pipelines. This node is the boundary between the two worlds:
it decodes each incoming id array to a string — with the BPE vocabulary
from ``DORA_TOKENIZER`` (a directory or tokenizer.json) when given,
byte-level codec otherwise — and re-emits it as a one-element string
array, ready for sinks that expect text (rerun sink, llama recorder,
openai server).
"""

from __future__ import annotations

import os

import pyarrow as pa

from dora_tpu.node import Node
from dora_tpu.tpu.bridge import arrow_to_host


def make_decoder():
    path = os.environ.get("DORA_TOKENIZER")
    if path:
        from dora_tpu.models.tokenizer import BPETokenizer

        tok = BPETokenizer.from_file(path)
        return lambda ids: tok.decode([int(i) for i in ids])
    from dora_tpu.models import tokenizer

    return lambda ids: tokenizer.decode(ids)


def main() -> None:
    decode = make_decoder()
    with Node() as node:
        for event in node:
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            ids = arrow_to_host(event["value"], event["metadata"]).reshape(-1)
            node.send_output("text", pa.array([decode(ids)]))


if __name__ == "__main__":
    main()
