"""Camera capture node.

Reference parity: node-hub/opencv-video-capture — captures a frame per
``tick`` input; env ``CAPTURE_PATH`` (device index or file),
``IMAGE_WIDTH``/``IMAGE_HEIGHT``/``ENCODING``; self-limits to 10 s under
CI (opencv_video_capture/main.py:11,79-82). Without OpenCV (or without a
camera) it degrades to a synthetic moving test pattern so dataflows stay
runnable anywhere.
"""

from __future__ import annotations

import os
import time

import numpy as np

from dora_tpu.node import Node


def _synthetic_frame(width: int, height: int, t: int) -> np.ndarray:
    yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
    r = 0.5 + 0.5 * np.sin(xx / 17.0 + t * 0.3)
    g = 0.5 + 0.5 * np.sin(yy / 13.0 - t * 0.2)
    b = 0.5 + 0.5 * np.sin((xx + yy) / 23.0 + t * 0.1)
    return (np.stack([b, g, r], axis=-1) * 255).astype(np.uint8)


def main() -> None:
    width = int(os.environ.get("IMAGE_WIDTH", "640"))
    height = int(os.environ.get("IMAGE_HEIGHT", "480"))
    encoding = os.environ.get("ENCODING", "bgr8")
    capture_path = os.environ.get("CAPTURE_PATH", "0")

    capture = None
    try:
        import cv2

        capture = cv2.VideoCapture(
            int(capture_path) if capture_path.isdigit() else capture_path
        )
        if not capture.isOpened():
            capture = None
    except Exception:
        capture = None

    deadline = time.time() + 10 if os.environ.get("CI") else None
    max_frames = int(os.environ.get("MAX_FRAMES", "0"))
    frame_index = 0
    with Node() as node:
        for event in node:
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            if capture is not None:
                ok, frame = capture.read()
                if not ok:
                    break
                frame = frame[:height, :width]
            else:
                frame = _synthetic_frame(width, height, frame_index)
            frame_index += 1
            node.send_output(
                "image",
                np.ascontiguousarray(frame).ravel(),
                {
                    "width": frame.shape[1],
                    "height": frame.shape[0],
                    "encoding": encoding,
                    "shape": list(frame.shape),
                    "dtype": str(frame.dtype),
                },
            )
            if deadline and time.time() > deadline:
                break
            if max_frames and frame_index >= max_frames:
                break


if __name__ == "__main__":
    main()
