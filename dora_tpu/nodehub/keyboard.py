"""Keyboard character source.

Reference parity: node-hub/dora-keyboard — emits one ``char`` output per
key press (dora_keyboard/main.py:7-16, via pynput). Here the terminal
itself is the keyboard: stdin is switched to cbreak mode and read one
character at a time, so the node works over SSH and inside containers
where an X11 event tap (pynput's backend) does not exist. Without a TTY
(CI, piped stdin) it degrades to replaying ``KEYBOARD_SYNTHETIC`` so
dataflows stay runnable anywhere.

Env: ``KEYBOARD_SYNTHETIC`` — string replayed as key presses when stdin
is not a terminal (default "hello"); ``MAX_CHARS`` — stop after N chars
(0 = unlimited); ``CHAR_DELAY_MS`` — spacing of synthetic presses.
"""

from __future__ import annotations

import os
import sys
import time

from dora_tpu.node import Node


def _read_tty_chars(node: Node, max_chars: int) -> None:
    import termios
    import tty

    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    sent = 0
    try:
        tty.setcbreak(fd)
        while True:
            ch = sys.stdin.read(1)
            if not ch or ch == "\x04":  # EOF / ctrl-d
                break
            node.send_output("char", ch.encode())
            sent += 1
            if max_chars and sent >= max_chars:
                break
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


def _replay_synthetic(node: Node, max_chars: int) -> None:
    text = os.environ.get("KEYBOARD_SYNTHETIC", "hello")
    delay = int(os.environ.get("CHAR_DELAY_MS", "10")) / 1000.0
    for i, ch in enumerate(text):
        if max_chars and i >= max_chars:
            break
        node.send_output("char", ch.encode())
        time.sleep(delay)


def main() -> None:
    max_chars = int(os.environ.get("MAX_CHARS", "0"))
    node_id = os.environ.get("NODE_ID")
    daemon_addr = os.environ.get("DORA_DAEMON_ADDR")
    node = Node(node_id=node_id, daemon_addr=daemon_addr) if node_id else Node()
    try:
        if sys.stdin.isatty():
            _read_tty_chars(node, max_chars)
        else:
            _replay_synthetic(node, max_chars)
    finally:
        node.close()


if __name__ == "__main__":
    main()
