"""Test-fixture node: echo every input back out.

Reference parity: node-hub/dora-echo — republishes each input value on the
``echo`` output.
"""

from __future__ import annotations

from dora_tpu.node import Node


def main() -> None:
    with Node() as node:
        for event in node:
            if event["type"] == "INPUT":
                node.send_output("echo", event["value"], event["metadata"])
            elif event["type"] == "STOP":
                break


if __name__ == "__main__":
    main()
