"""Test-fixture node: send a literal pyarrow value.

Reference parity: node-hub/pyarrow-sender — sends the Python literal from
the ``DATA`` env var as one output, then exits.
"""

from __future__ import annotations

import ast
import os
import time

import pyarrow as pa

from dora_tpu.node import Node


def main() -> None:
    data = ast.literal_eval(os.environ.get("DATA", "[1, 2, 3]"))
    count = int(os.environ.get("COUNT", "1"))
    delay = float(os.environ.get("DELAY", "0"))  # seconds before each send
    with Node() as node:
        for _ in range(count):
            if delay:
                time.sleep(delay)
            node.send_output("data", pa.array(data if isinstance(data, list) else [data]))


if __name__ == "__main__":
    main()
