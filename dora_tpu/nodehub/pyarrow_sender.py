"""Test-fixture node: send a literal pyarrow value.

Reference parity: node-hub/pyarrow-sender — sends the Python literal from
the ``DATA`` env var as one output, then exits.
"""

from __future__ import annotations

import ast
import os

import pyarrow as pa

from dora_tpu.node import Node


def main() -> None:
    data = ast.literal_eval(os.environ.get("DATA", "[1, 2, 3]"))
    count = int(os.environ.get("COUNT", "1"))
    with Node() as node:
        for _ in range(count):
            node.send_output("data", pa.array(data if isinstance(data, list) else [data]))


if __name__ == "__main__":
    main()
