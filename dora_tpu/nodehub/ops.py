"""TPU-tier operator factories for the model zoo.

Reference parity: node-hub AI nodes (dora-yolo, dora-qwenvl,
dora-distil-whisper, dora-vad) — re-expressed as fused jax operators
(``jax: dora_tpu.nodehub.ops:make_*`` in a dataflow YAML). Model weights
live in the operator's ``init_state``, so they are device-resident across
ticks; the daemon never sees them.

Model size is selected with the ``DORA_MODEL_SIZE`` env var ("tiny" for
tests/CI, "bench" for benchmarking shapes); checkpoints can be loaded
with ``DORA_CHECKPOINT`` (orbax directory, see dora_tpu.models.checkpoint).
"""

from __future__ import annotations

import os

import jax

from dora_tpu.tpu.api import JaxOperator


def _size() -> str:
    return os.environ.get("DORA_MODEL_SIZE", "tiny")


def _tp_sharding():
    """Megatron tensor-parallel placement rules for transformer weights —
    applied by the fused executor when the runtime serves on a DORA_MESH
    (dora_tpu.tpu.fuse.mesh_from_env); a no-op without a mesh."""
    from dora_tpu.models.layers import tp_rules

    return tp_rules()


def _normalize(image):
    """uint8 camera frames -> float in [0,1]; float frames pass through."""
    import jax.numpy as jnp

    if image.dtype == jnp.uint8:
        return image.astype(jnp.float32) / 255.0
    return image


def _maybe_restore(params, name: str):
    path = os.environ.get("DORA_CHECKPOINT")
    if path:
        from dora_tpu.models.checkpoint import restore

        params = restore(os.path.join(path, name), params)
    return _maybe_cast(params)


def _maybe_cast(params):
    """DORA_PARAM_DTYPE=bfloat16: store weights HBM-resident in bf16
    (serving config — halves memory, MXU-native; fp32 inits are freed
    by donation)."""
    dtype = os.environ.get("DORA_PARAM_DTYPE")
    if not dtype:
        return params
    import jax.numpy as jnp

    cast = jax.jit(
        lambda p: jax.tree.map(lambda x: x.astype(jnp.dtype(dtype)), p),
        donate_argnums=0,
    )
    return cast(params)


def make_detector() -> JaxOperator:
    """Image [H,W,3] float in [0,1] -> boxes/scores/classes (fixed K).

    With DORA_HF_CHECKPOINT pointing at a YOLOS safetensors directory,
    serves the real pretrained detector (reference parity: dora-yolo
    serving ultralytics weights, dora_yolo/main.py:37-104); image must
    arrive at the checkpoint's native resolution.
    """
    from dora_tpu.models import detection

    hf_path = _hf_checkpoint("yolos")
    if hf_path:
        from dora_tpu.models.hf import yolos

        cfg, params = yolos.load(hf_path)
        params = _maybe_cast(params)
        threshold = float(os.environ.get("DORA_DETECT_THRESHOLD", "0.5"))
        top_k = int(os.environ.get("DORA_DETECT_TOPK", str(cfg.n_det)))

        def hf_step(state, inputs):
            import jax.numpy as jnp

            image = _normalize(inputs["image"])[None]
            pixels = yolos.preprocess(image, cfg)
            out = yolos.detect(state, cfg, pixels, threshold, top_k)
            # Operator contract (shared with the self-contained detector,
            # consumed by nodehub/plot.py): absolute-pixel cxcywh.
            x1, y1, x2, y2 = jnp.moveaxis(out["boxes"][0], -1, 0)
            img_h, img_w = cfg.image_size
            boxes = jnp.stack(
                [
                    (x1 + x2) / 2 * img_w,
                    (y1 + y2) / 2 * img_h,
                    (x2 - x1) * img_w,
                    (y2 - y1) * img_h,
                ],
                axis=-1,
            )
            return state, {
                "boxes": boxes,
                "scores": out["scores"][0],
                "classes": out["classes"][0],
            }

        return JaxOperator(
            step=hf_step, init_state=params, sharding=_tp_sharding()
        )

    cfg = (
        detection.DetectorConfig.tiny()
        if _size() == "tiny"
        else detection.DetectorConfig()
    )
    params = _maybe_restore(
        detection.init_params(jax.random.PRNGKey(0), cfg), "detector"
    )

    def step(state, inputs):
        images = _normalize(inputs["image"])[None]  # add batch
        preds = detection.forward(state, cfg, images)
        out = jax.vmap(lambda p: detection.postprocess(cfg, p))(preds)
        return state, {
            "boxes": out["boxes"][0],
            "scores": out["scores"][0],
            "classes": out["classes"][0],
        }

    return JaxOperator(step=step, init_state=params, sharding=_tp_sharding())


def _hf_checkpoint(model_type_prefix: str) -> str | None:
    """Path from DORA_HF_CHECKPOINT when it holds a matching HF checkpoint
    (reference nodes load checkpoints by name through transformers,
    node-hub/dora-qwenvl/dora_qwenvl/main.py:24-33; here the path points
    at a downloaded safetensors directory)."""
    import json
    from pathlib import Path

    path = os.environ.get("DORA_HF_CHECKPOINT")
    if not path:
        return None
    config = Path(path) / "config.json"
    if not config.exists():
        raise FileNotFoundError(f"DORA_HF_CHECKPOINT={path}: no config.json")
    model_type = json.loads(config.read_text()).get("model_type", "")
    return path if model_type.startswith(model_type_prefix) else None


def _hf_tokenizer(path: str):
    from pathlib import Path

    from dora_tpu.models.tokenizer import BPETokenizer

    if (Path(path) / "tokenizer.json").exists():
        return BPETokenizer.from_file(path)
    return None


def make_vlm() -> JaxOperator:
    """Image [H,W,3] -> greedy caption tokens (prompt from DORA_PROMPT).

    With DORA_HF_CHECKPOINT pointing at a Qwen2-VL or InternVL
    safetensors directory, serves the real pretrained model (weights +
    BPE tokenizer); otherwise the self-contained trainable VLM with the
    byte tokenizer.
    """
    import jax.numpy as jnp

    from dora_tpu.models import tokenizer, vlm

    internvl_path = _hf_checkpoint("internvl")
    if internvl_path:
        from dora_tpu.models.hf import internvl

        max_new = int(os.environ.get("DORA_MAX_NEW_TOKENS", "16"))
        height = int(os.environ.get("IMAGE_HEIGHT", "224"))
        width = int(os.environ.get("IMAGE_WIDTH", "224"))
        max_tiles = int(os.environ.get("DORA_MAX_TILES", "12"))
        cfg, params = internvl.load(
            internvl_path, max_seq=int(os.environ.get("DORA_MAX_SEQ", "1024"))
        )
        params = _maybe_cast(params)
        if os.environ.get("DORA_INT8_DECODE") or os.environ.get(
            "DORA_INT4_DECODE"
        ):
            params = internvl.quantize_decode(params, cfg)
        tile = cfg.vision.image_size
        cols, rows, n_tiles = internvl.tile_grid(
            width, height, tile=tile, max_num=max_tiles
        )
        tok = _hf_tokenizer(internvl_path)
        prompt_text = os.environ.get("DORA_PROMPT", "Describe this image.")
        if tok is not None:
            text_ids = tok.encode(prompt_text)
        else:
            text_ids = [t % cfg.text.vocab for t in tokenizer.encode(prompt_text)]
        prompt_ids = internvl.build_prompt_ids(cfg, text_ids, n_tiles)
        from dora_tpu.models.spec_decode import gate_speculation

        speculative = gate_speculation(
            prompt_ids.shape[1], max_new, cfg.text.max_seq
        )
        serve = internvl.make_serving_step(
            cfg, prompt_ids, cols, rows, tile, max_new,
            speculative=speculative,
        )

        def internvl_step(state, inputs):
            tokens = serve(state, _normalize(inputs["image"]))
            return state, {"tokens": tokens[0]}

        return JaxOperator(
            step=internvl_step, init_state=params, sharding=_tp_sharding()
        )

    hf_path = _hf_checkpoint("qwen2_vl")
    if hf_path:
        import numpy as np

        from dora_tpu.models.hf import qwen2_vl

        max_new = int(os.environ.get("DORA_MAX_NEW_TOKENS", "16"))
        height = int(os.environ.get("IMAGE_HEIGHT", "224"))
        width = int(os.environ.get("IMAGE_WIDTH", "224"))
        cfg, params = qwen2_vl.load(
            hf_path, max_seq=int(os.environ.get("DORA_MAX_SEQ", "1024"))
        )
        if os.environ.get("DORA_INT8_DECODE") or os.environ.get(
            "DORA_INT4_DECODE"
        ):
            # Pretrained decode through the fused kernel tier (round 4):
            # quantized LM blocks + head; decode scan and speculative
            # verify route through ops.decode_block automatically.
            params = qwen2_vl.quantize_decode(params, cfg)
        tok = _hf_tokenizer(hf_path)
        prompt_text = os.environ.get("DORA_PROMPT", "Describe this image.")
        target_h, target_w = qwen2_vl.smart_resize(
            height, width, factor=cfg.vision.patch_size * cfg.vision.spatial_merge_size
        )
        if tok is not None:
            text_ids = tok.encode(prompt_text)
        else:  # no tokenizer.json shipped: byte-fallback text encoding
            text_ids = [t % cfg.vocab for t in tokenizer.encode(prompt_text)]
        prompt_ids = qwen2_vl.build_prompt_ids(
            cfg, text_ids, target_h, target_w
        )
        from dora_tpu.models.spec_decode import gate_speculation

        speculative = gate_speculation(
            prompt_ids.shape[1], max_new, cfg.max_seq
        )
        serve = qwen2_vl.make_serving_step(
            cfg, prompt_ids, target_h, target_w, max_new,
            speculative=speculative,
        )

        def hf_step(state, inputs):
            tokens = serve(state, _normalize(inputs["image"]))
            return state, {"tokens": tokens[0]}

        return JaxOperator(
            step=hf_step, init_state=params, sharding=_tp_sharding()
        )

    cfg = vlm.VLMConfig.tiny() if _size() == "tiny" else vlm.VLMConfig.bench_2b()
    params = _maybe_restore(vlm.init_params(jax.random.PRNGKey(0), cfg), "vlm")
    if os.environ.get("DORA_INT8_DECODE") or os.environ.get(
        "DORA_INT4_DECODE"
    ):
        # Bandwidth lever: quantized LM weights, dequantized at the MXU
        # edge (ops.int8_matmul / ops.int4 — quantize_decode picks the
        # width from the env). Applied after cast/restore so the stored
        # float weights are the quantization source.
        params = vlm.quantize_decode(params)
    prompt_text = os.environ.get("DORA_PROMPT", "describe")
    max_new = int(os.environ.get("DORA_MAX_NEW_TOKENS", "16"))
    prompt = jnp.asarray(
        [[t % cfg.vocab for t in tokenizer.encode(prompt_text)]], jnp.int32
    )

    from dora_tpu.models.spec_decode import gate_speculation

    speculative = gate_speculation(
        cfg.n_patches + prompt.shape[1], max_new, cfg.max_seq,
        batch_ok=prompt.shape[0] == 1,
    )

    # Round-5 composition: on a DORA_MESH with tp>1 and a quantized
    # fused layout, the decode scan rides the tensor-parallel KERNEL
    # tier (parallel/fused_tp.py) instead of the unfused XLA path — the
    # fastest path and the multi-chip path are the same path. The
    # prepared tp tree lives in the closure (not operator state): the
    # executor's sharding rules must not re-place its per-rank layout.
    tp_setup = None
    if vlm.fused_decode_ready(params, prompt.shape[0]) and not speculative:
        from dora_tpu.parallel import fused_tp as FTP
        from dora_tpu.tpu.fuse import mesh_from_env

        mesh = mesh_from_env()
        tp = FTP.tp_degree(mesh)
        if mesh is not None and FTP.tp_compatible(
            tp, heads=cfg.heads, kv_heads=cfg.kv_heads, ffn=cfg.ffn,
            vocab=cfg.vocab,
        ):
            try:
                tp_setup = (
                    FTP.prepare_decode_params(
                        params, mesh, heads=cfg.heads,
                        kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
                        layers=cfg.layers,
                    ),
                    mesh,
                )
            except ValueError:  # int4 groups do not tile on this mesh
                tp_setup = None

    def step(state, inputs):
        image = _normalize(inputs["image"])[None]
        if speculative:
            # Prompt-lookup speculation: identical greedy tokens, up to
            # k+1 per model pass (vlm.generate_speculative).
            tokens, _ = vlm.generate_speculative(
                state, cfg, image, prompt, max_new
            )
        elif tp_setup is not None:
            tokens = vlm.generate_tp(
                state, tp_setup[0], cfg, image, prompt, max_new,
                tp_setup[1],
            )
        else:
            tokens = vlm.generate(state, cfg, image, prompt, max_new)
        return state, {"tokens": tokens[0]}

    return JaxOperator(step=step, init_state=params, sharding=_tp_sharding())


def make_asr() -> JaxOperator:
    """Audio chunk [samples] float -> token ids.

    With DORA_HF_CHECKPOINT pointing at a Whisper-family safetensors
    directory, serves the real pretrained model.
    """
    from dora_tpu.models import asr, tokenizer

    hf_path = _hf_checkpoint("whisper")
    if hf_path:
        from dora_tpu.models.hf import whisper

        max_new = int(os.environ.get("DORA_MAX_NEW_TOKENS", "32"))
        cfg, params = whisper.load(hf_path)
        from dora_tpu.models.spec_decode import gate_speculation

        speculative = gate_speculation(1, max_new, cfg.max_target)
        serve = whisper.make_serving_step(cfg, max_new, speculative=speculative)

        def hf_step(state, inputs):
            tokens = serve(state, inputs["audio"])
            return state, {"tokens": tokens[0]}

        return JaxOperator(
            step=hf_step, init_state=params, sharding=_tp_sharding()
        )

    cfg = asr.ASRConfig.tiny() if _size() == "tiny" else asr.ASRConfig()
    params = _maybe_restore(asr.init_params(jax.random.PRNGKey(0), cfg), "asr")
    max_new = min(
        int(os.environ.get("DORA_MAX_NEW_TOKENS", "16")), cfg.max_tokens
    )
    bos = tokenizer.BOS % cfg.vocab

    def step(state, inputs):
        audio = inputs["audio"][None]
        tokens = asr.transcribe(state, cfg, audio, bos, max_new)
        return state, {"tokens": tokens[0]}

    return JaxOperator(step=step, init_state=params, sharding=_tp_sharding())


def make_translator() -> JaxOperator:
    """Text (utf-8 bytes or token ids) -> translated token ids.

    Reference parity: node-hub/dora-opus / dora-argotranslate (text in,
    translated text out through a pretrained encoder-decoder). Tokens ride
    the byte-level codec (dora_tpu.models.tokenizer), so the emitted ids
    decode back to text with ``tokenizer.decode``.
    """
    import jax.numpy as jnp

    from dora_tpu.models import tokenizer, translation

    cfg = (
        translation.TranslatorConfig.tiny()
        if _size() == "tiny"
        else translation.TranslatorConfig()
    )
    params = _maybe_restore(
        translation.init_params(jax.random.PRNGKey(0), cfg), "translator"
    )
    # Decode steps beyond the KV-cache capacity would silently clamp.
    max_new = min(
        int(os.environ.get("DORA_MAX_NEW_TOKENS", "16")), cfg.max_tokens
    )
    bos = tokenizer.BOS % cfg.vocab

    def step(state, inputs):
        src = inputs["text"].astype(jnp.int32) % cfg.vocab
        # Static-shape source window: trim or right-pad to max_src (the
        # pad id attends as ordinary context; real checkpoints mask it).
        src = src[: cfg.max_src]
        src = jnp.pad(src, (0, cfg.max_src - src.shape[0]),
                      constant_values=tokenizer.PAD % cfg.vocab)
        tokens = translation.translate(state, cfg, src[None], bos, max_new)
        return state, {"tokens": tokens[0]}

    return JaxOperator(step=step, init_state=params, sharding=_tp_sharding())


def make_tts() -> JaxOperator:
    """Text (utf-8 bytes / token ids) -> waveform samples.

    Reference parity: node-hub/dora-parler (text in, speech out,
    dora_parler/main.py:94-150). ``DORA_TTS_STYLE`` selects the voice
    (the reference's description prompt); output is float32 in [-1, 1]
    at ``cfg.sample_rate``.

    With DORA_HF_CHECKPOINT pointing at a VITS / MMS-TTS safetensors
    directory, serves the real pretrained model — text bytes are
    tokenized with the checkpoint's VITS convention (lowercase chars
    interleaved with pad 0) and synthesized deterministically.
    """
    import jax.numpy as jnp

    from dora_tpu.models import tokenizer, tts

    vits_path = _hf_checkpoint("vits")
    if vits_path:
        import json
        from pathlib import Path

        import numpy as np

        from dora_tpu.models.hf import vits

        cfg, params = vits.load(vits_path)
        vocab_file = Path(vits_path) / "vocab.json"
        vocab = (
            json.loads(vocab_file.read_text()) if vocab_file.exists() else None
        )

        def encode_text(raw: bytes) -> list[int]:
            text = raw.decode("utf-8", "ignore").lower()
            if vocab is None:  # no tokenizer shipped: byte-fallback ids
                ids = [b % cfg.vocab for b in text.encode()]
            else:
                ids = [vocab[ch] for ch in text if ch in vocab]
            # VITS convention: pad token 0 interleaved around each char.
            out = [0]
            for t in ids:
                out += [t, 0]
            return out

        def vits_step(state, inputs):
            raw = bytes(np.asarray(inputs["text"]).astype(np.uint8))
            ids = np.asarray([encode_text(raw)], np.int32)
            # Bucketed: pads text/frames to bucket edges so serving
            # varying-length sentences compiles at most once per bucket
            # instead of once per length (vits.synthesize_bucketed).
            wave = vits.synthesize_bucketed(state, cfg, ids)
            return state, {"audio": jnp.asarray(wave[0])}

        # host=True: synthesis length is data-dependent (predicted
        # durations), so the step cannot run under the fused jit.
        return JaxOperator(step=vits_step, init_state=params, host=True)

    cfg = tts.TTSConfig.tiny() if _size() == "tiny" else tts.TTSConfig()
    params = _maybe_restore(tts.init_params(jax.random.PRNGKey(0), cfg), "tts")
    style = int(os.environ.get("DORA_TTS_STYLE", "0")) % cfg.n_styles

    def step(state, inputs):
        text = inputs["text"].astype(jnp.int32) % cfg.vocab
        text = text[: cfg.max_text]
        text = jnp.pad(text, (0, cfg.max_text - text.shape[0]),
                       constant_values=tokenizer.PAD % cfg.vocab)
        wave = tts.synthesize(state, cfg, text[None], jnp.asarray([style]))
        return state, {"audio": wave[0]}

    return JaxOperator(step=step, init_state=params)


def make_vad() -> JaxOperator:
    """Audio chunk [samples] -> speech probability.

    With DORA_HF_CHECKPOINT pointing at a Wav2Vec2 audio-frame
    classification directory (superb/sd-class), serves the real
    pretrained model: per-chunk speech probability = max frame speech
    probability (reference job: dora-vad's Silero gate). Otherwise the
    self-contained GRU whose state threads across ticks in device
    memory."""
    import jax.numpy as jnp

    from dora_tpu.models import vad

    hf_path = _hf_checkpoint("wav2vec2")
    if hf_path:
        from dora_tpu.models.hf import wav2vec2

        cfg, params = wav2vec2.load(hf_path)

        def hf_step(state, inputs):
            probs = wav2vec2.speech_probability(state, cfg, inputs["audio"][None])
            return state, {"prob": jnp.max(probs, axis=-1)}

        return JaxOperator(step=hf_step, init_state=params)

    cfg = vad.VADConfig.tiny() if _size() == "tiny" else vad.VADConfig()
    params = _maybe_restore(vad.init_params(jax.random.PRNGKey(0), cfg), "vad")
    h0 = jnp.zeros((1, cfg.hidden), jnp.float32)

    def step(state, inputs):
        params, h = state
        audio = inputs["audio"][None]
        prob, h = vad.speech_prob(params, cfg, audio, h)
        return (params, h), {"prob": prob}

    return JaxOperator(step=step, init_state=(params, h0))
