"""Training-pair recorder: images + question + answer → LLaMA-Factory
sharegpt dataset.

Reference parity: node-hub/llama-factory-recorder
(llama_factory_recorder/main.py:100-200) — buffers every ``*image*``
input, updates the question on ``text``, and on each ``ground_truth``
writes the frames as PNGs plus a sharegpt-format entry
(``{"messages": [user "<image>"*N + question, assistant answer],
"images": [...]}``) appended to ``<entry>.json`` (JSON-lines), keeping
``dataset_info.json`` registered so LLaMA-Factory fine-tuning (the
reference's VLM-training loop) picks the dataset up directly.

Env: ``LLAMA_FACTORY_ROOT_PATH`` (required — dataset root; entries land
under ``<root>/data``), ``ENTRY_NAME`` (default ``dora_demo``,
auto-suffixed when taken), ``DEFAULT_QUESTION``.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

from dora_tpu.node import Node
from dora_tpu.nodehub.imaging import decode_image

DATASET_TAGS = {
    "role_tag": "role",
    "content_tag": "content",
    "user_tag": "user",
    "assistant_tag": "assistant",
}


def update_dataset_info(info_path: Path, entry_name: str) -> None:
    """Register the dataset in ``dataset_info.json`` (merge-not-clobber,
    reference main.py:17-45)."""
    info = {}
    if info_path.exists():
        try:
            info = json.loads(info_path.read_text())
        except json.JSONDecodeError:
            info = {}
    info[entry_name] = {
        "file_name": entry_name + ".json",
        "formatting": "sharegpt",
        "columns": {"messages": "messages", "images": "images"},
        "tags": DATASET_TAGS,
    }
    info_path.write_text(json.dumps(info, indent=4, ensure_ascii=False))


def unique_entry_name(data_dir: Path, entry_name: str) -> str:
    if not (data_dir / f"{entry_name}.json").exists():
        return entry_name
    i = 1
    while (data_dir / f"{entry_name}_{i}.json").exists():
        i += 1
    return f"{entry_name}_{i}"


def save_pair(
    data_dir: Path, entry_name: str, frames: dict[str, np.ndarray],
    question: str, answer: str,
) -> dict:
    """Write PNGs + append one sharegpt record; returns the record."""
    from PIL import Image

    image_dir = data_dir / entry_name
    image_dir.mkdir(parents=True, exist_ok=True)
    pair_index = len(list(image_dir.iterdir()))
    image_paths = []
    for event_id, frame in frames.items():
        rel = f"{entry_name}/{event_id.replace('/', '_')}-{pair_index}.png"
        Image.fromarray(frame).save(data_dir / rel)
        image_paths.append(rel)
    record = {
        "messages": [
            {"content": "<image>" * len(frames) + question, "role": "user"},
            {"content": answer, "role": "assistant"},
        ],
        "images": image_paths,
    }
    with open(data_dir / f"{entry_name}.json", "a", encoding="utf-8") as f:
        f.write(json.dumps(record, ensure_ascii=False) + "\n")
    return record


def _text_of(value) -> str:
    import pyarrow as pa

    if isinstance(value, pa.Array):
        items = value.to_pylist()
        return str(items[0]) if items else ""
    return bytes(value).decode(errors="replace")


def main() -> None:
    root = os.environ.get("LLAMA_FACTORY_ROOT_PATH")
    assert root, (
        "LLAMA_FACTORY_ROOT_PATH is not set; point it at the LLaMA-Factory "
        "checkout (or any directory) to receive the dataset"
    )
    data_dir = Path(root) / "data"
    data_dir.mkdir(parents=True, exist_ok=True)
    entry_name = unique_entry_name(
        data_dir, os.environ.get("ENTRY_NAME", "dora_demo")
    )

    question = os.environ.get("DEFAULT_QUESTION", "Describe this image")
    frames: dict[str, np.ndarray] = {}
    pairs = 0

    with Node() as node:
        for event in node:
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            input_id = event["id"]
            if "image" in input_id:
                frame = decode_image(event["value"], event["metadata"])
                if frame is not None:
                    frames[input_id] = frame
            elif input_id == "text":
                text = _text_of(event["value"])
                if text:
                    question = text
            elif input_id == "ground_truth":
                if not frames:
                    continue
                answer = _text_of(event["value"])
                save_pair(data_dir, entry_name, frames, question, answer)
                pairs += 1
                if pairs == 1:
                    # Register only once data exists: an aborted run must
                    # not leave dataset_info.json pointing at a missing file.
                    update_dataset_info(
                        data_dir / "dataset_info.json", entry_name
                    )

    print(f"recorded {pairs} pairs -> {data_dir / (entry_name + '.json')}")


if __name__ == "__main__":
    main()
