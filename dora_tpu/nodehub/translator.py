"""Pretrained translation node (Opus-MT / Marian).

Reference parity: node-hub/dora-opus/dora_opus/main.py — text in,
translated text out through a pretrained Marian checkpoint. Here the
model is the JAX Marian implementation (dora_tpu.models.hf.marian,
torch-parity-tested) and tokenization is the native sentencepiece
unigram segmenter (dora_tpu.models.spm) — host-side tokenize, jitted
encode+greedy-decode on device, host-side detokenize.

Env:
- ``DORA_HF_CHECKPOINT``: Marian safetensors directory (config.json,
  vocab.json, source.spm[, target.spm]). Required — this node exists to
  serve real weights; the trainable self-contained path stays on the
  ``make_translator`` jax operator.
- ``DORA_MAX_NEW_TOKENS`` (default 64), ``DORA_MAX_SRC`` (default 64).

Input events: ``text`` — an Arrow string array (each element translated
in order) or utf-8 bytes. Output: ``text`` — Arrow string array.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa

from dora_tpu.node import Node


def _texts_from_event(value) -> list[str]:
    if isinstance(value, (bytes, bytearray, memoryview)):
        return [bytes(value).decode("utf-8", errors="replace")]
    if isinstance(value, pa.ChunkedArray):
        value = value.combine_chunks()
    if isinstance(value, pa.Array):
        if pa.types.is_string(value.type) or pa.types.is_large_string(value.type):
            return [str(v) for v in value.to_pylist() if v is not None]
        # numeric array: utf-8 bytes / token ids from a byte-codec stage
        data = np.asarray(value.to_numpy(zero_copy_only=False))
        return [bytes(int(b) & 0xFF for b in data.reshape(-1)).decode(
            "utf-8", errors="replace")]
    return [str(value)]


def main() -> None:
    import jax.numpy as jnp

    from dora_tpu.models.hf import marian

    checkpoint = os.environ.get("DORA_HF_CHECKPOINT")
    if not checkpoint:
        raise RuntimeError(
            "dora_tpu.nodehub.translator serves a pretrained Marian "
            "checkpoint; set DORA_HF_CHECKPOINT (for the self-contained "
            "trainable path use the make_translator jax operator)"
        )
    max_new = int(os.environ.get("DORA_MAX_NEW_TOKENS", "64"))
    max_src = int(os.environ.get("DORA_MAX_SRC", "64"))
    cfg, params = marian.load(checkpoint, max_tokens=max_new)
    tok = marian.MarianTokenizer(checkpoint)

    def translate_one(text: str) -> str:
        ids = tok.encode(text)
        if len(ids) > max_src:  # truncate pieces but keep the closing </s>
            ids = ids[: max_src - 1] + [tok.eos_id]
        src = np.full((1, max_src), cfg.pad_token, np.int32)
        src[0, : len(ids)] = ids
        mask = jnp.asarray(np.arange(max_src)[None, :] < len(ids))
        out = np.asarray(
            marian.translate(params, cfg, jnp.asarray(src), max_new,
                             src_mask=mask)
        )[0]
        keep = []
        for t in out:
            if int(t) == cfg.eos_token:
                break
            keep.append(int(t))
        return tok.decode(keep)

    with Node() as node:
        for event in node:
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            texts = _texts_from_event(event["value"])
            node.send_output(
                "text", pa.array([translate_one(t) for t in texts])
            )


if __name__ == "__main__":
    main()
