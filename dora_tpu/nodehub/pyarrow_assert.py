"""Test-fixture node: assert every input equals a literal pyarrow value.

Reference parity: node-hub/pyarrow-assert — exits nonzero (failing the
dataflow) if any received input differs from the ``DATA`` env literal.
"""

from __future__ import annotations

import ast
import os
import sys

import pyarrow as pa

from dora_tpu.node import Node


def main() -> None:
    raw = os.environ.get("DATA", "[1, 2, 3]")
    data = ast.literal_eval(raw)
    expected = pa.array(data if isinstance(data, list) else [data])
    received = 0
    with Node() as node:
        for event in node:
            if event["type"] == "INPUT":
                value = event["value"]
                if not value.equals(expected):
                    print(
                        f"assertion failed: got {value!r}, expected {expected!r}",
                        file=sys.stderr,
                    )
                    sys.exit(1)
                received += 1
            elif event["type"] == "STOP":
                break
    min_count = int(os.environ.get("MIN_COUNT", "1"))
    if received < min_count:
        print(f"expected at least {min_count} inputs, got {received}", file=sys.stderr)
        sys.exit(1)
    print(f"asserted {received} inputs OK")


if __name__ == "__main__":
    main()
