"""Audio playback sink: play every ``audio`` input, or save WAV headless.

Reference parity: node-hub/dora-parler opens a pyaudio output stream and
plays synthesized chunks as they arrive (dora_parler/main.py:52-75).
Playback here goes through ``sounddevice`` when present; without an audio
stack (TPU pods, CI) each chunk is appended to a WAV file under
``SPEAKER_OUT`` so the speech path stays testable end to end.

Env: ``SAMPLE_RATE`` (default 16000), ``SPEAKER_OUT`` (default
``speaker-out``).
"""

from __future__ import annotations

import os
import wave
from pathlib import Path

import numpy as np

from dora_tpu.node import Node


def _as_float_wave(value, metadata=None) -> np.ndarray:
    import pyarrow as pa

    from dora_tpu.tpu.bridge import arrow_to_host

    if isinstance(value, pa.Array):
        wave_arr = np.asarray(arrow_to_host(value, metadata)).reshape(-1)
    else:
        wave_arr = np.frombuffer(bytes(value), dtype=np.float32)
    if wave_arr.dtype == np.int16:
        return wave_arr.astype(np.float32) / 32768.0
    return wave_arr.astype(np.float32)


def main() -> None:
    sample_rate = int(os.environ.get("SAMPLE_RATE", "16000"))
    out_dir = Path(os.environ.get("SPEAKER_OUT", "speaker-out"))

    stream = None
    try:
        import sounddevice

        stream = sounddevice.OutputStream(
            samplerate=sample_rate, channels=1, dtype="float32"
        )
        stream.start()
    except Exception:
        stream = None

    writer = None
    chunks = 0
    try:
        with Node() as node:
            for event in node:
                if event["type"] == "STOP":
                    break
                if event["type"] != "INPUT":
                    continue
                samples = _as_float_wave(event["value"], event["metadata"])
                if stream is not None:
                    stream.write(samples.reshape(-1, 1))
                else:
                    if writer is None:
                        out_dir.mkdir(parents=True, exist_ok=True)
                        writer = wave.open(str(out_dir / "speech.wav"), "wb")
                        writer.setnchannels(1)
                        writer.setsampwidth(2)
                        writer.setframerate(sample_rate)
                    pcm = (np.clip(samples, -1.0, 1.0) * 32767).astype("<i2")
                    writer.writeframes(pcm.tobytes())
                chunks += 1
    finally:
        if writer is not None:
            writer.close()
        if stream is not None:
            stream.stop()
            stream.close()
    print(f"played {chunks} chunks", flush=True)


if __name__ == "__main__":
    main()
