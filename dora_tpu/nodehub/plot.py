"""Plot / overlay sink node.

Reference parity: node-hub/opencv-plot — draws bounding boxes and text
onto frames and displays them. Headless-safe: with no display (or no
OpenCV) it writes annotated frames to ``PLOT_OUTPUT_DIR`` (or just counts
frames), so CI and benches can use the same graph as a workstation.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from dora_tpu.node import Node
from dora_tpu.tpu.bridge import arrow_to_host


def main() -> None:
    out_dir = os.environ.get("PLOT_OUTPUT_DIR")
    max_frames = int(os.environ.get("MAX_FRAMES", "0"))
    try:
        import cv2
    except Exception:
        cv2 = None

    frame = None
    meta = {}
    boxes = None
    shown = 0

    with Node() as node:
        for event in node:
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            if event["id"].endswith("image"):
                meta = event["metadata"]
                frame = arrow_to_host(event["value"], meta)
                if "shape" in meta:
                    frame = frame.reshape([int(s) for s in meta["shape"]])
            elif event["id"].endswith("boxes") or event["id"] == "bbox":
                boxes = arrow_to_host(event["value"], event["metadata"])
            if frame is None:
                continue
            canvas = np.array(frame)
            if boxes is not None and cv2 is not None and boxes.ndim == 2:
                for cx, cy, w, h in boxes[:, :4]:
                    p1 = (int(cx - w / 2), int(cy - h / 2))
                    p2 = (int(cx + w / 2), int(cy + h / 2))
                    cv2.rectangle(canvas, p1, p2, (0, 255, 0), 2)
            shown += 1
            if out_dir and cv2 is not None:
                Path(out_dir).mkdir(parents=True, exist_ok=True)
                cv2.imwrite(str(Path(out_dir) / f"frame_{shown:05d}.jpg"), canvas)
            elif cv2 is not None and os.environ.get("DISPLAY"):
                cv2.imshow("dora-tpu", canvas)
                cv2.waitKey(1)
            if max_frames and shown >= max_frames:
                break
    print(f"plotted {shown} frames")


if __name__ == "__main__":
    main()
