"""Shared image-frame decoding for node-hub sinks.

The wire contract for camera-class producers (reference:
opencv-video-capture, dora-rerun src/main.rs:60-120): a flat uint8 array
plus metadata ``encoding`` (bgr8 | rgb8 | jpeg | png), ``width``,
``height``. Sinks (visualizer, dataset recorder) decode to RGB [H, W, 3]
uint8 through this module.
"""

from __future__ import annotations

import io

import numpy as np


def as_numpy(value, metadata=None) -> np.ndarray:
    import pyarrow as pa

    from dora_tpu.tpu.bridge import arrow_to_host

    if isinstance(value, pa.Array):
        return np.asarray(arrow_to_host(value, metadata))
    return np.asarray(memoryview(value), dtype=np.uint8)


def decode_image(value, metadata) -> np.ndarray | None:
    """Metadata-driven decode to RGB [H, W, 3] uint8; None when the
    payload is too small for the declared geometry."""
    encoding = str(metadata.get("encoding", "bgr8"))
    if encoding in ("jpeg", "png"):
        from PIL import Image

        data = bytes(as_numpy(value).astype(np.uint8).reshape(-1))
        return np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    width = int(metadata.get("width", 640))
    height = int(metadata.get("height", 480))
    flat = as_numpy(value, metadata).astype(np.uint8).reshape(-1)
    if flat.size < width * height * 3:
        return None
    frame = flat[: width * height * 3].reshape(height, width, 3)
    if encoding == "bgr8":
        frame = frame[..., ::-1]
    return frame
