"""Visualizer sink: stream images, 2-D boxes, and text to a viewer.

Reference parity: node-hub/dora-rerun (src/main.rs:60-170) routes inputs
by id substring — ``image`` (bgr8/rgb8/jpeg/png from metadata
``encoding``/``width``/``height``), ``text``, ``boxes2d`` (bbox struct +
labels + conf, ``format`` defaults to xyxy) — into the Rerun viewer.

This sink keeps that exact routing contract. With the ``rerun`` SDK
installed it logs to a live viewer the same way; headless (the common
case on a TPU pod) it writes a **self-contained HTML replay** — frames as
embedded PNGs with box overlays drawn on a canvas and a scrolling text
log — so a dataflow can be visually inspected over nothing but a file
copy. Env: ``RERUN_OUT`` (output dir, default ``rerun-out``),
``README`` (logged as a text document, reference main.rs:46-57),
``MAX_LOG_FRAMES`` (HTML replay cap, default 300).
"""

from __future__ import annotations

import base64
import html
import io
import json
import os
from pathlib import Path

import numpy as np

from dora_tpu.node import Node


def _try_rerun():
    try:
        import rerun  # noqa: F401

        return rerun
    except ImportError:
        return None


from dora_tpu.nodehub.imaging import as_numpy as _as_numpy
from dora_tpu.nodehub.imaging import decode_image as _decode_image


def _decode_boxes(value, metadata) -> dict:
    """bbox struct {bbox, labels, conf} → python lists; xyxy default."""
    import pyarrow as pa

    fmt = str(metadata.get("format", "xyxy"))
    if isinstance(value, pa.Array) and pa.types.is_struct(value.type):
        struct = value
        bbox = np.asarray(
            struct.field("bbox").flatten().to_numpy(zero_copy_only=False),
            np.float32,
        ).reshape(-1, 4)
        labels = struct.field("labels").flatten().to_pylist()
        conf = struct.field("conf").flatten().to_pylist()
    else:
        bbox = _as_numpy(value).astype(np.float32).reshape(-1, 4)
        labels = [""] * len(bbox)
        conf = [1.0] * len(bbox)
    if fmt == "xywh":
        x, y, w, h = bbox.T
        bbox = np.stack([x, y, x + w, y + h], axis=1)
    return {
        "bbox": bbox.tolist(),
        "labels": [str(l) for l in labels],
        "conf": [float(c) for c in conf],
    }


def _png_b64(frame: np.ndarray) -> str:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(frame).save(buf, format="PNG")
    return base64.b64encode(buf.getvalue()).decode()


_HTML_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>dora-tpu replay</title><style>
body {{ font-family: sans-serif; background: #111; color: #eee; margin: 1em; }}
canvas {{ border: 1px solid #444; }} #log {{ white-space: pre-wrap;
font-family: monospace; max-height: 16em; overflow-y: auto; }}
</style></head><body>
<h3>dora-tpu replay · {title}</h3>
<canvas id="c" width="{width}" height="{height}"></canvas>
<div><input id="s" type="range" min="0" max="{last}" value="0"
style="width:{width}px"><span id="n"></span></div>
<div id="log"></div>
<script>
const FRAMES = {frames_json};
const TEXTS = {texts_json};
const c = document.getElementById("c"), ctx = c.getContext("2d");
const s = document.getElementById("s"), n = document.getElementById("n");
function draw(i) {{
  const f = FRAMES[i]; if (!f) return;
  n.textContent = " frame " + i + " · " + f.id;
  const img = new Image();
  img.onload = () => {{
    ctx.drawImage(img, 0, 0);
    ctx.lineWidth = 2; ctx.strokeStyle = "#4f4"; ctx.fillStyle = "#4f4";
    ctx.font = "12px monospace";
    for (const [j, b] of (f.boxes ? f.boxes.bbox : []).entries()) {{
      ctx.strokeRect(b[0], b[1], b[2] - b[0], b[3] - b[1]);
      const label = (f.boxes.labels[j] || "") + " " +
        (f.boxes.conf[j] || 0).toFixed(2);
      ctx.fillText(label, b[0] + 2, b[1] + 12);
    }}
  }};
  img.src = "data:image/png;base64," + f.png;
}}
s.oninput = () => draw(+s.value);
document.getElementById("log").textContent = TEXTS.join("\\n");
draw(0);
</script></body></html>
"""


class HtmlReplay:
    """Accumulates the event stream and renders the standalone HTML."""

    def __init__(self, max_frames: int):
        self.max_frames = max_frames
        self.frames: list[dict] = []
        self.texts: list[str] = []
        self.pending_boxes: dict | None = None
        self.size = (640, 480)

    def log_image(self, input_id: str, frame: np.ndarray) -> None:
        if len(self.frames) >= self.max_frames:
            return
        # Canvas must fit the largest stream (several "*image*" inputs of
        # different resolutions can share this sink).
        self.size = (
            max(self.size[0], frame.shape[1]) if self.frames else frame.shape[1],
            max(self.size[1], frame.shape[0]) if self.frames else frame.shape[0],
        )
        self.frames.append(
            {"id": input_id, "png": _png_b64(frame), "boxes": self.pending_boxes}
        )

    def log_boxes(self, boxes: dict) -> None:
        # Attach to the latest frame (and subsequent ones until replaced).
        self.pending_boxes = boxes
        if self.frames:
            self.frames[-1]["boxes"] = boxes

    def log_text(self, input_id: str, text: str) -> None:
        self.texts.append(f"[{input_id}] {text}")

    def write(self, path: Path, title: str) -> None:
        # "</" must not appear inside the inline <script> (a text payload
        # containing "</script>" would truncate it).
        def script_safe(value) -> str:
            return json.dumps(value).replace("</", "<\\/")

        path.write_text(
            _HTML_TEMPLATE.format(
                title=html.escape(title),
                width=self.size[0],
                height=self.size[1],
                last=max(len(self.frames) - 1, 0),
                frames_json=script_safe(self.frames),
                texts_json=script_safe(self.texts),
            )
        )


def main() -> None:
    out_dir = Path(os.environ.get("RERUN_OUT", "rerun-out"))
    out_dir.mkdir(parents=True, exist_ok=True)
    max_frames = int(os.environ.get("MAX_LOG_FRAMES", "300"))
    rr = _try_rerun()
    if rr is not None:
        rr.init("dora-tpu", spawn=bool(os.environ.get("RERUN_SPAWN")))
        rr.save(str(out_dir / "replay.rrd"))
    replay = HtmlReplay(max_frames)
    readme = os.environ.get("README", "")
    if readme:
        replay.log_text("README", readme)
        if rr is not None:
            rr.log("README", rr.TextDocument(readme))

    counts: dict[str, int] = {}
    with Node() as node:
        for event in node:
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            input_id, value, metadata = (
                event["id"], event["value"], event["metadata"],
            )
            counts[input_id] = counts.get(input_id, 0) + 1
            if "image" in input_id:
                frame = _decode_image(value, metadata)
                if frame is None:
                    continue
                replay.log_image(input_id, frame)
                if rr is not None:
                    rr.log(input_id, rr.Image(frame))
            elif "boxes2d" in input_id:
                boxes = _decode_boxes(value, metadata)
                replay.log_boxes(boxes)
                if rr is not None:
                    rr.log(
                        input_id,
                        rr.Boxes2D(
                            array=np.asarray(boxes["bbox"], np.float32),
                            array_format=rr.Box2DFormat.XYXY,
                            labels=boxes["labels"],
                        ),
                    )
            elif "text" in input_id:
                import pyarrow as pa

                text = (
                    " ".join(str(v) for v in value.to_pylist())
                    if isinstance(value, pa.Array)
                    else bytes(value).decode(errors="replace")
                )
                replay.log_text(input_id, text)
                if rr is not None:
                    rr.log(input_id, rr.TextLog(text))

    replay.write(out_dir / "replay.html", title=", ".join(sorted(counts)))
    print(f"visualized {counts} -> {out_dir / 'replay.html'}")


if __name__ == "__main__":
    main()
