"""Prepackaged nodes (the node hub).

Reference parity: node-hub/* (SURVEY.md §2.4). Each module exposes
``main()`` and is runnable as ``path: module:dora_tpu.nodehub.<name>`` in a
dataflow YAML (the TPU build's equivalent of the reference's console-script
entry points).

Test fixtures: pyarrow_sender / pyarrow_assert / echo
(reference: node-hub/pyarrow-sender, pyarrow-assert, dora-echo).
AI/I/O nodes live in sibling modules (camera, detection, vlm, asr, …).
"""
