"""Microphone capture node.

Reference parity: node-hub/dora-microphone — sounddevice capture emitting
float32 chunks every MAX_DURATION seconds. Without an audio device it
emits synthetic audio (tone bursts separated by silence — gives VAD/ASR
chains something structured to chew on).
"""

from __future__ import annotations

import os
import time

import numpy as np

from dora_tpu.node import Node


def main() -> None:
    sample_rate = int(os.environ.get("SAMPLE_RATE", "16000"))
    chunk_s = float(os.environ.get("MAX_DURATION", "0.5"))
    chunk = int(sample_rate * chunk_s)

    stream = None
    try:
        import sounddevice as sd

        stream = sd.InputStream(samplerate=sample_rate, channels=1, dtype="float32")
        stream.start()
    except Exception:
        stream = None

    deadline = time.time() + 10 if os.environ.get("CI") else None
    max_chunks = int(os.environ.get("MAX_CHUNKS", "0"))
    i = 0
    with Node() as node:
        for event in node:
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            if stream is not None:
                audio, _ = stream.read(chunk)
                audio = audio[:, 0]
            else:
                t = np.arange(chunk) / sample_rate
                if i % 4 < 2:  # tone burst
                    audio = (0.3 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)
                else:  # near-silence
                    audio = (0.001 * np.random.randn(chunk)).astype(np.float32)
            i += 1
            node.send_output(
                "audio",
                audio,
                {"sample_rate": sample_rate, "shape": [chunk], "dtype": "float32"},
            )
            if deadline and time.time() > deadline:
                break
            if max_chunks and i >= max_chunks:
                break


if __name__ == "__main__":
    main()
