"""OpenAI-compatible chat endpoint bridging HTTP into a dataflow.

Reference parity: node-hub/dora-openai-server (FastAPI) and
node-hub/openai-proxy-server (Rust hyper): POST /v1/chat/completions
publishes the user text on the ``text`` output and returns the next value
arriving on the ``response`` input. Stdlib http.server — no web-framework
dependency.

``"stream": true`` answers as Server-Sent Events
(``chat.completion.chunk`` deltas + ``[DONE]``, proxy parity:
openai-proxy-server/src/main.rs:368-399). A dataflow that emits its
answer in several ``response`` messages streams each as one delta; the
stream closes after ``STREAM_QUIET_MS`` (default 300) of silence
following the first chunk. HTTP requests are merged into the node's
event loop through a thread-safe queue — the stdlib counterpart of the
reference proxy's merged external-events stream (main.rs:37,72).

Concurrent mode (``DORA_OPENAI_CONCURRENT=1``, round 5): requests are
NOT serialized. Each POST publishes its prompt tagged with a
``request_id`` and response chunks route back by that id — pair with a
continuous-batching responder (nodehub/llm_server.py +
models/batch_engine.py) and N clients stream interleaved tokens
concurrently, each decode step serving every active request off one LM
weight pass. The reference's proxy serializes requests through the
dataflow (openai-proxy-server/src/main.rs:30-50); this is the axis it
concedes. Responder contract: every ``response`` message carries
metadata ``request_id`` (echoed) and ``done`` (bool, last chunk).

Dataflow usage::

    - id: api
      path: module:dora_tpu.nodehub.openai_server
      outputs: [text]
      inputs: {response: llm/op/tokens}
      env: {PORT: "8123"}
"""

from __future__ import annotations

import json
import os
import queue
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pyarrow as pa

from dora_tpu.node import Node


def main() -> None:
    import uuid

    port = int(os.environ.get("PORT", "8123"))
    timeout_s = float(os.environ.get("RESPONSE_TIMEOUT", "30"))
    max_requests = int(os.environ.get("MAX_REQUESTS", "0"))  # 0 = serve forever
    quiet_s = float(os.environ.get("STREAM_QUIET_MS", "300")) / 1000.0
    concurrent = os.environ.get("DORA_OPENAI_CONCURRENT", "0") not in (
        "", "0"
    )
    node = Node()
    responses: queue.Queue = queue.Queue()
    #: concurrent mode: request_id -> its private chunk queue
    routed: dict[str, queue.Queue] = {}
    routed_lock = tracked_lock("nodehub.openai.routed")
    # Serial mode holds this across send_output + the reply queue
    # get: whole-request serialization is the documented contract
    # (node.send_output is not thread-safe).
    send_lock = tracked_lock("nodehub.openai.send", allow_blocking=True)
    served = [0]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_GET(self):
            if self.path == "/v1/models":
                self._json(
                    {"object": "list",
                     "data": [{"id": "dora-tpu", "object": "model"}]}
                )
            else:
                self.send_error(404)

        def do_POST(self):
            if self.path != "/v1/chat/completions":
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", "0"))
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                messages = body.get("messages", [])
                text = next(
                    (m.get("content", "") for m in reversed(messages)
                     if m.get("role") == "user"),
                    "",
                )
            except (ValueError, AttributeError) as e:
                self.send_error(400, str(e))
                return
            stream = bool(body.get("stream"))
            model = body.get("model", "dora-tpu")
            if concurrent:
                self._serve_concurrent(body, text, stream, model)
                return
            with send_lock:
                # Drain stale responses, publish, await the next one.
                while not responses.empty():
                    responses.get_nowait()
                node.send_output("text", pa.array([text]))
                try:
                    answer = responses.get(timeout=timeout_s)
                except queue.Empty:
                    self.send_error(504, "dataflow did not answer in time")
                    return
                # From here the request counts as served no matter how the
                # write ends (a client disconnect mid-stream must not keep
                # a MAX_REQUESTS-bounded server alive forever) — but count
                # only after the write so shutdown cannot race an
                # in-flight response (the main loop polls `served`).
                try:
                    if stream:
                        # Forward follow-up chunks until the dataflow goes
                        # quiet (multi-message answers stream as deltas).
                        self._sse_start()
                        self._sse_chunk(model, {"role": "assistant"})
                        self._sse_chunk(model, {"content": answer})
                        while True:
                            try:
                                more = responses.get(timeout=quiet_s)
                            except queue.Empty:
                                break
                            self._sse_chunk(model, {"content": more})
                        self._sse_chunk(model, {}, finish="stop")
                        self.wfile.write(b"data: [DONE]\n\n")
                    else:
                        self._json(
                            {
                                "id": "chatcmpl-dora-tpu",
                                "object": "chat.completion",
                                "created": int(time.time()),
                                "model": model,
                                "choices": [
                                    {
                                        "index": 0,
                                        "message": {
                                            "role": "assistant",
                                            "content": answer,
                                        },
                                        "finish_reason": "stop",
                                    }
                                ],
                            }
                        )
                finally:
                    served[0] += 1

        def _serve_concurrent(self, body, text, stream, model):
            """Routed request: publish tagged with a request_id, stream
            chunks back as they arrive — other requests interleave
            freely (the responder batches them; nothing serializes)."""
            rid = uuid.uuid4().hex[:12]
            chunks: queue.Queue = queue.Queue()
            with routed_lock:
                routed[rid] = chunks
            try:
                meta = {"request_id": rid}
                if isinstance(body.get("max_tokens"), int):
                    meta["max_new_tokens"] = body["max_tokens"]
                # Multi-tenant LoRA routing: the requested model name
                # travels with the request; the serving node resolves a
                # non-base name against its adapter catalog and rejects
                # unknown tenants with a structured finish (so the 404
                # semantics live where the catalog lives, not here).
                if isinstance(model, str) and model:
                    meta["model"] = model
                # Traffic shaping: the body wins over the header so a
                # proxy-injected default never overrides an explicit
                # request. Unknown class strings pass through — the
                # responder folds them to its configured default.
                qos = (
                    body.get("qos_class")
                    or body.get("priority")
                    or self.headers.get("x-dora-qos")
                )
                if isinstance(qos, str) and qos:
                    meta["qos_class"] = qos
                deadline = body.get("deadline_ms")
                if deadline is None:
                    try:
                        deadline = float(
                            self.headers.get("x-dora-deadline-ms", "")
                        )
                    except ValueError:
                        deadline = None
                if isinstance(deadline, (int, float)) and deadline > 0:
                    meta["deadline_ms"] = float(deadline)
                with send_lock:  # send_output is not thread-safe
                    node.send_output("text", pa.array([text]), meta)
                if stream:
                    self._sse_start()
                    self._sse_chunk(model, {"role": "assistant"})
                parts: list[str] = []
                finished = False
                finish_reason = None  # responder's tag: "stop" | "length"
                extra: dict = {}  # shed/reject detail (retry_after_ms, ...)
                while True:
                    try:
                        delta, done, finish, extra = chunks.get(
                            timeout=timeout_s
                        )
                    except queue.Empty:
                        if not stream:
                            # Stalled mid-answer: a truncated completion
                            # marked "stop" would silently lie — fail
                            # like the serial path does.
                            self.send_error(
                                504, "dataflow did not answer in time"
                            )
                            return
                        break
                    if delta:
                        if stream:
                            self._sse_chunk(model, {"content": delta})
                        else:
                            parts.append(delta)
                    if done:
                        finished = True
                        finish_reason = finish
                        break
                if stream:
                    # Prefer the responder's own tag (done-by-EOS =
                    # "stop", done-by-cap = "length"); a stream that
                    # timed out before the done marker is truncated:
                    # say so ("length"), don't claim a clean stop.
                    self._sse_chunk(
                        model,
                        {},
                        finish=(finish_reason or "stop")
                        if finished
                        else "length",
                        extra=extra or None,
                    )
                    self.wfile.write(b"data: [DONE]\n\n")
                elif finished and not parts and finish_reason in (
                    "overloaded", "rejected"
                ):
                    # Shed (retriable, 429 + Retry-After) or structurally
                    # impossible (400) — a 200 with empty content would
                    # hide the backpressure from every standard client.
                    retry_ms = extra.get("retry_after_ms")
                    headers = (
                        {"Retry-After": str(max(1, int(retry_ms / 1000.0)))}
                        if retry_ms
                        else None
                    )
                    self._json(
                        {
                            "error": {
                                "message": f"request {finish_reason}",
                                "type": finish_reason,
                                **({"dora": extra} if extra else {}),
                            }
                        },
                        status=429 if finish_reason == "overloaded" else 400,
                        headers=headers,
                    )
                else:
                    self._json(
                        {
                            "id": f"chatcmpl-{rid}",
                            "object": "chat.completion",
                            "created": int(time.time()),
                            "model": model,
                            "choices": [
                                {
                                    "index": 0,
                                    "message": {
                                        "role": "assistant",
                                        "content": "".join(parts),
                                    },
                                    "finish_reason": finish_reason or "stop",
                                }
                            ],
                        }
                    )
            finally:
                with routed_lock:
                    routed.pop(rid, None)
                served[0] += 1

        def _sse_start(self):
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()

        def _sse_chunk(self, model: str, delta: dict, finish=None,
                       extra: dict | None = None):
            payload = {
                "id": "chatcmpl-dora-tpu",
                "object": "chat.completion.chunk",
                "created": int(time.time()),
                "model": model,
                "choices": [
                    {"index": 0, "delta": delta, "finish_reason": finish}
                ],
            }
            if extra:
                # Shed/reject detail (retry_after_ms, pages_needed, ...)
                # rides in a vendor key — OpenAI clients ignore it.
                payload["dora"] = extra
            self.wfile.write(f"data: {json.dumps(payload)}\n\n".encode())
            self.wfile.flush()

        def _json(self, payload: dict, status: int = 200,
                  headers: dict | None = None):
            data = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for key, val in (headers or {}).items():
                self.send_header(key, val)
            self.end_headers()
            self.wfile.write(data)

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    print(f"openai server listening on 127.0.0.1:{server.server_address[1]}")

    try:
        while True:
            if max_requests and served[0] >= max_requests:
                break
            event = node.recv(timeout=0.25)
            if event is None:
                if node.stream_ended:
                    break
                continue
            if event["type"] == "STOP":
                break
            if event["type"] != "INPUT":
                continue
            value = event["value"]
            if isinstance(value, pa.Array):
                items = value.to_pylist()
                if items and isinstance(items[0], str):
                    answer = " ".join(str(i) for i in items)
                else:
                    from dora_tpu.models import tokenizer

                    answer = tokenizer.decode(items)
            else:
                answer = bytes(value or b"").decode(errors="replace")
            meta = event.get("metadata") or {}
            rid = meta.get("request_id")
            if rid is not None:
                with routed_lock:
                    target = routed.get(rid)
                if target is not None:  # client gone: drop silently
                    extra = {
                        k: meta[k]
                        for k in ("retry_after_ms", "reject_reason",
                                  "pages_needed", "pool_pages", "max_seq")
                        if meta.get(k) is not None
                    }
                    target.put(
                        (answer, bool(meta.get("done")),
                         meta.get("finish"), extra)
                    )
                continue
            responses.put(answer)
    finally:
        server.shutdown()
        node.close()


if __name__ == "__main__":
    main()
