"""Replay source: re-emit a recorded session from Parquet.

The natural pair to ``nodehub/record.py`` (reference: dora-record writes
Parquet; nothing upstream replays it): point ``RECORD_DIR`` at a
recording and every ``<input>.parquet`` that matches one of this node's
declared outputs becomes an output stream, re-emitted in original
global order and paced by the recorded inter-arrival gaps — so a
captured camera/model session drives a dataflow deterministically
without the hardware that produced it. Recorded message metadata
(tensor shape/dtype, trace context) is re-attached, and rows stream
batch by batch (a multi-GB recording never materializes in memory).

Env: ``RECORD_DIR`` (required), ``REPLAY_SPEED`` (1.0 = real time,
2.0 = twice as fast, 0 = as fast as possible), ``REPLAY_LOOP``
(repeat count, default 1).
"""

from __future__ import annotations

import heapq
import json
import os
import time
from pathlib import Path

import pyarrow as pa

from dora_tpu.node import Node


def _stream_file(path: Path):
    """Yield (timestamp_ns, output_id, value, metadata) row by row."""
    import pyarrow.parquet as pq

    output_id = path.stem
    reader = pq.ParquetFile(path)
    has_metadata = "metadata" in reader.schema_arrow.names
    for batch in reader.iter_batches(batch_size=64):
        stamps = batch.column("timestamp_utc_ns").to_pylist()
        values = batch.column("value")
        metas = (
            batch.column("metadata").to_pylist()
            if has_metadata
            else [None] * len(stamps)
        )
        for i, ts in enumerate(stamps):
            metadata = json.loads(metas[i]) if metas[i] else {}
            yield ts, output_id, values[i].as_py(), metadata


def stream_recording(record_dir: Path, outputs):
    """Merged time-ordered event stream across the recorded files that
    match this node's declared outputs (others are skipped with a note —
    a graph that only consumes some streams must still replay)."""
    files = sorted(record_dir.glob("*.parquet"))
    if not files:
        raise SystemExit(f"replay: no *.parquet recordings under {record_dir}")
    selected = []
    for path in files:
        if path.stem in outputs:
            selected.append(path)
        else:
            print(f"replay: skipping {path.name} (not a declared output)",
                  flush=True)
    if not selected:
        raise SystemExit(
            f"replay: none of {[f.name for f in files]} match declared "
            f"outputs {sorted(outputs)}"
        )
    # key: order on timestamps only (values/metadata aren't comparable).
    return heapq.merge(
        *(_stream_file(p) for p in selected), key=lambda e: e[0]
    )


def main() -> None:
    record_dir = Path(os.environ.get("RECORD_DIR", "record"))
    speed = float(os.environ.get("REPLAY_SPEED", "1.0"))
    loops = int(os.environ.get("REPLAY_LOOP", "1"))

    sent = 0
    with Node() as node:
        declared = set(node.config.run_config.outputs)
        for _ in range(loops):
            prev_ts = None
            for ts, output_id, value, metadata in stream_recording(
                record_dir, declared
            ):
                if speed > 0 and prev_ts is not None and ts > prev_ts:
                    time.sleep((ts - prev_ts) / 1e9 / speed)
                prev_ts = ts
                node.send_output(output_id, pa.array(value), metadata)
                sent += 1
    print(f"replayed {sent} events from {record_dir}", flush=True)


if __name__ == "__main__":
    main()
