"""Recorder node: log every input stream to Parquet.

Reference parity: node-hub/dora-record (Rust) — one Parquet file per
input id with the HLC-adjacent receive timestamp, UTC wall time, and the
OpenTelemetry trace/span ids from the message metadata
(dora-record/src/main.rs:20-110).
"""

from __future__ import annotations

import os
import time
from pathlib import Path

import pyarrow as pa
import pyarrow.parquet as pq

from dora_tpu.node import Node
from dora_tpu.telemetry import parse_otel_context


def main() -> None:
    out_dir = Path(os.environ.get("RECORD_DIR", "record"))
    out_dir.mkdir(parents=True, exist_ok=True)
    writers: dict[str, pq.ParquetWriter] = {}
    counts: dict[str, int] = {}

    # A daemon grace-kill is SIGTERM; turn it into SystemExit so the
    # finally below runs and the Parquet footers land on disk.
    import signal

    def _term(signum, frame):
        raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _term)
    except (ValueError, OSError):
        pass  # not the main thread

    # Writers close in a finally: a recording that dies mid-dataflow
    # (upstream failure, grace kill, unhandled error) must still leave
    # valid Parquet files with every row received so far — a truncated
    # file without the footer is unreadable and loses the whole run.
    try:
        with Node() as node:
            for event in node:
                if event["type"] == "STOP":
                    break
                if event["type"] != "INPUT":
                    continue
                input_id = event["id"]
                value = event["value"]
                if not isinstance(value, pa.Array):
                    value = pa.array(
                        [bytes(value) if value is not None else b""]
                    )
                otel = parse_otel_context(
                    str(event["metadata"].get("open_telemetry_context", ""))
                )
                # Metadata rides along as JSON so a replay can re-attach
                # it (tensor shape/dtype are load-bearing for consumers).
                import json

                metadata_json = json.dumps(
                    {k: v for k, v in event["metadata"].items()
                     if isinstance(v, (str, int, float, bool, list))}
                )
                batch = pa.record_batch(
                    [
                        pa.array([time.time_ns()], pa.int64()),
                        pa.array([otel.get("traceparent", "")]),
                        pa.array([pa.scalar(value.to_pylist())]),
                        pa.array([metadata_json]),
                    ],
                    names=["timestamp_utc_ns", "trace", "value", "metadata"],
                )
                writer = writers.get(input_id)
                if writer is None:
                    path = out_dir / f"{input_id.replace('/', '_')}.parquet"
                    writer = pq.ParquetWriter(
                        path, batch.schema, compression="zstd"
                    )
                    writers[input_id] = writer
                writer.write_batch(batch)
                counts[input_id] = counts.get(input_id, 0) + 1
    finally:
        for writer in writers.values():
            try:
                writer.close()
            except Exception:
                pass
    print(f"recorded {counts}")


if __name__ == "__main__":
    main()
