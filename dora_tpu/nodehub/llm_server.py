"""Continuous-batching LLM responder for the OpenAI server.

Reference parity: node-hub/dora-openai-server pairs with ONE llm node
that answers one request at a time (openai-proxy-server/src/main.rs:
30-50 — requests serialize through the dataflow). This node batches:
every ``text`` input carrying a ``request_id`` is admitted into a
serving-engine slot, and each engine step advances ALL active requests
one token off a single LM weight stream (the batched fused kernels,
ops/decode_block). Token deltas stream back on ``response`` tagged
``{request_id, done}`` — the openai_server's concurrent mode routes
them to the right SSE stream.

Two engines (models/batch_engine.py):

* PAGED (default): KV lives in a pool of page-size blocks routed
  through per-slot block tables, prompts prefill in fixed-shape chunks
  interleaved with decode — concurrency scales with actual context
  held, long prompts don't stall active streams, and admission is
  page-aware (a request is admitted only while free pages cover
  prompt + max_new, so an admitted stream can never OOM mid-decode).
* DENSE (``DORA_PAGED_KV=0``): the round-5 `[slots, …, max_seq]` plane
  with synchronous bucket prefill.

Model: a Qwen2-family checkpoint from ``DORA_HF_CHECKPOINT`` (quantized
into the fused decode layout — int8 by default, DORA_INT4_DECODE=1 for
int4); without a checkpoint the node refuses loudly (a chat server with
random weights helps nobody).

The event loop runs at WINDOW granularity: each engine step launches
one fused K-tick decode window (DORA_MULTISTEP_K, default 8) and gets
up to K tokens per stream back off a single device round-trip, so host
dispatch/fetch cost amortizes across K tokens. Admissions, prefill
chunks and backlog draining happen at window boundaries — TTFT and
backlog latency quantize to one window.

Env: DORA_BATCH_SLOTS (default 16 paged / 4 dense) concurrent streams;
DORA_MAX_NEW_TOKENS (default 32) per-request cap (a request's
``max_tokens`` lowers it); DORA_MAX_SEQ cache length; DORA_PAGE_SIZE
(default 16) KV rows per page; DORA_PREFILL_CHUNK prefill chunk rows
(default min(256, max_seq)); DORA_MULTISTEP_K (default 8) fused decode
ticks per dispatch (1 = per-token dispatch); DORA_PAGED_KV=0 for the
dense engine (always per-token); DORA_SPEC_K (default 0 = off) drafts
k tokens per tick via prompt-lookup and verifies them in the same
dispatch — up to K·(k+1) tokens per round trip, greedy-exact — with
DORA_SPEC_NGRAM (default 2) the lookup ngram width.

Traffic shaping (descriptor ``qos:`` block -> DORA_QOS_* env):
requests carry a priority class (``interactive``/``standard``/
``batch``, wire metadata ``qos_class``, default
DORA_QOS_DEFAULT_CLASS) and optionally a queue-wait ``deadline_ms``;
admission drains classes by aged weight (DORA_QOS_AGING_S) so batch
never starves; DORA_QOS_DEPTH_{INTERACTIVE,STANDARD,BATCH} bound the
per-class backlog and DORA_QOS_SHED_WAIT_MS bounds queue wait — both
shed with a retriable ``overloaded`` chunk (+retry_after_ms) instead
of growing the backlog; DORA_QOS_PREEMPT=1 lets a blocked higher-class
request evict a lower-class decode (page grant freed whole; the victim
re-admits later and resumes token-identically by re-prefilling
prompt+emitted). DORA_AUTOTUNE_K=1 adds the SLO-driven window
autotuner (DORA_AUTOTUNE_INTERVAL_S / _LADDER / _HYSTERESIS /
_BURN_WINDOW_S): TTFT burn or shedding steps K down a rung and pauses
speculation, saturated decode-heavy windows step it back up.

Serving metrics (slots, free pages, backlog, decode tokens/s, TTFT
histogram) ship to the daemon every second and surface in
``dora-tpu metrics [--watch]``.

Elastic recovery (paged engines): ``DORA_CHECKPOINT_DIR`` (+
``DORA_CHECKPOINT_EVERY``, default 8 windows) snapshots live serving
state atomically — and on SIGTERM — and restores it on respawn,
resuming mid-generation streams token-identically; every response
chunk carries a ``seq`` so consumers dedup the at-least-once replay.
``DORA_MIGRATE_DIR`` makes this node a migration target: it stays
alive past end-of-stream and admits handoff files drained by
``dora-tpu migrate`` from another engine, continuing each stream
under its original trace id.

Dataflow usage::

    - id: llm
      path: module:dora_tpu.nodehub.llm_server
      inputs: {text: api/text}
      outputs: [response]
"""

from __future__ import annotations

import os
import time

import pyarrow as pa

from dora_tpu import profiling
from dora_tpu.metrics import percentile_from_counts
from dora_tpu.node import Node


def make_engine(params, cfg, eos=None):
    """Build the serving engine from the env knobs (paged by default)."""
    from dora_tpu.models.hf import qwen2

    paged = os.environ.get("DORA_PAGED_KV", "1") != "0"
    slots = int(
        os.environ.get("DORA_BATCH_SLOTS", "16" if paged else "4")
    )
    if not paged:
        return qwen2.make_batch_engine(
            params, cfg, max_slots=slots, eos=eos
        )
    page_size = int(os.environ.get("DORA_PAGE_SIZE", "16"))
    chunk_env = os.environ.get("DORA_PREFILL_CHUNK")
    chunk = int(chunk_env) if chunk_env else None
    window = int(os.environ.get("DORA_MULTISTEP_K", "8"))
    # Shared-prefix radix cache: default ON at the serving front door
    # (DORA_PREFIX_CACHE=0 restores the exact pre-cache program).
    prefix_on = os.environ.get("DORA_PREFIX_CACHE", "1") != "0"
    prefix_pages = int(os.environ.get("DORA_PREFIX_CACHE_PAGES", "0"))
    return qwen2.make_paged_engine(
        params, cfg, max_slots=slots, eos=eos, page_size=page_size,
        chunk=chunk, window=window, prefix_cache=prefix_on,
        prefix_cache_pages=prefix_pages,
    )


#: QoS priority classes, highest first. Weights are drain-order scores,
#: not shares: the scheduler admits the class whose HEAD has the top
#: score, where aging multiplies a head's weight by
#: ``1 + waited / aging_s`` — a parked ``batch`` head overtakes a fresh
#: ``interactive`` one after ``(8/1 - 1) * aging_s`` seconds, so batch
#: never starves forever but never jumps a live interactive burst.
QOS_CLASSES = ("interactive", "standard", "batch")
QOS_WEIGHTS = {"interactive": 8.0, "standard": 4.0, "batch": 1.0}

#: request ``model`` values that mean "the base model" (no LoRA
#: adapter): the OpenAI gateway's default, and the explicit aliases.
#: Any OTHER name is a multi-tenant LoRA adapter, resolved against the
#: engine's resident-adapter catalog (DORA_LORA_DIR stems).
BASE_MODEL_NAMES = ("", "dora-tpu", "base")


class QosConfig:
    """Traffic-shaping knobs, from the descriptor ``qos:`` block (the
    daemon injects it as ``DORA_QOS_*`` env at spawn; descriptor
    ``env:`` entries override). All bounds optional: unset = the
    pre-QoS behavior (single-class FIFO, never shed, never preempt)."""

    __slots__ = ("default_class", "depths", "shed_wait_s", "aging_s",
                 "preempt_on")

    def __init__(self, *, default_class="standard", depths=None,
                 shed_wait_s=None, aging_s=10.0, preempt_on=False):
        assert default_class in QOS_CLASSES, default_class
        self.default_class = default_class
        #: per-class parked-entry bound (None = unbounded)
        self.depths: dict[str, int | None] = {
            c: (depths or {}).get(c) for c in QOS_CLASSES
        }
        #: queue-wait shed deadline, seconds (None = wait forever)
        self.shed_wait_s = shed_wait_s
        #: aging time constant, seconds (0/None disables aging)
        self.aging_s = aging_s
        self.preempt_on = preempt_on

    @classmethod
    def from_env(cls) -> "QosConfig":
        def _f(key):
            raw = os.environ.get(key, "")
            try:
                return float(raw) if raw else None
            except ValueError:
                return None

        def _i(key):
            v = _f(key)
            return int(v) if v is not None else None

        default = os.environ.get("DORA_QOS_DEFAULT_CLASS", "standard")
        if default not in QOS_CLASSES:
            default = "standard"
        shed_ms = _f("DORA_QOS_SHED_WAIT_MS")
        aging = _f("DORA_QOS_AGING_S")
        return cls(
            default_class=default,
            depths={
                "interactive": _i("DORA_QOS_DEPTH_INTERACTIVE"),
                "standard": _i("DORA_QOS_DEPTH_STANDARD"),
                "batch": _i("DORA_QOS_DEPTH_BATCH"),
            },
            shed_wait_s=shed_ms / 1000.0 if shed_ms is not None else None,
            aging_s=aging if aging is not None else 10.0,
            preempt_on=os.environ.get("DORA_QOS_PREEMPT", "") == "1",
        )


class AdmissionQueue:
    """Per-class weighted backlog in front of a serving engine.

    Only ``fits()``-admissible requests ever enter (the caller rejects
    never-admissible ones up front), so every head can eventually start
    once capacity frees. :meth:`drain` must run at EVERY point capacity
    may have appeared — after a push, after an engine step freed
    slots/pages, and on the idle path — a parked request must never
    wait for unrelated traffic to trigger its admission (regression:
    tests/test_llm_backlog.py).

    Scheduling: each drain iteration admits the class whose HEAD entry
    scores highest (class weight aged by wait time, see QOS_WEIGHTS);
    within a class, FIFO. With every entry in one class this IS the old
    FIFO queue. There is deliberately no cross-class bypass: a small
    ``batch`` request never slips past a blocked ``interactive`` head —
    that's what preemption is for.

    Overload turns into signals instead of unbounded backlog:
    ``on_shed(key, reason, waited_s)`` fires when a push overflows its
    class depth bound or a parked entry exceeds the queue-wait deadline
    (config ``shed_wait_s``, tightened per-request by ``deadline_s``).
    ``preempt(cls)`` (optional) is consulted when the best head cannot
    be admitted: return True after evicting a lower-class victim (and
    re-parking it via :meth:`requeue`) to make drain re-score and
    retry; return False to leave the head parked.

    ``on_admit(key, waited_s)`` (optional) fires just before a parked
    request starts, with how long it sat in the backlog — the server
    feeds the ``backlog_wait`` histogram and the ``queued`` lifecycle
    span from it."""

    def __init__(self, engine, start, on_admit=None, clock=time.monotonic,
                 qos: QosConfig | None = None, on_shed=None, preempt=None,
                 on_stall=None):
        self._engine = engine
        self._start = start
        self._on_admit = on_admit
        self._clock = clock
        self._qos = qos or QosConfig()
        self._on_shed = on_shed
        self._preempt = preempt
        self._on_stall = on_stall
        #: key -> why its head-of-class admission is blocked
        #: (engine.admit_blocker); set once per parking episode so
        #: ``on_stall`` fires once, cleared on admit/shed.
        self._stall_reasons: dict[str, str] = {}
        #: class -> [[key, ids, max_new, t_in, deadline_s, adapter], ...]
        #: FIFO. ``adapter`` is the stream's LoRA tenant (None = base);
        #: it parks with the request and rides admission into
        #: ``engine.submit`` — a parked tenant must not lose its model.
        self._q: dict[str, list[list]] = {c: [] for c in QOS_CLASSES}

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

    def depths(self) -> dict[str, int]:
        """Per-class parked depth (the qos_depth gauges)."""
        return {c: len(q) for c, q in self._q.items()}

    def queued(self, key: str) -> bool:
        """Is ``key`` still parked (pushed but not yet admitted)?"""
        return any(
            entry[0] == key for q in self._q.values() for entry in q
        )

    def stall_reason(self, key: str) -> str | None:
        """Why ``key``'s current parking episode is blocked (None when
        it never reached the head while inadmissible). Valid inside the
        on_admit/on_shed callbacks — cleared right after."""
        return self._stall_reasons.get(key)

    def push(self, key: str, ids: list[int], max_new: int,
             qos: str | None = None, deadline_s: float | None = None,
             adapter: str | None = None) -> bool:
        """Park (then drain). Returns False when the entry was shed at
        the door because its class queue is at its depth bound."""
        cls = qos if qos in QOS_CLASSES else self._qos.default_class
        cap = self._qos.depths.get(cls)
        if cap is not None and len(self._q[cls]) >= cap:
            if self._on_shed is not None:
                self._on_shed(key, f"depth:{cls}", 0.0)
            return False
        self._q[cls].append(
            [key, ids, max_new, self._clock(), deadline_s, adapter]
        )
        self.drain()
        return True

    def requeue(self, key: str, ids: list[int], max_new: int,
                qos: str | None = None,
                adapter: str | None = None) -> None:
        """Park a preempted stream at the FRONT of its class, wait clock
        reset (aging credit is forfeited — a re-aged victim outscoring
        its preemptor would ping-pong the slot). No drain: only called
        from inside the preempt hook, mid-drain."""
        cls = qos if qos in QOS_CLASSES else self._qos.default_class
        self._q[cls].insert(
            0, [key, ids, max_new, self._clock(), None, adapter]
        )

    def _shed_expired(self) -> None:
        if self._on_shed is None:
            return
        now = self._clock()
        for q in self._q.values():
            kept = []
            for entry in q:
                limit = self._qos.shed_wait_s
                if entry[4] is not None:
                    limit = entry[4] if limit is None else min(limit, entry[4])
                waited = now - entry[3]
                if limit is not None and waited > limit:
                    self._on_shed(entry[0], "queue_wait", waited)
                    self._stall_reasons.pop(entry[0], None)
                else:
                    kept.append(entry)
            q[:] = kept

    def _best(self, now: float) -> str | None:
        best_cls, best_score = None, -1.0
        for cls in QOS_CLASSES:
            q = self._q[cls]
            if not q:
                continue
            score = QOS_WEIGHTS[cls]
            if self._qos.aging_s:
                score *= 1.0 + (now - q[0][3]) / self._qos.aging_s
            if score > best_score:
                best_cls, best_score = cls, score
        return best_cls

    def drain(self) -> None:
        self._shed_expired()
        while True:
            now = self._clock()
            cls = self._best(now)
            if cls is None:
                return
            key, ids, max_new, t_in, _dl, adapter = self._q[cls][0]
            # Dense engines predate the adapter kwarg; only paged
            # engines ever have a lora pool, and only they see tenant
            # requests (the front door rejects tenants otherwise).
            admissible = (
                self._engine.can_admit(len(ids), max_new, adapter)
                if adapter
                else self._engine.can_admit(len(ids), max_new)
            )
            if not admissible:
                if self._preempt is not None and self._preempt(cls):
                    continue  # a victim was evicted: re-score and retry
                # Attribute the stall: "adapter_residency" means
                # everything else admits but the tenant's adapter
                # cannot evict a pinned resident — without this tag it
                # reads as plain overload. Re-evaluated every drain
                # (a capacity stall can become adapter-gated as pages
                # free), but on_stall fires only on transitions.
                blocker = getattr(self._engine, "admit_blocker", None)
                reason = (
                    blocker(len(ids), max_new, adapter)
                    if blocker is not None else "capacity"
                ) or "capacity"
                if self._stall_reasons.get(key) != reason:
                    self._stall_reasons[key] = reason
                    if self._on_stall is not None:
                        self._on_stall(key, reason)
                return
            self._q[cls].pop(0)
            if self._on_admit is not None:
                self._on_admit(key, now - t_in)
            self._stall_reasons.pop(key, None)
            # Same compatibility split as can_admit: pre-adapter start
            # callbacks take exactly (key, ids, max_new).
            if adapter:
                self._start(key, ids, max_new, adapter)
            else:
                self._start(key, ids, max_new)

    def pending(self) -> list[tuple[str, list[int], int, str, str | None]]:
        """Parked requests in class-priority order — serialized into
        checkpoints and migration handoffs (the wait-start time and
        deadline are process-local and deliberately dropped)."""
        return [
            (k, list(ids), mn, cls, ad)
            for cls in QOS_CLASSES
            for k, ids, mn, _t, _dl, ad in self._q[cls]
        ]

    def take_all(self) -> list[tuple[str, list[int], int, str, str | None]]:
        """Drain the backlog without starting anything (migrate-out:
        parked requests travel with the live streams)."""
        out = self.pending()
        for q in self._q.values():
            q.clear()
        self._stall_reasons.clear()
        return out


def _run_loop(node, engine, backlog, metrics, handle_input, emit,
              report, clock=time.monotonic, on_tick=None, on_step=None,
              handle_migrate=None, handle_profile=None,
              on_engine_error=None, keep_alive=False,
              fleet_tick=None) -> None:
    """Window-granular serving loop, factored out of :func:`main` so
    tests can drive it with fake nodes/engines. Each iteration: drain
    one event, run one engine step (one prefill chunk + one K-tick
    decode window), then ALWAYS drain the backlog — capacity appears
    when a step frees slots/pages, but also the idle path must admit
    (a parked request with zero active streams used to sit until
    unrelated traffic arrived).

    Recovery hooks (all optional, wired by :func:`serve` when the env
    enables them): ``on_tick()`` runs first each iteration and returns
    True to stop (SIGTERM checkpoint), ``on_step()`` runs after a step's
    tokens are emitted (checkpoint cadence — never between step and
    emit, where the snapshot would count tokens the wire never saw),
    ``handle_migrate(event)`` drains live streams at this window
    boundary, ``on_engine_error()`` fails in-flight requests before a
    step exception propagates. ``keep_alive`` parks instead of exiting
    when the input stream ends (migration targets wait for handoffs
    until STOP)."""
    last_step_end: float | None = None
    report_last = clock()
    while True:
        if on_tick is not None and on_tick():
            break
        # Drain a BURST of pending events before the next window (the
        # first recv parks when the engine is idle; the rest only
        # poll). One recv per step would cap intake at one request per
        # dispatch — under an arrival burst the overload then queues
        # UPSTREAM of the admission plane, where QoS classes, queue
        # deadlines and preemption cannot see it (regression: the
        # --qos-soak bench leg read zero sheds at 2x overload). The
        # bound keeps a flood from starving the decode loop itself.
        event = None
        stop = False
        for burst in range(128):
            event = node.recv(
                timeout=0.0 if engine.active or burst else 0.25
            )
            if event is None:
                break
            if event["type"] == "STOP":
                stop = True
                break
            if event["type"] == "INPUT":
                handle_input(event)
            elif event["type"] == "MIGRATE" and handle_migrate is not None:
                handle_migrate(event)
            elif event["type"] == "PROFILE" and handle_profile is not None:
                handle_profile(event)
        if stop:
            break
        if (
            event is None
            and node.stream_ended
            and engine.active == 0
            and len(backlog) == 0
        ):
            if not keep_alive:
                break
            # Stream closed but handoffs may still arrive: don't spin
            # (recv returns immediately once the queue is closed).
            time.sleep(0.05)
        if engine.active:
            now = clock()
            if last_step_end is not None:
                # Host time between the end of the previous dispatch
                # and the start of this one: the gap the K-window
                # exists to amortize (p50/p99 in the SERVING table).
                metrics.dispatch_gap.observe((now - last_step_end) * 1e6)
            try:
                stepped = engine.step()
            except Exception:
                if on_engine_error is not None:
                    on_engine_error()
                raise
            for key, token, done in stepped:
                emit(key, token, done)
            last_step_end = clock()
            if on_step is not None:
                on_step()
        else:
            last_step_end = None  # a gap across idle is queue wait
        backlog.drain()
        now = clock()
        if now - report_last >= 1.0:
            report(now)
            report_last = now
        elif fleet_tick is not None:
            # Fleet digests can run FASTER than the 1 Hz metrics report
            # (DORA_FLEET_DIGEST_S below 1); report() itself also ticks
            # the publisher, so the slow cadence costs nothing extra.
            fleet_tick(now)


def serve(node, engine, metrics, *, encode, decode_one, eos=None,
          max_new_cap=32, tracer=None, clock=time.monotonic) -> None:
    """Run the serving loop over an already-built engine until the
    input stream ends, then close the node. Factored out of
    :func:`main` (which only adds checkpoint loading) so tests and
    demo dataflows can serve a stub engine through the REAL admission /
    backlog / lifecycle-tracing paths.

    Attaches the observability plane: a ``ServingTracer`` shared with
    the engine (request-lifecycle spans through the flight-recorder
    ring, linked to the carrier message's trace context), the
    ``ServingMetrics`` histograms the engine feeds (fetch latency,
    grant sizes), and the runtime XLA compile listener whose counter
    ships with every metrics report."""
    from dora_tpu import telemetry

    if tracer is None:
        tracer = telemetry.ServingTracer()
    # The engine records admitted/prefill_chunk/decode_window spans and
    # fetch/grant histograms through these hooks; both are no-ops /
    # plain counters unless DORA_TRACING=1.
    engine.tracer = tracer
    engine.serving_metrics = metrics
    telemetry.install_compile_listener()
    paged = hasattr(engine, "free_pages")
    # Elastic-recovery env knobs; all off by default, and only engines
    # exposing the checkpoint surface (paged) can use them.
    can_ckpt = hasattr(engine, "checkpoint_state")
    ckpt_dir = os.environ.get("DORA_CHECKPOINT_DIR") if can_ckpt else None
    ckpt_every = int(os.environ.get("DORA_CHECKPOINT_EVERY", "8") or 0)
    migrate_dir = os.environ.get("DORA_MIGRATE_DIR") if can_ckpt else None
    # SLO targets: the daemon injects the descriptor's `slo:` block as
    # DORA_SLO_* at spawn. The daemon-side history ring is the
    # authoritative burn-rate source; the node-side check exists so a
    # violation ALSO lands on this process's ENGINE trace track, with
    # the observed value at engine granularity.
    def _slo_env(key: str) -> float | None:
        raw = os.environ.get(key, "")
        try:
            return float(raw) if raw else None
        except ValueError:
            return None

    slo_ttft_ms = _slo_env("DORA_SLO_TTFT_P99_MS")
    slo_tok_s = _slo_env("DORA_SLO_TOKENS_PER_S_MIN")
    slo_queue = _slo_env("DORA_SLO_QUEUE_DEPTH_MAX")
    slo_prev: dict = {"t": None, "tokens": 0, "ttft": []}
    # Traffic shaping (descriptor qos: block -> DORA_QOS_* env).
    # Preemption needs the engine surface (preempt + per-slot request
    # ids) — the dense engine silently serves without it.
    qos = QosConfig.from_env()
    can_preempt = qos.preempt_on and hasattr(engine, "preempt")
    #: per-request QoS bookkeeping. req_prompt/req_emitted (token ids)
    #: exist so a preempted stream can resume by re-prefilling
    #: prompt + emitted — only tracked while preemption is on.
    req_class: dict[str, str] = {}
    #: engine key -> LoRA tenant name (absent/None = base model). Kept
    #: for every request while live so preemption requeues and
    #: migrate-out carry the stream's model with it.
    req_adapter: dict[str, str | None] = {}
    req_prompt: dict[str, list[int]] = {}
    req_emitted: dict[str, list[int]] = {}
    admit_seq: dict[str, int] = {}
    admit_counter = [0]
    preempted_keys: set[str] = set()
    #: engine key -> tokens whose cached-prefix path is PINNED while
    #: the preempted victim waits to resume (refcount custody, not slot
    #: custody: the pages stay in the prefix cache, immune to pool-
    #: pressure eviction, so resume re-prefills only the unshared tail)
    pinned_prefix: dict[str, list[int]] = {}
    #: engine key -> wire request_id. The ENGINE key is always unique
    #: (req-N): two in-flight requests carrying the same wire
    #: ``request_id`` must not share a slot key, or their token streams
    #: silently interleave — the wire id is carried separately and only
    #: stamped on the outgoing chunks.
    wire_ids: dict[str, str | None] = {}
    #: engine key -> arrival wall time, pending first token (TTFT)
    t_admitted: dict[str, float] = {}
    req_counter = [0]
    #: engine key -> next chunk sequence number. Recovery replays are
    #: at-least-once: after a crash-restore the engine re-decodes from
    #: the checkpoint, re-emitting chunks the wire already saw — with
    #: the SAME (request_id, seq) pair, so consumers dedup instead of
    #: double-printing.
    seqs: dict[str, int] = {}
    #: wire request_ids already admitted (checkpoint mode only): a
    #: daemon replay of an un-acked input must not re-admit a stream
    #: the restored engine is already running.
    seen_rids: dict[str, None] = {}

    def _forget(key: str) -> None:
        req_class.pop(key, None)
        req_adapter.pop(key, None)
        req_prompt.pop(key, None)
        req_emitted.pop(key, None)
        admit_seq.pop(key, None)
        stall_tags.pop(key, None)
        preempted_keys.discard(key)
        pinned = pinned_prefix.pop(key, None)
        if pinned is not None and hasattr(engine, "prefix_unpin"):
            # A parked victim that never resumed (shed, error, drain)
            # must release its eviction pin.
            engine.prefix_unpin(pinned)

    def emit_text(
        key: str, text: str, done: bool, finish: str | None = None,
        extra: dict | None = None,
    ) -> None:
        meta: dict = {"done": bool(done)}
        if done:
            # Done-by-EOS ("stop") vs done-by-cap ("length"): the server
            # reports this as the OpenAI finish_reason. Capacity signals
            # are retriable: "rejected" (could NEVER fit: pages needed
            # vs pool size ride in the payload) and "overloaded" (could
            # fit, shed under load; retry_after_ms rides along).
            meta["finish"] = finish or "stop"
        if extra:
            meta.update(extra)
        stalled = stall_tags.pop(key, None)
        if stalled is not None and "stall_reason" not in meta:
            meta["stall_reason"] = stalled
        seq = seqs.get(key, 0)
        meta["seq"] = seq
        if done:
            seqs.pop(key, None)
        else:
            seqs[key] = seq + 1
        rid = wire_ids.get(key)
        if rid is not None:
            meta["request_id"] = rid
        t0 = t_admitted.pop(key, None)
        if t0 is not None:
            # The paged engine runs its K-tick window AFTER the prefill
            # chunk that produced this first token, inside the same
            # step() — the token sat host-side for up to a whole window
            # before the loop could emit it. The engine measured that
            # sit time (emit_lag_s); subtracting it recovers sub-window
            # TTFT instead of quantizing to window granularity.
            lag = engine.emit_lag_s.pop(key, 0.0) if hasattr(
                engine, "emit_lag_s"
            ) else 0.0
            metrics.ttft.observe(max(0.0, clock() - t0 - lag) * 1e6)
        node.send_output("response", pa.array([text]), meta)
        if done:
            wire_ids.pop(key, None)
            _forget(key)
            tracer.finish(key, finish or "stop")

    def emit(key: str, token: int, done: bool) -> None:
        finish = None
        if done:
            finish = "stop" if (eos is not None and token == eos) else "length"
        metrics.decode_tokens += 1
        if can_preempt and not done and key in req_emitted:
            req_emitted[key].append(token)
        emit_text(key, decode_one(token), done, finish)

    #: keys whose backlog wait was attributed to adapter residency —
    #: the next wire chunk (first token or shed) carries the tag so the
    #: client can tell "tenant blocked" from plain overload.
    stall_tags: dict[str, str] = {}

    def on_stall(key: str, reason: str) -> None:
        if reason == "adapter_residency":
            metrics.adapter_stalls += 1
            tracer.instant("s_page_wait", key, "adapter_residency")

    def on_admit(key: str, waited_s: float) -> None:
        metrics.backlog_wait.observe(waited_s * 1e6)
        reason = backlog.stall_reason(key)
        if reason == "adapter_residency":
            stall_tags[key] = reason
        # The queued span closes at admission; the exporter derives its
        # start from the duration, so it covers the whole backlog wait.
        tracer.span("s_queued", key, dur_ns=int(waited_s * 1e9))

    def start(key: str, ids: list[int], max_new: int,
              adapter: str | None = None) -> None:
        admit_counter[0] += 1
        admit_seq[key] = admit_counter[0]
        if key in preempted_keys:
            # A preempted stream re-admitting: its prefill recomputes
            # prompt + emitted, so everything it decodes from here is
            # token-identical to the unpreempted run.
            preempted_keys.discard(key)
            metrics.resumed += 1
            tracer.span("s_resume", key, f"recompute={len(ids)}")
        if adapter:
            res = engine.submit(key, ids, max_new, adapter=adapter)
        else:
            res = engine.submit(key, ids, max_new)
        pinned = pinned_prefix.pop(key, None)
        if pinned is not None:
            # Unpin AFTER submit: the resume lookup refs the shared
            # pages into the new grant first, so dropping the eviction
            # pin can no longer lose them.
            engine.prefix_unpin(pinned)
        if res is not None:  # dense engine: first token is synchronous
            emit(key, *res)
        # paged engine: submit queues the prefill; the first token is
        # emitted by a later step() when the final chunk lands.

    def on_shed(key: str, reason: str, waited_s: float) -> None:
        # Overload -> fast retriable signal, never unbounded backlog:
        # the stream closes with finish "overloaded" and a retry hint
        # (clients with backoff re-enter the front door fresh).
        metrics.shed += 1
        t_admitted.pop(key, None)  # a shed stream has no first token
        tracer.instant("s_shed", key, f"{reason} waited={waited_s:.3f}s")
        retry_ms = int(max(100.0, (qos.shed_wait_s or 1.0) * 1000.0))
        extra = {"retry_after_ms": retry_ms}
        if backlog.stall_reason(key) == "adapter_residency":
            extra["stall_reason"] = "adapter_residency"
        emit_text(key, "", True, finish="overloaded", extra=extra)

    def try_preempt(cls: str) -> bool:
        """A ``cls`` head is blocked on capacity: evict ONE victim of a
        strictly lower class (lowest class first, then youngest — the
        cheapest recompute), park it for resume, and report whether
        anything was freed. The queue re-scores and retries after True,
        so multi-victim evictions happen one grant at a time."""
        if not can_preempt:
            return False
        rank = QOS_CLASSES.index(cls)
        victim, vkey = None, (-1, -1)
        for s in engine.slots:
            if s is None:
                continue
            k = s.request_id
            r = QOS_CLASSES.index(req_class.get(k, qos.default_class))
            if r <= rank:
                continue  # only strictly lower classes are victims
            if k not in req_prompt:
                # No resume bookkeeping (e.g. a checkpoint-restored
                # stream): evicting it could not be token-identical.
                continue
            cand = (r, admit_seq.get(k, 0))
            if cand > vkey:
                victim, vkey = k, cand
        if victim is None:
            return False
        meta = engine.preempt(victim)
        if meta is None:
            return False
        remaining = meta["max_new"] - meta["emitted"]
        if remaining <= 0:
            # Raced with completion; the slot is free either way.
            emit_text(victim, "", True, finish="length")
            return True
        preempted_keys.add(victim)
        resume_ids = (
            list(req_prompt.get(victim, []))
            + list(req_emitted.get(victim, []))
        )
        if hasattr(engine, "prefix_pin") and engine.prefix_pin(resume_ids):
            # The victim's cached prefix pages survive the park on
            # refcount custody: resume re-prefills only the unshared
            # tail instead of re-paying the whole prefill.
            pinned_prefix[victim] = resume_ids
        backlog.requeue(victim, resume_ids, remaining,
                       req_class.get(victim),
                       adapter=req_adapter.get(victim))
        return True

    #: requests that arrived while the engine couldn't admit them
    backlog = AdmissionQueue(
        engine, start, on_admit=on_admit, clock=clock,
        qos=qos, on_shed=on_shed,
        preempt=try_preempt if can_preempt else None,
        on_stall=on_stall,
    )

    def handle_input(event) -> None:
        from dora_tpu.telemetry import OTEL_CTX_KEY

        meta = event.get("metadata") or {}
        rid = meta.get("request_id")
        if ckpt_dir and rid is not None:
            # Checkpoint mode: the daemon replays un-acked inputs after
            # a respawn; a rid the restored engine already owns must not
            # be admitted twice.
            if rid in seen_rids:
                tracer.instant("s_reject", f"req:{rid}", "replay-dup")
                return
            seen_rids[rid] = None
            while len(seen_rids) > 4096:
                seen_rids.pop(next(iter(seen_rids)))
        value = event["value"]
        text = (
            value.to_pylist()[0]
            if isinstance(value, pa.Array)
            else bytes(value or b"").decode(errors="replace")
        )
        req_counter[0] += 1
        key = f"req-{req_counter[0]}"
        wire_ids[key] = rid
        metrics.requests += 1
        # Engine spans join the trace of the message that carried the
        # request in — one trace id covers send → route → deliver →
        # queued → admitted → … → finish.
        tracer.begin(key, str(meta.get(OTEL_CTX_KEY, "") or ""))
        ids = encode(text) or [0]
        max_new = min(
            int(meta.get("max_new_tokens", max_new_cap)),
            max_new_cap,
        )
        cls = meta.get("qos_class") or meta.get("priority")
        if cls not in QOS_CLASSES:
            cls = qos.default_class
        try:
            dl = float(meta.get("deadline_ms", "") or 0) / 1000.0
        except (TypeError, ValueError):
            dl = 0.0
        deadline_s = dl if dl > 0 else None
        req_class[key] = cls
        # Per-request model routing (the OpenAI ``model`` field, wired
        # through like qos_class): a non-base name is a LoRA tenant
        # served out of THIS engine's adapter pool — same slots, same
        # pages, one window executable.
        model = str(meta.get("model") or "")
        adapter = model if model not in BASE_MODEL_NAMES else None
        lora_pool = getattr(engine, "lora", None)
        req_adapter[key] = adapter
        if max_new <= 0:
            # max_tokens <= 0 asks for nothing: close the stream
            # empty instead of fabricating a token.
            metrics.rejected += 1
            tracer.instant("s_reject", key, "max_new<=0")
            emit_text(key, "", True, finish="length")
        elif adapter is not None and (
            lora_pool is None or not lora_pool.has(adapter)
        ):
            # Unknown tenant: NEVER servable here (no catalog entry /
            # no adapter pool at all) — a structured non-retriable
            # reject, distinct from capacity signals.
            metrics.rejected += 1
            tracer.instant("s_reject", key, f"unknown model {adapter!r}")
            emit_text(
                key, "", True, finish="rejected",
                extra={"reject_reason": "unknown_model", "model": adapter},
            )
        elif not (
            engine.fits(len(ids), max_new, adapter)
            if adapter
            else engine.fits(len(ids), max_new)
        ):
            # NEVER admissible: close the stream empty with a
            # structured retriable "rejected" (distinct from the shed
            # path's "overloaded" — retrying the same body cannot
            # help, the payload says why: its page grant exceeds the
            # whole pool / block table).
            metrics.rejected += 1
            extra: dict = {"reject_reason": "oversized"}
            if paged:
                extra["pages_needed"] = engine.pages_needed(
                    len(ids), max_new
                )
                extra["pool_pages"] = engine.allocator.num_pages - 1
                extra["max_seq"] = engine.max_seq
            tracer.instant("s_reject", key, f"oversized len={len(ids)}")
            emit_text(key, "", True, finish="rejected", extra=extra)
        else:
            t_admitted[key] = clock()
            if can_preempt:
                req_prompt[key] = list(ids)
                req_emitted[key] = []
            if not backlog.push(key, ids, max_new, cls, deadline_s,
                                adapter=adapter):
                return  # shed at the door (class depth bound)
            # push drains: admits now when the engine can, else parks
            # until capacity frees
            if backlog.queued(key):
                # Parked: no slot, or the page pool couldn't cover the
                # grant — the backlog wait (or a preemption) begins
                # here.
                tracer.instant(
                    "s_page_wait", key,
                    f"qos={cls} backlog={len(backlog)} "
                    f"free_pages={getattr(engine, 'free_pages', 0)}",
                )

    def check_slo(now: float) -> None:
        """Evaluate the DORA_SLO_* targets over the deltas since the
        previous report tick. TTFT p99 comes from this tick's histogram
        delta; tok/s is only judged while the engine is actually serving
        (an idle server decodes 0 tok/s without violating anything)."""
        if slo_ttft_ms is None and slo_tok_s is None and slo_queue is None:
            return
        prev_t, slo_prev["t"] = slo_prev["t"], now
        toks = metrics.decode_tokens
        counts = list(metrics.ttft.counts)
        if prev_t is None or now <= prev_t:
            slo_prev["tokens"] = toks
            slo_prev["ttft"] = counts
            return
        dt = now - prev_t
        if slo_ttft_ms is not None:
            delta = [c - p for c, p in zip(counts, slo_prev["ttft"])]
            if any(d > 0 for d in delta):
                p99 = percentile_from_counts(delta, 99)
                if p99 is not None and p99 > slo_ttft_ms * 1000.0:
                    tracer.instant(
                        "slo_violation", "(engine)",
                        f"ttft_p99_ms observed={p99 / 1000.0:.1f} "
                        f"target={slo_ttft_ms:g}",
                    )
        if slo_tok_s is not None:
            rate = (toks - slo_prev["tokens"]) / dt
            if (engine.active or toks > slo_prev["tokens"]) \
                    and rate < slo_tok_s:
                tracer.instant(
                    "slo_violation", "(engine)",
                    f"tokens_per_s observed={rate:.1f} "
                    f"target={slo_tok_s:g}",
                )
        if slo_queue is not None and len(backlog) > slo_queue:
            tracer.instant(
                "slo_violation", "(engine)",
                f"queue_depth observed={len(backlog)} "
                f"target={slo_queue:g}",
            )
        slo_prev["tokens"] = toks
        slo_prev["ttft"] = counts

    # ------------------------------------------------------------------
    # SLO-driven K autotuner (DORA_AUTOTUNE_K=1): a slow control loop
    # re-selecting the fused-window K from live signals. TTFT burn
    # (interval p99 over the DORA_SLO_TTFT_P99_MS target) or shedding
    # steps K DOWN one ladder rung and pauses speculation — shorter
    # windows mean finer admission boundaries and faster first tokens;
    # a saturated window (tokens/dispatch >= 3/4 of K) with no burn
    # steps K UP and resumes speculation — decode-heavy mixes drift
    # toward K=16 (BENCHMARKS round 10). Hysteresis: a signal must hold
    # for DORA_AUTOTUNE_HYSTERESIS consecutive intervals, and after a
    # retune the loop cools down as many intervals (change-rate cap:
    # at most one rung per hysteresis window). The loop never acts
    # before its burn window has a full complement of samples
    # (metrics_history.burn_window_complete — a freshly started
    # dataflow must not retune off a 3-sample "burn").
    # ------------------------------------------------------------------
    at_on = (
        os.environ.get("DORA_AUTOTUNE_K", "") == "1"
        and hasattr(engine, "set_window")
        and getattr(engine, "_window_factory", None) is not None
    )
    at_interval = float(os.environ.get("DORA_AUTOTUNE_INTERVAL_S", "5") or 5)
    at_hyst = max(1, int(os.environ.get("DORA_AUTOTUNE_HYSTERESIS", "2") or 2))
    at_burn_win = float(
        os.environ.get("DORA_AUTOTUNE_BURN_WINDOW_S", "60") or 60
    )
    _ladder_env = os.environ.get("DORA_AUTOTUNE_LADDER", "4,8,16")
    try:
        at_ladder = sorted(
            {int(x) for x in _ladder_env.split(",") if int(x) >= 1}
            | {getattr(engine, "window", 1)}
        )
    except ValueError:
        at_ladder = sorted({4, 8, 16} | {getattr(engine, "window", 1)})
    at_state = {
        "t": None, "tokens": 0, "dispatches": 0, "ttft": [],
        "samples": 0, "burn": 0, "calm": 0, "cooldown": 0,
        "shed": 0,
        "rung": at_ladder.index(getattr(engine, "window", at_ladder[0]))
        if getattr(engine, "window", None) in at_ladder else 0,
    }

    def autotune(now: float) -> None:
        if not at_on:
            return
        if at_state["t"] is None:
            at_state["t"] = now
            at_state["tokens"] = metrics.decode_tokens
            at_state["dispatches"] = metrics.host_dispatches
            at_state["ttft"] = list(metrics.ttft.counts)
            at_state["shed"] = metrics.shed
            return
        if now - at_state["t"] < at_interval:
            return
        from dora_tpu.metrics_history import burn_window_complete

        d_tok = metrics.decode_tokens - at_state["tokens"]
        d_disp = metrics.host_dispatches - at_state["dispatches"]
        d_shed = metrics.shed - at_state["shed"]
        counts = list(metrics.ttft.counts)
        d_ttft = [c - p for c, p in zip(counts, at_state["ttft"])]
        at_state["t"] = now
        at_state["tokens"] = metrics.decode_tokens
        at_state["dispatches"] = metrics.host_dispatches
        at_state["ttft"] = counts
        at_state["shed"] = metrics.shed
        at_state["samples"] += 1
        burn = d_shed > 0
        if slo_ttft_ms is not None and any(d > 0 for d in d_ttft):
            p99 = percentile_from_counts(d_ttft, 99)
            if p99 is not None and p99 > slo_ttft_ms * 1000.0:
                burn = True
        tpd = (d_tok / d_disp) if d_disp else 0.0
        k_now = at_ladder[at_state["rung"]]
        if burn:
            at_state["burn"] += 1
            at_state["calm"] = 0
        elif d_disp and tpd >= 0.75 * k_now:
            at_state["calm"] += 1
            at_state["burn"] = 0
        else:
            at_state["burn"] = 0
            at_state["calm"] = 0
        if not burn_window_complete(
            at_state["samples"], at_burn_win, at_interval
        ):
            return
        if at_state["cooldown"] > 0:
            at_state["cooldown"] -= 1
            return
        new_rung, spec_on, reason = None, None, ""
        if at_state["burn"] >= at_hyst and at_state["rung"] > 0:
            new_rung, spec_on = at_state["rung"] - 1, False
            reason = "shed" if d_shed > 0 else "ttft_burn"
        elif (
            at_state["calm"] >= at_hyst
            and at_state["rung"] < len(at_ladder) - 1
        ):
            new_rung, spec_on = at_state["rung"] + 1, True
            reason = "decode_heavy"
        if new_rung is None:
            return
        new_k = at_ladder[new_rung]
        if not engine.set_window(new_k, spec_on=spec_on):
            return
        at_state["rung"] = new_rung
        at_state["burn"] = at_state["calm"] = 0
        at_state["cooldown"] = at_hyst
        metrics.retunes += 1
        metrics.autotune_k = new_k
        tracer.instant(
            "k_retune", "(engine)",
            f"K {k_now}->{new_k} spec_k={engine.spec_k} "
            f"reason={reason} tpd={tpd:.2f}",
        )

    # Device utilization plane (dora_tpu.profiling): HBM gauges sampled
    # at report cadence, engine attribution/FLOPs counters copied into
    # the snapshot, and mfu / device_busy_fraction derived from the
    # interval deltas (reset-safe: a restored engine re-counts from
    # zero, so a negative delta is treated as the whole interval).
    monitor = (
        profiling.DeviceMonitor() if profiling.monitor_enabled() else None
    )
    util_prev = {"busy_ns": 0, "flops": 0, "t": clock()}
    # On-demand deep capture (cm.StartProfile/StopProfile): start arms
    # a deadline checked at report cadence; stop (or the deadline)
    # closes the capture and reports the artifact path to the daemon.
    profile_state: dict = {
        "active": False, "dir": "", "deadline": 0.0, "start_error": None,
    }

    def _finish_profile() -> None:
        artifact = profiling.stop_capture(
            profile_state["dir"], profile_state["start_error"]
        )
        profile_state["active"] = False
        profile_state["start_error"] = None
        tracer.instant("profile_stop", "(engine)", artifact)
        try:
            node.report_profile(artifact, None)
        except Exception:
            pass  # capture is best-effort; serving never blocks on it

    def handle_profile(event) -> None:
        md = event.get("metadata") or {}
        action = md.get("action", "")
        if action == "start":
            if profile_state["active"]:
                try:
                    node.report_profile("", "capture already active")
                except Exception:
                    pass
                return
            out_dir = os.path.join(
                profiling.profile_dir(),
                f"capture-{os.getpid()}-{int(time.time())}",
            )
            profile_state["dir"] = out_dir
            profile_state["start_error"] = profiling.start_capture(out_dir)
            profile_state["active"] = True
            profile_state["deadline"] = clock() + float(
                md.get("seconds") or 0.0
            )
            tracer.instant("profile_start", "(engine)", out_dir)
        elif action == "stop":
            if profile_state["active"]:
                _finish_profile()
            else:
                try:
                    node.report_profile("", "no capture active")
                except Exception:
                    pass

    # Fleet plane: publish this engine's state digest on its own cadence
    # (DORA_FLEET_DIGEST_S; 0 disables), piggybacked on the report path
    # so it never adds a wakeup to the serving loop.
    from dora_tpu import fleet as _fleet

    fleet_pub = _fleet.DigestPublisher(
        node, engine, tracer=tracer, clock=clock,
        hbm=lambda: (
            getattr(metrics, "hbm_used_bytes", 0) or 0,
            getattr(metrics, "hbm_limit_bytes", 0) or 0,
        ),
    )

    def report(now: float) -> None:
        metrics.slots_active = engine.active
        metrics.slots_total = engine.max_slots
        metrics.backlog_depth = len(backlog)
        metrics.prefill_chunks = getattr(engine, "chunks_run", 0)
        metrics.host_dispatches = getattr(engine, "dispatches", 0)
        metrics.host_fetches = getattr(engine, "fetches", 0)
        metrics.compiles = telemetry.compile_count()
        if paged:
            metrics.free_pages = engine.free_pages
            alloc = getattr(engine, "allocator", None)
            if alloc is not None:
                metrics.total_pages = alloc.num_pages - 1
                metrics.used_pages = alloc.in_use
                metrics.peak_used_pages = alloc.peak_in_use
                metrics.largest_contig_free = (
                    alloc.largest_contiguous_free()
                )
            pc = getattr(engine, "prefix_cache", None)
            if pc is not None:
                metrics.prefix_hits = pc.hits
                metrics.prefix_misses = pc.misses
                metrics.prefix_hit_tokens = pc.hit_tokens
                metrics.prefix_cached_pages = pc.size
                metrics.prefix_shared_pages = engine.shared_pages
                metrics.prefix_cow_copies = pc.cow_copies
                metrics.prefix_evictions = pc.evicted_pages
            metrics.kv_dtype = getattr(engine, "kv_dtype", "fp")
            if hasattr(engine, "kv_pool_bytes"):
                metrics.kv_pool_bytes = engine.kv_pool_bytes()
            if hasattr(engine, "kv_quant_error"):
                metrics.kv_quant_err = engine.kv_quant_error()
            lp = getattr(engine, "lora", None)
            if lp is not None:
                metrics.lora_resident = lp.resident
                metrics.lora_max_resident = lp.max_resident
                metrics.lora_resident_bytes = lp.resident_bytes()
                metrics.lora_loads = lp.loads
                metrics.lora_evictions = lp.evictions
                metrics.adapter_streams = lp.streams_by_adapter()
        metrics.qos_depth = backlog.depths()
        metrics.autotune_k = getattr(engine, "window", 0)
        if monitor is not None:
            metrics.device_compute_ns = getattr(engine, "device_compute_ns", 0)
            metrics.host_dispatch_ns = getattr(engine, "host_dispatch_ns", 0)
            metrics.device_fetch_ns = getattr(engine, "device_fetch_ns", 0)
            metrics.dispatched_flops = getattr(engine, "dispatched_flops", 0)
            metrics.useful_flops = getattr(engine, "useful_flops", 0)
            mem = monitor.memory()
            metrics.hbm_used_bytes = mem["used"]
            metrics.hbm_limit_bytes = mem["limit"]
            metrics.hbm_peak_bytes = mem["peak"]
            dt = now - util_prev["t"]
            if dt > 0:
                d_busy = metrics.device_compute_ns - util_prev["busy_ns"]
                if d_busy < 0:  # engine restored: counters restarted at 0
                    d_busy = metrics.device_compute_ns
                metrics.device_busy_fraction = min(
                    1.0, max(0.0, d_busy / (dt * 1e9))
                )
                d_flops = metrics.useful_flops - util_prev["flops"]
                if d_flops < 0:
                    d_flops = metrics.useful_flops
                peak = getattr(engine, "device_peak_flops", 0.0)
                metrics.mfu = (
                    min(1.0, (d_flops / dt) / peak) if peak > 0 else None
                )
            util_prev["busy_ns"] = metrics.device_compute_ns
            util_prev["flops"] = metrics.useful_flops
            util_prev["t"] = now
        if profile_state["active"] and now >= profile_state["deadline"]:
            _finish_profile()
        check_slo(now)
        autotune(now)
        try:
            node.report_serving(metrics.snapshot())
        except Exception:
            pass  # metrics are best-effort; serving never blocks on them
        fleet_pub.tick(now)

    # ------------------------------------------------------------------
    # elastic recovery: checkpoint/restore, drain-and-migrate, SIGTERM
    # ------------------------------------------------------------------
    import json

    def write_checkpoint(reason: str) -> None:
        """Snapshot everything a respawn needs to resume mid-generation
        token-identically. Written atomically (tmp + rename) so a kill
        mid-write leaves the previous snapshot intact. Only ever called
        at a window boundary — never between step() and emit, where the
        engine's emitted counters would count tokens the wire hasn't
        seen (restore must produce duplicates, never gaps)."""
        t0 = clock()
        state = {
            "engine": engine.checkpoint_state(),
            "backlog": [
                [k, list(ids), mn, cls, ad]
                for k, ids, mn, cls, ad in backlog.pending()
            ],
            "wire_ids": dict(wire_ids),
            "seqs": dict(seqs),
            "ctxs": {k: tracer.context(k) for k in wire_ids},
            "req_counter": req_counter[0],
            "seen_rids": list(seen_rids),
        }
        os.makedirs(ckpt_dir, exist_ok=True)
        tmp = os.path.join(ckpt_dir, "state.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(ckpt_dir, "state.json"))
        if os.environ.get("DORA_CHECKPOINT_PAGES") == "1":
            # KV page pools via orbax, for engines whose decode reads
            # the cache. Best-effort: pool persistence failing must not
            # take serving down with it.
            try:
                engine.save_pools(os.path.join(ckpt_dir, "pools"))
            except Exception:
                pass
        metrics.checkpoints += 1
        metrics.last_checkpoint_unix = time.time()
        tracer.span(
            "s_checkpoint", "(engine)",
            f"streams={len(state['engine']['slots'])} {reason}",
            dur_ns=int((clock() - t0) * 1e9),
        )

    def restore_checkpoint() -> None:
        spath = os.path.join(ckpt_dir, "state.json")
        if not os.path.exists(spath):
            return
        t0 = clock()
        with open(spath) as f:
            saved = json.load(f)
        pools = os.path.join(ckpt_dir, "pools")
        if os.environ.get("DORA_CHECKPOINT_PAGES") == "1" and os.path.isdir(
            pools
        ):
            try:
                engine.restore_pools(pools)
            except Exception:
                pass
        req_counter[0] = int(saved.get("req_counter", 0))
        wire_ids.update(saved.get("wire_ids") or {})
        seqs.update(
            {k: int(v) for k, v in (saved.get("seqs") or {}).items()}
        )
        for rid in saved.get("seen_rids") or []:
            seen_rids[rid] = None
        # Same context => same trace id: the resumed stream's spans
        # continue the pre-crash chain on the timeline.
        for k, ctx in (saved.get("ctxs") or {}).items():
            tracer.begin(k, ctx or "")
        restored = engine.restore_state(saved.get("engine") or {"slots": []})
        for entry in saved.get("backlog") or []:
            # Entries are [k, ids, max_new] pre-QoS, [.., class] after,
            # [.., adapter] after multi-tenant LoRA; the wait clock and
            # any deadline restart on restore.
            cls = entry[3] if len(entry) > 3 else None
            ad = entry[4] if len(entry) > 4 else None
            backlog.push(entry[0], list(entry[1]), int(entry[2]), cls,
                         adapter=ad)
        metrics.restored_streams += len(restored)
        tracer.span(
            "s_restore", "(engine)", f"streams={len(restored)}",
            dur_ns=int((clock() - t0) * 1e9),
        )

    migrations = [0]

    def handle_migrate(event) -> None:
        """Drain every live stream (and the parked backlog) into a
        handoff file another engine's ``DORA_MIGRATE_DIR`` poll admits.
        Runs at a window boundary, so clients see at most one window of
        added latency."""
        handoff_dir = (event.get("metadata") or {}).get("handoff_dir", "")
        if not handoff_dir or not can_ckpt:
            return
        t0 = clock()
        state = engine.drain_streams()
        parked = backlog.take_all()
        keys = [m["request_id"] for m in state["slots"]]
        keys += [entry[0] for entry in parked]
        payload = {
            "engine": state,
            "backlog": [
                [k, list(ids), mn, cls, ad]
                for k, ids, mn, cls, ad in parked
            ],
            "wire_ids": {k: wire_ids.get(k) for k in keys},
            "seqs": {k: seqs.get(k, 0) for k in keys},
            "ctxs": {k: tracer.context(k) for k in keys},
        }
        migrations[0] += 1
        fname = f"streams-{os.getpid()}-{migrations[0]}.json"
        os.makedirs(handoff_dir, exist_ok=True)
        tmp = os.path.join(handoff_dir, fname + ".tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, os.path.join(handoff_dir, fname))
        dur = int((clock() - t0) * 1e9)
        for k in keys:
            # Span BEFORE release: it must carry the stream's trace id
            # so the migrate-out leg links to the same chain the target
            # continues. No s_finish here — the stream isn't done, it
            # moved.
            tracer.span("s_migrate_out", k, f"dir={handoff_dir}", dur_ns=dur)
            tracer.release(k)
            wire_ids.pop(k, None)
            seqs.pop(k, None)
            t_admitted.pop(k, None)
            _forget(k)
        metrics.migrated_out += len(keys)

    def _admit_handoff(payload: dict, src: str) -> None:
        t0 = clock()
        mapping: dict[str, str] = {}

        def fresh(old: str) -> str:
            # Local keys are req-N; a migrated-in req-N from another
            # engine could collide, so every incoming stream gets a
            # fresh local key. The wire request_id and seq counter
            # travel untouched — dedup and SSE routing don't notice.
            req_counter[0] += 1
            nk = f"req-{req_counter[0]}"
            mapping[old] = nk
            return nk

        state = payload.get("engine") or {"slots": []}
        for m in state["slots"]:
            m["request_id"] = fresh(m["request_id"])
        parked = [
            (
                fresh(entry[0]), list(entry[1]), int(entry[2]),
                entry[3] if len(entry) > 3 else None,
                entry[4] if len(entry) > 4 else None,
            )
            for entry in payload.get("backlog") or []
        ]
        src_wire = payload.get("wire_ids") or {}
        src_seqs = payload.get("seqs") or {}
        src_ctxs = payload.get("ctxs") or {}
        for old, nk in mapping.items():
            wire_ids[nk] = src_wire.get(old)
            seqs[nk] = int(src_seqs.get(old, 0))
            # begin() with the source's serialized context keeps the
            # trace id — ONE contiguous trace spans both engines.
            tracer.begin(nk, src_ctxs.get(old) or "")
        try:
            engine.admit_streams(state)
        except RuntimeError:
            # Capacity raced away between the peek-time fits check and
            # the claim (local admissions landed first). restore_state
            # is not transactional — roll back whatever it admitted,
            # then close EVERY handoff stream with a retriable "error"
            # finish. The pre-fix failure mode dropped the streams with
            # no signal to the client at all (round-7 known issue).
            fresh_keys = set(mapping.values())
            for b, s in enumerate(engine.slots):
                if s is not None and s.request_id in fresh_keys:
                    if b in engine._prefillq:
                        engine._prefillq.remove(b)
                    engine._free_slot(b)
            for nk in mapping.values():
                metrics.rejected += 1
                tracer.instant("s_reject", nk, f"migrate-in overflow {src}")
                emit_text(nk, "", True, finish="error")
            return
        for nk, ids, mn, cls, ad in parked:
            backlog.push(nk, ids, mn, cls, adapter=ad)
        dur = int((clock() - t0) * 1e9)
        for nk in mapping.values():
            tracer.span("s_migrate_in", nk, f"from={src}", dur_ns=dur)
        metrics.migrated_in += len(mapping)

    def _handoff_fits(payload: dict) -> bool:
        """Can the target admit EVERY stream in the handoff right now?
        Decode streams re-take exactly the pages the source granted;
        mid-prefill streams re-submit through the normal admission
        math (chunk padding + speculative headroom included)."""
        metas = (payload.get("engine") or {}).get("slots") or []
        if len(metas) > engine.free_slots:
            return False
        pages = 0
        for m in metas:
            ad = m.get("adapter")
            if ad:
                # Tenant custody rides the stream: the target must be
                # able to serve (load) the stream's adapter or the
                # handoff stays on disk for a peer that can.
                lp = getattr(engine, "lora", None)
                if lp is None or not lp.has(ad):
                    return False
            if m.get("decode"):
                n = len(m.get("pages") or ())
                if n * engine.page_size > engine.max_seq:
                    return False  # block table too short for the stream
                pages += n
            else:
                plen = len(m.get("prompt") or ())
                mn = int(m.get("max_new", 0))
                if not engine.fits(plen, mn):
                    return False
                pages += engine.pages_needed(plen, mn)
        for entry in payload.get("backlog") or []:
            ad = entry[4] if len(entry) > 4 else None
            if ad:
                lp = getattr(engine, "lora", None)
                if lp is None or not lp.has(ad):
                    return False
        return pages <= engine.free_pages

    def poll_migrate_in() -> None:
        try:
            names = sorted(os.listdir(migrate_dir))
        except OSError:
            return
        for fname in names:
            if not (fname.startswith("streams-")
                    and fname.endswith(".json")):
                continue
            path = os.path.join(migrate_dir, fname)
            # Peek BEFORE claiming: an undersized target leaves the
            # handoff on disk — for a bigger peer polling the same dir,
            # or for a later poll once its own streams drain — instead
            # of claiming streams it cannot admit. Handoff files are
            # written once (tmp + rename), so the peeked content is the
            # claimed content.
            try:
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue
            if not _handoff_fits(payload):
                tracer.instant(
                    "s_migrate_defer", fname,
                    f"free_slots={engine.free_slots} "
                    f"free_pages={engine.free_pages}",
                )
                continue
            claimed = path + ".claimed"
            try:
                os.rename(path, claimed)  # atomic claim
            except OSError:
                continue
            _admit_handoff(payload, fname)
            try:
                os.remove(claimed)
            except OSError:
                pass

    stop_now = [False]
    step_count = [0]
    engine_failed = [False]

    def on_tick() -> bool:
        if migrate_dir:
            poll_migrate_in()
        if stop_now[0]:
            if ckpt_dir:
                try:
                    write_checkpoint("sigterm")
                except Exception:
                    pass
            return True
        return False

    def on_step() -> None:
        step_count[0] += 1
        if ckpt_every > 0 and step_count[0] % ckpt_every == 0:
            write_checkpoint("cadence")

    def on_engine_error() -> None:
        # A wedged engine must not leave SSE streams dangling: every
        # in-flight request (active or parked) closes with a retriable
        # "error" finish before the exception propagates and the
        # restart policy respawns the node.
        engine_failed[0] = True
        for key in list(wire_ids):
            try:
                emit_text(key, "", True, finish="error")
            except Exception:
                pass

    recovery_on = bool(ckpt_dir or migrate_dir)
    if ckpt_dir:
        import signal

        def _term(signum, frame):
            # Graceful drain: the loop checkpoints and exits cleanly on
            # the next tick instead of dying mid-window.
            stop_now[0] = True

        try:
            signal.signal(signal.SIGTERM, _term)
        except (ValueError, OSError):
            pass  # not the main thread (test harness)
        restore_checkpoint()

    clean = False
    try:
        _run_loop(
            node, engine, backlog, metrics, handle_input, emit, report,
            clock=clock,
            on_tick=on_tick if recovery_on else None,
            on_step=on_step if ckpt_dir else None,
            handle_migrate=handle_migrate if can_ckpt else None,
            handle_profile=handle_profile,
            on_engine_error=on_engine_error,
            keep_alive=bool(migrate_dir),
            fleet_tick=fleet_pub.tick if fleet_pub.enabled else None,
        )
        clean = True
    finally:
        # Only a CLEAN exit snapshots: after a crash (engine wedge, lost
        # daemon, anything that raised out of the loop) the last cadence
        # checkpoint is the trustworthy state — overwriting it with a
        # post-crash "exit" snapshot would resume from poisoned state.
        if ckpt_dir and clean and not engine_failed[0]:
            try:
                write_checkpoint("exit")
            except Exception:
                pass
        report(clock())
        node.close()


def _stub_main() -> None:
    """Serve the weight-free stub engine (``DORA_STUB_ENGINE=1``): the
    real admission / backlog / lifecycle-tracing / reporting paths over
    ``models.batch_engine.make_stub_paged_engine`` — what the
    observability e2e test and the serving-trace demo dataflow run when
    no checkpoint is available. Tokens are the stub's deterministic
    affine chain rendered as ``t<id>`` words, not language —
    ``DORA_STUB_CYCLE=N`` swaps in the period-N repeating rule (the
    speculative-decoding best case; pair with ``DORA_SPEC_K``)."""
    from dora_tpu.metrics import ServingMetrics
    from dora_tpu.models.batch_engine import make_stub_paged_engine

    cycle_env = os.environ.get("DORA_STUB_CYCLE", "")
    engine = make_stub_paged_engine(
        max_slots=int(os.environ.get("DORA_BATCH_SLOTS", "4")),
        window=int(os.environ.get("DORA_MULTISTEP_K", "4")),
        spec_k=int(os.environ.get("DORA_SPEC_K", "0") or 0),
        spec_ngram=int(os.environ.get("DORA_SPEC_NGRAM", "2") or 2),
        cycle=int(cycle_env) if cycle_env else None,
        prefix_cache=os.environ.get("DORA_PREFIX_CACHE", "1") != "0",
        prefix_cache_pages=int(
            os.environ.get("DORA_PREFIX_CACHE_PAGES", "0") or 0
        ),
        # Multi-tenant LoRA front door over the stub (any model name
        # resolves to a deterministic shift adapter — see
        # make_stub_paged_engine): the --lora-ab bench and the routing
        # tests exercise admission/eviction/gauges engine-free.
        lora_max_resident=int(
            os.environ.get("DORA_LORA_MAX_RESIDENT", "0") or 0
        ),
    )
    delay = float(os.environ.get("DORA_STEP_DELAY_S", "0") or 0)
    if delay > 0:
        # Chaos-harness hook: the stub decodes in microseconds, far too
        # fast to land a mid-generation kill deterministically. A
        # per-window sleep stretches generation into a predictable
        # strike window without touching token content.
        orig_step = engine.step

        def _throttled_step():
            time.sleep(delay)
            return orig_step()

        engine.step = _throttled_step
    serve(
        Node(), engine, ServingMetrics(engine="paged"),
        encode=lambda text: [ord(ch) % 97 for ch in text] or [1],
        decode_one=lambda t: f" t{t}",
        max_new_cap=int(os.environ.get("DORA_MAX_NEW_TOKENS", "8")),
    )


def main() -> None:
    from dora_tpu.metrics import ServingMetrics
    from dora_tpu.models.hf import qwen2

    path = os.environ.get("DORA_HF_CHECKPOINT")
    if not path:
        if os.environ.get("DORA_STUB_ENGINE", "") not in ("", "0"):
            return _stub_main()
        raise RuntimeError(
            "llm_server needs DORA_HF_CHECKPOINT (a Qwen2-family "
            "safetensors directory; or DORA_STUB_ENGINE=1 for the "
            "weight-free stub engine)"
        )
    max_seq = int(os.environ.get("DORA_MAX_SEQ", "2048"))
    max_new_cap = int(os.environ.get("DORA_MAX_NEW_TOKENS", "32"))

    cfg, params = qwen2.load(path, max_seq=max_seq)
    if not os.environ.get("DORA_INT8_DECODE") and not os.environ.get(
        "DORA_INT4_DECODE"
    ):
        os.environ["DORA_INT8_DECODE"] = "1"  # engine needs the fused layout
    params = qwen2.quantize_decode(params, cfg)

    from dora_tpu.nodehub.ops import _hf_tokenizer

    tok = _hf_tokenizer(path)
    eos = None
    if tok is not None:
        for name in ("<|im_end|>", "<|endoftext|>", "</s>", "<|eot_id|>"):
            if name in tok.added:
                eos = tok.added[name]
                break

    def encode(text: str) -> list[int]:
        if tok is not None:
            return tok.encode(text)
        from dora_tpu.models import tokenizer

        return [t % cfg.vocab for t in tokenizer.encode(text)]

    def decode_one(token: int) -> str:
        if tok is not None:
            return tok.decode([token])
        from dora_tpu.models import tokenizer

        return tokenizer.decode([token])

    engine = make_engine(params, cfg, eos=eos)
    metrics = ServingMetrics(
        engine="paged" if hasattr(engine, "free_pages") else "dense"
    )
    serve(
        Node(), engine, metrics,
        encode=encode, decode_one=decode_one, eos=eos,
        max_new_cap=max_new_cap,
    )


if __name__ == "__main__":
    main()
