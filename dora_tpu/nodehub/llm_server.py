"""Continuous-batching LLM responder for the OpenAI server.

Reference parity: node-hub/dora-openai-server pairs with ONE llm node
that answers one request at a time (openai-proxy-server/src/main.rs:
30-50 — requests serialize through the dataflow). This node batches:
every ``text`` input carrying a ``request_id`` is admitted into a
models/batch_engine.BatchEngine slot, and each engine step advances ALL
active requests one token off a single LM weight stream (the batched
fused kernels, ops/decode_block.attention_batch_step). Token deltas
stream back on ``response`` tagged ``{request_id, done}`` — the
openai_server's concurrent mode routes them to the right SSE stream.

Model: a Qwen2-family checkpoint from ``DORA_HF_CHECKPOINT`` (quantized
into the fused decode layout — int8 by default, DORA_INT4_DECODE=1 for
int4); without a checkpoint the node refuses loudly (a chat server with
random weights helps nobody).

Env: DORA_BATCH_SLOTS (default 4) concurrent streams;
DORA_MAX_NEW_TOKENS (default 32) per-request cap (a request's
``max_tokens`` lowers it); DORA_MAX_SEQ cache length.

Dataflow usage::

    - id: llm
      path: module:dora_tpu.nodehub.llm_server
      inputs: {text: api/text}
      outputs: [response]
"""

from __future__ import annotations

import os

import pyarrow as pa

from dora_tpu.node import Node


def main() -> None:
    from dora_tpu.models.hf import qwen2

    path = os.environ.get("DORA_HF_CHECKPOINT")
    if not path:
        raise RuntimeError(
            "llm_server needs DORA_HF_CHECKPOINT (a Qwen2-family "
            "safetensors directory)"
        )
    max_seq = int(os.environ.get("DORA_MAX_SEQ", "2048"))
    max_new_cap = int(os.environ.get("DORA_MAX_NEW_TOKENS", "32"))
    slots = int(os.environ.get("DORA_BATCH_SLOTS", "4"))

    cfg, params = qwen2.load(path, max_seq=max_seq)
    if not os.environ.get("DORA_INT8_DECODE") and not os.environ.get(
        "DORA_INT4_DECODE"
    ):
        os.environ["DORA_INT8_DECODE"] = "1"  # engine needs the fused layout
    params = qwen2.quantize_decode(params, cfg)

    from dora_tpu.nodehub.ops import _hf_tokenizer

    tok = _hf_tokenizer(path)
    eos = None
    if tok is not None:
        for name in ("<|im_end|>", "<|endoftext|>", "</s>", "<|eot_id|>"):
            if name in tok.added:
                eos = tok.added[name]
                break

    def encode(text: str) -> list[int]:
        if tok is not None:
            return tok.encode(text)
        from dora_tpu.models import tokenizer

        return [t % cfg.vocab for t in tokenizer.encode(text)]

    def decode_one(token: int) -> str:
        if tok is not None:
            return tok.decode([token])
        from dora_tpu.models import tokenizer

        return tokenizer.decode([token])

    engine = qwen2.make_batch_engine(params, cfg, max_slots=slots, eos=eos)
    node = Node()
    #: requests that arrived while every slot was busy (FIFO admission;
    #: only length-admissible requests ever enter, so a freed slot can
    #: always take the head)
    backlog: list[tuple[str, list[int], int]] = []
    #: engine key -> wire request_id (None for untagged requests from
    #: the serial openai_server mode, whose chunks must carry NO
    #: request_id so the server's legacy queue receives them)
    wire_ids: dict[str, str | None] = {}
    anon_counter = [0]

    def emit_text(
        key: str, text: str, done: bool, finish: str | None = None
    ) -> None:
        meta: dict = {"done": bool(done)}
        if done:
            # Done-by-EOS ("stop") vs done-by-cap ("length"): the server
            # reports this as the OpenAI finish_reason.
            meta["finish"] = finish or "stop"
        rid = wire_ids.get(key)
        if rid is not None:
            meta["request_id"] = rid
        node.send_output("response", pa.array([text]), meta)
        if done:
            wire_ids.pop(key, None)

    def emit(key: str, token: int, done: bool) -> None:
        finish = None
        if done:
            finish = "stop" if (eos is not None and token == eos) else "length"
        emit_text(key, decode_one(token), done, finish)

    def start(key: str, ids: list[int], max_new: int) -> None:
        token, done = engine.submit(key, ids, max_new)
        emit(key, token, done)

    def admit_backlog() -> None:
        while backlog and engine.free_slots:
            start(*backlog.pop(0))

    try:
        while True:
            # Active decode: poll only (the engine must keep stepping);
            # idle: park in recv until a request arrives.
            event = node.recv(timeout=0.0 if engine.active else 0.25)
            if event is None and node.stream_ended and engine.active == 0:
                break
            if event is not None:
                if event["type"] == "STOP":
                    break
                if event["type"] == "INPUT":
                    meta = event.get("metadata") or {}
                    rid = meta.get("request_id")
                    value = event["value"]
                    text = (
                        value.to_pylist()[0]
                        if isinstance(value, pa.Array)
                        else bytes(value or b"").decode(errors="replace")
                    )
                    anon_counter[0] += 1
                    key = rid if rid is not None else f"anon-{anon_counter[0]}"
                    wire_ids[key] = rid
                    ids = encode(text) or [0]
                    max_new = min(
                        int(meta.get("max_new_tokens", max_new_cap)),
                        max_new_cap,
                    )
                    if max_new <= 0:
                        # max_tokens <= 0 asks for nothing: close the
                        # stream empty instead of fabricating a token.
                        emit_text(key, "", True, finish="length")
                    elif not engine.fits(len(ids), max_new):
                        # Oversized: close the stream empty — never
                        # fabricate a token as a "successful" answer.
                        emit_text(key, "", True, finish="length")
                    elif not engine.free_slots:
                        backlog.append((key, ids, max_new))
                    else:
                        start(key, ids, max_new)
            for key, token, done in engine.step():
                emit(key, token, done)
            admit_backlog()
    finally:
        node.close()


if __name__ == "__main__":
    main()
