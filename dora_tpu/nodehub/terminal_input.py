"""Interactive dynamic sender: type a value, it goes into the dataflow.

Reference parity: node-hub/terminal-input — a *dynamic* node (``path:
dynamic`` in the YAML) started by hand in a terminal; each line typed is
parsed with ``ast.literal_eval`` (falling back to a string) and sent on
the ``data`` output, and anything routed back to this node is printed
(terminal_input/main.py:36-96). Non-interactive use: set ``DATA`` to send
one value and exit — that is also the CI path.

Connect it with ``NODE_ID`` (+ ``DORA_DAEMON_ADDR``) like every dynamic
node; retries until the dataflow is up, as the reference does.
"""

from __future__ import annotations

import ast
import os
import sys
import time

from dora_tpu.node import Node


def parse_value(text: str):
    """``ast.literal_eval`` with the reference's fall-back-to-string rule."""
    try:
        return ast.literal_eval(text)
    except (ValueError, SyntaxError):
        return text


def _to_payload(value):
    import pyarrow as pa

    if isinstance(value, (list, tuple)):
        return pa.array(list(value))
    return pa.array([value])


def _connect(node_id: str | None) -> Node:
    daemon_addr = os.environ.get("DORA_DAEMON_ADDR")
    if not node_id:
        # Spawned mode: a failure here (e.g. no DORA_NODE_CONFIG) is
        # permanent — surface it instead of retrying.
        return Node()
    last_err = ""
    while True:
        try:
            return Node(node_id=node_id, daemon_addr=daemon_addr)
        except (OSError, RuntimeError) as err:  # dataflow not up yet
            if str(err) != last_err:
                print(err)
                last_err = str(err)
            print("Waiting for dataflow to be spawned", flush=True)
            time.sleep(1)


def main() -> None:
    node_id = os.environ.get("NODE_ID")
    data = os.environ.get("DATA")
    node = _connect(node_id)
    try:
        if data is not None:
            node.send_output("data", _to_payload(parse_value(data)))
            return
        while True:
            try:
                line = input("Provide the data you want to send:  ")
            except EOFError:
                break
            node.send_output("data", _to_payload(parse_value(line)))
            # Drain replies briefly so request/response demos read naturally.
            while True:
                event = node.next(timeout=0.2)
                if event is not None and event["type"] == "INPUT":
                    print(f"Received: {event['value']}", flush=True)
                else:
                    break
    finally:
        node.close()


if __name__ == "__main__":
    main()
