"""Print every input to stdout (dynamic-node-friendly sink).

Reference parity: node-hub/terminal-print (Rust). Start it inside a
dataflow (``path: module:dora_tpu.nodehub.terminal_print``) or attach it
dynamically (``path: dynamic`` + run this module with NODE_ID set).
"""

from __future__ import annotations

import os

from dora_tpu.node import Node


def main() -> None:
    node_id = os.environ.get("NODE_ID")
    daemon_addr = os.environ.get("DORA_DAEMON_ADDR")
    node = Node(node_id=node_id, daemon_addr=daemon_addr) if node_id else Node()
    try:
        for event in node:
            if event["type"] == "INPUT":
                print(f"[{event['id']}] {event['value']}", flush=True)
            elif event["type"] == "STOP":
                break
    finally:
        node.close()


if __name__ == "__main__":
    main()
