"""Parallelism: device meshes, sharding helpers, ring attention.

The reference has no ML parallelism (SURVEY.md §2.9); its scale axes are
graph fan-out and multi-machine placement. The TPU build adds the tensor
tier: models shard over a `jax.sharding.Mesh` with named axes

  * ``dp`` — data parallel (batch),
  * ``tp`` — tensor parallel (heads / hidden, rides ICI),
  * ``sp`` — sequence parallel (ring attention for long context).

XLA inserts the collectives (psum/all-gather/reduce-scatter/ppermute)
from sharding annotations; nothing here hand-schedules communication
except the ring-attention ppermute loop, which is explicit by design.
"""

from dora_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_SP,
    AXIS_TP,
    make_mesh,
    shard,
    shard_params,
)
from dora_tpu.parallel.ring import ring_attention
from dora_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "AXIS_DP",
    "AXIS_TP",
    "AXIS_SP",
    "make_mesh",
    "shard",
    "shard_params",
    "ring_attention",
    "ulysses_attention",
]
