"""Multi-host JAX initialization for distributed dataflows.

Reference parity: the reference's multi-machine axis is daemon-per-machine
with TCP forwarding (SURVEY §2.9); the TPU build adds the tensor plane:
one daemon per TPU host, `jax.distributed` across hosts (DCN), XLA
collectives over ICI within a slice. The daemon exposes its machine id
and the coordinator address via environment variables when spawning
nodes, so a TPU-tier runtime node on every host of a slice can join the
same global mesh.

Env contract (set per node in the dataflow YAML, or by the deployment):

  DORA_JAX_COORDINATOR   host:port of process 0 (jax.distributed)
  DORA_JAX_NUM_PROCESSES total process count
  DORA_JAX_PROCESS_ID    this process's index
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_initialized = False


def maybe_init_distributed() -> bool:
    """Initialize jax.distributed from the env contract if present.

    Returns True when running multi-host (after init), False for
    single-host. Idempotent.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = os.environ.get("DORA_JAX_COORDINATOR")
    if not coordinator:
        return False
    import jax

    num_processes = int(os.environ.get("DORA_JAX_NUM_PROCESSES", "1"))
    process_id = int(os.environ.get("DORA_JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "jax.distributed up: process %d/%d, %d global devices",
        process_id, num_processes, len(jax.devices()),
    )
    return True


def global_mesh(dp: int = -1, tp: int = 1, sp: int = 1):
    """A mesh over all global devices (multi-host aware): call after
    maybe_init_distributed(). Lay tp/sp on the fastest (ICI) axis by
    keeping them within a host where possible."""
    import jax

    from dora_tpu.parallel.mesh import make_mesh

    maybe_init_distributed()
    return make_mesh(dp=dp, tp=tp, sp=sp, devices=jax.devices())
