"""Device-mesh construction and sharding helpers."""

from __future__ import annotations

from typing import Any

import numpy as np

AXIS_DP = "dp"  # data (batch)
AXIS_TP = "tp"  # tensor (heads / ffn hidden)
AXIS_SP = "sp"  # sequence (ring attention)


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1, devices=None):
    """Build a Mesh with named axes (dp, tp, sp). Axis sizes must multiply
    to the device count; pass dp=-1 to absorb the remainder into data
    parallelism."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp == -1:
        if n % (tp * sp):
            raise ValueError(f"{n} devices not divisible by tp*sp={tp * sp}")
        dp = n // (tp * sp)
    if dp * tp * sp != n:
        raise ValueError(f"dp*tp*sp={dp * tp * sp} != {n} devices")
    grid = np.array(devices).reshape(dp, tp, sp)
    return Mesh(grid, (AXIS_DP, AXIS_TP, AXIS_SP))


def named(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def shard(x, mesh, *spec):
    """Constrain (inside jit) or place (outside jit) ``x`` on the mesh."""
    import jax

    sharding = named(mesh, *spec)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def shard_params(params: Any, mesh, rules) -> Any:
    """Place a parameter pytree on the mesh.

    ``rules`` maps a path-suffix predicate to a PartitionSpec: a list of
    ``(match, spec)`` where ``match`` is a substring of the '/'-joined
    parameter path. First match wins; default is full replication.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def place(path, leaf):
        path_str = "/".join(str(getattr(k, "key", k)) for k in path)
        for match, spec in rules:
            if match in path_str:
                return jax.device_put(leaf, NamedSharding(mesh, spec))
        return jax.device_put(leaf, NamedSharding(mesh, PartitionSpec()))

    return jax.tree_util.tree_map_with_path(place, params)
