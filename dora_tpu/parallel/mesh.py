"""Device-mesh construction and sharding helpers."""

from __future__ import annotations

from typing import Any

import numpy as np

AXIS_DP = "dp"  # data (batch)
AXIS_TP = "tp"  # tensor (heads / ffn hidden)
AXIS_SP = "sp"  # sequence (ring attention)


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1, devices=None):
    """Build a Mesh with named axes (dp, tp, sp). Axis sizes must multiply
    to the device count; pass dp=-1 to absorb the remainder into data
    parallelism."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp == -1:
        if n % (tp * sp):
            raise ValueError(f"{n} devices not divisible by tp*sp={tp * sp}")
        dp = n // (tp * sp)
    if dp * tp * sp != n:
        raise ValueError(f"dp*tp*sp={dp * tp * sp} != {n} devices")
    grid = np.array(devices).reshape(dp, tp, sp)
    return Mesh(grid, (AXIS_DP, AXIS_TP, AXIS_SP))


def named(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def shard(x, mesh, *spec):
    """Constrain (inside jit) or place (outside jit) ``x`` on the mesh."""
    import jax

    sharding = named(mesh, *spec)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def shard_params(params: Any, mesh, rules) -> Any:
    """Place a parameter pytree on the mesh.

    ``rules`` is a list of ``(name, spec)`` matched against the leaf's
    FINAL path component exactly (substring matching would silently catch
    look-alikes — 'embed' must not shard 'pos_embed'). First match wins;
    default is full replication. A matched leaf whose dimension does not
    divide the mesh axis falls back to replication instead of crashing —
    real checkpoint shapes (odd vocab sizes, 196-patch position tables)
    must serve on any mesh.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def divisible(leaf, spec) -> bool:
        shape = getattr(leaf, "shape", ())
        for dim, axes in zip(shape, tuple(spec) + (None,) * len(shape)):
            if axes is None:
                continue
            # A dimension splits over the PRODUCT of its mesh axes.
            total = 1
            for axis in (axes if isinstance(axes, tuple) else (axes,)):
                total *= axis_sizes.get(axis, 1)
            if dim % total:
                return False
        return True

    def place(path, leaf):
        name = str(getattr(path[-1], "key", path[-1])) if path else ""
        for match, spec in rules:
            if name == match:
                if not divisible(leaf, spec):
                    break  # replicate: shape does not tile on this mesh
                return jax.device_put(leaf, NamedSharding(mesh, spec))
        return jax.device_put(leaf, NamedSharding(mesh, PartitionSpec()))

    return jax.tree_util.tree_map_with_path(place, params)
