"""Ring attention: exact attention over sequences sharded across devices.

Long-context sequence parallelism for the TPU tier (SURVEY.md §5.7): the
sequence axis is sharded over the mesh's ``sp`` axis; each device holds a
Q/K/V block and K/V blocks rotate around the ring via ``ppermute`` (ICI
neighbor exchange) while a numerically-stable log-sum-exp accumulator
merges partial attention — compute overlaps communication and no device
ever materializes the full sequence. (Liu et al., "Ring Attention with
Blockwise Transformers"; see PAPERS.md.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from dora_tpu.parallel.mesh import AXIS_SP


def _block_attend(q, k, v, mask=None):
    """One Q-block × K/V-block partial attention.

    Returns (unnormalized out, running max m, running denom l) for
    log-sum-exp merging. Shapes: q [B,H,Tq,D], k/v [B,H,Tk,D].
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    m = jnp.max(scores, axis=-1, keepdims=True)  # [B,H,Tq,1]
    # Fully-masked rows: max is -inf; clamp so exp() stays finite.
    m = jnp.maximum(m, jnp.finfo(scores.dtype).min / 2)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return out, m, l


def _merge(acc, new):
    """Merge two partial attention accumulators with stable LSE."""
    out_a, m_a, l_a = acc
    out_b, m_b, l_b = new
    m = jnp.maximum(m_a, m_b)
    a_scale = jnp.exp(m_a - m)
    b_scale = jnp.exp(m_b - m)
    return (out_a * a_scale + out_b * b_scale, m, l_a * a_scale + l_b * b_scale)


def ring_attention(q, k, v, mesh, causal: bool = True, axis: str = AXIS_SP):
    """Exact (optionally causal) attention with q/k/v sharded on ``axis``
    along the sequence dimension. Shapes: [batch, heads, seq, head_dim].

    Causality across blocks uses global positions: block ``i`` attends to
    block ``j`` fully when j < i, diagonally when j == i, not at all when
    j > i.
    """
    sp = mesh.shape[axis]
    if sp == 1:
        out, m, l = _block_attend(q, k, v, _causal_mask(q.shape[2], k.shape[2], 0, 0) if causal else None)
        return out / l

    def local(q, k, v):
        idx = jax.lax.axis_index(axis)
        block_len = q.shape[2]
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def step(carry, _):
            acc, kv, src = carry
            k_blk, v_blk = kv
            if causal:
                mask = _block_causal_mask(block_len, idx, src, sp)
            else:
                mask = None
            partial = _block_attend(q, k_blk, v_blk, mask)
            acc = _merge(acc, partial)
            # Rotate K/V to the next device; src index follows the ring.
            k_nxt = jax.lax.ppermute(k_blk, axis, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis, perm)
            src_nxt = (src - 1) % sp
            return (acc, (k_nxt, v_nxt), src_nxt), None

        # Derive the zero accumulator from q so every component carries q's
        # device-varying type (a plain jnp.zeros would be "replicated" and
        # mismatch the scan carry under shard_map's VMA checking).
        zero = (
            jnp.zeros_like(q),
            q[..., :1] * 0 + jnp.finfo(q.dtype).min / 2,
            q[..., :1] * 0,
        )
        (acc, _, _), _ = jax.lax.scan(step, (zero, (k, v), idx), None, length=sp)
        out, m, l = acc
        return out / jnp.maximum(l, 1e-20)

    spec = P(None, None, axis, None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


def _causal_mask(tq, tk, q_off, k_off):
    qi = jnp.arange(tq)[:, None] + q_off
    ki = jnp.arange(tk)[None, :] + k_off
    return qi >= ki


def _block_causal_mask(block_len, q_block_idx, k_block_idx, sp):
    """Causal mask between the local Q block and the K block currently held
    (global block indices)."""
    q_off = q_block_idx * block_len
    k_off = k_block_idx * block_len
    full = _causal_mask(block_len, block_len, q_off, k_off)
    return full[None, None, :, :]
