"""Ulysses sequence parallelism: all-to-all head redistribution.

The second long-context strategy next to ring attention (SURVEY §5.7 /
§2.9; "DeepSpeed Ulysses", see PAPERS.md): with the sequence sharded
over ``sp``, two ``all_to_all`` exchanges turn the layout
[seq/sp, heads] → [seq, heads/sp] so every device runs *dense* attention
over the full sequence for its head slice, then back. Communication is
two all-to-alls of the activations (O(T·D/sp) per device, independent of
T²) instead of ring's sp-step K/V rotation — cheaper when heads ≥ sp
and the per-device full-sequence score matrix fits, while ring wins at
extreme lengths. Both are exact; tests assert parity with dense
attention on the virtual mesh.

Constraint: ``heads % sp == 0`` (the head axis is what gets scattered).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from dora_tpu.parallel.mesh import AXIS_SP


def ulysses_attention(q, k, v, mesh, causal: bool = True, axis: str = AXIS_SP):
    """Exact (optionally causal) attention with q/k/v sharded on ``axis``
    along the sequence dimension; [batch, heads, seq, head_dim].

    all_to_all #1 gathers the full sequence while scattering heads;
    dense attention runs per head slice; all_to_all #2 restores the
    sequence sharding.
    """
    sp = mesh.shape[axis]
    b, h, t_local, d = q.shape
    if sp == 1:
        return _dense(q, k, v, causal, 0)
    if h % sp:
        raise ValueError(f"ulysses: heads={h} not divisible by sp={sp}")

    def local(q, k, v):
        # [B, h, T/sp, D] -> [B, h/sp, T, D]: scatter heads, gather seq.
        def gather_seq(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        def scatter_seq(x):
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        qg, kg, vg = gather_seq(q), gather_seq(k), gather_seq(v)
        out = _dense(qg, kg, vg, causal, 0)
        return scatter_seq(out)

    spec = P(None, None, axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def _dense(q, k, v, causal: bool, offset: int):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
        q.shape[-1]
    ).astype(q.dtype)
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        qi = jnp.arange(tq)[:, None] + offset
        ki = jnp.arange(tk)[None, :]
        scores = jnp.where(
            (qi >= ki)[None, None], scores, jnp.finfo(scores.dtype).min
        )
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
