"""Tensor-parallel fused decode: the Pallas kernel tier over a tp mesh.

Round-4 seam (VERDICT r4): the fused decode kernels (ops/decode_block.py)
were batch-1 AND single-device — "fastest" and "multi-chip" were disjoint
paths. This module composes them: the same three kernels run per tp rank
on weight shards, with one f32 ``psum`` per sublayer stitching the
Megatron column/row-parallel partials back together, and a pmax/pmin pair
turning per-rank lm_head argmax winners into the global greedy token.

Layout (one-time host-side prep, :func:`prepare_decode_params`):

* ``wqkv`` [D, (H+2KV)*hd] — columns permuted into rank-block order
  (rank r holds ``[q_r | k_r | v_r]``) then sharded ``P(None, 'tp')``;
  the contiguous shard_map slice per rank is exactly the fused qkv
  weight of its local heads. Same permutation rides on scales + bias.
* ``wo`` [H*hd, D] — rows are head-major, so rank r's rows ARE its
  heads: natural ``P('tp', None)``, partial output psummed.
* ``w_gateup`` [D, 2F] — ``[gate | up]`` permuted to rank blocks
  ``[gate_r | up_r]``; ``w_down`` [F, D] row-sharded to match (rank r
  owns ffn rows ``r*F/tp..``), partial down-projection psummed.
* ``lm_head`` [D, V] — vocab-sharded ``P(None, 'tp')``; each rank's
  kernel returns (argmax, max) over its shard and the global winner is
  ``pmin`` of global indices among ``pmax``-achievers — preserving
  jnp.argmax's first-index tie-break exactly.
* KV caches — sharded over the kv-head axis; the in-place cache update
  stays per-rank and never crosses the interconnect.

Exactness: kernels run with ``residual=False`` so per-rank partials are
raw f32 deltas; the psum and residual-add happen in f32, mirroring the
single-device kernels' f32 accumulate — asserted token-identical on the
virtual mesh (tests/test_fused_tp.py, __graft_entry__ serving dryrun).

Reference parity: none — the reference (torch/CUDA eager, NCCL data
plane) has no tensor-parallel serving at all. This is the TPU-first
completeness axis: XLA collectives over ICI via shard_map.
"""

from __future__ import annotations

from functools import partial

import numpy as np

AXIS = "tp"


def tp_degree(mesh) -> int:
    if mesh is None:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(AXIS, 1)


def tp_compatible(tp: int, *, heads: int, kv_heads: int, ffn: int,
                  vocab: int) -> bool:
    """True when the fused kernel tier can shard over ``tp`` ranks:
    every partitioned dimension must tile. (kv_heads caps tp for GQA
    models — Qwen2-VL-2B's kv_heads=2 serves fused-tp at tp<=2; wider
    meshes fall back to the unfused XLA path, which replicates KV.)"""
    return (
        tp > 1
        and heads % tp == 0
        and kv_heads % tp == 0
        and ffn % tp == 0
        and vocab % tp == 0
    )


# ---------------------------------------------------------------------------
# column permutations (rank-block order)
# ---------------------------------------------------------------------------


def _perm_qkv(heads: int, kv_heads: int, head_dim: int, tp: int):
    """Column permutation [q|k|v] -> [q_0|k_0|v_0 | q_1|k_1|v_1 | ...]."""
    hl, kvl = heads // tp, kv_heads // tp
    q0, k0 = 0, heads * head_dim
    v0 = k0 + kv_heads * head_dim
    idx = []
    for r in range(tp):
        idx.append(np.arange(q0 + r * hl * head_dim, q0 + (r + 1) * hl * head_dim))
        idx.append(np.arange(k0 + r * kvl * head_dim, k0 + (r + 1) * kvl * head_dim))
        idx.append(np.arange(v0 + r * kvl * head_dim, v0 + (r + 1) * kvl * head_dim))
    return np.concatenate(idx)


def _perm_gateup(ffn: int, tp: int):
    """[gate|up] -> [gate_0|up_0 | gate_1|up_1 | ...]."""
    fl = ffn // tp
    idx = []
    for r in range(tp):
        idx.append(np.arange(r * fl, (r + 1) * fl))
        idx.append(np.arange(ffn + r * fl, ffn + (r + 1) * fl))
    return np.concatenate(idx)


# ---------------------------------------------------------------------------
# parameter prep
# ---------------------------------------------------------------------------


def _qw(d: dict):
    if "int4" in d:
        return d["int4"], d["gscale"]
    return d["int8"], d["scale"]


def _check_row_groups(w, s, tp: int, what: str) -> None:
    """int4 row-sharding must slice whole nibble-pack groups."""
    if w.dtype == np.uint8 or str(w.dtype) == "uint8":
        k = 2 * w.shape[0]
        group = k // s.shape[0]
        if (k // tp) % group:
            raise ValueError(
                f"{what}: K={k} over tp={tp} does not tile int4 "
                f"groups of {group}"
            )


def prepare_decode_params(params, mesh, *, heads: int, kv_heads: int,
                          head_dim: int, layers: int, eps: float = 1e-6):
    """Quantized fused-layout params -> the tp decode tree, placed.

    Input is the quantize_decode tree (fused wqkv/w_gateup dicts, int8
    or int4). Output is a flat-per-block tree of plain arrays (the _qw
    dispatch resolved) with columns permuted into rank-block order and
    every leaf device_put with its tp sharding. bf16 prefill sidecars
    are NOT carried — prefill rides the original tree.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = tp_degree(mesh)

    def put(arr, *spec):
        return jax.device_put(arr, NamedSharding(mesh, P(*spec)))

    pq = _perm_qkv(heads, kv_heads, head_dim, tp)
    out = {"blocks": {}}
    for i in range(layers):
        blk = params["blocks"][str(i)]
        wqkv, sqkv = _qw(blk["wqkv"])
        wo, swo = _qw(blk["wo"])
        wgu, sgu = _qw(blk["w_gateup"])
        wd, sd = _qw(blk["w_down"])
        _check_row_groups(wo, swo, tp, f"blocks.{i}.wo")
        _check_row_groups(wd, sd, tp, f"blocks.{i}.w_down")
        ffn = wd.shape[0] * (2 if "int4" in blk["w_down"] else 1)
        pgu = _perm_gateup(ffn, tp)
        n_qkv = (heads + 2 * kv_heads) * head_dim
        bqkv = blk.get("bqkv")
        if bqkv is None:
            bqkv = jnp.zeros((n_qkv,), jnp.float32)
        bgu = blk.get("b_gateup")
        if bgu is None:
            bgu = jnp.zeros((2 * ffn,), jnp.float32)
        out["blocks"][str(i)] = {
            "attn_norm": put(blk["attn_norm"], ),
            "wqkv": put(jnp.asarray(wqkv)[:, pq], None, AXIS),
            "sqkv": put(jnp.asarray(sqkv)[:, pq], None, AXIS),
            "bqkv": put(jnp.asarray(bqkv)[pq], AXIS),
            "wo": put(wo, AXIS, None),
            "swo": put(swo, AXIS, None) if swo.shape[0] > 1 else put(swo),
            "ffn_norm": put(blk["ffn_norm"]),
            "wgu": put(jnp.asarray(wgu)[:, pgu], None, AXIS),
            "sgu": put(jnp.asarray(sgu)[:, pgu], None, AXIS),
            "bgu": put(jnp.asarray(bgu)[pgu], AXIS),
            "wd": put(wd, AXIS, None),
            "sd": put(sd, AXIS, None) if sd.shape[0] > 1 else put(sd),
        }
    wh, sh = _qw(params["lm_head"])
    out["out_norm"] = put(params["out_norm"])
    out["wh"] = put(wh, None, AXIS)
    out["sh"] = put(sh, None, AXIS)
    return out


def _specs(params_tp, layers: int):
    """The in_specs pytree mirroring prepare_decode_params placement."""
    from jax.sharding import PartitionSpec as P

    col, row, rep = P(None, AXIS), P(AXIS, None), P()
    blocks = {}
    for i in range(layers):
        blk = params_tp["blocks"][str(i)]
        blocks[str(i)] = {
            "attn_norm": rep, "wqkv": col, "sqkv": col, "bqkv": P(AXIS),
            "wo": row, "swo": row if blk["swo"].shape[0] > 1 else rep,
            "ffn_norm": rep, "wgu": col, "sgu": col, "bgu": P(AXIS),
            "wd": row, "sd": row if blk["sd"].shape[0] > 1 else rep,
        }
    return {"blocks": blocks, "out_norm": rep, "wh": col, "sh": col}


def cache_spec():
    """KV caches shard over the kv-head axis: [B, KV, S, hd]."""
    from jax.sharding import PartitionSpec as P

    return P(None, AXIS, None, None)


def shard_caches(caches, mesh):
    """Place a freshly prefetched cache tree on the tp mesh (inside jit
    this is a resharding constraint; outside, a device_put)."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, cache_spec())

    def place(x):
        if isinstance(x, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(x, sharding)
        return jax.device_put(x, sharding)

    return jax.tree.map(place, caches)


# ---------------------------------------------------------------------------
# the tp pass
# ---------------------------------------------------------------------------


def decode_pass_tp(params_tp, x, caches, position, cos_rows, sin_rows, *,
                   heads: int, kv_heads: int, head_dim: int, layers: int,
                   mesh, eps: float = 1e-6):
    """M-row fused greedy pass over the tp mesh (shard_map).

    Mirrors models/vlm.fused_decode_pass: x [M, D] embedded rows,
    cos/sin [M, hd] rope rows, caches [1, KV, S, hd] per layer (sharded
    over KV). Returns (greedy [M] int32 — replicated — and the
    in-place-updated sharded caches).
    """
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from dora_tpu.ops import decode_block as DB

    tp = tp_degree(mesh)
    heads_l, kv_l = heads // tp, kv_heads // tp
    vocab_l = params_tp["wh"].shape[1] // tp
    m = x.shape[0]
    attn = DB.attention_step if m == 1 else DB.attention_chunk_step
    rep = P()

    def body(params, x, caches, pos, cos, sin):
        r = jax.lax.axis_index(AXIS)
        new_caches = {}
        for i in range(layers):
            blk = params["blocks"][str(i)]
            kc = caches[str(i)]["k"][0]  # [KV_l, S, hd]
            vc = caches[str(i)]["v"][0]
            o, kc, vc = attn(
                x, blk["attn_norm"], blk["wqkv"], blk["sqkv"], blk["bqkv"],
                cos, sin, kc, vc, blk["wo"], blk["swo"], pos,
                heads=heads_l, kv_heads=kv_l, head_dim=head_dim, eps=eps,
                residual=False,
            )
            o = jax.lax.psum(o, AXIS)
            x = (x.astype(jnp.float32) + o).astype(x.dtype)
            new_caches[str(i)] = {"k": kc[None], "v": vc[None]}
            a = DB.mlp_step(
                x, blk["ffn_norm"], blk["wgu"], blk["sgu"], blk["bgu"],
                blk["wd"], blk["sd"], eps=eps, residual=False,
            )
            a = jax.lax.psum(a, AXIS)
            x = (x.astype(jnp.float32) + a).astype(x.dtype)
        idx, val = DB.lm_head_argmax(
            x, params["out_norm"], params["wh"], params["sh"], eps=eps,
            return_val=True,
        )
        # Global argmax with jnp.argmax's first-index tie-break: among
        # ranks achieving the global max, the smallest global index wins.
        gmax = jax.lax.pmax(val, AXIS)
        cand = jnp.where(
            val >= gmax, idx + r * vocab_l, jnp.int32(2**31 - 1)
        )
        gidx = jax.lax.pmin(cand, AXIS)
        return gidx, new_caches

    cspec = {str(i): {"k": cache_spec(), "v": cache_spec()}
             for i in range(layers)}
    return shard_map(
        partial(body),
        mesh=mesh,
        in_specs=(_specs(params_tp, layers), rep, cspec, rep, rep, rep),
        out_specs=(rep, cspec),
        check_vma=False,
    )(params_tp, x, caches, position, cos_rows, sin_rows)
