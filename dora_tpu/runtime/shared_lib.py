"""Shared-library operator host: dlopen a C-ABI operator into the runtime.

Reference parity: binaries/runtime/src/operator/shared_lib.rs:29-295 —
load the library, resolve dora_init_operator / dora_on_event /
dora_drop_operator, translate daemon events into ABI calls, route the
send_output callback back into the node. ABI: native/dora_operator_api.h.
"""

from __future__ import annotations

import ctypes
import logging
from pathlib import Path

from dora_tpu.core.descriptor import OperatorDefinition, SharedLibrarySource
from dora_tpu.tpu.api import DoraStatus

logger = logging.getLogger(__name__)

_EVENT_INPUT = 0
_EVENT_INPUT_CLOSED = 1
_EVENT_STOP = 2

_SEND_FN = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_void_p,  # context
    ctypes.c_char_p,  # output id
    ctypes.POINTER(ctypes.c_ubyte),  # data
    ctypes.c_size_t,  # len
    ctypes.c_char_p,  # encoding
)


class _Event(ctypes.Structure):
    _fields_ = [
        ("type", ctypes.c_int),
        ("id", ctypes.c_char_p),
        ("data", ctypes.POINTER(ctypes.c_ubyte)),
        ("data_len", ctypes.c_size_t),
        ("encoding", ctypes.c_char_p),
    ]


class _SendOutput(ctypes.Structure):
    _fields_ = [("context", ctypes.c_void_p), ("send", _SEND_FN)]


from dora_tpu.core.validate import adjust_shared_library_path


class SharedLibOperatorHost:
    """Hosts one C-ABI operator instance."""

    def __init__(self, definition: OperatorDefinition, node, working_dir: Path):
        assert isinstance(definition.source, SharedLibrarySource)
        self.definition = definition
        self.node = node
        self.stopped = False
        path = Path(definition.source.source)
        if not path.is_absolute():
            path = working_dir / path
        path = adjust_shared_library_path(path)
        self._lib = ctypes.CDLL(str(path))
        self._lib.dora_init_operator.restype = ctypes.c_void_p
        self._lib.dora_on_event.restype = ctypes.c_int
        self._lib.dora_on_event.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(_Event),
            ctypes.POINTER(_SendOutput),
        ]
        self._lib.dora_drop_operator.argtypes = [ctypes.c_void_p]
        self._state = self._lib.dora_init_operator()

        op_id = str(definition.id)

        def send(_ctx, output_id, data, data_len, encoding) -> int:
            try:
                payload = bytes(
                    ctypes.cast(
                        data, ctypes.POINTER(ctypes.c_ubyte * data_len)
                    ).contents
                ) if data_len else b""
                encoding_str = (encoding or b"raw").decode()
                if encoding_str == "arrow-ipc":
                    from dora_tpu.node.arrow import ipc_deserialize

                    value = ipc_deserialize(payload)
                else:
                    value = payload
                node.send_output(f"{op_id}/{output_id.decode()}", value)
                return 0
            except Exception:
                logger.exception("shared-lib operator send_output failed")
                return 1

        # Keep the callback alive for the operator's lifetime.
        self._send_cb = _SEND_FN(send)
        self._send_struct = _SendOutput(context=None, send=self._send_cb)

    def on_event(self, event: dict) -> DoraStatus:
        if self.stopped:
            return DoraStatus.STOP
        kind = event["type"]
        if kind == "INPUT":
            payload, encoding = self._encode_value(event)
            if payload:
                buf = (ctypes.c_ubyte * len(payload)).from_buffer_copy(payload)
                self._buf = buf  # pin until the call returns
                data_ptr = ctypes.cast(buf, ctypes.POINTER(ctypes.c_ubyte))
            else:
                data_ptr = None
            c_event = _Event(
                type=_EVENT_INPUT,
                id=(event["id"] or "").encode(),
                data=data_ptr,
                data_len=len(payload) if payload else 0,
                encoding=encoding,
            )
        elif kind == "INPUT_CLOSED":
            c_event = _Event(type=_EVENT_INPUT_CLOSED,
                             id=(event["id"] or "").encode())
        else:
            c_event = _Event(type=_EVENT_STOP)
        status = DoraStatus(
            self._lib.dora_on_event(
                self._state, ctypes.byref(c_event), ctypes.byref(self._send_struct)
            )
        )
        if status != DoraStatus.CONTINUE:
            self.stopped = True
        return status

    @staticmethod
    def _encode_value(event: dict):
        value = event.get("value")
        if value is None:
            return None, b"raw"
        import pyarrow as pa

        if isinstance(value, pa.Array):
            from dora_tpu.node.arrow import ipc_serialize

            return ipc_serialize(value), b"arrow-ipc"
        return bytes(value), b"raw"

    def close(self) -> None:
        if self._state:
            try:
                self._lib.dora_drop_operator(self._state)
            except Exception:
                pass
            self._state = None
