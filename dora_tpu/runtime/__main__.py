"""Entry point for daemon-spawned runtime nodes
(reference: dora_runtime::main, binaries/runtime/src/lib.rs:28-106)."""

import faulthandler
import signal
import sys


def main() -> None:
    # Debuggability: `kill -USR1 <pid>` dumps all Python stacks to stderr
    # (lands in the node's daemon-side log file).
    faulthandler.register(signal.SIGUSR1)
    from dora_tpu.runtime import run

    sys.exit(run())


if __name__ == "__main__":
    main()
