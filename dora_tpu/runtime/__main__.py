"""Entry point for daemon-spawned runtime nodes
(reference: dora_runtime::main, binaries/runtime/src/lib.rs:28-106)."""

import sys


def main() -> None:
    from dora_tpu.telemetry import install_stack_dump

    install_stack_dump()
    from dora_tpu.runtime import run

    sys.exit(run())


if __name__ == "__main__":
    main()
