"""The operator runtime: a node process hosting operators.

Reference parity: binaries/runtime — a special node that bridges daemon
events to operator callbacks. Improvements over the reference: one runtime
hosts MANY operators (the reference supports exactly one per process,
runtime/src/lib.rs:44-51), and jax operators fuse into a single XLA
computation per tick (dora_tpu.tpu.fuse) with edges resident in HBM.

Python operators keep the reference convention: the source file defines
``class Operator`` with ``on_event(event, send_output) -> DoraStatus``
(binaries/runtime/src/operator/python.rs:93-107), with hot-reload that
preserves the instance ``__dict__`` (python.rs:129-185).
"""

from __future__ import annotations

import importlib.util
import logging
import sys
from pathlib import Path
from typing import Any

from dora_tpu.core.descriptor import (
    Descriptor,
    JaxSource,
    OperatorDefinition,
    PythonSource,
    RuntimeNode,
    SharedLibrarySource,
    WasmSource,
)
from dora_tpu.node import Node
from dora_tpu.telemetry import OTEL_CTX_KEY, span
from dora_tpu.tpu.api import DoraStatus

logger = logging.getLogger(__name__)


class PythonOperatorHost:
    """Hosts one Python operator instance (reference: operator/python.rs)."""

    def __init__(self, definition: OperatorDefinition, node: Node, working_dir: Path):
        self.definition = definition
        self.node = node
        self.working_dir = working_dir
        self.stopped = False
        self.instance = self._instantiate()

    def _load_module(self):
        source: PythonSource = self.definition.source
        path = Path(source.source)
        if not path.is_absolute():
            path = self.working_dir / path
        spec = importlib.util.spec_from_file_location(
            f"dora_tpu_pyop_{self.definition.id}", path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module

    def _instantiate(self):
        module = self._load_module()
        cls = getattr(module, "Operator")
        instance = cls()
        # Reference sets the dataflow descriptor as a class attribute
        # (python.rs: `dataflow_descriptor`).
        instance.dataflow_descriptor = self.node.dataflow_descriptor()
        return instance

    def reload(self) -> None:
        """Re-import the source, preserving operator state (__dict__)."""
        old_dict = dict(self.instance.__dict__)
        try:
            self.instance = self._instantiate()
            self.instance.__dict__.update(old_dict)
            logger.info("operator %s reloaded", self.definition.id)
        except Exception:
            logger.exception("hot-reload of %s failed; keeping old code",
                             self.definition.id)

    def on_event(self, event: dict) -> DoraStatus:
        if self.stopped:
            return DoraStatus.STOP

        # With tracing off, span() is a single attribute check that
        # forwards parent_ctx unchanged; with it on, the operator span
        # parents the node's per-message t_send spans downstream.
        parent_ctx = str((event.get("metadata") or {}).get(OTEL_CTX_KEY, ""))
        with span(f"{self.definition.id}/on_event", parent_ctx) as ctx:

            def send_output(output_id: str, data=None, metadata=None):
                metadata = dict(metadata or {})
                # Propagate the trace continuation downstream (reference:
                # runtime/src/operator/python.rs:188-213).
                if ctx:
                    metadata.setdefault(OTEL_CTX_KEY, ctx)
                self.node.send_output(
                    f"{self.definition.id}/{output_id}", data, metadata
                )

            status = self.instance.on_event(event, send_output)
        if status is None:
            return DoraStatus.CONTINUE
        status = DoraStatus(int(status))
        if status == DoraStatus.STOP:
            self.stopped = True
        return status


def run() -> int:
    """Runtime node main loop (spawned with DORA_NODE_CONFIG set).

    Operator loading happens BEFORE ``Node()`` joins the start barrier:
    a jax operator factory may initialize gigabytes of model weights on
    the TPU, and subscribing first would release upstream producers (a
    camera on a timer) minutes before this node can consume — the
    barrier exists exactly to prevent that."""
    import os as _os

    from dora_tpu.daemon.spawn import NODE_CONFIG_ENV, decode_node_config

    raw_config = _os.environ.get(NODE_CONFIG_ENV)
    if not raw_config:
        raise RuntimeError("runtime must be spawned by a daemon "
                           f"({NODE_CONFIG_ENV} is not set)")
    config = decode_node_config(raw_config)
    descriptor = Descriptor.parse(config.dataflow_descriptor)
    me = descriptor.node(config.node_id)
    if not isinstance(me.kind, RuntimeNode):
        raise RuntimeError(f"node {config.node_id!r} is not a runtime node")
    working_dir = Path.cwd()

    has_jax = any(
        isinstance(op.source, JaxSource) for op in me.kind.operators
    )
    if has_jax:
        # Multi-host tensor plane (SURVEY §2.9): when the deployment sets
        # the DORA_JAX_* contract, this runtime joins the global mesh
        # (one runtime node per TPU host) before any operator loads, so
        # DORA_MESH sharding spans hosts — ICI within a slice, DCN across.
        from dora_tpu.parallel.distributed import maybe_init_distributed

        maybe_init_distributed()
    for op in me.kind.operators:
        if isinstance(op.source, WasmSource):
            # Reference parity: declared, not runnable
            # (binaries/runtime/src/operator/mod.rs:65-67).
            raise RuntimeError(
                f"operator {op.id!r}: WASM operators are not supported yet"
            )

    fused = None
    if has_jax:
        import time as _time

        from dora_tpu.tpu.fuse import FusedExecutor, FusedGraph

        t0 = _time.perf_counter()
        graph = FusedGraph.build(me, descriptor, working_dir)
        fused = FusedExecutor(graph)
        logger.info(
            "fused %d jax operators in %.1fs (topo %s); external in=%s out=%s",
            len(graph.operators), _time.perf_counter() - t0, graph.topo,
            sorted(graph.external_inputs | graph.timer_inputs),
            sorted(graph.external_outputs),
        )

    node = Node()  # subscribes: joins the start barrier only now
    logger.info("subscribed; start barrier passed")
    python_hosts: dict[str, Any] = {}  # callback-style hosts (python + C ABI)
    for op in me.kind.operators:
        if isinstance(op.source, PythonSource):
            python_hosts[str(op.id)] = PythonOperatorHost(op, node, working_dir)
        elif isinstance(op.source, SharedLibrarySource):
            from dora_tpu.runtime.shared_lib import SharedLibOperatorHost

            python_hosts[str(op.id)] = SharedLibOperatorHost(
                op, node, working_dir
            )

    # Per-event processing honors the YAML queue_size contract end to
    # end: while a tick runs, the node's bounded event buffer
    # (EventStream.DEFAULT_MAX_QUEUE) stops pulling, events back up in
    # the daemon's per-input queues, and drop-oldest applies there — a
    # camera with queue_size 1 lags the fused model by at most the few
    # in-flight events, never by an unbounded replayed backlog.
    if fused is not None and fused.pipeline_depth > 0:
        # Completed pipelined fetches wake the parked recv below, so the
        # loop emits finished tick outputs immediately even when the
        # trigger stream goes quiet — no polling interval, no idle burn.
        fused.on_fetch_done = node.wake

    stop_all = False
    while True:
        event = node.recv()
        # Emit every completed pipelined tick on EVERY iteration, not
        # just on WAKE: a wake dropped against a full event queue (full
        # queue == more events coming == more iterations) must not
        # strand a finished output behind non-harvesting events.
        if fused is not None and fused.has_in_flight:
            for outputs in fused.harvest():
                for out_id, (arr, meta) in outputs.items():
                    node.send_output(out_id, arr, meta)
        if event is None:
            if node.stream_ended:
                break
            continue
        if event["type"] == "WAKE":
            continue  # handled by the harvest above
        if event["type"] == "INPUT":
            op_id, _, input_id = (event["id"] or "").partition("/")
            host = python_hosts.get(op_id)
            if host is not None:
                status = host.on_event(
                    {
                        "type": "INPUT",
                        "id": input_id,
                        "value": event["value"],
                        "metadata": event["metadata"],
                    }
                )
                if status == DoraStatus.STOP_ALL:
                    stop_all = True
            elif fused is not None:
                if fused.pipeline_depth > 0:
                    # Async serving: dispatch without fetching, then emit
                    # whatever earlier ticks have completed — the fetch
                    # round-trip overlaps the next frame's compute.
                    fused.on_event_async(
                        event["id"], event["value"], event["metadata"]
                    )
                    for outputs in fused.harvest():
                        for out_id, (arr, meta) in outputs.items():
                            node.send_output(out_id, arr, meta)
                else:
                    outputs = fused.on_event(
                        event["id"], event["value"], event["metadata"]
                    )
                    if outputs:
                        for out_id, (arr, meta) in outputs.items():
                            node.send_output(out_id, arr, meta)
        elif event["type"] == "RELOAD":
            target = event.get("operator_id")
            for op_id, host in python_hosts.items():
                if target in (None, op_id):
                    if hasattr(host, "reload"):  # C-ABI ops don't hot-reload
                        host.reload()
        elif event["type"] == "INPUT_CLOSED":
            continue
        elif event["type"] == "STOP":
            break
        if stop_all or (
            python_hosts
            and all(h.stopped for h in python_hosts.values())
            and fused is None
        ):
            break

    if fused is not None and fused.pipeline_depth > 0:
        # Stream end: flush in-flight ticks so the tail frames are
        # delivered before the node leaves (order preserved).
        try:
            for outputs in fused.harvest(block=True):
                for out_id, (arr, meta) in outputs.items():
                    node.send_output(out_id, arr, meta)
        except Exception:
            logger.exception("pipelined flush failed")
    if fused is not None:
        fused.close()

    for host in python_hosts.values():
        if not host.stopped:
            try:
                host.on_event({"type": "STOP", "id": None, "value": None,
                               "metadata": {}})
            except Exception:
                pass
        close = getattr(host, "close", None)
        if close is not None:
            close()
    node.close()
    return 0
