"""dora-tpu: a TPU-native dataflow framework.

A YAML-described graph of nodes exchanging Apache-Arrow messages through a
per-machine daemon, coordinated across machines by a control-plane
coordinator — with a first-class TPU execution tier: operators marked
``runtime: tpu`` are JAX-traced functions fused into a single XLA computation
per dataflow tick, so tensors stay in device HBM across node boundaries.

Capability blueprint: the dora-rs reference (see SURVEY.md). This package is
a ground-up TPU-first design, not a port.
"""

__version__ = "0.1.0"

# The wire-protocol version; nodes and daemons refuse to talk across
# incompatible protocol versions (reference: dora-message semver check,
# libraries/message/src/lib.rs:28-43).
PROTOCOL_VERSION = "0.1.0"


def __getattr__(name):
    # Lazy re-exports so that `import dora_tpu` stays cheap for CLI tools
    # and subprocess nodes (jax import alone costs ~2s).
    try:
        if name == "Node":
            from dora_tpu.node import Node

            return Node
        if name == "Descriptor":
            from dora_tpu.core.descriptor import Descriptor

            return Descriptor
    except ImportError as e:
        raise AttributeError(f"cannot import dora_tpu.{name}: {e}") from e
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
