"""Well-known ports and topics.

Reference parity: libraries/core/src/topics.rs:3-8. We keep the same default
port numbers so dataflows migrating from the reference need no config change.
"""

# Coordinator listens here for daemon registrations (data-plane control).
DORA_COORDINATOR_PORT_DEFAULT = 53290

# Each daemon listens here for dynamic-node connections on its machine.
DORA_DAEMON_LOCAL_LISTEN_PORT_DEFAULT = 53291

# Coordinator listens here for CLI control connections.
DORA_COORDINATOR_PORT_CONTROL_DEFAULT = 6012

MANUAL_STOP = "dora/stop"

# Outputs larger than this are passed via shared memory instead of inline
# bytes (reference: ZERO_COPY_THRESHOLD, apis/rust/node/src/node/mod.rs:40).
ZERO_COPY_THRESHOLD = 4096
