"""Input/communication configuration types.

Reference parity: dora-core config (libraries/core/src/config.rs:131-375) —
`InputMapping{Timer,User}` parsed from "node/output" or "dora/timer/millis/100"
strings, `Input{mapping,queue_size}`, `CommunicationConfig`.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Any, Mapping, Union

from dora_tpu.ids import DataId, NodeId, OutputId

# ---------------------------------------------------------------------------
# Input mappings
# ---------------------------------------------------------------------------

#: The pseudo-node that owns timer streams ("dora/timer/millis/100").
DORA_NODE_ID = NodeId("dora")

_TIMER_UNITS_NS = {
    "nanos": 1,
    "micros": 1_000,
    "millis": 1_000_000,
    "secs": 1_000_000_000,
}


@dataclass(frozen=True)
class TimerMapping:
    """Input fed by a daemon-owned periodic timer."""

    interval_ns: int

    @property
    def data_id(self) -> DataId:
        # Canonical form uses the coarsest exact unit.
        for unit in ("secs", "millis", "micros", "nanos"):
            div = _TIMER_UNITS_NS[unit]
            if self.interval_ns % div == 0:
                return DataId(f"timer-{unit}-{self.interval_ns // div}")
        raise AssertionError("unreachable")

    def __str__(self) -> str:
        for unit in ("secs", "millis", "micros", "nanos"):
            div = _TIMER_UNITS_NS[unit]
            if self.interval_ns % div == 0:
                return f"dora/timer/{unit}/{self.interval_ns // div}"
        raise AssertionError("unreachable")


@dataclass(frozen=True)
class UserMapping:
    """Input fed by another node's output."""

    source: NodeId
    output: DataId

    @property
    def output_id(self) -> OutputId:
        return OutputId(self.source, self.output)

    def __str__(self) -> str:
        return f"{self.source}/{self.output}"


InputMapping = Union[TimerMapping, UserMapping]


def parse_input_mapping(s: str) -> InputMapping:
    """Parse "source/output" or "dora/timer/<unit>/<n>"."""
    parts = s.split("/")
    if parts[0] == str(DORA_NODE_ID):
        if len(parts) == 4 and parts[1] == "timer" and parts[2] in _TIMER_UNITS_NS:
            try:
                n = int(parts[3])
            except ValueError:
                raise ValueError(f"invalid timer interval in {s!r}") from None
            if n <= 0:
                raise ValueError(f"timer interval must be positive: {s!r}")
            return TimerMapping(interval_ns=n * _TIMER_UNITS_NS[parts[2]])
        raise ValueError(
            f"unknown dora input {s!r} (expected dora/timer/<unit>/<n> with "
            f"unit in {sorted(_TIMER_UNITS_NS)})"
        )
    # "<node>/<output>" where output may itself contain '/' (runtime-node
    # streams are namespaced "<operator>/<output>").
    if len(parts) >= 2 and all(parts):
        return UserMapping(source=NodeId(parts[0]), output=DataId("/".join(parts[1:])))
    raise ValueError(f"expected '<node>/<output>' or dora timer, got {s!r}")


DEFAULT_QUEUE_SIZE = 10


@dataclass(frozen=True)
class Input:
    """One input slot: where it comes from plus its bounded-queue size.

    Overflowing queues drop the *oldest* event (reference:
    binaries/daemon/src/node_communication/mod.rs:320-359).
    """

    mapping: InputMapping
    queue_size: int = DEFAULT_QUEUE_SIZE

    @classmethod
    def parse(cls, value: Any) -> "Input":
        if isinstance(value, str):
            return cls(mapping=parse_input_mapping(value))
        if isinstance(value, Mapping):
            extra = set(value) - {"source", "queue_size"}
            if extra:
                raise ValueError(f"unknown input keys: {sorted(extra)}")
            if "source" not in value:
                raise ValueError(f"input mapping missing 'source': {value!r}")
            qs = value.get("queue_size", DEFAULT_QUEUE_SIZE)
            if not isinstance(qs, int) or qs < 1:
                raise ValueError(f"queue_size must be a positive int, got {qs!r}")
            return cls(mapping=parse_input_mapping(value["source"]), queue_size=qs)
        raise ValueError(f"invalid input spec: {value!r}")

    def to_dict(self) -> Any:
        if self.queue_size == DEFAULT_QUEUE_SIZE:
            return str(self.mapping)
        return {"source": str(self.mapping), "queue_size": self.queue_size}


# ---------------------------------------------------------------------------
# Communication config
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LocalCommunicationConfig:
    """node<->daemon transport on one machine: tcp | shmem | uds."""

    kind: str = "tcp"

    _KINDS = ("tcp", "shmem", "uds")

    def __post_init__(self):
        if self.kind not in self._KINDS:
            raise ValueError(
                f"unknown local communication {self.kind!r}; expected one of {self._KINDS}"
            )


@dataclass(frozen=True)
class CommunicationConfig:
    local: LocalCommunicationConfig = field(default_factory=LocalCommunicationConfig)
    remote: str = "tcp"

    @classmethod
    def parse(cls, value: Mapping[str, Any] | None) -> "CommunicationConfig":
        if not value:
            return cls()
        local = value.get("local", value.get("_unstable_local", "tcp"))
        if isinstance(local, Mapping):
            local = local.get("kind", "tcp")
        # Reference spellings (dataflow_socket.yml uses "UnixDomain").
        local = {
            "UnixDomain": "uds",
            "Tcp": "tcp",
            "Shmem": "shmem",
            "SharedMemory": "shmem",
        }.get(str(local), str(local).lower())
        remote = value.get("remote", value.get("_unstable_remote", "tcp"))
        if isinstance(remote, Mapping):
            remote = remote.get("kind", "tcp")
        if remote != "tcp":
            raise ValueError(f"unknown remote communication {remote!r}; only 'tcp'")
        return cls(local=LocalCommunicationConfig(str(local)), remote=str(remote))


# ---------------------------------------------------------------------------
# Env expansion
# ---------------------------------------------------------------------------

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}|\$([A-Za-z_][A-Za-z0-9_]*)")


def expand_env(value: Any, env: Mapping[str, str] | None = None) -> Any:
    """Expand $VAR / ${VAR} inside string values (reference:
    libraries/core/src/descriptor/mod.rs:541-550)."""
    if env is None:
        env = os.environ
    if isinstance(value, str):

        def sub(m: re.Match) -> str:
            name = m.group(1) or m.group(2)
            return env.get(name, m.group(0))

        return _ENV_RE.sub(sub, value)
    return value
