"""The YAML dataflow descriptor: parse, resolve, validate, visualize.

Reference parity: dora-core Descriptor
(libraries/core/src/descriptor/mod.rs:25-260): four node kinds — Standard
(``path:``), Custom (``custom:``), Runtime (``operators:``), SingleOperator
(``operator:``) — resolved into a uniform ``ResolvedNode`` list; operator
sources SharedLibrary|Python; ``SHELL_SOURCE``/``DYNAMIC_SOURCE`` markers.

TPU-first additions:
  * operator source ``jax: module.path:factory`` (or a ``.py`` path exposing
    the factory) — a JAX-traced operator function executed on the TPU tier.
  * contiguous subgraphs of jax operators are fused into one XLA computation
    per tick by the TPU runtime (see dora_tpu.tpu.fuse).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

import yaml

from dora_tpu.core.config import (
    CommunicationConfig,
    Input,
    TimerMapping,
    UserMapping,
    expand_env,
)
from dora_tpu.ids import DataId, NodeId, OperatorId, OutputId

# Special `path:` markers.
SHELL_SOURCE = "shell"
DYNAMIC_SOURCE = "dynamic"

DEFAULT_OPERATOR_ID = "op"


# ---------------------------------------------------------------------------
# Operator model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PythonSource:
    """A Python operator: a .py file defining ``class Operator`` with
    ``on_event(event, send_output) -> DoraStatus``."""

    source: str
    conda_env: str | None = None


@dataclass(frozen=True)
class SharedLibrarySource:
    """A native operator: shared library exporting the C operator ABI
    (dora_init_operator / dora_on_event / dora_drop_operator)."""

    source: str


@dataclass(frozen=True)
class WasmSource:
    """A WASM operator module. Accepted by the descriptor for parity with
    the reference, which declares this variant but does not run it
    ("WASM operators are not supported yet",
    binaries/runtime/src/operator/mod.rs:65-67; hidden from its schema via
    schemars(skip)) — the runtime here rejects it with the same message."""

    source: str


@dataclass(frozen=True)
class JaxSource:
    """A TPU-tier operator: ``module.path:factory`` or ``file.py:factory``.

    The factory returns a :class:`dora_tpu.tpu.api.JaxOperator` — a pure
    function ``(state, inputs) -> (state, outputs)`` plus init state —
    which the TPU runtime traces and fuses with adjacent jax operators.
    """

    source: str

    def split(self) -> tuple[str, str]:
        mod, sep, fn = self.source.partition(":")
        return (mod, fn if sep else "make_operator")


OperatorSource = PythonSource | SharedLibrarySource | JaxSource | WasmSource


@dataclass(frozen=True)
class OperatorDefinition:
    id: OperatorId
    source: OperatorSource
    inputs: dict[DataId, Input] = field(default_factory=dict)
    outputs: frozenset[DataId] = frozenset()
    name: str | None = None
    description: str | None = None
    build: str | None = None
    send_stdout_as: str | None = None

    @classmethod
    def parse(cls, value: Mapping[str, Any], default_id: str | None = None) -> "OperatorDefinition":
        op_id = value.get("id", default_id)
        if op_id is None:
            raise ValueError(f"operator missing 'id': {value!r}")
        sources = [
            k for k in ("python", "shared-library", "jax", "wasm") if k in value
        ]
        if len(sources) != 1:
            raise ValueError(
                f"operator {op_id!r} must have exactly one of "
                f"python/shared-library/jax/wasm, got {sources}"
            )
        kind = sources[0]
        raw = value[kind]
        if kind == "python":
            if isinstance(raw, Mapping):
                source: OperatorSource = PythonSource(
                    source=str(raw["source"]), conda_env=raw.get("conda_env")
                )
            else:
                source = PythonSource(source=str(raw))
        elif kind == "shared-library":
            source = SharedLibrarySource(source=str(raw))
        elif kind == "wasm":
            source = WasmSource(source=str(raw))
        else:
            source = JaxSource(source=str(raw))
        return cls(
            id=OperatorId(str(op_id)),
            source=source,
            inputs=_parse_inputs(value.get("inputs")),
            outputs=_parse_outputs(value.get("outputs")),
            name=value.get("name"),
            description=value.get("description"),
            build=value.get("build"),
            send_stdout_as=value.get("send_stdout_as"),
        )


# ---------------------------------------------------------------------------
# Node model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Deploy:
    machine: str | None = None

    @classmethod
    def parse(cls, value: Mapping[str, Any] | None) -> "Deploy":
        if not value:
            return cls()
        return cls(machine=value.get("machine"))


@dataclass(frozen=True)
class RestartPolicy:
    """Per-node elastic-recovery policy (``restart:`` in the descriptor).

    A node that fails post-barrier (nonzero exit, signal, spawn error)
    is respawned by its daemon up to ``max_attempts`` times with
    exponential backoff (``backoff_base_s * 2**attempt`` capped at
    ``backoff_max_s``, plus jitter), and its un-acked in-flight inputs
    are replayed from the daemon-side replay buffer. Grace kills,
    cascading failures, and pre-barrier failures never respawn.
    """

    max_attempts: int = 0
    backoff_base_s: float = 0.5
    backoff_max_s: float = 15.0

    @classmethod
    def parse(cls, value: Any) -> "RestartPolicy | None":
        if value is None or value is False:
            return None
        if value is True:
            return cls(max_attempts=1)
        if isinstance(value, int):
            return cls(max_attempts=value) if value > 0 else None
        if not isinstance(value, Mapping):
            raise ValueError(
                f"'restart' must be a mapping, int, or bool, got {type(value).__name__}"
            )
        unknown = set(value) - {"max_attempts", "backoff_base_s", "backoff_max_s"}
        if unknown:
            raise ValueError(f"unknown restart keys: {sorted(unknown)}")
        policy = cls(
            max_attempts=int(value.get("max_attempts", 1)),
            backoff_base_s=float(value.get("backoff_base_s", 0.5)),
            backoff_max_s=float(value.get("backoff_max_s", 15.0)),
        )
        if policy.max_attempts < 0 or policy.backoff_base_s < 0:
            raise ValueError("restart: max_attempts/backoff_base_s must be >= 0")
        return policy if policy.max_attempts > 0 else None


@dataclass(frozen=True)
class SloPolicy:
    """Per-node service-level objectives (``slo:`` in the descriptor).

    Targets are evaluated against the daemon's metrics history ring
    (``dora_tpu.metrics_history``) every sampling interval; violations
    flag the sample, feed the 1 m / 10 m burn-rate gauges, and land in
    the flight recorder as ``slo_violation`` instants on the trace
    timeline. All targets are optional; an empty mapping is rejected
    (an ``slo:`` block that checks nothing is a descriptor bug).
    """

    ttft_p99_ms: float | None = None
    tokens_per_s_min: float | None = None
    queue_depth_max: int | None = None

    @classmethod
    def parse(cls, value: Any) -> "SloPolicy | None":
        if value is None:
            return None
        if not isinstance(value, Mapping):
            raise ValueError(
                f"'slo' must be a mapping, got {type(value).__name__}"
            )
        unknown = set(value) - {
            "ttft_p99_ms", "tokens_per_s_min", "queue_depth_max"
        }
        if unknown:
            raise ValueError(f"unknown slo keys: {sorted(unknown)}")
        if not value:
            raise ValueError("'slo' must set at least one objective")
        for key in ("ttft_p99_ms", "tokens_per_s_min", "queue_depth_max"):
            raw = value.get(key)
            if raw is not None and not isinstance(raw, (int, float)):
                raise ValueError(f"slo {key} must be a number")
        policy = cls(
            ttft_p99_ms=(
                float(value["ttft_p99_ms"])
                if value.get("ttft_p99_ms") is not None
                else None
            ),
            tokens_per_s_min=(
                float(value["tokens_per_s_min"])
                if value.get("tokens_per_s_min") is not None
                else None
            ),
            queue_depth_max=(
                int(value["queue_depth_max"])
                if value.get("queue_depth_max") is not None
                else None
            ),
        )
        for key, target in policy.as_targets().items():
            if target < 0:
                raise ValueError(f"slo {key} must be >= 0")
        return policy

    def as_targets(self) -> dict[str, float]:
        """Non-None objectives as a plain dict (the history ring's
        ``slo_targets`` entry and the node's DORA_SLO_* env values)."""
        out = {}
        if self.ttft_p99_ms is not None:
            out["ttft_p99_ms"] = self.ttft_p99_ms
        if self.tokens_per_s_min is not None:
            out["tokens_per_s_min"] = self.tokens_per_s_min
        if self.queue_depth_max is not None:
            out["queue_depth_max"] = self.queue_depth_max
        return out


QOS_CLASSES = ("interactive", "standard", "batch")


@dataclass(frozen=True)
class QosPolicy:
    """Traffic shaping for a serving node (``qos:`` in the descriptor).

    Requests carry a priority class (``interactive`` / ``standard`` /
    ``batch``); the admission queue drains classes by weight with aging
    so ``batch`` never starves forever. Per-class depth bounds and the
    queue-wait deadline turn overload into fast retriable
    ``overloaded`` chunks instead of unbounded backlog, and
    ``preempt`` lets an inadmissible higher-class request evict a
    lower-class decode (recompute-on-resume, token-identical).
    """

    default_class: str = "standard"
    depth_interactive: int | None = None
    depth_standard: int | None = None
    depth_batch: int | None = None
    shed_wait_ms: float | None = None
    aging_s: float | None = None
    preempt: bool | None = None

    _KEYS = (
        "default_class",
        "depth_interactive",
        "depth_standard",
        "depth_batch",
        "shed_wait_ms",
        "aging_s",
        "preempt",
    )

    @classmethod
    def parse(cls, value: Any) -> "QosPolicy | None":
        if value is None:
            return None
        if not isinstance(value, Mapping):
            raise ValueError(
                f"'qos' must be a mapping, got {type(value).__name__}"
            )
        unknown = set(value) - set(cls._KEYS)
        if unknown:
            raise ValueError(f"unknown qos keys: {sorted(unknown)}")
        if not value:
            raise ValueError("'qos' must set at least one knob")
        default_class = value.get("default_class", "standard")
        if default_class not in QOS_CLASSES:
            raise ValueError(
                f"qos default_class must be one of {QOS_CLASSES}, "
                f"got {default_class!r}"
            )
        for key in ("depth_interactive", "depth_standard", "depth_batch"):
            raw = value.get(key)
            if raw is not None and (not isinstance(raw, int) or raw < 1):
                raise ValueError(f"qos {key} must be an int >= 1")
        for key in ("shed_wait_ms", "aging_s"):
            raw = value.get(key)
            if raw is not None and (
                not isinstance(raw, (int, float)) or raw < 0
            ):
                raise ValueError(f"qos {key} must be a number >= 0")
        preempt = value.get("preempt")
        if preempt is not None and not isinstance(preempt, bool):
            raise ValueError("qos preempt must be a bool")
        return cls(
            default_class=str(default_class),
            depth_interactive=value.get("depth_interactive"),
            depth_standard=value.get("depth_standard"),
            depth_batch=value.get("depth_batch"),
            shed_wait_ms=(
                float(value["shed_wait_ms"])
                if value.get("shed_wait_ms") is not None
                else None
            ),
            aging_s=(
                float(value["aging_s"])
                if value.get("aging_s") is not None
                else None
            ),
            preempt=preempt,
        )

    def as_env(self) -> dict[str, str]:
        """Set knobs as ``DORA_QOS_*`` suffix -> value strings (the
        daemon injects these before the node's own env, so descriptor
        ``env:`` entries can still override)."""
        out = {"DEFAULT_CLASS": self.default_class}
        if self.depth_interactive is not None:
            out["DEPTH_INTERACTIVE"] = str(self.depth_interactive)
        if self.depth_standard is not None:
            out["DEPTH_STANDARD"] = str(self.depth_standard)
        if self.depth_batch is not None:
            out["DEPTH_BATCH"] = str(self.depth_batch)
        if self.shed_wait_ms is not None:
            out["SHED_WAIT_MS"] = str(self.shed_wait_ms)
        if self.aging_s is not None:
            out["AGING_S"] = str(self.aging_s)
        if self.preempt is not None:
            out["PREEMPT"] = "1" if self.preempt else "0"
        return out


@dataclass(frozen=True)
class CustomNode:
    """A node that is its own executable (or a dynamic/externally-attached
    process)."""

    source: str
    args: str | None = None
    build: str | None = None
    send_stdout_as: str | None = None
    inputs: dict[DataId, Input] = field(default_factory=dict)
    outputs: frozenset[DataId] = frozenset()

    @property
    def is_dynamic(self) -> bool:
        return self.source == DYNAMIC_SOURCE


@dataclass(frozen=True)
class RuntimeNode:
    """A node hosting operators inside the operator runtime."""

    operators: tuple[OperatorDefinition, ...]


@dataclass(frozen=True)
class ResolvedNode:
    id: NodeId
    name: str | None
    description: str | None
    env: dict[str, Any]
    deploy: Deploy
    kind: CustomNode | RuntimeNode
    restart: RestartPolicy | None = None
    slo: SloPolicy | None = None
    qos: QosPolicy | None = None
    #: Explicit serving-engine declaration (``serving: true`` in YAML).
    #: ``slo:``/``qos:`` validation trusts this over the source-name
    #: heuristic, so custom serving nodes under any source name pass.
    #: None = undeclared (heuristic applies); False = declared non-serving.
    serving: bool | None = None

    @property
    def inputs(self) -> dict[DataId, Input]:
        """All inputs, namespaced ``<op>/<input>`` for runtime nodes."""
        if isinstance(self.kind, CustomNode):
            return dict(self.kind.inputs)
        out: dict[DataId, Input] = {}
        for op in self.kind.operators:
            for input_id, inp in op.inputs.items():
                out[DataId(f"{op.id}/{input_id}")] = inp
        return out

    @property
    def outputs(self) -> frozenset[DataId]:
        """All outputs, namespaced ``<op>/<output>`` for runtime nodes."""
        if isinstance(self.kind, CustomNode):
            return self.kind.outputs
        return frozenset(
            DataId(f"{op.id}/{o}") for op in self.kind.operators for o in op.outputs
        )

    def fused_internal_inputs(self) -> frozenset[DataId]:
        """Inputs satisfied *inside* the node by the fused jax subgraph
        (both endpoints are jax operators of this node). These edges are SSA
        values in one XLA computation — the daemon must not build routing
        queues for them, and the source output is never published
        (dora_tpu.tpu.fuse lowers them)."""
        if not isinstance(self.kind, RuntimeNode):
            return frozenset()
        jax_ops = {
            str(op.id)
            for op in self.kind.operators
            if isinstance(op.source, JaxSource)
        }
        internal = set()
        for op in self.kind.operators:
            if str(op.id) not in jax_ops:
                continue
            for input_id, inp in op.inputs.items():
                m = inp.mapping
                if isinstance(m, UserMapping) and str(m.source) == str(self.id):
                    src_op = str(m.output).partition("/")[0]
                    if src_op in jax_ops:
                        internal.add(DataId(f"{op.id}/{input_id}"))
        return frozenset(internal)

    @property
    def send_stdout_as(self) -> str | None:
        if isinstance(self.kind, CustomNode):
            return self.kind.send_stdout_as
        for op in self.kind.operators:
            if op.send_stdout_as:
                return f"{op.id}/{op.send_stdout_as}"
        return None


# ---------------------------------------------------------------------------
# Parsing helpers
# ---------------------------------------------------------------------------


def _parse_inputs(value: Any) -> dict[DataId, Input]:
    if value is None:
        return {}
    if not isinstance(value, Mapping):
        raise ValueError(f"'inputs' must be a mapping, got {type(value).__name__}")
    return {DataId(str(k)): Input.parse(v) for k, v in value.items()}


def _parse_outputs(value: Any) -> frozenset[DataId]:
    if value is None:
        return frozenset()
    if not isinstance(value, (list, tuple)):
        raise ValueError(f"'outputs' must be a list, got {type(value).__name__}")
    return frozenset(DataId(str(v)) for v in value)


_NODE_KIND_KEYS = ("path", "custom", "operators", "operator")


# ---------------------------------------------------------------------------
# Descriptor
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Descriptor:
    """A parsed dataflow YAML."""

    nodes: tuple[ResolvedNode, ...]
    communication: CommunicationConfig = field(default_factory=CommunicationConfig)
    alerts: "AlertsPolicy | None" = None
    raw: dict[str, Any] = field(default_factory=dict, compare=False)

    # -- constructors -------------------------------------------------------

    @classmethod
    def read(cls, path: str | Path) -> "Descriptor":
        path = Path(path)
        text = path.read_text()
        return cls.parse(yaml.safe_load(text))

    @classmethod
    def parse(cls, raw: Mapping[str, Any]) -> "Descriptor":
        if not isinstance(raw, Mapping):
            raise ValueError("dataflow descriptor must be a YAML mapping")
        known = {"nodes", "communication", "deploy", "_unstable_deploy", "env", "alerts"}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown top-level keys: {sorted(unknown)}")
        nodes_raw = raw.get("nodes")
        if not nodes_raw:
            raise ValueError("dataflow has no nodes")
        global_env = raw.get("env") or {}
        # Top-level deploy provides per-node defaults (e.g. default machine).
        default_deploy = Deploy.parse(raw.get("deploy") or raw.get("_unstable_deploy"))
        nodes = tuple(
            cls._parse_node(n, global_env, default_deploy) for n in nodes_raw
        )
        ids = [n.id for n in nodes]
        dupes = {i for i in ids if ids.count(i) > 1}
        if dupes:
            raise ValueError(f"duplicate node ids: {sorted(dupes)}")
        # Lazy import: alerts.py pulls in metrics/metrics_history, which
        # descriptor consumers (schema generation, node CLIs) don't need
        # unless the descriptor actually carries an alerts: block.
        alerts = None
        if raw.get("alerts") is not None:
            from dora_tpu.alerts import AlertsPolicy

            alerts = AlertsPolicy.parse(raw.get("alerts"))
        return cls(
            nodes=nodes,
            communication=CommunicationConfig.parse(raw.get("communication")),
            alerts=alerts,
            raw=dict(raw),
        )

    @classmethod
    def _parse_node(
        cls,
        value: Mapping[str, Any],
        global_env: Mapping[str, Any],
        default_deploy: "Deploy | None" = None,
    ) -> ResolvedNode:
        if "id" not in value:
            raise ValueError(f"node missing 'id': {value!r}")
        node_id = NodeId(str(value["id"]))
        kinds = [k for k in _NODE_KIND_KEYS if k in value]
        if len(kinds) != 1:
            raise ValueError(
                f"node {node_id!r} must have exactly one of {_NODE_KIND_KEYS}, got {kinds}"
            )
        env = {**global_env, **(value.get("env") or {})}
        env = {str(k): expand_env(v) for k, v in env.items()}
        kind_key = kinds[0]

        if kind_key == "path":
            kind: CustomNode | RuntimeNode = CustomNode(
                source=expand_env(str(value["path"])),
                args=value.get("args"),
                build=value.get("build"),
                send_stdout_as=value.get("send_stdout_as"),
                inputs=_parse_inputs(value.get("inputs")),
                outputs=_parse_outputs(value.get("outputs")),
            )
        elif kind_key == "custom":
            c = value["custom"]
            kind = CustomNode(
                source=expand_env(str(c["source"])),
                args=c.get("args"),
                build=c.get("build"),
                send_stdout_as=c.get("send_stdout_as"),
                inputs=_parse_inputs(c.get("inputs")),
                outputs=_parse_outputs(c.get("outputs")),
            )
            env = {**env, **{str(k): expand_env(v) for k, v in (c.get("envs") or {}).items()}}
        elif kind_key == "operators":
            ops = tuple(OperatorDefinition.parse(o) for o in value["operators"])
            if not ops:
                raise ValueError(f"node {node_id!r} has an empty 'operators' list")
            op_ids = [o.id for o in ops]
            if len(set(op_ids)) != len(op_ids):
                raise ValueError(f"node {node_id!r} has duplicate operator ids")
            kind = RuntimeNode(operators=ops)
        else:  # single "operator" shorthand -> runtime node with one operator
            op = OperatorDefinition.parse(value["operator"], default_id=DEFAULT_OPERATOR_ID)
            kind = RuntimeNode(operators=(op,))

        deploy = Deploy.parse(value.get("deploy") or value.get("_unstable_deploy"))
        if deploy.machine is None and default_deploy is not None:
            deploy = default_deploy
        serving = value.get("serving")
        if serving is not None and not isinstance(serving, bool):
            raise ValueError(
                f"node {node_id!r}: 'serving' must be a boolean, got "
                f"{serving!r}"
            )
        return ResolvedNode(
            id=node_id,
            name=value.get("name"),
            description=value.get("description"),
            env=env,
            deploy=deploy,
            kind=kind,
            restart=RestartPolicy.parse(value.get("restart")),
            slo=SloPolicy.parse(value.get("slo")),
            qos=QosPolicy.parse(value.get("qos")),
            serving=serving,
        )

    # -- queries ------------------------------------------------------------

    def node(self, node_id: NodeId | str) -> ResolvedNode:
        for n in self.nodes:
            if n.id == node_id:
                return n
        raise KeyError(f"no node {node_id!r} in dataflow")

    def output_ids(self) -> set[OutputId]:
        out: set[OutputId] = set()
        for n in self.nodes:
            for o in n.outputs:
                out.add(OutputId(n.id, o))
        return out

    def machines(self) -> set[str]:
        return {n.deploy.machine or "" for n in self.nodes}

    def check(self, working_dir: str | Path | None = None) -> None:
        from dora_tpu.core.validate import check_dataflow

        check_dataflow(self, working_dir)

    def visualize_as_mermaid(self) -> str:
        from dora_tpu.core.visualize import visualize_as_mermaid

        return visualize_as_mermaid(self)


def new_dataflow_uuid() -> str:
    """UUIDv7-style (time-ordered) dataflow id, as the reference uses."""
    # uuid.uuid7 landed in 3.14; compose one: 48-bit unix-ms + random.
    import os
    import time

    ms = time.time_ns() // 1_000_000
    rand = os.urandom(10)
    b = ms.to_bytes(6, "big") + rand
    b = bytearray(b)
    b[6] = (b[6] & 0x0F) | 0x70  # version 7
    b[8] = (b[8] & 0x3F) | 0x80  # variant
    return str(uuid.UUID(bytes=bytes(b)))
