"""JSON Schema for the dataflow descriptor (editor/IDE support).

Reference parity: libraries/core/src/bin/generate_schema.rs derives
``dora-schema.json`` from the Rust Descriptor types via schemars so YAML
editors validate and autocomplete dataflows. Here the schema is authored
against the same grammar the parser implements
(dora_tpu.core.descriptor / dora_tpu.core.config) — the test suite keeps
the two in lock-step by validating every example dataflow against it and
asserting parser/schema agreement on rejection cases.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

SCHEMA_ID = "https://dora-tpu.dev/dora-schema.json"

#: "<node>/<output>" (output may be namespaced further) or a dora timer.
_INPUT_MAPPING_PATTERN = r"^[^/\s]+(/[^/\s]+)+$"

_INPUT = {
    "description": (
        "Input slot: '<node>/<output>' / 'dora/timer/<unit>/<n>' string, "
        "or a mapping with an explicit bounded queue size."
    ),
    "oneOf": [
        {"type": "string", "pattern": _INPUT_MAPPING_PATTERN},
        {
            "type": "object",
            "properties": {
                "source": {
                    "type": "string",
                    "pattern": _INPUT_MAPPING_PATTERN,
                },
                "queue_size": {"type": "integer", "minimum": 1},
            },
            "required": ["source"],
            "additionalProperties": False,
        },
    ],
}

_INPUTS = {
    "type": "object",
    "additionalProperties": {"$ref": "#/definitions/input"},
}

_OUTPUTS = {
    "type": "array",
    "items": {"type": "string", "minLength": 1},
}

_ENV = {
    "type": "object",
    "additionalProperties": {"type": ["string", "number", "boolean"]},
}

_DEPLOY = {
    "type": "object",
    "properties": {"machine": {"type": "string"}},
    "additionalProperties": False,
}

_OPERATOR = {
    "description": (
        "One operator hosted by the runtime: exactly one source of "
        "python / shared-library / jax."
    ),
    "type": "object",
    "properties": {
        "id": {"type": "string", "minLength": 1},
        "name": {"type": "string"},
        "description": {"type": "string"},
        "build": {"type": "string"},
        "send_stdout_as": {"type": "string"},
        "inputs": {"$ref": "#/definitions/inputs"},
        "outputs": {"$ref": "#/definitions/outputs"},
        "python": {
            "oneOf": [
                {"type": "string"},
                {
                    "type": "object",
                    "properties": {
                        "source": {"type": "string"},
                        "conda_env": {"type": "string"},
                    },
                    "required": ["source"],
                    "additionalProperties": False,
                },
            ]
        },
        "shared-library": {"type": "string"},
        "jax": {
            "type": "string",
            "description": (
                "TPU-tier operator factory: 'module.path:factory' or "
                "'file.py:factory' returning a JaxOperator"
            ),
        },
    },
    "oneOf": [
        {"required": ["python"]},
        {"required": ["shared-library"]},
        {"required": ["jax"]},
    ],
    "additionalProperties": False,
}

_CUSTOM = {
    "type": "object",
    "properties": {
        "source": {"type": "string"},
        "args": {"type": "string"},
        "build": {"type": "string"},
        "send_stdout_as": {"type": "string"},
        "envs": {"$ref": "#/definitions/env"},
        "inputs": {"$ref": "#/definitions/inputs"},
        "outputs": {"$ref": "#/definitions/outputs"},
    },
    "required": ["source"],
    "additionalProperties": False,
}

_RESTART = {
    "description": (
        "Elastic-recovery policy: respawn this node on post-barrier "
        "failure. true = one attempt; an integer = that many attempts; "
        "a mapping tunes the exponential backoff."
    ),
    "oneOf": [
        {"type": "boolean"},
        {"type": "integer", "minimum": 0},
        {
            "type": "object",
            "properties": {
                "max_attempts": {"type": "integer", "minimum": 0},
                "backoff_base_s": {"type": "number", "minimum": 0},
                "backoff_max_s": {"type": "number", "minimum": 0},
            },
            "additionalProperties": False,
        },
    ],
}

_SLO = {
    "description": (
        "Service-level objectives for this node, evaluated against the "
        "metrics history ring every sampling interval; violations feed "
        "the 1m/10m burn-rate gauges and the trace timeline. At least "
        "one objective must be set."
    ),
    "type": "object",
    "properties": {
        "ttft_p99_ms": {"type": "number", "minimum": 0},
        "tokens_per_s_min": {"type": "number", "minimum": 0},
        "queue_depth_max": {"type": "integer", "minimum": 0},
    },
    "minProperties": 1,
    "additionalProperties": False,
}

_QOS = {
    "description": (
        "Traffic shaping for a serving node: request priority classes "
        "(interactive/standard/batch) with weighted aged admission, "
        "bounded per-class queue depths, a queue-wait shed deadline, "
        "and preemption of lower-class decodes by page eviction. At "
        "least one knob must be set."
    ),
    "type": "object",
    "properties": {
        "default_class": {
            "type": "string",
            "enum": ["interactive", "standard", "batch"],
        },
        "depth_interactive": {"type": "integer", "minimum": 1},
        "depth_standard": {"type": "integer", "minimum": 1},
        "depth_batch": {"type": "integer", "minimum": 1},
        "shed_wait_ms": {"type": "number", "minimum": 0},
        "aging_s": {"type": "number", "minimum": 0},
        "preempt": {"type": "boolean"},
    },
    "minProperties": 1,
    "additionalProperties": False,
}

_ALERT_RULE = {
    "description": (
        "One declarative alert rule evaluated against the metrics "
        "history ring: a windowed predicate over matching series, with "
        "for-duration and firing-side hysteresis."
    ),
    "type": "object",
    "properties": {
        "name": {"type": "string", "minLength": 1},
        "kind": {
            "type": "string",
            "enum": ["gauge", "rate", "ratio", "gauge_ratio",
                     "percentile", "burn"],
        },
        "selector": {
            "type": "string",
            "minLength": 1,
            "description": (
                "Flat series key (flatten_snapshot naming, e.g. "
                "'srv:*:shed', 'queue:*') with at most one '*'"
            ),
        },
        "op": {"type": "string", "enum": [">", ">=", "<", "<="]},
        "threshold": {"type": "number"},
        "for_s": {"type": "number", "minimum": 0},
        "clear_s": {"type": "number", "minimum": 0},
        "resolve_threshold": {"type": "number"},
        "severity": {
            "type": "string",
            "enum": ["info", "warning", "critical"],
        },
        "window_s": {"type": "number", "exclusiveMinimum": 0},
        "percentile": {"type": "number", "minimum": 0, "maximum": 100},
        "denominator": {"type": "string", "minLength": 1},
        "min_rate": {"type": "number", "minimum": 0},
        "labels": {
            "type": "object",
            "additionalProperties": {"type": "string"},
        },
    },
    "required": ["name", "kind", "selector", "op", "threshold"],
    "additionalProperties": False,
}

_ALERTS = {
    "description": (
        "Alerting plane: extra rules merged over the built-in default "
        "pack (same-name overrides), plus pack rules disabled by name."
    ),
    "type": "object",
    "properties": {
        "rules": {
            "type": "array",
            "items": {"$ref": "#/definitions/alert_rule"},
        },
        "disable": {
            "type": "array",
            "items": {"type": "string", "minLength": 1},
        },
    },
    "additionalProperties": False,
}

_NODE = {
    "type": "object",
    "properties": {
        "id": {"type": "string", "minLength": 1},
        "name": {"type": "string"},
        "description": {"type": "string"},
        "env": {"$ref": "#/definitions/env"},
        "deploy": {"$ref": "#/definitions/deploy"},
        "_unstable_deploy": {"$ref": "#/definitions/deploy"},
        "restart": {"$ref": "#/definitions/restart"},
        "slo": {"$ref": "#/definitions/slo"},
        "qos": {"$ref": "#/definitions/qos"},
        # node kinds (exactly one)
        "path": {
            "type": "string",
            "description": (
                "Executable / script path, 'shell', 'dynamic', a "
                "'module:pkg.mod' Python module, or a URL"
            ),
        },
        "custom": {"$ref": "#/definitions/custom"},
        "operators": {
            "type": "array",
            "items": {"$ref": "#/definitions/operator"},
            "minItems": 1,
        },
        "operator": {"$ref": "#/definitions/operator"},
        # custom-node keys allowed beside `path:`
        "args": {"type": "string"},
        "build": {"type": "string"},
        "send_stdout_as": {"type": "string"},
        "inputs": {"$ref": "#/definitions/inputs"},
        "outputs": {"$ref": "#/definitions/outputs"},
    },
    "required": ["id"],
    "oneOf": [
        {"required": ["path"]},
        {"required": ["custom"]},
        {"required": ["operators"]},
        {"required": ["operator"]},
    ],
    # Keep additionalProperties open like the reference's published schema
    # (generate_schema.rs flips it to true so IDEs keep validating `id`
    # even inside the oneOf variants).
    "additionalProperties": True,
}

_COMMUNICATION = {
    "type": "object",
    "properties": {
        "local": {
            "oneOf": [
                {"type": "string", "enum": ["tcp", "uds", "shmem"]},
                {
                    "type": "object",
                    "properties": {"kind": {"type": "string"}},
                    "additionalProperties": True,
                },
            ]
        },
        "_unstable_local": True,
        "remote": {
            "oneOf": [
                {"type": "string", "enum": ["tcp"]},
                {
                    "type": "object",
                    "properties": {"kind": {"type": "string"}},
                    "additionalProperties": True,
                },
            ]
        },
        "_unstable_remote": True,
    },
    "additionalProperties": False,
}


def descriptor_schema() -> dict[str, Any]:
    """The dataflow-YAML JSON Schema (draft-07)."""
    return {
        "$schema": "http://json-schema.org/draft-07/schema#",
        "$id": SCHEMA_ID,
        "title": "dora-tpu dataflow descriptor",
        "type": "object",
        "properties": {
            "nodes": {
                "type": "array",
                "items": {"$ref": "#/definitions/node"},
                "minItems": 1,
            },
            "communication": {"$ref": "#/definitions/communication"},
            "deploy": {"$ref": "#/definitions/deploy"},
            "_unstable_deploy": {"$ref": "#/definitions/deploy"},
            "env": {"$ref": "#/definitions/env"},
            "alerts": {"$ref": "#/definitions/alerts"},
        },
        "required": ["nodes"],
        "additionalProperties": False,
        "definitions": {
            "node": _NODE,
            "operator": _OPERATOR,
            "custom": _CUSTOM,
            "input": _INPUT,
            "inputs": _INPUTS,
            "outputs": _OUTPUTS,
            "env": _ENV,
            "deploy": _DEPLOY,
            "restart": _RESTART,
            "slo": _SLO,
            "qos": _QOS,
            "alerts": _ALERTS,
            "alert_rule": _ALERT_RULE,
            "communication": _COMMUNICATION,
        },
    }


def generate_schema(path: str | Path | None = None) -> Path:
    """Write ``dora-schema.json`` (reference: generate_schema.rs writes it
    next to the core crate's Cargo.toml)."""
    out = Path(path) if path else Path("dora-schema.json")
    out.write_text(json.dumps(descriptor_schema(), indent=2) + "\n")
    return out


def main() -> int:
    import sys

    out = generate_schema(sys.argv[1] if len(sys.argv) > 1 else None)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
