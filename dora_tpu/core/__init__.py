"""Core graph model: typed config, YAML descriptor, validation, topics."""

from dora_tpu.core.config import (  # noqa: F401
    CommunicationConfig,
    Input,
    InputMapping,
    LocalCommunicationConfig,
    TimerMapping,
    UserMapping,
)
from dora_tpu.core.descriptor import Descriptor, ResolvedNode  # noqa: F401
