"""Mermaid flowchart rendering of a dataflow.

Reference parity: libraries/core/src/descriptor/visualize.rs:9-60.
"""

from __future__ import annotations

from dora_tpu.core.config import TimerMapping, UserMapping
from dora_tpu.core.descriptor import CustomNode, Descriptor, JaxSource, RuntimeNode


def visualize_as_mermaid(descriptor: Descriptor) -> str:
    lines = ["flowchart TB"]

    timers: set[TimerMapping] = set()

    for node in descriptor.nodes:
        if isinstance(node.kind, RuntimeNode):
            tpu = any(isinstance(op.source, JaxSource) for op in node.kind.operators)
            label = "tpu-runtime" if tpu else "runtime"
            lines.append(f"subgraph {node.id} [\"{node.id} ({label})\"]")
            for op in node.kind.operators:
                lines.append(f"  {node.id}/{op.id}[\"{op.name or op.id}\"]")
            lines.append("end")
        else:
            assert isinstance(node.kind, CustomNode)
            suffix = " (dynamic)" if node.kind.is_dynamic else ""
            lines.append(f"  {node.id}[\"{node.name or node.id}{suffix}\"]")

    for node in descriptor.nodes:
        for input_id, inp in node.inputs.items():
            m = inp.mapping
            target = _input_target(node, input_id)
            if isinstance(m, TimerMapping):
                timers.add(m)
                lines.append(f"  {_timer_node_id(m)} -- {input_id} --> {target}")
            else:
                assert isinstance(m, UserMapping)
                src = descriptor.node(m.source)
                source_ref = _output_source(src, str(m.output))
                lines.append(f"  {source_ref} -- {m.output} as {input_id} --> {target}")

    for t in sorted(timers, key=lambda t: t.interval_ns):
        lines.insert(1, f"  {_timer_node_id(t)}[\\{t}/]")

    return "\n".join(lines) + "\n"


def _timer_node_id(t: TimerMapping) -> str:
    return f"dora_timer_{t.interval_ns}"


def _input_target(node, input_id: str) -> str:
    if isinstance(node.kind, RuntimeNode) and "/" in input_id:
        op, _, _rest = input_id.partition("/")
        return f"{node.id}/{op}"
    return str(node.id)


def _output_source(node, output_id: str) -> str:
    if isinstance(node.kind, RuntimeNode) and "/" in output_id:
        op, _, _rest = output_id.partition("/")
        return f"{node.id}/{op}"
    return str(node.id)
