"""Dataflow validation.

Reference parity: libraries/core/src/descriptor/validate.rs:15-190 — source
paths exist, every input maps to a declared output of an existing node, no
self-cycles through timers needed, python version match (N/A here: single
interpreter).
"""

from __future__ import annotations

import shutil
from pathlib import Path

from dora_tpu.core.config import TimerMapping, UserMapping
from dora_tpu.core.descriptor import (
    DYNAMIC_SOURCE,
    SHELL_SOURCE,
    CustomNode,
    Descriptor,
    JaxSource,
    PythonSource,
    RuntimeNode,
    SharedLibrarySource,
)


class ValidationError(ValueError):
    pass


def check_dataflow(descriptor: Descriptor, working_dir: str | Path | None = None) -> None:
    """Raise ValidationError on the first problem found."""
    working_dir = Path(working_dir) if working_dir else None

    declared_outputs = descriptor.output_ids()
    node_ids = {n.id for n in descriptor.nodes}

    for node in descriptor.nodes:
        # 1. sources resolvable
        if isinstance(node.kind, CustomNode):
            _check_custom_source(node.id, node.kind, working_dir)
        else:
            assert isinstance(node.kind, RuntimeNode)
            for op in node.kind.operators:
                _check_operator_source(node.id, op.id, op.source, working_dir)

        # 2. every input refers to an existing node + declared output
        for input_id, inp in node.inputs.items():
            m = inp.mapping
            if isinstance(m, TimerMapping):
                continue
            assert isinstance(m, UserMapping)
            if m.source not in node_ids:
                raise ValidationError(
                    f"input {node.id}/{input_id}: source node {m.source!r} does not exist"
                )
            if m.output_id not in declared_outputs:
                raise ValidationError(
                    f"input {node.id}/{input_id}: node {m.source!r} has no "
                    f"output {m.output!r}"
                )


def _check_custom_source(node_id, kind: CustomNode, working_dir: Path | None) -> None:
    source = kind.source
    if source in (DYNAMIC_SOURCE, SHELL_SOURCE):
        return
    if "://" in source:  # URL source, downloaded at spawn time
        return
    if source.startswith("module:"):  # installed Python module (node hub)
        return
    path = Path(source)
    if working_dir and not path.is_absolute():
        path = working_dir / path
    if path.exists():
        return
    # Not a file — accept anything on PATH (e.g. "python", an installed
    # node-hub entry point).
    if shutil.which(source):
        return
    raise ValidationError(f"node {node_id!r}: source {source!r} not found")


def adjust_shared_library_path(path: Path) -> Path:
    """'op' -> 'libop.so' / 'op.so' when the bare name does not exist
    (reference: adjust_shared_library_path, libraries/core/src/lib.rs:14-31)."""
    if path.exists():
        return path
    for candidate in (path.with_name(f"lib{path.name}.so"),
                      path.with_name(f"{path.name}.so")):
        if candidate.exists():
            return candidate
    return path


def _check_operator_source(node_id, op_id, source, working_dir: Path | None) -> None:
    if isinstance(source, (PythonSource, SharedLibrarySource)):
        src = source.source
        if "://" in src:
            return
        path = Path(src)
        if working_dir and not path.is_absolute():
            path = working_dir / path
        if isinstance(source, SharedLibrarySource):
            path = adjust_shared_library_path(path)
        if not path.exists():
            raise ValidationError(
                f"operator {node_id}/{op_id}: source {src!r} not found"
            )
        if isinstance(source, PythonSource) and path.suffix != ".py":
            raise ValidationError(
                f"operator {node_id}/{op_id}: python source must be a .py file"
            )
    elif isinstance(source, JaxSource):
        mod, _fn = source.split()
        if mod.endswith(".py"):
            path = Path(mod)
            if working_dir and not path.is_absolute():
                path = working_dir / path
            if not path.exists():
                raise ValidationError(
                    f"operator {node_id}/{op_id}: jax source file {mod!r} not found"
                )
        # module-path sources are resolved at spawn time (import may require
        # the node's env); nothing to check statically.
