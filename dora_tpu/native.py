"""ctypes bindings to the native C++ shared-memory layer (native/shmem.cpp).

The library is built on demand with g++ (cached next to this file as
``_native.so``); nodes in other languages link the same C ABI directly.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_SRC = _HERE.parent / "native" / "shmem.cpp"
_LIB = _HERE / "_native.so"

# Serializes the one-time g++ build/load; compile time under the
# lock is expected.
_lock = tracked_lock("native.build", allow_blocking=True)
_lib: ctypes.CDLL | None = None


def _src_digest() -> str:
    import hashlib

    return hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]


def build_native(force: bool = False) -> Path:
    """Compile native/shmem.cpp to dora_tpu/_native.so if needed.

    Staleness is keyed on a source-content hash (mtime lies after git
    checkouts), and the build publishes atomically (temp file +
    os.replace) so concurrent first-use imports in spawned node processes
    never dlopen a half-written library.
    """
    stamp = _HERE / "_native.build-id"
    digest = _src_digest()
    if _LIB.exists() and not force:
        if stamp.exists() and stamp.read_text().strip() == digest:
            return _LIB
    tmp = _HERE / f"_native.{os.getpid()}.tmp.so"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", str(tmp), str(_SRC), "-lrt", "-pthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)
        stamp.write_text(digest)
    finally:
        tmp.unlink(missing_ok=True)
    return _LIB


def build_node_api(force: bool = False) -> Path:
    """Compile the C/C++ node API (native/node_api.cpp + shmem.cpp) into
    dora_tpu/libdora_node_api.so for C/C++ nodes to link against."""
    import hashlib

    native_dir = _HERE.parent / "native"
    sources = [native_dir / "node_api.cpp", native_dir / "shmem.cpp"]
    headers = [native_dir / "dora_node_api.h", native_dir / "dtp_shmem.h",
               native_dir / "msgpack.hpp"]
    lib = _HERE / "libdora_node_api.so"
    stamp = _HERE / "libdora_node_api.build-id"
    digest = hashlib.sha256(
        b"".join(p.read_bytes() for p in sources + headers)
    ).hexdigest()[:16]
    if lib.exists() and not force and stamp.exists() \
            and stamp.read_text().strip() == digest:
        return lib
    tmp = _HERE / f"libdora_node_api.{os.getpid()}.tmp.so"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-I", str(native_dir), "-o", str(tmp),
        *[str(s) for s in sources], "-lrt", "-pthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, lib)
        stamp.write_text(digest)
    finally:
        tmp.unlink(missing_ok=True)
    return lib


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        build_native()
        lib = ctypes.CDLL(str(_LIB))
        # regions
        lib.dtp_region_create.restype = ctypes.c_void_p
        lib.dtp_region_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.dtp_region_open.restype = ctypes.c_void_p
        lib.dtp_region_open.argtypes = [ctypes.c_char_p]
        lib.dtp_region_ptr.restype = ctypes.c_void_p
        lib.dtp_region_ptr.argtypes = [ctypes.c_void_p]
        lib.dtp_region_size.restype = ctypes.c_uint64
        lib.dtp_region_size.argtypes = [ctypes.c_void_p]
        lib.dtp_region_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dtp_region_unlink.argtypes = [ctypes.c_char_p]
        # channels
        lib.dtp_channel_create.restype = ctypes.c_void_p
        lib.dtp_channel_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.dtp_channel_open.restype = ctypes.c_void_p
        lib.dtp_channel_open.argtypes = [ctypes.c_char_p]
        lib.dtp_channel_capacity.restype = ctypes.c_uint32
        lib.dtp_channel_capacity.argtypes = [ctypes.c_void_p]
        lib.dtp_channel_send.restype = ctypes.c_int
        lib.dtp_channel_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.dtp_channel_try_send.restype = ctypes.c_int
        lib.dtp_channel_try_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.dtp_channel_recv.restype = ctypes.c_int64
        lib.dtp_channel_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_int,
        ]
        lib.dtp_channel_disconnect.argtypes = [ctypes.c_void_p]
        lib.dtp_channel_is_disconnected.restype = ctypes.c_int
        lib.dtp_channel_is_disconnected.argtypes = [ctypes.c_void_p]
        lib.dtp_channel_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return lib


class ShmemError(RuntimeError):
    pass


class Disconnected(ShmemError):
    pass


class ShmemRegion:
    """A named shared-memory region, zero-copy readable/writable.

    The region object itself implements the buffer protocol (PEP 688) with
    export counting: take zero-copy views as ``np.frombuffer(region, ...)``
    or ``memoryview(region)`` — ``close()`` then refuses to unmap while such
    views are alive (unmapping under a live view is a segfault, not an
    exception). The ``.buf`` property is for transient access only
    (``region.buf[0:4] = b"head"``); views derived from a ``.buf`` you hold
    are not individually tracked.
    """

    def __init__(self, handle: int, name: str, owner: bool):
        self._h = handle
        self.name = name
        self.owner = owner
        lib = _load()
        self.size = lib.dtp_region_size(handle)
        ptr = lib.dtp_region_ptr(handle)
        self._carray = (ctypes.c_ubyte * self.size).from_address(ptr)
        self._exports = 0

    def __buffer__(self, flags) -> memoryview:
        if not self._h:
            raise ShmemError(f"shmem region {self.name!r} is closed")
        self._exports += 1
        return memoryview(self._carray).cast("B")

    def __release_buffer__(self, view: memoryview) -> None:
        self._exports -= 1
        view.release()

    @property
    def buf(self) -> memoryview:
        """A fresh transient view; do not store slices of it past close()."""
        return memoryview(self)

    def __len__(self) -> int:
        return self.size

    @classmethod
    def create(cls, name: str, size: int) -> "ShmemRegion":
        h = _load().dtp_region_create(name.encode(), size)
        if not h:
            raise ShmemError(f"failed to create shmem region {name!r} ({size} B)")
        return cls(h, name, owner=True)

    @classmethod
    def open(cls, name: str) -> "ShmemRegion":
        h = _load().dtp_region_open(name.encode())
        if not h:
            raise ShmemError(f"failed to open shmem region {name!r}")
        return cls(h, name, owner=False)

    def close(self, unlink: bool | None = None, force: bool = False) -> None:
        """Unmap (and unlink, if owner). Refuses to unmap while zero-copy
        views (numpy arrays, sub-memoryviews) created from ``.buf`` are
        still alive — unmapping under them would turn later reads into a
        segfault. ``force=True`` unmaps anyway (caller guarantees no view
        is touched again)."""
        if not self._h:
            return
        if self._exports > 0 and not force:
            import gc

            gc.collect()  # views may be unreachable but not yet collected
            if self._exports > 0:
                raise BufferError(
                    f"shmem region {self.name!r} still has {self._exports} live "
                    f"zero-copy view(s); drop them before close() (or pass "
                    f"force=True)"
                )
        self._carray = None
        _load().dtp_region_close(
            self._h, 1 if (self.owner if unlink is None else unlink) else 0
        )
        self._h = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ShmemChannel:
    """Synchronous request-reply channel inside one shmem segment.

    One side is the *server* (daemon), one the *client* (node); messages
    alternate request/reply, sharing the payload area.
    """

    def __init__(self, handle: int, name: str, is_server: bool):
        self._h = handle
        self.name = name
        self.is_server = is_server
        self._lib = _load()
        self.capacity = self._lib.dtp_channel_capacity(handle)
        self._recv_buf = ctypes.create_string_buffer(self.capacity)

    @classmethod
    def create(cls, name: str, capacity: int = 1 << 20) -> "ShmemChannel":
        h = _load().dtp_channel_create(name.encode(), capacity)
        if not h:
            raise ShmemError(f"failed to create shmem channel {name!r}")
        return cls(h, name, is_server=True)

    @classmethod
    def open(cls, name: str) -> "ShmemChannel":
        h = _load().dtp_channel_open(name.encode())
        if not h:
            raise ShmemError(f"failed to open shmem channel {name!r}")
        return cls(h, name, is_server=False)

    def send(self, data: bytes) -> None:
        if not self._h:
            raise ShmemError(f"channel {self.name} is closed")
        rc = self._lib.dtp_channel_send(
            self._h, data, len(data), 1 if self.is_server else 0
        )
        self._check_send_rc(rc, len(data))

    def try_send(self, data: bytes) -> bool:
        """Non-blocking send; False when the previous message in this
        direction is still unconsumed (caller should fall back to a
        blocking send off the hot thread)."""
        if not self._h:
            raise ShmemError(f"channel {self.name} is closed")
        rc = self._lib.dtp_channel_try_send(
            self._h, data, len(data), 1 if self.is_server else 0
        )
        if rc == -1:
            return False
        self._check_send_rc(rc, len(data))
        return True

    def _check_send_rc(self, rc: int, size: int) -> None:
        if rc == -2:
            raise Disconnected(f"channel {self.name} disconnected")
        if rc == -3:
            raise ShmemError(
                f"message of {size} B exceeds channel capacity {self.capacity}"
            )
        if rc != 0:
            raise ShmemError(f"send failed with {rc}")

    def recv(self, timeout: float | None = None) -> bytes | None:
        """Receive one message; None on timeout; raises Disconnected."""
        if not self._h:
            raise ShmemError(f"channel {self.name} is closed")
        timeout_ms = -1 if timeout is None else max(0, int(timeout * 1000))
        n = self._lib.dtp_channel_recv(
            self._h,
            self._recv_buf,
            self.capacity,
            timeout_ms,
            1 if self.is_server else 0,
        )
        if n >= 0:
            # string_at copies exactly n bytes (``.raw[:n]`` would copy the
            # whole channel capacity first).
            return ctypes.string_at(self._recv_buf, n)
        if n == -1:
            return None
        if n == -2:
            raise Disconnected(f"channel {self.name} disconnected")
        raise ShmemError(f"recv failed with {n}")

    @property
    def disconnected(self) -> bool:
        if not self._h:
            return True
        return bool(self._lib.dtp_channel_is_disconnected(self._h))

    def disconnect(self) -> None:
        if self._h:
            self._lib.dtp_channel_disconnect(self._h)

    def close(self, unlink: bool | None = None) -> None:
        if self._h:
            self._lib.dtp_channel_close(
                self._h, 1 if (self.is_server if unlink is None else unlink) else 0
            )
            self._h = 0
            # Release the capacity-sized recv scratch now: closed channel
            # objects can be retained by daemon bookkeeping (a finished
            # dataflow's conns stay listed for the teardown unlink pass),
            # and holding 1 MB per finished connection accumulates.
            self._recv_buf = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def unlink_region(name: str) -> None:
    _load().dtp_region_unlink(name.encode())
