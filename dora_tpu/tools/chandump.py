"""Dump the header of a native shmem channel (forensics for wedged runs).

Reads the ChannelHeader atomics (native/shmem.cpp) straight out of
/dev/shm without touching the protocol — safe on a live or wedged
channel. Usage::

    python -m dora_tpu.tools.chandump            # every dtp-* channel
    python -m dora_tpu.tools.chandump NAME...    # specific regions
"""

from __future__ import annotations

import struct
import sys
from pathlib import Path

MAGIC = 0xD02A79C2

# offsetof() per g++ on this platform (see native/shmem.cpp ChannelHeader)
_FIELDS = [
    ("magic", 0, "I"),
    ("capacity", 4, "I"),
    ("server_event", 8, "I"),
    ("client_event", 12, "I"),
    ("c2s_free", 16, "I"),
    ("s2c_free", 20, "I"),
    ("c2s_pending", 24, "I"),
    ("s2c_pending", 28, "I"),
    ("disconnected", 32, "I"),
    ("len", 40, "Q"),
]


def dump_channel(path: Path) -> dict:
    raw = path.read_bytes()[:48]
    out = {}
    for name, off, fmt in _FIELDS:
        (out[name],) = struct.unpack_from("<" + fmt, raw, off)
    out["is_channel"] = out["magic"] == MAGIC
    return out


def format_channel(name: str, h: dict) -> str:
    if not h["is_channel"]:
        return f"{name}: not a channel (raw region)"
    return (
        f"{name}: cap={h['capacity']} len={h['len']} "
        f"srv_ev={h['server_event']} cli_ev={h['client_event']} "
        f"c2s_pend={h['c2s_pending']} s2c_pend={h['s2c_pending']} "
        f"c2s_free={h['c2s_free']} s2c_free={h['s2c_free']} "
        f"disc={h['disconnected']}"
    )


def main(argv: list[str]) -> int:
    shm = Path("/dev/shm")
    paths = (
        [shm / a for a in argv]
        if argv
        else sorted(p for p in shm.glob("dtp-*") if p.is_file())
    )
    for p in paths:
        try:
            print(format_channel(p.name, dump_channel(p)))
        except OSError as e:
            print(f"{p.name}: unreadable ({e})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
