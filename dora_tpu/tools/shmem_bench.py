"""Shmem channel self-benchmark: cross-process request-reply round-trips.

Reference parity: libraries/shared-memory-server/src/bin/bench.rs — Ping/Pong
round-trip timing. Run: python -m dora_tpu.tools.shmem_bench [payload_bytes]
"""

from __future__ import annotations

import statistics
import subprocess
import sys
import time
import uuid

from dora_tpu.native import ShmemChannel

CHILD = """
import sys
sys.path.insert(0, {repo!r})
from dora_tpu.native import ShmemChannel
c = ShmemChannel.open({name!r})
try:
    while True:
        msg = c.recv(timeout=10)
        if msg is None:
            break
        c.send(msg)
except Exception:
    pass
"""


def run(payload: int = 64, iters: int = 5000) -> dict:
    import os

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    name = f"/dtp_bench_{uuid.uuid4().hex[:8]}"
    server = ShmemChannel.create(name, capacity=max(1 << 16, payload + 64))
    proc = subprocess.Popen(
        [sys.executable, "-c", CHILD.format(repo=repo, name=name)]
    )
    msg = b"x" * payload
    try:
        # warmup
        for _ in range(100):
            server.send(msg)
            server.recv(timeout=10)
        lat = []
        for _ in range(iters):
            t0 = time.perf_counter_ns()
            server.send(msg)
            server.recv(timeout=10)
            lat.append(time.perf_counter_ns() - t0)
    finally:
        server.disconnect()
        proc.wait(timeout=5)
        server.close()
    lat.sort()
    return {
        "payload_bytes": payload,
        "iters": iters,
        "rtt_p50_us": lat[len(lat) // 2] / 1000,
        "rtt_p99_us": lat[int(len(lat) * 0.99)] / 1000,
        "rtt_mean_us": statistics.fmean(lat) / 1000,
    }


if __name__ == "__main__":
    payload = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    stats = run(payload)
    print(
        f"shmem request-reply RTT ({stats['payload_bytes']} B x {stats['iters']}): "
        f"p50={stats['rtt_p50_us']:.1f}us p99={stats['rtt_p99_us']:.1f}us "
        f"mean={stats['rtt_mean_us']:.1f}us"
    )
