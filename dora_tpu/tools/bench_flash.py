"""Flash-attention long-context bench on the live backend.

Run on the TPU: python -m dora_tpu.tools.bench_flash
Validates the VMEM-flat claim (T=8192/16384 compile and run with the
same footprint as T=2k) and reports achieved attention TFLOP/s. Timing
chains data-dependent iterations and fetches a scalar (the axon tunnel
only synchronizes on host fetch — see bench_vlm.py).
"""

from __future__ import annotations

import statistics
import time

import jax
import jax.numpy as jnp

from dora_tpu.models import layers as L
from dora_tpu.ops import flash_attention


def _time_scalar(fn, rounds: int = 5) -> float:
    float(fn())
    samples = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        float(fn())
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def bench(t: int, h: int = 8, d: int = 128, causal: bool = True,
          iters: int = 8, check_parity: bool = False) -> None:
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (1, h, t, d), jnp.bfloat16)
    k = jax.random.normal(kk, (1, h, t, d), jnp.bfloat16)
    v = jax.random.normal(kv, (1, h, t, d), jnp.bfloat16)

    @jax.jit
    def chain(q, k, v):
        def body(_, acc):
            out = flash_attention(q + acc.astype(q.dtype) * 1e-9, k, v,
                                  causal=causal)
            return jnp.max(out).astype(jnp.float32) * 1e-9
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

    rtt = _time_scalar(jax.jit(lambda: jnp.float32(0)))
    sec = max(_time_scalar(lambda: chain(q, k, v)) - rtt, 1e-9) / iters
    # scores + values matmuls; causal halves the live area
    flops = 4.0 * h * t * t * d * (0.5 if causal else 1.0)
    print(
        f"T={t:6d} causal={causal}  {sec*1e3:8.2f} ms  "
        f"{flops/sec/1e12:6.1f} TFLOP/s",
        flush=True,
    )
    if check_parity:
        ours = flash_attention(q, k, v, causal=causal)
        mask = L.causal_mask(t, t) if causal else None
        ref = L.attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), mask,
        )
        import numpy as np

        err = np.abs(
            np.asarray(ours, np.float32) - np.asarray(ref)
        ).max()
        print(f"         parity vs dense (f32 ref): max abs err {err:.3e}")


if __name__ == "__main__":
    print(f"backend={jax.default_backend()}")
    bench(2048, check_parity=True)
    bench(8192)
    bench(16384)
