"""Deliberate-starvation stress harness for the shmem transport.

Reproduces the round-2 flake (`test_c_node_large_payload_shmem` timeout
under machine load): runs the python->C->python large-payload dataflow
repeatedly while CPU burners saturate the scheduler. On a hang it
captures forensics before killing anything: channel-header dumps
(chandump), SIGUSR1 python stack dumps, daemon-side logs.

Usage::

    python -m dora_tpu.tools.stress_shmem [--iters 20] [--burners 6]
        [--timeout 60]

Exit status 0 = all iterations completed; 1 = a hang was caught (the
forensics are printed).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent

RUNNER = """
import asyncio, faulthandler, gc, signal, sys, traceback
faulthandler.register(signal.SIGUSR1, chain=True)
from dora_tpu.daemon.core import Daemon, run_dataflow_async


def await_chain(task):
    out = []
    coro = task.get_coro()
    while coro is not None:
        frame = getattr(coro, "cr_frame", None) or getattr(coro, "gi_frame", None)
        if frame is not None:
            out.append(f"{frame.f_code.co_filename}:{frame.f_lineno} "
                       f"{frame.f_code.co_name}")
        nxt = getattr(coro, "cr_await", None) or getattr(coro, "gi_yieldfrom", None)
        if nxt is coro or nxt is None:
            if nxt is not None:
                out.append(f"awaiting {nxt!r}")
            break
        coro = nxt
    return out


def dump_state() -> None:
    import os, signal
    print("=== in-process hang dump ===", file=sys.stderr)
    for task in asyncio.all_tasks():
        print(f"task {task.get_name()}: {task}", file=sys.stderr)
        for line in await_chain(task):
            print(f"    {line}", file=sys.stderr)
    for obj in gc.get_objects():
        if isinstance(obj, Daemon):
            for df in obj.dataflows.values():
                for nid, running in df.running_nodes.items():
                    if running.process is not None and not running.finished:
                        try:
                            os.kill(running.process.pid, signal.SIGUSR1)
                            print(f"  SIGUSR1 -> {nid} pid={running.process.pid}",
                                  file=sys.stderr)
                        except ProcessLookupError:
                            pass
                print(f"dataflow {df.id}:", file=sys.stderr)
                for nid, q in df.queues.items():
                    print(
                        f"  queue {nid}: entries={len(q.entries)} "
                        f"closed={q.closed} waiter={q.waiter}",
                        file=sys.stderr,
                    )
                for nid, dq in df.drop_queues.items():
                    print(
                        f"  dropq {nid}: tokens={len(dq.tokens)} "
                        f"closed={dq.closed} waiter={dq.waiter}",
                        file=sys.stderr,
                    )
                print(f"  open_outputs={sorted(map(str, df.open_outputs))}",
                      file=sys.stderr)
                print(f"  open_inputs={df.open_inputs}", file=sys.stderr)
                print(f"  tokens={df.tokens}", file=sys.stderr)
                print(f"  running="
                      f"{ {n: r.finished for n, r in df.running_nodes.items()} }",
                      file=sys.stderr)
                for conn in df.shmem_conns:
                    print(
                        f"  conn {conn.channel.name}: closing={conn._closing} "
                        f"incoming={conn._incoming.qsize()}",
                        file=sys.stderr,
                    )
    faulthandler.dump_traceback(file=sys.stderr)
    sys.stderr.flush()


async def main() -> int:
    work = asyncio.ensure_future(
        run_dataflow_async(sys.argv[1], local_comm="shmem")
    )
    try:
        result = await asyncio.wait_for(asyncio.shield(work), float(sys.argv[2]))
    except asyncio.TimeoutError:
        dump_state()
        # Give the wedged nodes' SIGUSR1 stack dumps time to drain through
        # the daemon's stderr pumps into the log files before teardown.
        await asyncio.sleep(3)
        return 3
    if not result.is_ok():
        print("FAILED:", result.errors(), flush=True)
        return 2
    print("ITERATION-OK", flush=True)
    return 0


sys.exit(asyncio.run(main()))
"""

CHECKER = """
from dora_tpu.node import Node

node = Node()
seen = 0
for event in node:
    if event["type"] != "INPUT":
        continue
    data = bytes(event["value"])
    assert len(data) == 100_000, len(data)
    assert data == bytes(range(256)) * 390 + bytes(160), "corrupt"
    seen += 1
node.close()
assert seen == 3, seen
print("large payloads ok")
"""

SENDER = """
from dora_tpu.node import Node

payload = bytes(range(256)) * 390 + bytes(160)
with Node() as node:
    for _ in range(3):
        node.send_output("data", payload)
"""


def compile_relay(tmp: Path) -> Path:
    from tests.test_c_node_api import C_RELAY  # reuse the exact test node

    src = tmp / "relay.c"
    src.write_text(textwrap.dedent(C_RELAY))
    out = tmp / "relay"
    native = REPO / "native"
    subprocess.run(
        ["g++", "-O1", "-std=c++17", "-I", str(native), str(src),
         str(native / "node_api.cpp"), str(native / "shmem.cpp"),
         "-o", str(out), "-lrt", "-pthread"],
        check=True,
    )
    return out


def collect_forensics(
    child: subprocess.Popen, stderr_path: Path, burners: list
) -> None:
    print("=" * 70)
    print("HANG DETECTED — forensics before teardown")
    print("=" * 70, flush=True)
    subprocess.run([sys.executable, "-m", "dora_tpu.tools.chandump"])
    # Un-starve the machine first: if the hang self-heals without load it
    # is a livelock, not a deadlock — report which.
    for b in burners:
        b.kill()
    try:
        child.wait(timeout=10)
        print("SELF-HEALED after removing load: livelock, not deadlock")
        return
    except subprocess.TimeoutExpired:
        print("still hung 10s after load removed: genuine deadlock")
    # SIGUSR1 the whole process group: every python process dumps thread
    # stacks to its stderr (nodes: daemon-side log files; runner: its
    # stderr file). SIGUSR2 to the runner: asyncio task dump.
    try:
        os.killpg(child.pid, signal.SIGUSR1)
        os.kill(child.pid, signal.SIGUSR2)
    except ProcessLookupError:
        pass
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            if "task dump" in stderr_path.read_text():
                break
        except OSError:
            pass
        time.sleep(1)
    time.sleep(2)  # let node-side dumps drain into daemon log files
    print("--- runner stderr (thread + task dumps) ---")
    try:
        print(stderr_path.read_text())
    except OSError as e:
        print(f"unreadable: {e}")
    print("--- channel state after dumps ---")
    subprocess.run([sys.executable, "-m", "dora_tpu.tools.chandump"])
    try:
        ps = subprocess.run(
            ["ps", "-eo", "pid,ppid,stat,etime,args"], capture_output=True,
            text=True)
        lines = [l for l in ps.stdout.splitlines()
                 if "checker" in l or "relay" in l or "runner" in l]
        print("\n".join(lines))
    except Exception:
        pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--burners", type=int, default=6)
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--keep-logs", action="store_true")
    args = ap.parse_args()

    tmp = Path(tempfile.mkdtemp(prefix="dtp-stress-"))
    relay = compile_relay(tmp)
    (tmp / "checker.py").write_text(textwrap.dedent(CHECKER))
    (tmp / "big_sender.py").write_text(textwrap.dedent(SENDER))
    import yaml

    df = tmp / "dataflow.yml"
    df.write_text(yaml.safe_dump({
        "nodes": [
            {"id": "sender", "path": "big_sender.py", "outputs": ["data"]},
            {"id": "relay", "path": str(relay),
             "inputs": {"in": "sender/data"}, "outputs": ["echo"]},
            {"id": "checker", "path": "checker.py",
             "inputs": {"in": "relay/echo"}},
        ],
        "communication": {"local": "shmem"},
    }))
    runner = tmp / "runner.py"
    runner.write_text(textwrap.dedent(RUNNER))

    burners = [
        subprocess.Popen([sys.executable, "-c", "while True: pass"])
        for _ in range(args.burners)
    ]
    print(f"{args.burners} burners up; {args.iters} iterations, "
          f"{args.timeout}s timeout each", flush=True)
    failed = 0
    try:
        for i in range(args.iters):
            t0 = time.monotonic()
            env = dict(os.environ)
            env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
            stderr_path = tmp / f"runner-{i}.stderr"
            with open(stderr_path, "wb") as stderr_file:
                child = subprocess.Popen(
                    [sys.executable, str(runner), str(df), str(args.timeout)],
                    cwd=tmp, start_new_session=True, env=env,
                    stderr=stderr_file,
                )
                try:
                    rc = child.wait(timeout=args.timeout + 60)
                except subprocess.TimeoutExpired:
                    collect_forensics(child, stderr_path, burners)
                    failed = 1
                    os.killpg(child.pid, signal.SIGKILL)
                    child.wait()
                    print(f"iter {i}: HANG (forensics above; logs under {tmp})")
                    break
            dt = time.monotonic() - t0
            print(f"iter {i}: rc={rc} {dt:.1f}s", flush=True)
            if rc == 3:
                failed = 1
                print(f"iter {i}: HANG (in-process dump in {stderr_path})")
                print(stderr_path.read_text())
                subprocess.run([sys.executable, "-m", "dora_tpu.tools.chandump"])
                try:
                    os.killpg(child.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                break
            if rc != 0:
                failed = 1
                break
    finally:
        for b in burners:
            b.kill()
        leftovers = sorted(Path("/dev/shm").glob("dtp-*"))
        if leftovers and failed:
            print(f"leaked shm: {[p.name for p in leftovers]}")
    return failed


if __name__ == "__main__":
    sys.exit(main())
