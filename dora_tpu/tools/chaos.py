"""Fault-injection harness: kill a chosen dataflow node mid-run.

Every node process spawned by the daemon carries ``DORA_CHAOS_ID`` in
its environment, set to ``<dataflow-id>:<node-id>`` (daemon/spawn.py).
This tool finds victims by scanning ``/proc/*/environ`` for that marker
— no pid files, no cooperation from the victim — and delivers a signal
(SIGKILL by default: the point is to exercise the UNGRACEFUL paths,
respawn + replay + checkpoint restore).

CLI::

    python -m dora_tpu.tools.chaos --victim <dataflow>:<node> \
        [--after 1.5] [--signal 9] [--timeout 30] [--seed 7]

``--after`` sleeps before striking (with ±20 % seeded jitter when
``--seed`` is given, so chaos schedules are reproducible but not
phase-locked to the dataflow). ``--timeout`` bounds the wait for the
victim to appear; exit code 1 if it never does.

The module is import-friendly for tests: ``find_pids`` / ``wait_for`` /
``kill`` are plain functions with no side effects at import time.
"""

from __future__ import annotations

import argparse
import os
import random
import signal as _signal
import sys
import time

CHAOS_ENV = "DORA_CHAOS_ID"


def _environ_of(pid: str) -> dict[str, str]:
    try:
        raw = open(f"/proc/{pid}/environ", "rb").read()
    except OSError:
        return {}
    out: dict[str, str] = {}
    for chunk in raw.split(b"\0"):
        if b"=" in chunk:
            k, _, v = chunk.partition(b"=")
            out[k.decode(errors="replace")] = v.decode(errors="replace")
    return out


def find_pids(dataflow_id: str | None = None,
              node_id: str | None = None) -> list[int]:
    """Pids whose ``DORA_CHAOS_ID`` matches ``<dataflow>:<node>``.

    ``None`` wildcards either half: ``find_pids(node_id="llm")`` finds
    the llm node of whatever dataflow is running."""
    hits: list[int] = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        marker = _environ_of(entry).get(CHAOS_ENV)
        if not marker or ":" not in marker:
            continue
        df, _, node = marker.rpartition(":")
        if dataflow_id is not None and df != dataflow_id:
            continue
        if node_id is not None and node != node_id:
            continue
        hits.append(int(entry))
    return hits


def wait_for(dataflow_id: str | None, node_id: str | None,
             timeout_s: float = 30.0,
             poll_s: float = 0.1) -> list[int]:
    """Poll until at least one matching victim exists (or timeout)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        pids = find_pids(dataflow_id, node_id)
        if pids:
            return pids
        time.sleep(poll_s)
    return []


def kill(pids: list[int], sig: int = _signal.SIGKILL) -> list[int]:
    """Deliver ``sig`` to each pid; returns the pids actually hit
    (a victim may have exited between discovery and delivery)."""
    struck: list[int] = []
    for pid in pids:
        try:
            os.kill(pid, sig)
            struck.append(pid)
        except OSError:
            pass
    return struck


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dora-tpu-chaos",
        description="kill -9 a dataflow node mid-run (fault injection)",
    )
    parser.add_argument(
        "--victim", required=True, metavar="DATAFLOW:NODE",
        help="target as <dataflow-id>:<node-id>; either half may be '*'",
    )
    parser.add_argument("--after", type=float, default=0.0,
                        help="seconds to wait before striking")
    parser.add_argument("--signal", type=int, default=int(_signal.SIGKILL),
                        help="signal number (default 9)")
    parser.add_argument("--timeout", type=float, default=30.0,
                        help="max seconds to wait for the victim to appear")
    parser.add_argument("--seed", type=int, default=None,
                        help="seed the strike-time jitter (reproducible runs)")
    args = parser.parse_args(argv)

    df, _, node = args.victim.rpartition(":")
    df_id = None if df in ("", "*") else df
    node_id = None if node in ("", "*") else node

    delay = args.after
    if args.seed is not None and delay > 0:
        delay *= 0.8 + 0.4 * random.Random(args.seed).random()
    if delay > 0:
        time.sleep(delay)

    pids = wait_for(df_id, node_id, timeout_s=args.timeout)
    if not pids:
        print(f"chaos: no victim matching {args.victim!r}", file=sys.stderr)
        return 1
    struck = kill(pids, args.signal)
    print(f"chaos: sent signal {args.signal} to {struck}")
    return 0 if struck else 1


if __name__ == "__main__":
    sys.exit(main())
