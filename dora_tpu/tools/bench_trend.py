"""Benchmark trend tracking: append every ``bench.py`` run to
``BENCH_history.jsonl`` and flag regressions against the last comparable
run.

Raw bench numbers from different machines (or the same machine in a
different state) are not comparable, so every appended record carries:

* an **environment fingerprint** — platform, CPU count, Python version,
  and the perf-relevant ``DORA_*`` knobs, hashed to a short id. Only
  runs with the same fingerprint are compared.
* an **ambient-throughput calibration** — a ~0.2 s in-process hashing
  loop measured at append time. If the machine itself got slower (noisy
  neighbors, thermal throttling, a busy CI host), the calibration moves
  with it and the comparison is skipped instead of mis-flagged as a code
  regression — the same reasoning that interleaves the A/B legs in
  ``bench.py``.

A watched metric that is >10% worse than the previous fingerprint-matched
run (with calibration within 20%) is reported in ``regressions`` — the
caller prints them and ships them inside the bench JSON line; the history
file is the long-term record BENCHMARKS.md rounds are written from.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import sys
import time
from pathlib import Path
from typing import Any

#: metric path (dot-separated into the bench record) -> better direction
WATCHED: dict[str, str] = {
    "value": "lower",  # 40 MB p50 latency (us)
    "msgs_per_sec_1kib.daemon": "higher",
    "msgs_per_sec_1kib.p2p": "higher",
    "p50_us_1kib.daemon": "lower",
    "p99_us_1kib.daemon": "lower",
    "e2e_fps": "higher",
    # Traffic-shaping soak: the on/off interactive TTFT p99 ratio —
    # a drift toward 1.0 means shaping stopped buying latency.
    "serving_qos_soak.interactive_p99_on_vs_off": "lower",
    # Shared-prefix cache A/B: hit-request TTFT p50 ratio on/off — a
    # drift toward 1.0 means cache hits stopped buying first-token
    # latency (the default-on gate is <= 0.5).
    "serving_prefix_ab.hit_p50_on_vs_off": "lower",
    # Alerting-plane A/B: msgs/sec overhead of the default rule pack
    # evaluating each history tick vs engine off — a drift upward means
    # rule evaluation crept onto the budget (the gate is <= 3%).
    "alerts_ab.overhead_pct": "lower",
    # Device-monitor A/B: wall-clock with the utilization plane on vs
    # off — a drift upward means the default-on monitor got expensive
    # (the gate is <= 3%).
    "serving_profiling_ab.overhead_pct": "lower",
    # Quantized serving: concurrent streams admitted into the fp
    # pool's byte budget, int8 vs fp — a drift downward means the
    # scale-plane overhead grew (the gate is >= 1.8).
    "serving_quant_ab.capacity.int8_capacity_ratio": "higher",
    # Spec acceptance under int8 KV: the round-18 guidance is that
    # acceptance counters, not token identity, are the drift signal
    # when KV is quantized — a downward drift means rounding started
    # flipping draft verifications.
    "serving_quant_ab.spec.spec_acceptance": "higher",
    # Fleet-digest A/B: serving wall-clock with the engine-state
    # exporter publishing at 0.5 s vs off — a drift upward means the
    # digest walk crept onto the decode path (the gate is <= 3%).
    "fleet_digest_ab.overhead_pct": "lower",
    # Multi-tenant LoRA: aggregate tok/s of one N-adapter engine vs N
    # single-tenant engines in the same HBM budget — a drift toward
    # 1.0 means the shared fused window stopped amortizing across
    # tenants (the gate is >= 1.5).
    "serving_lora_ab.lora_aggregate_ratio": "higher",
}

#: flag when a watched metric is worse than the previous run by more
REGRESSION_PCT = 10.0
#: skip the comparison when the machine's own speed moved more than this
CALIBRATION_DRIFT_PCT = 20.0

#: env knobs that change what the bench measures (part of the fingerprint)
_ENV_KNOBS = (
    "DORA_SEND_COALESCE",
    "DORA_INT8_DECODE",
    "DORA_PIPELINE_DEPTH",
    "DORA_MULTISTEP_K",
    "BENCH_SMALL_MSGS",
    "BENCH_SMALL_RUNS",
    "BENCH_LATENCY_RUNS",
)


def env_fingerprint() -> dict:
    """The comparability key: hardware/interpreter identity + the env
    knobs that change the measured configuration."""
    parts = {
        "platform": sys.platform,
        "machine": platform.machine(),
        "python": platform.python_version(),
        "cpus": os.cpu_count(),
        "env": {k: os.environ[k] for k in _ENV_KNOBS if k in os.environ},
    }
    digest = hashlib.sha256(
        json.dumps(parts, sort_keys=True).encode()
    ).hexdigest()[:12]
    return {"id": digest, **parts}


def ambient_throughput(budget_s: float = 0.2) -> float:
    """MB/s of in-process blake2b over 64 KiB blocks for ``budget_s`` —
    a quick proxy for "how fast is this machine right now"."""
    block = b"\xa5" * 65536
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        hashlib.blake2b(block).digest()
        n += 1
    elapsed = time.perf_counter() - t0
    return round(n * len(block) / 1e6 / elapsed, 1) if elapsed else 0.0


def _get(record: dict, path: str) -> Any:
    cur: Any = record
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _load_last_matching(path: Path, fingerprint_id: str) -> dict | None:
    if not path.exists():
        return None
    last = None
    for line in path.read_text().splitlines():
        try:
            entry = json.loads(line)
        except ValueError:
            continue  # a torn write must not wedge trend tracking
        if entry.get("fingerprint", {}).get("id") == fingerprint_id:
            last = entry
    return last


def compare(
    record: dict, prev_entry: dict, ambient_mb_s: float
) -> tuple[list[dict], str | None]:
    """Watched-metric deltas vs the previous fingerprint-matched entry.

    Returns ``(regressions, note)`` — ``note`` explains a skipped
    comparison (calibration drift)."""
    prev_ambient = prev_entry.get("ambient_mb_s") or 0.0
    if prev_ambient and ambient_mb_s:
        drift = abs(ambient_mb_s - prev_ambient) / prev_ambient * 100.0
        if drift > CALIBRATION_DRIFT_PCT:
            return [], (
                f"ambient throughput moved {drift:.0f}% "
                f"({prev_ambient} -> {ambient_mb_s} MB/s): "
                "machine state changed, comparison skipped"
            )
    regressions = []
    prev_record = prev_entry.get("record", {})
    for path, direction in WATCHED.items():
        cur, prev = _get(record, path), _get(prev_record, path)
        if not isinstance(cur, (int, float)) or not isinstance(
            prev, (int, float)
        ) or not prev:
            continue
        worse_pct = (
            (cur - prev) / prev * 100.0
            if direction == "lower"
            else (prev - cur) / prev * 100.0
        )
        if worse_pct > REGRESSION_PCT:
            regressions.append({
                "metric": path,
                "previous": prev,
                "current": cur,
                "worse_pct": round(worse_pct, 1),
            })
    return regressions, None


def record_run(record: dict, history_path: Path | str) -> dict:
    """Append one bench record to the history file and diff it against
    the previous fingerprint-matched run. Returns the trend summary the
    bench line ships (fingerprint id, calibration, regressions)."""
    path = Path(history_path)
    fp = env_fingerprint()
    ambient = ambient_throughput()
    prev = _load_last_matching(path, fp["id"])
    regressions: list[dict] = []
    note = None
    baseline_ts = None
    if prev is not None:
        baseline_ts = prev.get("ts")
        regressions, note = compare(record, prev, ambient)
    entry = {
        "ts": round(time.time(), 3),
        "fingerprint": fp,
        "ambient_mb_s": ambient,
        "record": record,
    }
    with path.open("a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    out: dict[str, Any] = {
        "fingerprint": fp["id"],
        "ambient_mb_s": ambient,
        "baseline_ts": baseline_ts,
        "regressions": regressions,
    }
    if note:
        out["note"] = note
    return out
