"""Microbench: int8 dequant-matmul vs bf16 matmul on decode shapes.

Run on the TPU: python -m dora_tpu.tools.bench_int8
Each timing chains iterations with a data dependency and reduces to a
scalar (axon tunnel only synchronizes on host fetch — see bench_vlm.py).
"""

from __future__ import annotations

import statistics
import time

import jax
import jax.numpy as jnp

from dora_tpu.ops.int8_matmul import int8_matmul, quantize_int8

ITERS = 1024


def _time_scalar(fn, rounds: int = 5) -> float:
    float(fn())  # compile
    samples = []
    for _ in range(rounds):
        t = time.perf_counter()
        float(fn())
        samples.append(time.perf_counter() - t)
    return statistics.median(samples)


def bench_shape(m: int, k: int, n: int) -> None:
    kx, kw = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, (m, k), jnp.bfloat16)
    w = jax.random.normal(kw, (k, n), jnp.float32)
    wq = quantize_int8(w)
    w16 = w.astype(jnp.bfloat16)
    q, s = wq["int8"], wq["scale"]

    @jax.jit
    def chain_bf16(x, w):
        def body(_, acc):
            y = (x + acc * 1e-9) @ w
            return jnp.max(y).astype(jnp.float32) * 1e-9
        return jax.lax.fori_loop(0, ITERS, body, jnp.float32(0))

    @jax.jit
    def chain_int8(x, q, s):
        def body(_, acc):
            y = int8_matmul(x + acc.astype(x.dtype) * 1e-9, q, s)
            return jnp.max(y).astype(jnp.float32) * 1e-9
        return jax.lax.fori_loop(0, ITERS, body, jnp.float32(0))

    rtt = _time_scalar(jax.jit(lambda: jnp.float32(0)))
    t16 = (_time_scalar(lambda: chain_bf16(x, w16)) - rtt) / ITERS
    t8 = (_time_scalar(lambda: chain_int8(x, q, s)) - rtt) / ITERS
    gbs16 = k * n * 2 / t16 / 1e9
    gbs8 = k * n * 1 / t8 / 1e9
    print(
        f"[{m}x{k}x{n}] bf16 {t16*1e6:8.1f}us ({gbs16:6.1f} GB/s)  "
        f"int8 {t8*1e6:8.1f}us ({gbs8:6.1f} GB/s)  "
        f"speedup {t16/t8:5.2f}x",
        flush=True,
    )


if __name__ == "__main__":
    print(f"backend={jax.default_backend()}")
    bench_shape(16, 1536, 8960)    # ffn up (M padded to sublane anyway)
    bench_shape(16, 8960, 1536)    # ffn down
    bench_shape(16, 1536, 1536)    # attn qo
    bench_shape(16, 1536, 152064)  # lm_head
