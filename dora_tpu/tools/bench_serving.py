"""A/B the paged serving engine against the dense engine.

Two axes, one JSON line on stdout:

* ``streams4``  — 4 concurrent requests, paged vs dense (both engines
  hold 4 slots). The paged path must be throughput-neutral here: block
  table indirection is supposed to cost ~nothing at the batch size the
  dense engine was built for (the ±3% acceptance gate).
* ``streams16`` — 16 requests arriving at once. The paged engine holds
  16 slots inside the dense engine's 4-slot KV footprint and serves
  them concurrently; the dense engine (4 slots, SAME HBM) must queue
  12 of them — wall clock and TTFT p99 show what paging buys.

A third axis behind ``--multistep``: the K-sweep of the fused
multi-step decode window (K in {1, 4, 8, 16}) at 4 and 16 streams,
reporting HOST ROUND-TRIPS (engine dispatches + device->host fetches)
per emitted token next to tok/s. Round-trips are host-side counts —
immune to the tunnel-drift caveat that clouds wall-clock numbers
(KNOWN_ISSUES round 4: ``block_until_ready`` does not synchronize the
axon-tunneled chip, so e2e timings drift; the dispatch-amortization
claim rides the counters, not the clock).

Model: ``DORA_HF_CHECKPOINT`` when set (real numbers on the TPU box);
otherwise a tiny random Qwen2 is built in-process and the numbers are
relative-only (CPU smoke A/B, same code path).

A fourth axis behind ``--trace-ab``: the 16-stream paged run with the
serving observability plane attached, tracing off vs on (interleaved),
reporting the wall-clock overhead of the request-lifecycle span
records — the serving counterpart of bench.py's recorder A/B gate
(≤3%).

A fifth axis behind ``--spec-ab``: speculative decoding inside the
fused window (DORA_SPEC_K), spec_k in {0, 2, 4} x K in {1, 8} on the
stub engine's repetitive (best-case acceptance) and random (worst-case)
token rules — tokens per dispatch and acceptance rate per cell.

A sixth axis behind ``--qos-soak``: open-loop Poisson mixed-class
overload through the REAL serve() admission path (stub engine, no
weights), QoS shaping on vs off over the identical arrival trace —
per-class TTFT p50/p99, shed rate, preempt/resume counts. The
acceptance headline is ``interactive_p99_on_vs_off`` < 1.0: shaping
must buy the interactive class latency under overload, paid for by the
batch class, never by silent loss (completion accounting rides along).

A seventh axis behind ``--prefix-ab``: the shared-prefix KV cache at
admission (DORA_PREFIX_CACHE), a Zipf-popular template workload (hot
system prompts, unique tails) replayed open-loop with the cache on vs
off over the identical arrival trace — hit rate, TTFT p50/p99 for hit
requests vs the same requests uncached, prefill-chunk deltas, pool
occupancy. The acceptance headline is ``hit_p50_on_vs_off`` <= 0.5: a
cache hit must at least halve first-token latency to justify the
serving default-on.

An eighth axis behind ``--quant-ab``: quantized serving
(DORA_KV_INT8 / DORA_WEIGHT_BITS) — the same 4-stream workload on fp
vs int8-KV vs int8-KV + int4-weight engines (greedy token agreement
against the fp leg rides along), plus a capacity leg that counts how
many concurrent streams each KV dtype admits into the SAME pool byte
budget through the real ``can_admit``/``submit`` path. The
acceptance headline is ``int8_capacity_ratio`` >= 1.8 (a
spec-acceptance leg rides along: acceptance counters under int8 KV vs
fp — the round-18 drift signal).

A ninth axis behind ``--lora-ab``: multi-tenant LoRA serving — the
aggregate tokens/s of ONE paged engine serving N adapter tenants vs N
separate engines splitting the same HBM budget, plus an adapter-churn
leg asserting zero steady-state compiles while tenants rotate through
the resident budget. The acceptance headline is
``lora_aggregate_ratio`` >= 1.5.

Usage::

    python -m dora_tpu.tools.bench_serving [--multistep | --trace-ab |
                                            --spec-ab | --qos-soak |
                                            --prefix-ab | --quant-ab |
                                            --lora-ab]
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time
from collections import deque


def _tiny_checkpoint(tmp: str) -> str:
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    config = Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    Qwen2ForCausalLM(config).eval().save_pretrained(
        tmp, safe_serialization=True
    )
    return tmp


def _serve(engine, prompts, max_new: int):
    """Push every request at t0, drain to completion. Returns
    (tokens_emitted, wall_s, ttft_s per request) — TTFT includes queue
    wait, which is the point: an engine that can't admit pays it."""
    backlog = deque(enumerate(prompts))
    t0 = time.perf_counter()
    ttft: dict[int, float] = {}
    tokens = 0
    active_keys: set[int] = set()
    while backlog or active_keys:
        while backlog and engine.can_admit(len(backlog[0][1]), max_new):
            rid, ids = backlog.popleft()
            active_keys.add(rid)
            res = engine.submit(str(rid), ids, max_new)
            if res is not None:  # dense: first token is synchronous
                tokens += 1
                ttft.setdefault(rid, time.perf_counter() - t0)
                if res[1]:
                    active_keys.discard(rid)
        for key, _token, done in engine.step():
            rid = int(key)
            tokens += 1
            ttft.setdefault(rid, time.perf_counter() - t0)
            if done:
                active_keys.discard(rid)
    return tokens, time.perf_counter() - t0, list(ttft.values())


def _stats(tokens: int, wall: float, ttfts: list[float]) -> dict:
    ordered = sorted(ttfts)
    return {
        "decode_tok_s": round(tokens / wall, 1) if wall > 0 else None,
        "wall_s": round(wall, 3),
        "tokens": tokens,
        "ttft_p50_ms": round(statistics.median(ordered) * 1e3, 1),
        "ttft_p99_ms": round(
            ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))] * 1e3, 1
        ),
    }


def _multistep_sweep(qwen2, path: str, real: bool) -> dict:
    """K-sweep of the multi-step decode window: host round-trips per
    emitted token + tok/s at K in {1, 4, 8, 16}, 4 and 16 streams.

    The workload is decode-heavy on purpose (short prompts, long
    generations): the window amortizes per-TOKEN dispatch/fetch cost,
    so the regime where decode dominates prefill is the one the ≥4x
    K=8-vs-K=1 round-trip gate is stated for. Warmup legs run short
    (shapes are identical regardless of max_new, so compiles are the
    same); measured legs read counter DELTAS around the run."""
    import jax
    import numpy as np

    # A longer cache than the engine-A/B smoke so generations are long
    # enough for decode to dominate (tiny CPU: 4-token prompts, 120 new
    # tokens inside max_seq 128).
    if real:
        max_seq = int(os.environ.get("DORA_MAX_SEQ", "512"))
        page_size, chunk, plen = 16, 64, 64
        max_new = {4: min(256, max_seq - plen), 16: 32}
    else:
        max_seq, page_size, chunk, plen = 128, 8, 8, 4
        max_new = {4: 120, 16: 24}

    cfg, params = qwen2.load(path, max_seq=max_seq)
    os.environ.setdefault("DORA_INT8_DECODE", "1")
    params = qwen2.quantize_decode(params, cfg)
    rng = np.random.default_rng(3)

    def prompts(n: int) -> list[list[int]]:
        return [
            rng.integers(0, cfg.vocab, size=plen).tolist() for _ in range(n)
        ]

    out: dict = {
        "backend": jax.default_backend(),
        "model": "checkpoint" if real else "tiny-random",
        "plen": plen,
        "max_new": {str(s): m for s, m in max_new.items()},
        "k_sweep": {},
    }
    per_k: dict[int, dict] = {}
    for streams in (4, 16):
        leg: dict = {}
        for k in (1, 4, 8, 16):
            engine = qwen2.make_paged_engine(
                params, cfg, max_slots=streams, page_size=page_size,
                chunk=chunk, window=k,
            )
            _serve(engine, prompts(streams), 4)  # warmup: compile only
            d0, f0 = engine.dispatches, engine.fetches
            tokens, wall, ttfts = _serve(
                engine, prompts(streams), max_new[streams]
            )
            trips = (engine.dispatches - d0) + (engine.fetches - f0)
            stats = _stats(tokens, wall, ttfts)
            stats["round_trips"] = trips
            stats["rt_per_token"] = round(trips / tokens, 4)
            stats["tokens_per_dispatch"] = round(
                tokens / (engine.dispatches - d0), 2
            )
            leg[f"k{k}"] = stats
        out["k_sweep"][f"streams{streams}"] = leg
        per_k[streams] = leg
    # The acceptance headline: K=8 vs K=1 round-trips per token at 4
    # streams (the decode-dominated leg).
    s4 = per_k[4]
    out["k8_vs_k1_rt_reduction"] = round(
        s4["k1"]["rt_per_token"] / s4["k8"]["rt_per_token"], 2
    )
    return out


def _trace_ab(qwen2, path: str, real: bool) -> dict:
    """Serving-span instrumentation overhead: the 16-stream paged run
    with the full observability plane attached (ServingTracer +
    ServingMetrics on the engine, lifecycle spans through the
    flight-recorder ring) A/B'd tracing-off vs tracing-on, trials
    interleaved so both sides see the same machine conditions — the
    recorder-A/B methodology from bench.py's message-plane legs applied
    to the engine step path. Both sides carry the tracer and metrics
    objects; the off side pays exactly what production pays without
    ``DORA_TRACING=1`` (one attribute check per hook site), so
    ``overhead_pct`` isolates the span records themselves."""
    import numpy as np

    from dora_tpu import telemetry
    from dora_tpu.metrics import ServingMetrics

    if real:
        max_seq = int(os.environ.get("DORA_MAX_SEQ", "512"))
        page_size, chunk, plen, max_new = 16, 64, 64, 32
    else:
        max_seq, page_size, chunk, plen, max_new = 64, 8, 8, 4, 8

    cfg, params = qwen2.load(path, max_seq=max_seq)
    os.environ.setdefault("DORA_INT8_DECODE", "1")
    params = qwen2.quantize_decode(params, cfg)
    rng = np.random.default_rng(7)

    def prompts(n: int) -> list[list[int]]:
        return [
            rng.integers(0, cfg.vocab, size=plen).tolist() for _ in range(n)
        ]

    engine = qwen2.make_paged_engine(
        params, cfg, max_slots=16, page_size=page_size, chunk=chunk
    )
    engine.serving_metrics = ServingMetrics("paged")
    tracer = telemetry.ServingTracer()
    engine.tracer = tracer
    _serve(engine, prompts(16), max_new)  # warmup: compiles only
    trials = int(os.environ.get("DORA_BENCH_TRIALS", "5"))
    walls: dict[str, list[float]] = {"off": [], "on": []}
    span_events = 0
    for _ in range(trials):
        for mode in ("off", "on"):
            on = mode == "on"
            telemetry.TRACING.active = on
            telemetry.FLIGHT.enabled = on
            telemetry.FLIGHT.clear()
            for i in range(16):
                tracer.begin(str(i))
            _tokens, wall, _ = _serve(engine, prompts(16), max_new)
            for i in range(16):
                tracer.finish(str(i))
            if on:
                span_events = len(telemetry.FLIGHT.events())
            walls[mode].append(wall)
    telemetry.TRACING.active = False
    telemetry.FLIGHT.enabled = False
    off_w = statistics.median(walls["off"])
    on_w = statistics.median(walls["on"])
    return {
        "off_wall_s": round(off_w, 4),
        "on_wall_s": round(on_w, 4),
        "overhead_pct": (
            round((on_w - off_w) / off_w * 100, 2) if off_w else None
        ),
        "span_events_per_run": span_events,
        "trials": trials,
    }


def _serve_tokens(engine, prompts, max_new: int):
    """Like :func:`_serve` for paged engines, but keeps each stream's
    emitted token sequence — the quant A/B compares greedy tokens
    per position, not just counts."""
    backlog = deque(enumerate(prompts))
    seqs: dict[int, list[int]] = {i: [] for i in range(len(prompts))}
    active: set[int] = set()
    t0 = time.perf_counter()
    ttft: dict[int, float] = {}
    while backlog or active:
        while backlog and engine.can_admit(len(backlog[0][1]), max_new):
            rid, ids = backlog.popleft()
            active.add(rid)
            engine.submit(str(rid), ids, max_new)
        for key, token, done in engine.step():
            rid = int(key)
            seqs[rid].append(int(token))
            ttft.setdefault(rid, time.perf_counter() - t0)
            if done:
                active.discard(rid)
    return seqs, time.perf_counter() - t0, list(ttft.values())


def _quant_ab(qwen2, path: str, real: bool) -> dict:
    """Quantized-serving A/B behind ``--quant-ab``: throughput + greedy
    token agreement for fp-KV vs int8-KV vs int8-KV + int4-weight
    engines on the identical prompt set, then a capacity leg counting
    concurrent admissions into the SAME pool byte budget (the int8
    pool is auto-resized into the fp pool's HBM bytes by
    ``make_paged_engine``; per-page scale planes are part of the
    footprint). Agreement is a per-position token match fraction vs
    the fp leg — 1.0 for the int8-KV leg on the tiny CI model,
    expected slightly below on real models with near-tie continuations
    (KNOWN_ISSUES round 18). The w4 leg's agreement measures the
    *weight* quantization (int4 weights are a different model, so low
    agreement there is expected and not a KV-error signal)."""
    import jax
    import numpy as np

    if real:
        max_seq = int(os.environ.get("DORA_MAX_SEQ", "512"))
        page_size, chunk, plen, max_new = 16, 64, 64, 64
    else:
        max_seq, page_size, chunk, plen, max_new = 64, 8, 8, 4, 24

    cfg, params = qwen2.load(path, max_seq=max_seq)
    os.environ.setdefault("DORA_INT8_DECODE", "1")
    params8 = qwen2.quantize_decode(params, cfg)
    prev = os.environ.get("DORA_WEIGHT_BITS")
    os.environ["DORA_WEIGHT_BITS"] = "4"
    try:
        params4 = qwen2.quantize_decode(params, cfg)
    finally:
        if prev is None:
            del os.environ["DORA_WEIGHT_BITS"]
        else:
            os.environ["DORA_WEIGHT_BITS"] = prev
    rng = np.random.default_rng(11)
    work = [
        rng.integers(0, cfg.vocab, size=plen).tolist() for _ in range(4)
    ]

    out: dict = {
        "backend": jax.default_backend(),
        "model": "checkpoint" if real else "tiny-random",
        "plen": plen,
        "max_new": max_new,
        "streams": 4,
    }
    seqs_by_leg: dict[str, dict[int, list[int]]] = {}
    for name, leg_params, kv8 in (
        ("fp", params8, False),
        ("kv_int8", params8, True),
        ("kv_int8_w4", params4, True),
    ):
        engine = qwen2.make_paged_engine(
            leg_params, cfg, max_slots=4, page_size=page_size,
            chunk=chunk, kv_int8=kv8,
        )
        _serve_tokens(engine, work, 4)  # warmup: compiles only
        seqs, wall, ttfts = _serve_tokens(engine, work, max_new)
        tokens = sum(len(s) for s in seqs.values())
        stats = _stats(tokens, wall, ttfts)
        stats["kv_dtype"] = engine.kv_dtype
        stats["pool_bytes"] = sum(
            int(x.nbytes) for x in jax.tree.leaves(engine.pools)
        )
        out[name] = stats
        seqs_by_leg[name] = seqs

    def agree(ref: dict, other: dict):
        total = match = 0
        for rid, ref_seq in ref.items():
            for a, b in zip(ref_seq, other.get(rid, [])):
                total += 1
                match += int(a == b)
        return round(match / total, 4) if total else None

    out["greedy_agreement_vs_fp"] = {
        "kv_int8": agree(seqs_by_leg["fp"], seqs_by_leg["kv_int8"]),
        "kv_int8_w4": agree(seqs_by_leg["fp"], seqs_by_leg["kv_int8_w4"]),
    }

    # Capacity leg: admission-path head count. Both engines get the
    # default pool BYTE budget (int8 auto-resizes page count into it);
    # streams are admitted through the real can_admit/submit page
    # granting until the pool refuses. No step() runs — admission is
    # host-side bookkeeping, so the leg holds zero compiles.
    cap: dict[str, dict] = {}
    for name, kv8 in (("fp", False), ("int8", True)):
        engine = qwen2.make_paged_engine(
            params8, cfg, max_slots=512, page_size=page_size,
            chunk=chunk, kv_int8=kv8,
        )
        n = 0
        while n < 512 and engine.can_admit(plen, max_new):
            engine.submit(f"cap{n}", work[0], max_new)
            n += 1
        cap[name] = {
            "streams": n,
            "pool_bytes": sum(
                int(x.nbytes) for x in jax.tree.leaves(engine.pools)
            ),
            "usable_pages": engine.allocator.num_pages - 1,
        }
    out["capacity"] = {
        "fp": cap["fp"],
        "int8": cap["int8"],
        "pool_budget_ratio": round(
            cap["int8"]["pool_bytes"] / cap["fp"]["pool_bytes"], 3
        ),
        # The acceptance headline: concurrent streams admitted into the
        # same HBM footprint, int8 vs fp (gate: >= 1.8).
        "int8_capacity_ratio": round(
            cap["int8"]["streams"] / cap["fp"]["streams"], 2
        ),
    }

    # Spec-acceptance leg: the round-18 guidance is that under int8 KV
    # the SIGNAL is the acceptance counters, not token identity — a
    # near-tie continuation that flips under rounding shows up as a
    # drafted-token rejection long before it shows up in quality evals.
    # Run the identical workload with speculation on for fp vs int8 KV
    # and report the acceptance fraction per leg; bench_trend watches
    # ``spec.spec_acceptance`` (the int8 leg) for downward drift.
    from dora_tpu.metrics import ServingMetrics

    spec: dict = {}
    for name, kv8 in (("fp", False), ("int8", True)):
        engine = qwen2.make_paged_engine(
            params8, cfg, max_slots=4, page_size=page_size,
            chunk=chunk, kv_int8=kv8, spec_k=2,
        )
        _serve_tokens(engine, work, 4)  # warmup: compiles only
        engine.serving_metrics = ServingMetrics(engine="paged")
        _serve_tokens(engine, work, max_new)
        sm = engine.serving_metrics
        spec[f"acceptance_{name}"] = (
            round(sm.spec_accepted / sm.spec_drafted, 4)
            if sm.spec_drafted else None
        )
        spec[f"drafted_{name}"] = sm.spec_drafted
    spec["spec_acceptance"] = spec["acceptance_int8"]
    out["spec"] = spec
    return out


def _lora_ab() -> dict:
    """Multi-tenant LoRA A/B behind ``--lora-ab``: aggregate tokens/s
    of ONE paged engine serving N adapter tenants vs N separate
    engines splitting the same HBM budget (pages and slots divided
    N ways), identical per-tenant workload. The separate engines run
    to completion back to back and their walls sum — the timesharing
    model of N single-tenant engines on one host. The shared engine
    amortizes every fused K-window dispatch across all tenants'
    streams, which is the whole perf claim: the acceptance headline is
    ``lora_aggregate_ratio`` >= 1.5.

    A churn leg rides along: with a resident budget of 2 slots, 6
    tenants rotate through admission/eviction while the XLA compile
    listener counts backend compiles — the adapter id is traced DATA,
    so steady-state churn must hold ZERO compiles
    (``churn.steady_state_compiles``)."""
    from dora_tpu import telemetry
    from dora_tpu.models.batch_engine import make_stub_paged_engine

    tenants, per_tenant, max_new = 4, 2, 64
    max_seq, page_size, chunk, pages = 128, 8, 16, 64
    # Every engine pays this per window dispatch: the decode window on
    # real hardware is weight-streaming-bound, so its cost is ~flat in
    # active slots — which is exactly what the multi-tenant claim
    # amortizes. The bare CPU stub's ~free step would instead measure
    # host token bookkeeping (identical on both sides) and bury the
    # dispatch-count difference the A/B exists to show.
    step_cost_s = 0.002
    names = [f"tenant-{i}" for i in range(tenants)]
    prompts = {n: [[3 + i], [11 + i]] for i, n in enumerate(names)}

    def serve_tenants(engine, work):
        """(key, ids, adapter) triples, pushed at t0, drained."""
        backlog = deque(work)
        active: set[str] = set()
        tokens = 0
        t0 = time.perf_counter()
        while backlog or active:
            while backlog and engine.can_admit(
                len(backlog[0][1]), max_new, backlog[0][2]
            ):
                key, ids, ad = backlog.popleft()
                active.add(key)
                engine.submit(key, ids, max_new, adapter=ad)
            for key, _tok, done in engine.step():
                tokens += 1
                if done:
                    active.discard(key)
        return tokens, time.perf_counter() - t0

    out: dict = {
        "tenants": tenants,
        "streams_per_tenant": per_tenant,
        "max_new": max_new,
        "pool_pages": pages,
    }

    # Shared: one engine, all tenants resident, every stream concurrent.
    shared = make_stub_paged_engine(
        max_slots=tenants * per_tenant, max_seq=max_seq,
        page_size=page_size, chunk=chunk, num_pages=pages,
        lora_max_resident=tenants, tick_sleep_s=step_cost_s,
    )
    work = [
        (f"{n}/{j}", ids, n)
        for n in names for j, ids in enumerate(prompts[n])
    ]
    serve_tenants(shared, work)  # warmup: compiles only
    tokens, wall = serve_tenants(shared, work)
    out["shared"] = {
        "tokens": tokens,
        "wall_s": round(wall, 3),
        "tok_s": round(tokens / wall, 1),
    }

    # Separate: N plain engines, each with 1/N of the pages and slots
    # (same total HBM), each serving only its own tenant's streams.
    sep_tokens = sep_wall = 0.0
    engines = [
        make_stub_paged_engine(
            max_slots=per_tenant, max_seq=max_seq, page_size=page_size,
            chunk=chunk, num_pages=max(2, pages // tenants),
            tick_sleep_s=step_cost_s,
        )
        for _ in names
    ]
    for engine, n in zip(engines, names):
        serve_tenants(
            engine, [(f"{n}/w{j}", ids, None)
                     for j, ids in enumerate(prompts[n])]
        )  # warmup
    for engine, n in zip(engines, names):
        t, w = serve_tenants(
            engine, [(f"{n}/{j}", ids, None)
                     for j, ids in enumerate(prompts[n])]
        )
        sep_tokens += t
        sep_wall += w
    out["separate"] = {
        "tokens": int(sep_tokens),
        "wall_s": round(sep_wall, 3),
        "tok_s": round(sep_tokens / sep_wall, 1),
        "pages_each": max(2, pages // tenants),
    }
    # The acceptance headline: aggregate throughput, one multi-tenant
    # engine vs N single-tenant engines in the same byte budget
    # (gate: >= 1.5).
    out["lora_aggregate_ratio"] = round(
        out["shared"]["tok_s"] / out["separate"]["tok_s"], 2
    )

    # Churn leg: 6 tenants through a 2-slot resident budget. Adapter
    # ids are traced data and the stacked pool has a fixed shape, so
    # once the window shapes are warm, admission/eviction churn must
    # not recompile anything.
    churn = make_stub_paged_engine(
        max_slots=2, max_seq=max_seq, page_size=page_size, chunk=chunk,
        num_pages=pages, lora_max_resident=2,
    )
    churn_names = [f"churn-{i}" for i in range(6)]
    serve_tenants(
        churn, [(f"warm/{n}", [5], n) for n in churn_names[:2]]
    )  # warmup: compile the lora window shapes
    telemetry.install_compile_listener()
    c0 = telemetry.compile_count()
    for cycle in range(2):
        for n in churn_names:
            serve_tenants(churn, [(f"{cycle}/{n}", [7], n)])
    out["churn"] = {
        "adapters": len(churn_names),
        "resident_budget": 2,
        "loads": churn.lora.loads,
        "evictions": churn.lora.evictions,
        "steady_state_compiles": telemetry.compile_count() - c0,
    }
    return out


def _spec_ab() -> dict:
    """Speculative decoding A/B behind ``--spec-ab``: acceptance rate x
    tokens-per-dispatch, spec_k in {0, 2, 4} crossed with window K in
    {1, 8}, on the stub paged engine — the REAL spec window program
    (ngram lookup, k+1-row verify, ragged emission) over a weight-free
    token rule, so ACCEPTANCE is controlled by construction instead of
    depending on what a tiny random model happens to repeat:

    * ``repetitive`` — the period-4 cycle rule, prompt-lookup's best
      case (looping/templated text): drafts come true, every verify
      accepts, dispatches collapse.
    * ``random`` — the affine full-period rule: a trailing ngram's
      continuation never repeats, ~0% acceptance, every dispatch pays
      the k+1-row verify for one token — the worst-case overhead leg.

    Tokens-per-dispatch reads host counter deltas around the measured
    run (warmup leg compiles the shapes), the same methodology as the
    ``--multistep`` sweep — counts, not clocks."""
    from dora_tpu.metrics import ServingMetrics
    from dora_tpu.models.batch_engine import make_stub_paged_engine

    max_seq, page_size, chunk, max_new, streams = 128, 8, 16, 96, 4
    prompts = [[5], [6], [7], [8]]
    out: dict = {
        "max_new": max_new,
        "streams": streams,
        "legs": {},
    }
    for leg, cycle in (("repetitive", 4), ("random", None)):
        leg_out: dict = {}
        for K in (1, 8):
            for k in (0, 2, 4):
                engine = make_stub_paged_engine(
                    max_slots=streams, max_seq=max_seq,
                    page_size=page_size, chunk=chunk, window=K,
                    spec_k=k, cycle=cycle,
                )
                _serve(engine, prompts, 4)  # warmup: compile only
                engine.serving_metrics = ServingMetrics(engine="paged")
                d0 = engine.dispatches
                tokens, _wall, _ = _serve(engine, prompts, max_new)
                sm = engine.serving_metrics
                leg_out[f"k{k}_K{K}"] = {
                    "tokens": tokens,
                    "dispatches": engine.dispatches - d0,
                    "tokens_per_dispatch": round(
                        tokens / (engine.dispatches - d0), 2
                    ),
                    "acceptance": (
                        round(sm.spec_accepted / sm.spec_drafted, 3)
                        if sm.spec_drafted
                        else None
                    ),
                }
        out["legs"][leg] = leg_out
    # Acceptance headlines: spec-on vs spec-off at the shipped window
    # (K=8) — the >=1.5x repetitive gate and the <=10% random-leg
    # regression bound.
    rep, rnd = out["legs"]["repetitive"], out["legs"]["random"]
    out["rep_k4_vs_k0_tpd_at_k8"] = round(
        rep["k4_K8"]["tokens_per_dispatch"]
        / rep["k0_K8"]["tokens_per_dispatch"], 2
    )
    out["rand_k4_vs_k0_tpd_at_k8"] = round(
        rnd["k4_K8"]["tokens_per_dispatch"]
        / rnd["k0_K8"]["tokens_per_dispatch"], 2
    )
    return out


def _profiling_ab() -> dict:
    """Device-monitor A/B behind ``--profiling-ab``: the round-16
    utilization plane (per-window ``block_until_ready`` attribution +
    FLOPs ledger) on vs off at 16 streams on the stub paged engine,
    trials interleaved — the ``_trace_ab`` methodology applied to the
    monitor flag. ONE engine serves both sides with
    ``engine.device_monitor`` toggled between serves (exactly what
    ``DORA_DEVICE_MONITOR`` controls): a fresh engine per side measures
    construction variance — allocator layout, first-touch page faults,
    build-order bias worth ~3-5% on a run this short — instead of the
    monitor. The estimator is the **median of per-trial paired ratios**:
    each trial's off/on serves run back-to-back (~tens of ms apart), so
    slow ambient drift — a busy CI host speeding up or bogging down over
    the run — hits both legs of a pair equally and divides out, where a
    pooled off-median vs on-median comparison would charge it to
    whichever side the drift happened to land on. The gate is <= 3%
    wall-clock overhead — same bar as the serving-trace recorder,
    because the plane is default-on."""
    from dora_tpu.models.batch_engine import make_stub_paged_engine

    max_seq, page_size, chunk, max_new, streams = 256, 8, 16, 192, 16
    prompts = [[i + 5] for i in range(streams)]
    trials = int(os.environ.get("DORA_BENCH_TRIALS", "14"))
    engine = make_stub_paged_engine(
        max_slots=streams, max_seq=max_seq, page_size=page_size,
        chunk=chunk, window=8,
    )
    _serve(engine, prompts, 4)  # warmup: compile only
    walls: dict[str, list[float]] = {"off": [], "on": []}
    for i in range(trials):
        # Alternate pair order so first-in-pair warmth cancels instead
        # of biasing one side.
        for mode in (("off", "on") if i % 2 == 0 else ("on", "off")):
            engine.device_monitor = mode == "on"
            _, wall, _ = _serve(engine, prompts, max_new)
            walls[mode].append(wall)
    engine.device_monitor = True
    ratios = [
        on / off
        for off, on in zip(walls["off"], walls["on"])
        if off > 0
    ]
    overhead = (statistics.median(ratios) - 1.0) * 100.0 if ratios else 0.0
    return {
        "streams": streams,
        "max_new": max_new,
        "trials": trials,
        "monitor_off_wall_s": round(statistics.median(walls["off"]), 4),
        "monitor_on_wall_s": round(statistics.median(walls["on"]), 4),
        "overhead_pct": round(overhead, 2),
        "gate_pct": 3.0,
        "pass": overhead <= 3.0,
    }


def _serve_ticked(engine, prompts, max_new: int, tick) -> tuple[int, float]:
    """The ``_serve`` drain loop with a per-iteration ``tick()`` hook —
    where the production serving loop would tick its fleet digest
    publisher. Both A/B arms run THIS loop so the hook's call overhead
    is common-mode; only the publish work differs."""
    backlog = deque(enumerate(prompts))
    t0 = time.perf_counter()
    tokens = 0
    active_keys: set[int] = set()
    while backlog or active_keys:
        tick()
        while backlog and engine.can_admit(len(backlog[0][1]), max_new):
            rid, ids = backlog.popleft()
            active_keys.add(rid)
            res = engine.submit(str(rid), ids, max_new)
            if res is not None:
                tokens += 1
                if res[1]:
                    active_keys.discard(rid)
        for key, _token, done in engine.step():
            tokens += 1
            if done:
                active_keys.discard(int(key))
    return tokens, time.perf_counter() - t0


def _fleet_digest_ab() -> dict:
    """Fleet-digest A/B behind ``--fleet-digest-ab``: the engine-state
    exporter (dora_tpu/fleet.py build_digest — radix-tree top-N walk,
    fits()-derived capacity, fingerprint) publishing at an aggressive
    0.5 s cadence vs off, on the 16-stream stub serving leg, trials
    interleaved with the ``_profiling_ab`` paired-ratio methodology
    (median of per-trial on/off ratios; ambient drift divides out).
    The cadence is 4x the shipped default (DORA_FLEET_DIGEST_S=2), so
    the gate bounds a worst-plausible config, not the default. Gate:
    <= 3% wall-clock overhead — same bar as the other default-on
    observability planes. The prefix cache is ON so every digest walks
    a populated tree (the expensive path), and the publisher sinks into
    a node fake — wire cost is the metrics plane's, already gated."""
    from dora_tpu import fleet
    from dora_tpu.models.batch_engine import make_stub_paged_engine

    max_seq, page_size, chunk, max_new, streams = 256, 8, 16, 192, 16
    cadence_s = 0.5
    prompts = [[i + 5] for i in range(streams)]
    trials = int(os.environ.get("DORA_BENCH_TRIALS", "14"))
    engine = make_stub_paged_engine(
        max_slots=streams, max_seq=max_seq, page_size=page_size,
        chunk=chunk, window=8, prefix_cache=True,
    )
    _serve(engine, prompts, 4)  # warmup: compile + warm the radix tree

    class _Sink:
        def __init__(self):
            self.digests = 0

        def report_engine_state(self, digest):
            self.digests += 1

    published = 0
    walls: dict[str, list[float]] = {"off": [], "on": []}
    for i in range(trials):
        for mode in (("off", "on") if i % 2 == 0 else ("on", "off")):
            sink = _Sink()
            pub = fleet.DigestPublisher(
                sink, engine, model_id="stub",
                interval_s=cadence_s if mode == "on" else 0,
            )
            _, wall = _serve_ticked(engine, prompts, max_new, pub.tick)
            walls[mode].append(wall)
            published += sink.digests
    ratios = [
        on / off
        for off, on in zip(walls["off"], walls["on"])
        if off > 0
    ]
    overhead = (statistics.median(ratios) - 1.0) * 100.0 if ratios else 0.0
    return {
        "streams": streams,
        "max_new": max_new,
        "trials": trials,
        "cadence_s": cadence_s,
        "digests_published": published,
        "digest_off_wall_s": round(statistics.median(walls["off"]), 4),
        "digest_on_wall_s": round(statistics.median(walls["on"]), 4),
        "overhead_pct": round(overhead, 2),
        "gate_pct": 3.0,
        "pass": overhead <= 3.0,
    }


class _OpenLoopNode:
    """Node fake feeding serve() a pre-scheduled open-loop arrival
    trace: recv() releases an event once its arrival time has passed —
    the ARRIVALS don't slow down when the engine backs up, which is the
    property that makes overload visible (a closed loop self-throttles
    and hides it)."""

    def __init__(self, schedule):
        #: [(t_offset_s, event), ...] sorted by offset
        self._schedule = list(schedule)
        self._t0 = time.perf_counter()
        self.stream_ended = False
        self.sent: list[tuple[float, dict]] = []

    def now(self) -> float:
        return time.perf_counter() - self._t0

    def recv(self, timeout=None):
        if not self._schedule:
            self.stream_ended = True
            return None
        if self.now() >= self._schedule[0][0]:
            return self._schedule.pop(0)[1]
        return None

    def send_output(self, output_id, value, metadata=None):
        self.sent.append((self.now(), dict(metadata or {})))

    def report_serving(self, snapshot):
        pass

    def close(self):
        pass


def _qos_soak() -> dict:
    """Mixed-class Poisson overload soak behind ``--qos-soak`` (see
    module docstring). Identical seeded arrival trace both legs; the
    off leg drops the class tags and the shaping env — the pre-QoS
    single-class FIFO."""
    import numpy as np

    from dora_tpu.metrics import ServingMetrics
    from dora_tpu.models.batch_engine import make_stub_paged_engine
    from dora_tpu.nodehub.llm_server import serve

    streams = int(os.environ.get("DORA_BENCH_QOS_STREAMS", "1200"))
    max_new, tick_sleep = 8, 0.0008
    # One prefill chunk per step bounds admission to ~1/window_wall
    # streams/s; the arrival rate doubles it — a sustained overload.
    rate = 2.0 / (4 * tick_sleep)
    rng = np.random.default_rng(7)
    gaps = rng.exponential(1.0 / rate, size=streams)
    classes = rng.choice(
        ["interactive", "standard", "batch"], size=streams,
        p=[0.25, 0.35, 0.40],
    )
    arrivals = []
    t = 0.0
    for n in range(streams):
        t += float(gaps[n])
        arrivals.append((t, f"q{n}", str(classes[n])))

    qos_env = {
        "DORA_QOS_PREEMPT": "1",
        "DORA_QOS_SHED_WAIT_MS": "1500",
        "DORA_QOS_DEPTH_BATCH": "256",
    }

    def leg(shaped: bool) -> dict:
        saved = {k: os.environ.pop(k, None) for k in qos_env}
        if shaped:
            os.environ.update(qos_env)
        try:
            engine = make_stub_paged_engine(
                max_slots=8, max_seq=64, page_size=8, chunk=16,
                window=4, tick_sleep_s=tick_sleep,
            )
            schedule = [
                (at, {
                    "type": "INPUT",
                    "metadata": {
                        "request_id": rid,
                        "max_new_tokens": max_new,
                        **({"qos_class": cls} if shaped else {}),
                    },
                    "value": f"prompt {rid}".encode(),
                })
                for at, rid, cls in arrivals
            ]
            node = _OpenLoopNode(schedule)
            metrics = ServingMetrics(engine="paged")
            t0 = time.perf_counter()
            serve(
                node, engine, metrics,
                encode=lambda text: [ord(ch) % 97 + 1 for ch in text],
                decode_one=lambda tok: f" t{tok}",
                max_new_cap=max_new,
            )
            wall = time.perf_counter() - t0
            by_rid: dict[str, dict] = {}
            for ts, meta in node.sent:
                rid = meta.get("request_id")
                if rid is None:
                    continue
                s = by_rid.setdefault(rid, {"t0": ts, "finish": None})
                if meta.get("done"):
                    s["finish"] = meta.get("finish")
            ttft: dict[str, list[float]] = {
                "interactive": [], "standard": [], "batch": []
            }
            finishes: dict[str, int] = {}
            for at, rid, cls in arrivals:
                s = by_rid.get(rid)
                assert s is not None and s["finish"], (
                    f"stream {rid} silently lost"
                )
                finishes[s["finish"]] = finishes.get(s["finish"], 0) + 1
                if s["finish"] in ("stop", "length"):
                    ttft[cls].append(s["t0"] - at)

            def pct(vals, q):
                if not vals:
                    return None
                o = sorted(vals)
                return round(
                    o[min(len(o) - 1, int(len(o) * q))] * 1e3, 1
                )

            return {
                "wall_s": round(wall, 2),
                "finishes": finishes,
                "shed": metrics.shed,
                "preempted": metrics.preempted,
                "resumed": metrics.resumed,
                "ttft_ms": {
                    cls: {
                        "n": len(vals),
                        "p50": pct(vals, 0.50),
                        "p99": pct(vals, 0.99),
                    }
                    for cls, vals in ttft.items()
                },
            }
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    on, off = leg(shaped=True), leg(shaped=False)
    # Off leg is single-class: slice its TTFTs by the class the SAME
    # rid carried in the on leg — the A/B compares the same requests.
    p99_on = on["ttft_ms"]["interactive"]["p99"]
    p99_off = off["ttft_ms"]["interactive"]["p99"]
    return {
        "streams": streams,
        "arrival_rate_per_s": round(rate, 1),
        "max_new": max_new,
        "qos_on": on,
        "qos_off": off,
        "interactive_p99_on_vs_off": (
            round(p99_on / p99_off, 3)
            if p99_on is not None and p99_off
            else None
        ),
    }


def _prefix_ab() -> dict:
    """Shared-prefix cache A/B behind ``--prefix-ab``: a Zipf-popular
    template workload (few hot system prompts, many unique tails — the
    multi-tenant serving shape the radix cache targets) replayed
    open-loop against the stub paged engine twice, cache on vs off,
    same seeded arrival trace.

    ``chunk_sleep_s`` gives each prefill chunk a measurable device
    cost, so TTFT is proportional to chunks actually run — a cache hit
    skips the shared-prefix chunks and the A/B shows up in first-token
    latency, not just counters. The headline gate compares TTFT p50 of
    the on-leg's HIT requests against the SAME request ids in the off
    leg (>= 2x reduction justifies default-on); hit rate, prefill-chunk
    deltas, pool occupancy, and eviction counts ride along."""
    import numpy as np

    from dora_tpu.metrics import ServingMetrics
    from dora_tpu.models.batch_engine import make_stub_paged_engine
    from dora_tpu.nodehub.llm_server import serve

    streams = int(os.environ.get("DORA_BENCH_PREFIX_STREAMS", "120"))
    templates, prefix_len, tail_len = 8, 64, 8
    max_new, chunk_sleep = 8, 0.002
    rng = np.random.default_rng(11)
    # Zipf(1.2) popularity over the template set: template 0 dominates,
    # the tail templates are cold — hits concentrate where reuse does.
    weights = 1.0 / np.arange(1, templates + 1) ** 1.2
    weights /= weights.sum()
    picks = rng.choice(templates, size=streams, p=weights)
    # Light open-loop load: TTFT is dominated by the prefill the
    # request actually runs, not by backlog wait, so the A/B reads as
    # chunks-skipped, not queueing theory.
    gaps = rng.exponential(0.015, size=streams)
    tmpl_ids = [
        [int(t) for t in rng.integers(1, 90, size=prefix_len)]
        for _ in range(templates)
    ]
    arrivals = []
    t = 0.0
    for n in range(streams):
        t += float(gaps[n])
        tail = [int(x) for x in rng.integers(1, 90, size=tail_len)]
        arrivals.append((t, f"p{n}", tmpl_ids[picks[n]] + tail))

    def leg(cache: bool) -> dict:
        engine = make_stub_paged_engine(
            max_slots=8, max_seq=128, page_size=8, chunk=16,
            window=4, chunk_sleep_s=chunk_sleep,
            prefix_cache=cache,
        )
        hit_rids: set[str] = set()
        pc = engine.prefix_cache
        if pc is not None:
            # serve() renames streams req-N; recover the trace's rid by
            # prompt identity (tails are unique by construction).
            rid_by_prompt = {tuple(ids): rid for _at, rid, ids in arrivals}
            orig_submit = engine.submit

            def submit(key, ids, max_new):
                h0 = pc.hits
                res = orig_submit(key, ids, max_new)
                if pc.hits > h0:
                    hit_rids.add(rid_by_prompt[tuple(ids)])
                return res

            engine.submit = submit
        schedule = [
            (at, {
                "type": "INPUT",
                "metadata": {
                    "request_id": rid,
                    "max_new_tokens": max_new,
                },
                "value": " ".join(str(t) for t in ids).encode(),
            })
            for at, rid, ids in arrivals
        ]
        node = _OpenLoopNode(schedule)
        metrics = ServingMetrics(engine="paged")
        c0 = engine.chunks_run
        t0 = time.perf_counter()
        serve(
            node, engine, metrics,
            encode=lambda text: [int(t) for t in text.split()],
            decode_one=lambda tok: f" t{tok}",
            max_new_cap=max_new,
        )
        wall = time.perf_counter() - t0
        ttft_by_rid: dict[str, float] = {}
        for ts, meta in node.sent:
            rid = meta.get("request_id")
            if rid is not None and rid not in ttft_by_rid:
                ttft_by_rid[rid] = ts
        ttfts = {}
        for at, rid, _ids in arrivals:
            assert rid in ttft_by_rid, f"stream {rid} silently lost"
            ttfts[rid] = ttft_by_rid[rid] - at
        out = {
            "wall_s": round(wall, 2),
            "prefill_chunks": engine.chunks_run - c0,
            "peak_used_pages": engine.allocator.peak_in_use,
            "total_pages": engine.allocator.num_pages,
            "ttfts": ttfts,
            "hit_rids": sorted(hit_rids),
        }
        if pc is not None:
            out["cache"] = pc.stats()
        return out

    def pct(vals, q):
        if not vals:
            return None
        o = sorted(vals)
        return round(o[min(len(o) - 1, int(len(o) * q))] * 1e3, 2)

    on, off = leg(cache=True), leg(cache=False)
    hit_rids = set(on["hit_rids"])
    hit_on = [v for r, v in on["ttfts"].items() if r in hit_rids]
    hit_off = [v for r, v in off["ttfts"].items() if r in hit_rids]
    all_on = list(on["ttfts"].values())
    all_off = list(off["ttfts"].values())
    for legd in (on, off):  # raw per-rid map served its purpose
        del legd["ttfts"], legd["hit_rids"]
    cache = on.get("cache", {})
    lookups = cache.get("hits", 0) + cache.get("misses", 0)
    p50_on, p50_off = pct(hit_on, 0.50), pct(hit_off, 0.50)
    return {
        "streams": streams,
        "templates": templates,
        "prefix_len": prefix_len,
        "tail_len": tail_len,
        "hit_rate": round(cache.get("hits", 0) / lookups, 3) if lookups else None,
        "hit_requests": len(hit_rids),
        "cache_on": on,
        "cache_off": off,
        "ttft_ms": {
            "hit_on": {"p50": p50_on, "p99": pct(hit_on, 0.99)},
            "hit_rids_off": {"p50": p50_off, "p99": pct(hit_off, 0.99)},
            "all_on": {"p50": pct(all_on, 0.50), "p99": pct(all_on, 0.99)},
            "all_off": {"p50": pct(all_off, 0.50), "p99": pct(all_off, 0.99)},
        },
        # The default-on gate: hit-request TTFT p50, cache on vs the
        # same requests cache off. <= 0.5 means >= 2x faster.
        "hit_p50_on_vs_off": (
            round(p50_on / p50_off, 3) if p50_on is not None and p50_off
            else None
        ),
    }


def main() -> int:
    import numpy as np

    from dora_tpu.models.hf import qwen2

    if "--prefix-ab" in sys.argv[1:]:
        # Stub-engine leg: the cache lives in the admission plane; the
        # A/B measures chunks skipped, not model quality.
        print(json.dumps({"prefix_ab": _prefix_ab()}))
        return 0
    if "--qos-soak" in sys.argv[1:]:
        # Stub-engine leg: the QoS machinery is engine-agnostic, the
        # soak measures the ADMISSION plane, not the model.
        print(json.dumps({"qos_soak": _qos_soak()}))
        return 0
    if "--spec-ab" in sys.argv[1:]:
        # Stub-engine leg: no checkpoint needed, acceptance is shaped
        # by the token rule, not model weights.
        print(json.dumps({"spec_ab": _spec_ab()}))
        return 0
    if "--lora-ab" in sys.argv[1:]:
        # Stub-engine leg: the claim is dispatch amortization across
        # tenants plus zero-compile churn — scheduler properties,
        # independent of model weights.
        print(json.dumps({"lora_ab": _lora_ab()}))
        return 0
    if "--profiling-ab" in sys.argv[1:]:
        # Stub-engine leg: the monitor's cost is per-window host work
        # (block_until_ready + counter math), independent of weights.
        print(json.dumps({"profiling_ab": _profiling_ab()}))
        return 0
    if "--fleet-digest-ab" in sys.argv[1:]:
        # Stub-engine leg: digest cost is host-side scheduler reads
        # (radix walk, allocator counters), independent of weights.
        print(json.dumps({"fleet_digest_ab": _fleet_digest_ab()}))
        return 0
    path = os.environ.get("DORA_HF_CHECKPOINT")
    real = bool(path)
    tmp = None
    if not real:
        tmp = tempfile.mkdtemp(prefix="bench-serving-")
        path = _tiny_checkpoint(tmp)
    if "--multistep" in sys.argv[1:]:
        print(json.dumps({"multistep": _multistep_sweep(qwen2, path, real)}))
        return 0
    if "--trace-ab" in sys.argv[1:]:
        print(json.dumps({"trace_ab": _trace_ab(qwen2, path, real)}))
        return 0
    if "--quant-ab" in sys.argv[1:]:
        print(json.dumps({"quant_ab": _quant_ab(qwen2, path, real)}))
        return 0
    # Workload scales with the model: the real box gets 64-token prompts
    # and 32 new tokens inside the default (dense-4-footprint) pool; the
    # tiny CPU smoke shrinks everything to stay admissible at 16 streams
    # within the same footprint rule.
    if real:
        max_seq = int(os.environ.get("DORA_MAX_SEQ", "512"))
        page_size, chunk, plen, max_new = 16, 64, 64, 32
    else:
        max_seq, page_size, chunk, plen, max_new = 64, 8, 8, 4, 4

    cfg, params = qwen2.load(path, max_seq=max_seq)
    os.environ.setdefault("DORA_INT8_DECODE", "1")
    params = qwen2.quantize_decode(params, cfg)
    rng = np.random.default_rng(0)

    def prompts(n: int) -> list[list[int]]:
        return [
            rng.integers(0, cfg.vocab, size=plen).tolist() for _ in range(n)
        ]

    import jax

    out: dict = {
        "backend": jax.default_backend(),
        "model": "checkpoint" if real else "tiny-random",
        "plen": plen,
        "max_new": max_new,
    }

    dense4 = qwen2.make_batch_engine(params, cfg, max_slots=4)
    paged4 = qwen2.make_paged_engine(
        params, cfg, max_slots=4, page_size=page_size, chunk=chunk
    )
    paged16 = qwen2.make_paged_engine(
        params, cfg, max_slots=16, page_size=page_size, chunk=chunk
    )

    # Warmup: run each engine through the full workload shape once so
    # the measured round holds zero compiles (the paged engine's
    # steady-state guarantee; the dense engine compiles its buckets).
    _serve(dense4, prompts(4), max_new)
    _serve(paged4, prompts(4), max_new)
    _serve(paged16, prompts(16), max_new)

    p4 = _stats(*_serve(paged4, prompts(4), max_new))
    d4 = _stats(*_serve(dense4, prompts(4), max_new))
    out["streams4"] = {
        "paged": p4,
        "dense": d4,
        "paged_vs_dense": (
            round(p4["decode_tok_s"] / d4["decode_tok_s"], 3)
            if p4["decode_tok_s"] and d4["decode_tok_s"]
            else None
        ),
    }

    p16 = _stats(*_serve(paged16, prompts(16), max_new))
    d16 = _stats(*_serve(dense4, prompts(16), max_new))
    pool_bytes = sum(x.nbytes for x in jax.tree.leaves(paged16.pools))
    dense_bytes = sum(
        x.nbytes for x in jax.tree.leaves(qwen2.init_cache(cfg, 4))
    )
    out["streams16"] = {
        "paged_16slot": p16,
        "dense_4slot_queued": d16,
        "paged_pool_bytes": pool_bytes,
        "dense_4slot_cache_bytes": dense_bytes,
        "wall_speedup": (
            round(d16["wall_s"] / p16["wall_s"], 2)
            if p16["wall_s"] and d16["wall_s"]
            else None
        ),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
