"""Coordinator implementation.

Reference parity: binaries/coordinator/src/{lib,run/mod,control,listener,
log_subscriber}.rs. Heartbeat constants match the reference
(coordinator→daemon 3 s, warn >15 s, drop >30 s; lib.rs:134,566-600).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any

from dora_tpu import PROTOCOL_VERSION
from dora_tpu.clock import HLC
from dora_tpu.core.descriptor import Descriptor, new_dataflow_uuid
from dora_tpu.message import coordinator as cm
from dora_tpu.message.common import (
    DataflowResult,
    LogMessage,
    NodeResult,
    log_level_at_least,
)
from dora_tpu.message.serde import decode_timestamped, encode_timestamped
from dora_tpu.transport.framing import (
    ConnectionClosed,
    recv_frame_async,
    send_frame_async,
)

logger = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_S = 3.0
HEARTBEAT_WARN_S = 15.0
HEARTBEAT_DROP_S = 30.0


@dataclass
class DaemonHandle:
    machine_id: str
    outbox: asyncio.Queue
    listen_addr: str  # inter-daemon data address "host:port"
    last_heartbeat: float = field(default_factory=time.monotonic)
    connected: bool = True
    #: the register connection's StreamWriter (tests force-drop it to
    #: exercise the daemon's reconnect path)
    writer: Any = None


@dataclass
class RunningDataflow:
    uuid: str
    name: str | None
    descriptor: Descriptor
    machines: set[str]
    pending_machines: set[str]  # not yet ReadyOnMachine
    exited_before_subscribe: list[str] = field(default_factory=list)
    finished_machines: set[str] = field(default_factory=set)
    node_results: dict[str, NodeResult] = field(default_factory=dict)
    #: futures resolved with the final DataflowResult (CLI stop/attach waits)
    finish_waiters: list[asyncio.Future] = field(default_factory=list)
    spawn_errors: list[str] = field(default_factory=list)


@dataclass
class LogSubscriber:
    dataflow_id: str
    level: str
    writer: asyncio.StreamWriter


class Coordinator:
    """One coordinator per cluster."""

    def __init__(self):
        self.clock = HLC()
        self.daemons: dict[str, DaemonHandle] = {}
        self.running: dict[str, RunningDataflow] = {}
        self.archived: dict[str, tuple[RunningDataflow, DataflowResult]] = {}
        self.log_subscribers: list[LogSubscriber] = []
        self._daemon_server: asyncio.AbstractServer | None = None
        self._control_server: asyncio.AbstractServer | None = None
        self.daemon_port: int | None = None
        self.control_port: int | None = None
        self._heartbeat_task: asyncio.Task | None = None
        self._destroyed = asyncio.Event()
        #: correlation for log-file requests: (dataflow_id, node_id) -> future
        self._log_waiters: dict[tuple[str, str], asyncio.Future] = {}
        #: correlation for metrics requests: (dataflow_id, machine) -> future
        self._metrics_waiters: dict[tuple[str, str], asyncio.Future] = {}
        self._trace_waiters: dict[tuple[str, str], asyncio.Future] = {}
        self._history_waiters: dict[tuple[str, str], asyncio.Future] = {}
        self._alerts_waiters: dict[tuple[str, str], asyncio.Future] = {}
        self._fleet_waiters: dict[tuple[str, str], asyncio.Future] = {}
        #: correlation for deep-capture requests: (dataflow_id, node_id)
        #: -> future resolved by ProfileReplyFromDaemon
        self._profile_waiters: dict[tuple[str, str], asyncio.Future] = {}
        #: Prometheus exposition endpoint (DORA_PROM_PORT)
        self._prom_server: asyncio.AbstractServer | None = None
        self.prom_port: int | None = None
        self._otlp_task: asyncio.Task | None = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self, daemon_port: int = 0, control_port: int = 0) -> None:
        self._daemon_server = await asyncio.start_server(
            self._handle_daemon, host="0.0.0.0", port=daemon_port
        )
        self.daemon_port = self._daemon_server.sockets[0].getsockname()[1]
        self._control_server = await asyncio.start_server(
            self._handle_control, host="0.0.0.0", port=control_port
        )
        self.control_port = self._control_server.sockets[0].getsockname()[1]
        self._heartbeat_task = asyncio.create_task(self._heartbeat_loop())
        # Prometheus text exposition (DORA_PROM_PORT; empty = off, 0 = a
        # free port, surfaced as self.prom_port).
        prom_port = os.environ.get("DORA_PROM_PORT", "")
        if prom_port != "":
            self._prom_server = await asyncio.start_server(
                self._handle_prom_scrape, host="0.0.0.0", port=int(prom_port)
            )
            self.prom_port = self._prom_server.sockets[0].getsockname()[1]
        # OTLP push (same endpoint resolution as tracing; no-op without
        # the otel metrics SDK or an endpoint).
        from dora_tpu.telemetry import init_cluster_metrics_export

        self._otlp_task = init_cluster_metrics_export(
            "dora-coordinator", self.prom_snapshots
        )

    async def close(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
        if self._otlp_task is not None:
            self._otlp_task.cancel()
        for server in (self._daemon_server, self._control_server,
                       self._prom_server):
            if server is not None:
                server.close()
                try:
                    await server.wait_closed()
                except Exception:
                    pass

    async def wait_destroyed(self) -> None:
        await self._destroyed.wait()

    # ------------------------------------------------------------------
    # daemon connections (register port)
    # ------------------------------------------------------------------

    async def _handle_daemon(self, reader, writer) -> None:
        machine_id: str | None = None
        handle: DaemonHandle | None = None
        try:
            frame = await recv_frame_async(reader)
            msg = decode_timestamped(frame, self.clock).inner
            if not isinstance(msg, cm.RegisterDaemon):
                await self._send(writer, cm.RegisterDaemonReply(error="expected RegisterDaemon"))
                return
            error = None
            ours = PROTOCOL_VERSION.split(".")[:2]
            if msg.protocol_version.split(".")[:2] != ours:
                error = (
                    f"incompatible protocol {msg.protocol_version} "
                    f"(coordinator speaks {PROTOCOL_VERSION})"
                )
            elif msg.machine_id in self.daemons and self.daemons[msg.machine_id].connected:
                # Re-register replaces the existing (likely half-open)
                # connection: a daemon only reconnects after losing its
                # side, and the heartbeat watchdog may not have noticed
                # yet. Last registration wins.
                logger.warning(
                    "machine %r re-registered; replacing stale connection",
                    msg.machine_id,
                )
                stale = self.daemons[msg.machine_id]
                stale.connected = False
                if stale.writer is not None:
                    try:
                        stale.writer.close()
                    except Exception:
                        pass
            await self._send(writer, cm.RegisterDaemonReply(error=error))
            if error:
                return
            machine_id = msg.machine_id
            peer_host = writer.get_extra_info("peername")[0]
            handle = DaemonHandle(
                machine_id=machine_id,
                outbox=asyncio.Queue(),
                listen_addr=f"{peer_host}:{msg.listen_port}",
                writer=writer,
            )
            self.daemons[machine_id] = handle
            logger.info("daemon %r registered (data %s)", machine_id, handle.listen_addr)
            sender = asyncio.create_task(self._daemon_sender(handle, writer))
            try:
                while True:
                    frame = await recv_frame_async(reader)
                    event = decode_timestamped(frame, self.clock).inner
                    self._handle_daemon_event(handle, event)
            finally:
                sender.cancel()
        except (ConnectionClosed, ConnectionError):
            pass
        except Exception:
            logger.exception("daemon connection failed")
        finally:
            # Identity check: if the daemon already re-registered, the
            # machine id maps to a FRESH handle — marking disconnected by
            # id alone would clobber the live re-registration.
            if handle is not None and self.daemons.get(machine_id) is handle:
                handle.connected = False
            try:
                writer.close()
            except Exception:
                pass

    async def _daemon_sender(self, handle: DaemonHandle, writer) -> None:
        try:
            while True:
                msg = await handle.outbox.get()
                await self._send(writer, msg)
        except (asyncio.CancelledError, ConnectionError, ConnectionClosed):
            pass

    async def _send(self, writer, msg: Any) -> None:
        await send_frame_async(writer, encode_timestamped(msg, self.clock))

    def _daemon_send(self, machine_id: str, msg: Any) -> None:
        handle = self.daemons.get(machine_id)
        if handle is not None and handle.connected:
            handle.outbox.put_nowait(msg)

    def _handle_daemon_event(self, handle: DaemonHandle, event: Any) -> None:
        handle.last_heartbeat = time.monotonic()
        if isinstance(event, cm.DaemonHeartbeat):
            return
        if isinstance(event, cm.ReadyOnMachine):
            self._machine_ready(handle.machine_id, event)
        elif isinstance(event, cm.AllNodesFinished):
            self._machine_finished(handle.machine_id, event)
        elif isinstance(event, cm.SpawnDataflowResult):
            df = self.running.get(event.dataflow_id)
            if df is not None and event.error:
                df.spawn_errors.append(f"{handle.machine_id}: {event.error}")
        elif isinstance(event, cm.DaemonLog):
            self._publish_log(event.log)
        elif isinstance(event, cm.LogsReplyFromDaemon):
            self.deliver_logs_reply(event.dataflow_id, event.node_id, event.logs)
        elif isinstance(event, cm.MetricsReplyFromDaemon):
            fut = self._metrics_waiters.get((event.dataflow_id, event.machine_id))
            if fut is not None and not fut.done():
                fut.set_result(event.metrics)
        elif isinstance(event, cm.TraceReplyFromDaemon):
            fut = self._trace_waiters.get((event.dataflow_id, event.machine_id))
            if fut is not None and not fut.done():
                fut.set_result(event.trace)
        elif isinstance(event, cm.MetricsHistoryReplyFromDaemon):
            fut = self._history_waiters.get(
                (event.dataflow_id, event.machine_id)
            )
            if fut is not None and not fut.done():
                fut.set_result(event.history)
        elif isinstance(event, cm.AlertsReplyFromDaemon):
            fut = self._alerts_waiters.get(
                (event.dataflow_id, event.machine_id)
            )
            if fut is not None and not fut.done():
                fut.set_result(event.alerts)
        elif isinstance(event, cm.FleetReplyFromDaemon):
            fut = self._fleet_waiters.get(
                (event.dataflow_id, event.machine_id)
            )
            if fut is not None and not fut.done():
                fut.set_result(event.fleet)
        elif isinstance(event, cm.ProfileReplyFromDaemon):
            fut = self._profile_waiters.get(
                (event.dataflow_id, event.node_id)
            )
            if fut is not None and not fut.done():
                fut.set_result((event.artifact, event.error))
        else:
            logger.warning("unexpected daemon event %s", type(event).__name__)

    # ------------------------------------------------------------------
    # dataflow lifecycle
    # ------------------------------------------------------------------

    def _machine_ready(self, machine_id: str, event: cm.ReadyOnMachine) -> None:
        df = self.running.get(event.dataflow_id)
        if df is None:
            return
        df.pending_machines.discard(machine_id)
        df.exited_before_subscribe.extend(event.exited_before_subscribe)
        if not df.pending_machines:
            for machine in df.machines:
                self._daemon_send(
                    machine,
                    cm.AllNodesReady(
                        dataflow_id=df.uuid,
                        exited_before_subscribe=df.exited_before_subscribe,
                    ),
                )

    def _machine_finished(self, machine_id: str, event: cm.AllNodesFinished) -> None:
        df = self.running.get(event.dataflow_id)
        if df is None:
            return
        df.finished_machines.add(machine_id)
        df.node_results.update(event.result.node_results)
        if df.finished_machines >= df.machines:
            result = DataflowResult(uuid=df.uuid, node_results=df.node_results)
            del self.running[df.uuid]
            self.archived[df.uuid] = (df, result)
            for fut in df.finish_waiters:
                if not fut.done():
                    fut.set_result(result)
            df.finish_waiters.clear()

    async def start_dataflow(
        self,
        raw_descriptor: dict,
        name: str | None,
        local_working_dir: str | None,
    ) -> str:
        """Validate, partition by machine, and spawn on every daemon
        (reference: run/mod.rs:22-111)."""
        descriptor = Descriptor.parse(raw_descriptor)
        descriptor.check(local_working_dir)
        if name is not None:
            for df in self.running.values():
                if df.name == name:
                    raise ValueError(f"a dataflow named {name!r} is already running")

        machines = {n.deploy.machine or "" for n in descriptor.nodes}
        default_machine = ""
        if "" in machines and "" not in self.daemons:
            # Single registered daemon serves machine-less nodes.
            connected = [m for m, h in self.daemons.items() if h.connected]
            if len(connected) == 1:
                default_machine = connected[0]
                machines = {default_machine if m == "" else m for m in machines}
            else:
                raise ValueError(
                    "dataflow has nodes without deploy.machine but "
                    f"{len(connected)} daemons are connected"
                )
        missing = [m for m in machines if m not in self.daemons or not self.daemons[m].connected]
        if missing:
            raise ValueError(f"no daemon connected for machine(s) {missing}")

        uuid = new_dataflow_uuid()
        df = RunningDataflow(
            uuid=uuid,
            name=name,
            descriptor=descriptor,
            machines=set(machines),
            pending_machines=set(machines),
        )
        self.running[uuid] = df

        listen_ports = {
            m: self.daemons[m].listen_addr for m in machines
        }
        for machine in machines:
            local_nodes = [
                str(n.id)
                for n in descriptor.nodes
                if (n.deploy.machine or default_machine) == machine
            ]
            spawn_nodes = [
                nid
                for nid in local_nodes
                if not _is_dynamic(descriptor, nid)
            ]
            self._daemon_send(
                machine,
                cm.SpawnDataflowNodes(
                    dataflow_id=uuid,
                    working_dir=local_working_dir or ".",
                    nodes=local_nodes,
                    dataflow_descriptor=dict(raw_descriptor),
                    spawn_nodes=spawn_nodes,
                    machine_listen_ports=listen_ports,
                ),
            )
        return uuid

    def stop_dataflow(self, uuid: str, grace_s: float | None) -> asyncio.Future:
        """Send StopDataflow to every involved daemon; the returned future
        resolves with the final DataflowResult (deferred reply, reference:
        coordinator/src/lib.rs:283-301)."""
        df = self.running.get(uuid)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if df is None:
            if uuid in self.archived:
                fut.set_result(self.archived[uuid][1])
            else:
                fut.set_exception(KeyError(f"no running dataflow {uuid!r}"))
            return fut
        df.finish_waiters.append(fut)
        for machine in df.machines:
            self._daemon_send(
                machine, cm.StopDataflow(dataflow_id=uuid, grace_duration_s=grace_s)
            )
        return fut

    def _query_target(self, dataflow_uuid: str | None, name: str | None):
        """Shared target resolution for QueryMetrics/QueryTrace: explicit
        uuid/name wins; otherwise the single running dataflow, else the
        single archived one. Returns a uuid or a ready-to-send Error."""
        target = dataflow_uuid or name
        if target is not None:
            return self.resolve_name(target)
        if len(self.running) == 1:
            return next(iter(self.running))
        if self.running:
            return cm.Error(
                message="multiple dataflows running; pass --uuid or --name"
            )
        if len(self.archived) == 1:
            return next(iter(self.archived))
        return cm.Error(message="no dataflow running")

    def resolve_name(self, name_or_uuid: str) -> str:
        """uuid | unique name -> uuid (reference: lib.rs:90-122)."""
        if name_or_uuid in self.running or name_or_uuid in self.archived:
            return name_or_uuid
        matches = [u for u, df in self.running.items() if df.name == name_or_uuid]
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            raise KeyError(f"multiple running dataflows named {name_or_uuid!r}")
        # Finished dataflows stay addressable by name: logs and metrics
        # are explicitly queryable after completion (most recent wins —
        # archived insertion order is completion order).
        archived = [
            u for u, (df, _) in self.archived.items() if df.name == name_or_uuid
        ]
        if archived:
            return archived[-1]
        raise KeyError(f"no dataflow named {name_or_uuid!r}")

    async def request_logs(self, uuid: str, node_id: str) -> bytes:
        df = self.running.get(uuid)
        if df is None and uuid in self.archived:
            df = self.archived[uuid][0]
        if df is None:
            raise KeyError(f"unknown dataflow {uuid!r}")
        node = df.descriptor.node(node_id)
        machine = node.deploy.machine or next(iter(df.machines))
        fut = asyncio.get_running_loop().create_future()
        self._log_waiters[(uuid, node_id)] = fut
        self._daemon_send(machine, cm.LogsRequest(dataflow_id=uuid, node_id=node_id))
        try:
            return await asyncio.wait_for(fut, timeout=10)
        finally:
            self._log_waiters.pop((uuid, node_id), None)

    def deliver_logs_reply(self, uuid: str, node_id: str, logs: bytes) -> None:
        fut = self._log_waiters.get((uuid, node_id))
        if fut is not None and not fut.done():
            fut.set_result(logs)

    async def request_metrics(self, uuid: str) -> dict:
        """Fan a MetricsRequest out to every involved daemon and merge the
        per-machine snapshots (dora_tpu.metrics.merge_snapshots). Works for
        archived dataflows too — daemons keep finished dataflow state."""
        from dora_tpu.metrics import merge_snapshots

        df = self.running.get(uuid)
        if df is None and uuid in self.archived:
            df = self.archived[uuid][0]
        if df is None:
            raise KeyError(f"unknown dataflow {uuid!r}")
        loop = asyncio.get_running_loop()
        futs = []
        for machine in sorted(df.machines):
            fut = loop.create_future()
            self._metrics_waiters[(uuid, machine)] = fut
            self._daemon_send(machine, cm.MetricsRequest(dataflow_id=uuid))
            futs.append(fut)
        try:
            snapshots = await asyncio.wait_for(
                asyncio.gather(*futs, return_exceptions=True), timeout=10
            )
        finally:
            for machine in df.machines:
                self._metrics_waiters.pop((uuid, machine), None)
        return merge_snapshots([s for s in snapshots if isinstance(s, dict)])

    async def request_metrics_history(self, uuid: str) -> dict:
        """Fan a MetricsHistoryRequest out to every involved daemon and
        merge the per-machine rings onto one clock-aligned timeline
        (dora_tpu.metrics_history.merge_history_snapshots). Works for
        archived dataflows too — daemons keep finished dataflow state,
        ring included."""
        from dora_tpu.metrics_history import merge_history_snapshots

        df = self.running.get(uuid)
        if df is None and uuid in self.archived:
            df = self.archived[uuid][0]
        if df is None:
            raise KeyError(f"unknown dataflow {uuid!r}")
        loop = asyncio.get_running_loop()
        futs = []
        for machine in sorted(df.machines):
            fut = loop.create_future()
            self._history_waiters[(uuid, machine)] = fut
            self._daemon_send(machine, cm.MetricsHistoryRequest(dataflow_id=uuid))
            futs.append(fut)
        try:
            snapshots = await asyncio.wait_for(
                asyncio.gather(*futs, return_exceptions=True), timeout=10
            )
        finally:
            for machine in df.machines:
                self._history_waiters.pop((uuid, machine), None)
        return merge_history_snapshots(
            [s for s in snapshots if isinstance(s, dict)]
        )

    async def request_alerts(self, uuid: str) -> dict:
        """Fan an AlertsRequest out to every involved daemon and union
        the per-machine alert statuses (dora_tpu.alerts.merge_alert_status
        — instances keep their machine-qualified keys, counters sum).
        Works for archived dataflows too — daemons keep finished dataflow
        state, alert engine included, so a post-mortem `dora-tpu alerts`
        still shows what fired."""
        from dora_tpu.alerts import merge_alert_status

        df = self.running.get(uuid)
        if df is None and uuid in self.archived:
            df = self.archived[uuid][0]
        if df is None:
            raise KeyError(f"unknown dataflow {uuid!r}")
        loop = asyncio.get_running_loop()
        futs = []
        for machine in sorted(df.machines):
            fut = loop.create_future()
            self._alerts_waiters[(uuid, machine)] = fut
            self._daemon_send(machine, cm.AlertsRequest(dataflow_id=uuid))
            futs.append(fut)
        try:
            statuses = await asyncio.wait_for(
                asyncio.gather(*futs, return_exceptions=True), timeout=10
            )
        finally:
            for machine in df.machines:
                self._alerts_waiters.pop((uuid, machine), None)
        return merge_alert_status(
            [s for s in statuses if isinstance(s, dict) and s]
        )

    async def request_fleet(self, uuid: str) -> dict:
        """Fan a FleetRequest out to every involved daemon and merge
        the per-machine digest snapshots into one clock-aligned fleet
        view (dora_tpu.fleet.merge_fleet_snapshots). Works for archived
        dataflows too — daemons keep finished dataflow state, last
        digests included, so a post-mortem `dora-tpu fleet` still shows
        the final replica states."""
        from dora_tpu.fleet import merge_fleet_snapshots

        df = self.running.get(uuid)
        if df is None and uuid in self.archived:
            df = self.archived[uuid][0]
        if df is None:
            raise KeyError(f"unknown dataflow {uuid!r}")
        loop = asyncio.get_running_loop()
        futs = []
        for machine in sorted(df.machines):
            fut = loop.create_future()
            self._fleet_waiters[(uuid, machine)] = fut
            self._daemon_send(machine, cm.FleetRequest(dataflow_id=uuid))
            futs.append(fut)
        try:
            snapshots = await asyncio.wait_for(
                asyncio.gather(*futs, return_exceptions=True), timeout=10
            )
        finally:
            for machine in df.machines:
                self._fleet_waiters.pop((uuid, machine), None)
        return merge_fleet_snapshots(
            [s for s in snapshots if isinstance(s, dict)]
        )

    async def request_trace(self, uuid: str) -> dict:
        """Fan a TraceRequest out to every involved daemon and merge the
        per-machine ring snapshots onto one clock-aligned timeline
        (dora_tpu.tracing.merge_trace_snapshots). Works for archived
        dataflows too — daemons keep finished dataflow state."""
        from dora_tpu.tracing import merge_trace_snapshots

        df = self.running.get(uuid)
        if df is None and uuid in self.archived:
            df = self.archived[uuid][0]
        if df is None:
            raise KeyError(f"unknown dataflow {uuid!r}")
        loop = asyncio.get_running_loop()
        futs = []
        for machine in sorted(df.machines):
            fut = loop.create_future()
            self._trace_waiters[(uuid, machine)] = fut
            self._daemon_send(machine, cm.TraceRequest(dataflow_id=uuid))
            futs.append(fut)
        try:
            snapshots = await asyncio.wait_for(
                asyncio.gather(*futs, return_exceptions=True), timeout=10
            )
        finally:
            for machine in df.machines:
                self._trace_waiters.pop((uuid, machine), None)
        return merge_trace_snapshots(
            [s for s in snapshots if isinstance(s, dict)]
        )

    # ------------------------------------------------------------------
    # Prometheus exposition (DORA_PROM_PORT) + OTLP push
    # ------------------------------------------------------------------

    async def prom_snapshots(self) -> dict[str, dict]:
        """Merged snapshots of every running + archived dataflow, keyed
        by exposition label (name when set, uuid otherwise). Archived
        dataflows whose daemons are gone time out quickly rather than
        wedging the scrape."""
        targets = [(u, df.name) for u, df in self.running.items()]
        targets += [
            (u, df.name)
            for u, (df, _) in self.archived.items()
            if u not in self.running
        ]
        out: dict[str, dict] = {}
        for uuid, name in targets:
            label = name or uuid
            if label in out:
                label = uuid  # name collision across runs: fall back
            try:
                out[label] = await asyncio.wait_for(
                    self.request_metrics(uuid), timeout=3
                )
            except Exception:
                continue
        return out

    async def _handle_prom_scrape(self, reader, writer) -> None:
        """Minimal HTTP/1.1 for `GET /metrics` — one endpoint, close
        after response; anything fancier belongs behind a real scraper."""
        from dora_tpu import prom

        try:
            request_line = await reader.readline()
            while True:  # drain headers
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request_line.decode("latin1").split()
            path = (parts[1].split("?")[0] if len(parts) > 1 else "/")
            if len(parts) > 1 and parts[0] == "GET" and path in ("/metrics", "/"):
                body = prom.render_exposition(await self.prom_snapshots())
                payload = body.encode()
                status = "200 OK"
                ctype = prom.CONTENT_TYPE
            else:
                payload = b"not found\n"
                status = "404 Not Found"
                ctype = "text/plain"
            writer.write(
                (
                    f"HTTP/1.1 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + payload
            )
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    # ------------------------------------------------------------------
    # log streaming
    # ------------------------------------------------------------------

    def _publish_log(self, log: LogMessage) -> None:
        dead = []
        for sub in self.log_subscribers:
            if sub.dataflow_id != log.dataflow_id:
                continue
            if not log_level_at_least(log.level, sub.level):
                continue
            try:
                asyncio.create_task(self._send(sub.writer, log))
            except Exception:
                dead.append(sub)
        for sub in dead:
            self.log_subscribers.remove(sub)

    # ------------------------------------------------------------------
    # heartbeat watchdog
    # ------------------------------------------------------------------

    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(HEARTBEAT_INTERVAL_S)
            now = time.monotonic()
            for machine, handle in list(self.daemons.items()):
                if not handle.connected:
                    continue
                silent = now - handle.last_heartbeat
                if silent > HEARTBEAT_DROP_S:
                    logger.error("daemon %r silent for %.0fs; dropping", machine, silent)
                    handle.connected = False
                    continue
                if silent > HEARTBEAT_WARN_S:
                    logger.warning("daemon %r silent for %.0fs", machine, silent)
                self._daemon_send(machine, cm.Heartbeat())

    # ------------------------------------------------------------------
    # control connections (CLI port)
    # ------------------------------------------------------------------

    async def _handle_control(self, reader, writer) -> None:
        try:
            while True:
                frame = await recv_frame_async(reader)
                request = decode_timestamped(frame, self.clock).inner
                if isinstance(request, cm.LogSubscribe):
                    # Connection becomes a push stream (control.rs:106-115).
                    self.log_subscribers.append(
                        LogSubscriber(
                            dataflow_id=request.dataflow_id,
                            level=request.level,
                            writer=writer,
                        )
                    )
                    return  # keep open; never reply
                reply = await self.handle_control_request(request)
                await self._send(writer, reply)
                if isinstance(reply, cm.DestroyOk):
                    return
        except (ConnectionClosed, ConnectionError):
            pass
        except Exception:
            logger.exception("control connection failed")
        finally:
            if not any(s.writer is writer for s in self.log_subscribers):
                try:
                    writer.close()
                except Exception:
                    pass

    async def handle_control_request(self, request: Any) -> Any:
        """The in-process control seam (also used by tests and the CLI's
        embedded mode)."""
        try:
            return await self._control_request_inner(request)
        except Exception as e:
            return cm.Error(message=str(e))

    async def _control_request_inner(self, request: Any) -> Any:
        if isinstance(request, cm.Start):
            uuid = await self.start_dataflow(
                request.dataflow, request.name, request.local_working_dir
            )
            return cm.DataflowStarted(uuid=uuid)
        if isinstance(request, cm.Check):
            df = self.running.get(request.dataflow_uuid)
            if df is not None:
                if df.spawn_errors:
                    return cm.Error(message="; ".join(df.spawn_errors))
                return cm.DataflowSpawnResult(uuid=df.uuid)
            if request.dataflow_uuid in self.archived:
                result = self.archived[request.dataflow_uuid][1]
                return cm.DataflowStopped(uuid=result.uuid, result=result)
            return cm.Error(message=f"unknown dataflow {request.dataflow_uuid!r}")
        if isinstance(request, (cm.StopRequest, cm.StopByName)):
            if isinstance(request, cm.StopByName):
                uuid = self.resolve_name(request.name)
            else:
                uuid = request.dataflow_uuid
            result = await self.stop_dataflow(uuid, request.grace_duration_s)
            return cm.DataflowStopped(uuid=uuid, result=result)
        if isinstance(request, cm.ReloadRequest):
            df = self.running.get(request.dataflow_id)
            if df is None:
                return cm.Error(message=f"unknown dataflow {request.dataflow_id!r}")
            node = df.descriptor.node(request.node_id)
            machine = node.deploy.machine or next(iter(df.machines))
            self._daemon_send(
                machine,
                cm.ReloadDataflow(
                    dataflow_id=df.uuid,
                    node_id=request.node_id,
                    operator_id=request.operator_id,
                ),
            )
            return cm.DataflowReloaded(uuid=df.uuid)
        if isinstance(request, cm.MigrateNode):
            target = request.dataflow_uuid or request.name
            if target is not None:
                uuid = self.resolve_name(target)
            else:
                uuid = self._query_target(None, None)
                if isinstance(uuid, cm.Error):
                    return uuid
            df = self.running.get(uuid)
            if df is None:
                return cm.Error(message=f"dataflow {uuid!r} is not running")
            node = df.descriptor.node(request.node_id)
            machine = node.deploy.machine or next(iter(df.machines))
            self._daemon_send(
                machine,
                cm.MigrateDataflowNode(
                    dataflow_id=df.uuid,
                    node_id=request.node_id,
                    handoff_dir=request.handoff_dir,
                ),
            )
            return cm.NodeMigrated(
                uuid=df.uuid,
                node_id=request.node_id,
                handoff_dir=request.handoff_dir,
            )
        if isinstance(request, (cm.StartProfile, cm.StopProfile)):
            target = request.dataflow_uuid or request.name
            if target is not None:
                uuid = self.resolve_name(target)
            else:
                uuid = self._query_target(None, None)
                if isinstance(uuid, cm.Error):
                    return uuid
            df = self.running.get(uuid)
            if df is None:
                return cm.Error(message=f"dataflow {uuid!r} is not running")
            node = df.descriptor.node(request.node_id)
            machine = node.deploy.machine or next(iter(df.machines))
            starting = isinstance(request, cm.StartProfile)
            seconds = request.seconds if starting else 0.0
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._profile_waiters[(df.uuid, request.node_id)] = fut
            self._daemon_send(
                machine,
                cm.ProfileDataflowNode(
                    dataflow_id=df.uuid,
                    node_id=request.node_id,
                    action="start" if starting else "stop",
                    seconds=seconds,
                ),
            )
            # The node runs the capture to its deadline before replying:
            # the start wait covers capture duration + report cadence;
            # stop only waits for the next report tick.
            timeout = seconds + 15.0 if starting else 10.0
            try:
                artifact, error = await asyncio.wait_for(fut, timeout=timeout)
            except asyncio.TimeoutError:
                return cm.Error(
                    message=f"profile reply from {request.node_id!r} "
                    f"timed out after {timeout:.0f}s"
                )
            finally:
                self._profile_waiters.pop((df.uuid, request.node_id), None)
            return cm.ProfileReply(
                uuid=df.uuid,
                node_id=request.node_id,
                artifact=artifact,
                error=error,
            )
        if isinstance(request, cm.Logs):
            uuid = self.resolve_name(request.uuid or request.name)
            logs = await self.request_logs(uuid, request.node)
            return cm.LogsReply(logs=logs)
        if isinstance(request, cm.QueryMetrics):
            uuid = self._query_target(request.dataflow_uuid, request.name)
            if isinstance(uuid, cm.Error):
                return uuid
            metrics = await self.request_metrics(uuid)
            return cm.MetricsReply(dataflow_uuid=uuid, metrics=metrics)
        if isinstance(request, cm.QueryMetricsHistory):
            uuid = self._query_target(request.dataflow_uuid, request.name)
            if isinstance(uuid, cm.Error):
                return uuid
            history = await self.request_metrics_history(uuid)
            return cm.MetricsHistoryReply(dataflow_uuid=uuid, history=history)
        if isinstance(request, cm.QueryAlerts):
            uuid = self._query_target(request.dataflow_uuid, request.name)
            if isinstance(uuid, cm.Error):
                return uuid
            alerts = await self.request_alerts(uuid)
            return cm.AlertsReply(dataflow_uuid=uuid, alerts=alerts)
        if isinstance(request, cm.QueryFleet):
            uuid = self._query_target(request.dataflow_uuid, request.name)
            if isinstance(uuid, cm.Error):
                return uuid
            fleet = await self.request_fleet(uuid)
            return cm.FleetReply(dataflow_uuid=uuid, fleet=fleet)
        if isinstance(request, cm.QueryTrace):
            uuid = self._query_target(request.dataflow_uuid, request.name)
            if isinstance(uuid, cm.Error):
                return uuid
            trace = await self.request_trace(uuid)
            return cm.TraceReply(dataflow_uuid=uuid, trace=trace)
        if isinstance(request, cm.ListDataflows):
            entries = [
                cm.DataflowListEntry(uuid=u, name=df.name)
                for u, df in self.running.items()
            ]
            return cm.DataflowList(dataflows=entries)
        if isinstance(request, cm.DaemonConnected):
            return cm.DaemonConnectedReply(
                connected=any(h.connected for h in self.daemons.values())
            )
        if isinstance(request, cm.ConnectedMachines):
            return cm.ConnectedMachinesReply(
                machines=sorted(m for m, h in self.daemons.items() if h.connected)
            )
        if isinstance(request, cm.Destroy):
            for uuid in list(self.running):
                try:
                    await self.stop_dataflow(uuid, None)
                except Exception:
                    pass
            for machine in list(self.daemons):
                self._daemon_send(machine, cm.DestroyDaemon())
            self._destroyed.set()
            return cm.DestroyOk()
        return cm.Error(message=f"unknown control request {type(request).__name__}")


def _is_dynamic(descriptor: Descriptor, node_id: str) -> bool:
    from dora_tpu.core.descriptor import CustomNode

    node = descriptor.node(node_id)
    return isinstance(node.kind, CustomNode) and node.kind.is_dynamic
