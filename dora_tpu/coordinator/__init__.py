"""The control-plane coordinator (one per cluster).

Reference parity: binaries/coordinator — daemon registry keyed by machine
id, dataflow lifecycle across machines (spawn partitioning, ReadyOnMachine
aggregation → AllNodesReady broadcast, finished-machine aggregation →
archive + deferred CLI replies), stop/reload/logs proxying, heartbeat
watchdog, per-dataflow log subscribers.

Testability seam kept from the reference (coordinator/src/lib.rs:42-46):
`Coordinator.handle_control_request` is directly callable in-process, so
integration tests drive the full lifecycle without sockets.
"""

from dora_tpu.coordinator.core import Coordinator

__all__ = ["Coordinator"]
