"""dora-tpu CLI entry point.

Reference parity: binaries/cli/src/main.rs (clap command tree), up.rs
(spawn/kill coordinator+daemon), attach.rs (poll + ctrl-c stop + log
stream), build.rs, check.rs, graph.rs, logs.rs, template/.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import yaml

from dora_tpu import __version__
from dora_tpu.core.topics import (
    DORA_COORDINATOR_PORT_CONTROL_DEFAULT,
    DORA_COORDINATOR_PORT_DEFAULT,
)
from dora_tpu.message import coordinator as cm

PID_DIR = Path(os.environ.get("DORA_TPU_STATE_DIR", "/tmp/dora-tpu"))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _read_descriptor(path: str):
    from dora_tpu.core.descriptor import Descriptor

    return Descriptor.read(path)


def _spawn_detached(args: list[str], log_name: str) -> int:
    PID_DIR.mkdir(parents=True, exist_ok=True)
    log = open(PID_DIR / f"{log_name}.log", "ab")
    process = subprocess.Popen(
        [sys.executable, "-m", "dora_tpu.cli.main"] + args,
        stdout=log,
        stderr=log,
        start_new_session=True,
    )
    (PID_DIR / f"{log_name}.pid").write_text(str(process.pid))
    return process.pid


def _kill_pidfile(log_name: str) -> bool:
    pidfile = PID_DIR / f"{log_name}.pid"
    if not pidfile.exists():
        return False
    try:
        os.kill(int(pidfile.read_text()), signal.SIGTERM)
        killed = True
    except (ProcessLookupError, ValueError):
        killed = False
    pidfile.unlink(missing_ok=True)
    return killed


def _control(args):
    from dora_tpu.cli.control import connect

    return connect(getattr(args, "coordinator_addr", None))


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------


def cmd_check(args) -> int:
    import json

    from dora_tpu.analysis import errors as _errors
    from dora_tpu.analysis.alertcheck import check_alerts
    from dora_tpu.analysis.graphcheck import check_descriptor

    descriptor = _read_descriptor(args.dataflow)
    findings = check_descriptor(descriptor, Path(args.dataflow).parent)
    findings += check_alerts(descriptor)
    if getattr(args, "json", False):
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        if not _errors(findings):
            print(f"{args.dataflow}: OK ({len(descriptor.nodes)} nodes)")
    return 1 if _errors(findings) else 0


def cmd_lint(args) -> int:
    """Run the static-analysis passes (``dora-tpu lint``).

    ``--self`` lints this installation's own package tree: jaxlint over
    the jit-heavy dirs, the env registry, serde/wire coverage, and the
    raw-``threading.Lock`` wiring check. With explicit paths, only
    jaxlint runs over those files/dirs.
    """
    import json

    from dora_tpu.analysis import errors as _errors
    from dora_tpu.analysis import jaxlint

    findings = []
    if args.paths:
        findings += jaxlint.lint_paths([Path(p) for p in args.paths])
    if args.self or not args.paths:
        import dora_tpu
        from dora_tpu.analysis import envreg, wirecheck
        from dora_tpu.analysis.lockcheck import lint_lock_wiring

        pkg_root = Path(dora_tpu.__file__).parent
        repo_root = pkg_root.parent
        findings += jaxlint.lint_self(pkg_root)
        findings += envreg.lint(pkg_root, repo_root / "README.md")
        findings += wirecheck.lint(repo_root)
        findings += lint_lock_wiring(pkg_root)
        # Default alert pack + sink env: a pack rule naming a renamed
        # series key is a bug in this repo, not in a user descriptor.
        from dora_tpu.analysis.alertcheck import check_alerts
        from dora_tpu.core.descriptor import Descriptor

        pack_holder = Descriptor.parse(
            {"nodes": [{"id": "_lint", "path": "noop.py"}]}
        )
        findings += check_alerts(pack_holder)
    if args.json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        errs = _errors(findings)
        warns = len(findings) - len(errs)
        print(f"lint: {len(errs)} error(s), {warns} warning(s)")
    return 1 if _errors(findings) else 0


def cmd_graph(args) -> int:
    descriptor = _read_descriptor(args.dataflow)
    mermaid = descriptor.visualize_as_mermaid()
    if args.mermaid:
        print(mermaid)
    else:
        html = (
            "<!doctype html><html><body><pre class='mermaid'>\n"
            + mermaid
            + "\n</pre><script type='module'>import mermaid from "
            "'https://cdn.jsdelivr.net/npm/mermaid@11/dist/mermaid.esm.min.mjs';"
            "mermaid.initialize({startOnLoad:true});</script></body></html>"
        )
        out = Path(args.dataflow).with_suffix(".html")
        out.write_text(html)
        print(f"wrote {out}")
    return 0


def cmd_schema(args) -> int:
    """Emit the dataflow JSON schema (reference: generate_schema.rs)."""
    import json

    from dora_tpu.core.schema import descriptor_schema, generate_schema

    if args.output:
        out = generate_schema(args.output)
        print(f"wrote {out}")
    else:
        print(json.dumps(descriptor_schema(), indent=2))
    return 0


def cmd_build(args) -> int:
    """Run each node's / operator's `build:` command (reference: build.rs)."""
    from dora_tpu.core.descriptor import CustomNode, RuntimeNode

    descriptor = _read_descriptor(args.dataflow)
    working_dir = Path(args.dataflow).resolve().parent
    for node in descriptor.nodes:
        builds = []
        if isinstance(node.kind, CustomNode) and node.kind.build:
            builds.append(node.kind.build)
        elif isinstance(node.kind, RuntimeNode):
            builds += [op.build for op in node.kind.operators if op.build]
        for build in builds:
            print(f"[{node.id}] {build}")
            rc = subprocess.run(build, shell=True, cwd=working_dir).returncode
            if rc != 0:
                print(f"build of node {node.id!r} failed with {rc}", file=sys.stderr)
                return rc
    return 0


def cmd_up(args) -> int:
    """Spawn coordinator + daemon for this machine (reference: up.rs)."""
    from dora_tpu.cli.control import ControlConnection

    try:
        with ControlConnection(args.coordinator_addr) as c:
            c.request(cm.DaemonConnected())
            print("coordinator + daemon already up")
            return 0
    except OSError:
        pass
    _spawn_detached(
        ["coordinator", "--port", str(DORA_COORDINATOR_PORT_DEFAULT),
         "--control-port", str(DORA_COORDINATOR_PORT_CONTROL_DEFAULT)],
        "coordinator",
    )
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            with ControlConnection(args.coordinator_addr) as c:
                c.request(cm.DaemonConnected())
            break
        except OSError:
            time.sleep(0.2)
    else:
        print("coordinator did not come up", file=sys.stderr)
        return 1
    _spawn_detached(
        ["daemon", "--coordinator-addr",
         args.coordinator_addr or f"127.0.0.1:{DORA_COORDINATOR_PORT_DEFAULT}"],
        "daemon",
    )
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with ControlConnection(args.coordinator_addr) as c:
            if c.request(cm.DaemonConnected()).connected:
                print("coordinator + daemon up")
                return 0
        time.sleep(0.2)
    print("daemon did not register", file=sys.stderr)
    return 1


def cmd_destroy(args) -> int:
    try:
        with _control(args) as c:
            c.request(cm.Destroy())
            print("destroyed")
    except SystemExit:
        pass
    _kill_pidfile("daemon")
    _kill_pidfile("coordinator")
    return 0


def cmd_start(args) -> int:
    raw = yaml.safe_load(Path(args.dataflow).read_text())
    working_dir = str(Path(args.dataflow).resolve().parent)
    with _control(args) as c:
        reply = c.request(
            cm.Start(dataflow=raw, name=args.name, local_working_dir=working_dir)
        )
        uuid = reply.uuid
        print(uuid)
        if not args.attach:
            return 0
        return _attach(c, uuid, args, working_dir)


def _attach(c, uuid: str, args=None, working_dir: str | None = None) -> int:
    """Poll Check until the dataflow finishes; ctrl-c requests a stop; a
    second control connection streams live logs; with --hot-reload,
    changed Python operator sources trigger a Reload
    (reference: attach.rs:20-209)."""
    stream_stop = _start_log_stream(args, uuid)
    watcher = (
        _HotReloadWatcher(args.dataflow, working_dir)
        if args is not None and getattr(args, "hot_reload", False)
        else None
    )
    try:
        while True:
            reply = c.request(cm.Check(dataflow_uuid=uuid))
            if isinstance(reply, cm.DataflowStopped):
                return _print_result(reply.result)
            if watcher is not None:
                for node_id, operator_id in watcher.changed():
                    print(f"reloading {node_id}/{operator_id or ''}")
                    c.request(
                        cm.ReloadRequest(
                            dataflow_id=uuid,
                            node_id=node_id,
                            operator_id=operator_id,
                        )
                    )
            time.sleep(1.0)
    except KeyboardInterrupt:
        print("\nstopping dataflow...")
        reply = c.request(cm.StopRequest(dataflow_uuid=uuid, grace_duration_s=None))
        return _print_result(reply.result)
    finally:
        if stream_stop is not None:
            stream_stop()


def _start_log_stream(args, uuid: str):
    """LogSubscribe on a second connection; prints pushed LogMessages."""
    import threading

    from dora_tpu.cli.control import ControlConnection

    try:
        conn = ControlConnection(getattr(args, "coordinator_addr", None))
    except Exception:
        return None
    conn.send_only(cm.LogSubscribe(dataflow_id=uuid, level="info"))

    def pump():
        try:
            for msg in conn.stream():
                node = getattr(msg, "node_id", None) or ""
                print(f"  [{node}] {getattr(msg, 'message', msg)}")
        except Exception:
            pass

    thread = threading.Thread(target=pump, daemon=True)
    thread.start()
    return conn.close


class _HotReloadWatcher:
    """mtime-poll Python operator sources of a dataflow
    (reference: attach.rs file watcher -> Reload).

    Scope is exact parity with the reference: *Python operators only*.
    The reference deliberately excludes custom nodes ("Reloading Custom
    Nodes is not supported", attach.rs:45-46) and non-Python operators
    (attach.rs:59-60) — a custom node owns its process, so a mid-dataflow
    code swap would really be a restart, with subscriptions/drop-token
    state severed; the runtime-hosted Python operator is the one place a
    live swap is sound (runtime/__init__.py preserves the instance
    __dict__ across reloads)."""

    def __init__(self, dataflow_path: str, working_dir: str | None):
        from dora_tpu.core.descriptor import (
            Descriptor,
            PythonSource,
            RuntimeNode,
        )

        self.entries: list[tuple[Path, str, str | None, float]] = []
        descriptor = Descriptor.read(dataflow_path)
        base = Path(working_dir or Path(dataflow_path).parent)
        for node in descriptor.nodes:
            if not isinstance(node.kind, RuntimeNode):
                continue
            for op in node.kind.operators:
                if isinstance(op.source, PythonSource):
                    path = Path(op.source.source)
                    if not path.is_absolute():
                        path = base / path
                    if path.exists():
                        self.entries.append(
                            (path, str(node.id), str(op.id), path.stat().st_mtime)
                        )

    def changed(self):
        out = []
        for i, (path, node_id, op_id, mtime) in enumerate(self.entries):
            try:
                now = path.stat().st_mtime
            except OSError:
                continue
            if now > mtime:
                self.entries[i] = (path, node_id, op_id, now)
                out.append((node_id, op_id))
        return out


def _print_result(result) -> int:
    if result.is_ok():
        print(f"dataflow {result.uuid} finished successfully")
        return 0
    for node_id, error in result.errors():
        print(f"node {node_id!r} failed: {error}", file=sys.stderr)
    return 1


def cmd_stop(args) -> int:
    with _control(args) as c:
        if args.name:
            reply = c.request(
                cm.StopByName(name=args.name, grace_duration_s=args.grace_duration)
            )
        elif args.uuid:
            reply = c.request(
                cm.StopRequest(dataflow_uuid=args.uuid, grace_duration_s=args.grace_duration)
            )
        else:
            listed = c.request(cm.ListDataflows()).dataflows
            if len(listed) != 1:
                print(
                    f"{len(listed)} dataflows running; pass --uuid or --name",
                    file=sys.stderr,
                )
                return 1
            reply = c.request(
                cm.StopRequest(
                    dataflow_uuid=listed[0].uuid, grace_duration_s=args.grace_duration
                )
            )
        return _print_result(reply.result)


def cmd_list(args) -> int:
    with _control(args) as c:
        for entry in c.request(cm.ListDataflows()).dataflows:
            print(f"{entry.uuid}  {entry.name or ''}")
    return 0


def cmd_metrics(args) -> int:
    """Aggregated per-link counters and latency percentiles for a dataflow
    (``--watch`` refreshes top-style with rates from counter deltas)."""
    import json

    from dora_tpu.cli.metrics_view import render_metrics

    with _control(args) as c:
        prev = None
        history: list[dict] = []
        last_at: float | None = None
        while True:
            reply = c.request(
                cm.QueryMetrics(dataflow_uuid=args.uuid, name=args.name)
            )
            now = time.monotonic()
            if isinstance(reply, cm.Error):
                print(reply.message, file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(reply.metrics, indent=2, sort_keys=True))
                return 0
            # Watch rates come from the daemon-side history ring
            # (server-side deltas: first tick has real rates, counter
            # resets already handled in the ring). CLI-side two-snapshot
            # diffing over the MEASURED elapsed time stays as the
            # fallback for daemons with history sampling disabled.
            rates = None
            if args.watch:
                hist_reply = c.request(
                    cm.QueryMetricsHistory(
                        dataflow_uuid=args.uuid, name=args.name
                    )
                )
                if (
                    not isinstance(hist_reply, cm.Error)
                    and hist_reply.history.get("samples")
                ):
                    rates = hist_reply.history.get("rates")
            elapsed = now - last_at if last_at is not None else None
            text = render_metrics(
                reply.dataflow_uuid,
                reply.metrics,
                prev=prev,
                interval=(
                    (elapsed if elapsed is not None else args.interval)
                    if args.watch else None
                ),
                history=history if args.watch else None,
                rates=rates,
            )
            if not args.watch:
                print(text, end="")
                return 0
            print("\x1b[2J\x1b[H" + text, end="", flush=True)
            prev = reply.metrics
            history.append(reply.metrics)
            del history[:-48]  # sparkline window
            last_at = now
            time.sleep(args.interval)


def cmd_top(args) -> int:
    """Live full-cluster dashboard: nodes, queues, SERVING, RECOVERY,
    PAGES and SLO burn, with rates and sparklines drawn from the
    daemon-side metrics history ring (QueryMetricsHistory)."""
    import json

    from dora_tpu.cli.top_view import render_top

    with _control(args) as c:
        while True:
            reply = c.request(
                cm.QueryMetrics(dataflow_uuid=args.uuid, name=args.name)
            )
            if isinstance(reply, cm.Error):
                print(reply.message, file=sys.stderr)
                return 1
            hist_reply = c.request(
                cm.QueryMetricsHistory(
                    dataflow_uuid=args.uuid, name=args.name
                )
            )
            if isinstance(hist_reply, cm.Error):
                print(hist_reply.message, file=sys.stderr)
                return 1
            if args.json:
                payload = dict(hist_reply.history)
                payload["fleet"] = reply.metrics.get("fleet", {})
                print(json.dumps(payload, indent=2, sort_keys=True))
                return 0
            text = render_top(
                reply.dataflow_uuid, reply.metrics, hist_reply.history
            )
            if args.once:
                print(text, end="")
                return 0
            print("\x1b[2J\x1b[H" + text, end="", flush=True)
            time.sleep(args.interval)


def cmd_alerts(args) -> int:
    """Current alert status of a dataflow: per-rule instance states from
    the daemon-side engines, merged by the coordinator (archived
    dataflows included — a post-mortem still shows what fired)."""
    import json

    from dora_tpu.cli.alerts_view import render_alerts

    with _control(args) as c:
        while True:
            reply = c.request(
                cm.QueryAlerts(dataflow_uuid=args.uuid, name=args.name)
            )
            if isinstance(reply, cm.Error):
                print(reply.message, file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(reply.alerts, indent=2, sort_keys=True))
                return 0
            text = render_alerts(reply.dataflow_uuid, reply.alerts)
            if not args.watch:
                print(text, end="")
                return 0
            print("\x1b[2J\x1b[H" + text, end="", flush=True)
            time.sleep(args.interval)


def cmd_fleet(args) -> int:
    """Cluster fleet view: every serving replica's latest engine-state
    digest (prefix-cache summary, free-stream capacity, occupancy,
    config fingerprint) merged across machines by the coordinator —
    the observability surface the placement router consumes."""
    import json

    from dora_tpu.cli.fleet_view import render_fleet

    with _control(args) as c:
        while True:
            reply = c.request(
                cm.QueryFleet(dataflow_uuid=args.uuid, name=args.name)
            )
            if isinstance(reply, cm.Error):
                print(reply.message, file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(reply.fleet, indent=2, sort_keys=True))
                return 0
            text = render_fleet(reply.dataflow_uuid, reply.fleet)
            if not args.watch:
                print(text, end="")
                return 0
            print("\x1b[2J\x1b[H" + text, end="", flush=True)
            time.sleep(args.interval)


def cmd_trace(args) -> int:
    """Export a dataflow's merged, clock-aligned message timeline as
    Chrome trace JSON (load in Perfetto / chrome://tracing). ``--check``
    runs the offline exporter schema self-check instead."""
    import json

    from dora_tpu.tracing import self_check, to_chrome_trace, validate_chrome_trace

    if args.check:
        problems = self_check()
        for problem in problems:
            print(problem, file=sys.stderr)
        if problems:
            return 1
        print("trace export schema: OK")
        return 0
    with _control(args) as c:
        reply = c.request(cm.QueryTrace(dataflow_uuid=args.uuid, name=args.name))
        if isinstance(reply, cm.Error):
            print(reply.message, file=sys.stderr)
            return 1
        trace = to_chrome_trace(reply.trace)
        for problem in validate_chrome_trace(trace):
            print(f"warning: {problem}", file=sys.stderr)
        text = json.dumps(trace)
        if args.out:
            Path(args.out).write_text(text)
            print(
                f"wrote {args.out} ({len(trace['traceEvents'])} events) — "
                "load in Perfetto (ui.perfetto.dev) or chrome://tracing"
            )
        else:
            print(text)
    return 0


def cmd_migrate(args) -> int:
    """Drain a serving node's live KV streams into a handoff directory
    another engine (running with ``DORA_MIGRATE_DIR`` pointed at it)
    admits and continues — each stream under its original trace id."""
    handoff_dir = str(Path(args.handoff_dir).resolve())
    with _control(args) as c:
        reply = c.request(
            cm.MigrateNode(
                dataflow_uuid=args.uuid,
                node_id=args.node,
                handoff_dir=handoff_dir,
                name=args.name,
            )
        )
        if isinstance(reply, cm.Error):
            print(reply.message, file=sys.stderr)
            return 1
        print(
            f"migrating {reply.node_id} of {reply.uuid}: "
            f"streams drain into {reply.handoff_dir}"
        )
    return 0


def cmd_profile(args) -> int:
    """Run an on-demand deep profile capture (``jax.profiler.trace``)
    on a serving node and print the artifact path. ``--stop`` ends an
    in-flight capture early. On backends without a working profiler the
    artifact is a synthetic JSON marker explaining why."""
    with _control(args) as c:
        if args.stop:
            request = cm.StopProfile(
                dataflow_uuid=args.uuid, node_id=args.node, name=args.name,
            )
        else:
            request = cm.StartProfile(
                dataflow_uuid=args.uuid, node_id=args.node,
                seconds=args.seconds, name=args.name,
            )
        reply = c.request(request)
        if isinstance(reply, cm.Error):
            print(reply.message, file=sys.stderr)
            return 1
        if reply.error:
            print(
                f"profile on {reply.node_id} of {reply.uuid} failed: "
                f"{reply.error}",
                file=sys.stderr,
            )
            return 1
        print(reply.artifact)
    return 0


def cmd_logs(args) -> int:
    with _control(args) as c:
        reply = c.request(cm.Logs(uuid=args.uuid, name=args.name, node=args.node))
        text = reply.logs.decode(errors="replace")
        if getattr(args, "level", None):
            from dora_tpu.message.common import (
                log_level_at_least,
                parse_level_prefix,
            )

            # Same classifier the daemon's log pump uses; lines without
            # a recognizable prefix count as "info" here (the pump's
            # stderr default isn't knowable from the merged file).
            text = "".join(
                line + "\n"
                for line in text.splitlines()
                if log_level_at_least(
                    parse_level_prefix(line) or "info", args.level
                )
            )
        sys.stdout.write(text)
    return 0


def cmd_coordinator(args) -> int:
    from dora_tpu.coordinator import Coordinator

    async def main():
        coordinator = Coordinator()
        await coordinator.start(daemon_port=args.port, control_port=args.control_port)
        if not args.quiet:
            print(
                f"coordinator up (daemons: {coordinator.daemon_port}, "
                f"control: {coordinator.control_port})"
            )
        await coordinator.wait_destroyed()
        await coordinator.close()

    asyncio.run(main())
    return 0


def cmd_daemon(args) -> int:
    from dora_tpu.daemon.core import Daemon, run_dataflow_async

    if args.run_dataflow:
        async def standalone():
            result = await run_dataflow_async(
                args.run_dataflow, local_comm=args.local_comm
            )
            return _print_result(result)

        return asyncio.run(standalone())

    daemon = Daemon(local_comm=args.local_comm or "tcp")
    asyncio.run(daemon.run(args.coordinator_addr, args.machine_id))
    return 0


def cmd_runtime(args) -> int:
    from dora_tpu.runtime.__main__ import main as runtime_main

    runtime_main()
    return 0


def cmd_new(args) -> int:
    from dora_tpu.cli.template import create

    return create(
        args.kind, args.name, Path(args.path or args.name), lang=args.lang
    )


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dora-tpu", description="TPU-native dataflow framework CLI"
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command", required=True)

    def coordinator_addr(p):
        p.add_argument(
            "--coordinator-addr",
            default=None,
            help=f"control address (default 127.0.0.1:{DORA_COORDINATOR_PORT_CONTROL_DEFAULT})",
        )

    p = sub.add_parser("check", help="validate a dataflow YAML")
    p.add_argument("dataflow")
    p.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser(
        "lint",
        help="static analysis: jax recompile hazards, env registry, "
        "serde coverage, lock wiring",
    )
    p.add_argument(
        "paths", nargs="*", help="files/dirs for jaxlint (default: --self)"
    )
    p.add_argument(
        "--self", action="store_true",
        help="lint this installation's own package tree (all passes)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("graph", help="visualize a dataflow as mermaid/HTML")
    p.add_argument("dataflow")
    p.add_argument("--mermaid", action="store_true", help="print mermaid source")
    p.set_defaults(fn=cmd_graph)

    p = sub.add_parser(
        "schema", help="emit the dataflow JSON schema (editor support)"
    )
    p.add_argument("-o", "--output", help="write to a file instead of stdout")
    p.set_defaults(fn=cmd_schema)

    p = sub.add_parser("build", help="run the build commands of all nodes")
    p.add_argument("dataflow")
    p.set_defaults(fn=cmd_build)

    p = sub.add_parser("up", help="spawn coordinator + daemon on this machine")
    coordinator_addr(p)
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("destroy", help="stop coordinator + daemon")
    coordinator_addr(p)
    p.set_defaults(fn=cmd_destroy)

    p = sub.add_parser("start", help="start a dataflow")
    p.add_argument("dataflow")
    p.add_argument("--name", default=None)
    p.add_argument("--attach", action="store_true", help="wait for completion")
    p.add_argument(
        "--hot-reload",
        action="store_true",
        help="with --attach: reload Python operators when their source changes",
    )
    coordinator_addr(p)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop a running dataflow")
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    p.add_argument("--grace-duration", type=float, default=None)
    coordinator_addr(p)
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("list", help="list running dataflows")
    coordinator_addr(p)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser(
        "metrics", help="show a dataflow's routing/latency metrics"
    )
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    p.add_argument(
        "--watch", action="store_true", help="refresh top-style with rates"
    )
    p.add_argument(
        "--interval", type=float, default=2.0, help="--watch refresh seconds"
    )
    p.add_argument(
        "--json", action="store_true", help="print the raw merged snapshot"
    )
    coordinator_addr(p)
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "top",
        help="live cluster dashboard (rates/sparklines from the history ring)",
    )
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    p.add_argument(
        "--interval", type=float, default=2.0, help="refresh seconds"
    )
    p.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the raw merged history instead of the dashboard",
    )
    coordinator_addr(p)
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "alerts",
        help="show a dataflow's alert status (pending/firing per rule)",
    )
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    p.add_argument(
        "--watch", action="store_true", help="refresh top-style"
    )
    p.add_argument(
        "--interval", type=float, default=2.0, help="--watch refresh seconds"
    )
    p.add_argument(
        "--json", action="store_true", help="print the raw merged status"
    )
    coordinator_addr(p)
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser(
        "fleet",
        help="show every serving replica's engine-state digest (fleet view)",
    )
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    p.add_argument(
        "--watch", action="store_true", help="refresh top-style"
    )
    p.add_argument(
        "--interval", type=float, default=2.0, help="--watch refresh seconds"
    )
    p.add_argument(
        "--json", action="store_true", help="print the raw merged view"
    )
    coordinator_addr(p)
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "trace",
        help="export a dataflow's message timeline (Chrome trace / Perfetto)",
    )
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    p.add_argument(
        "--out", default=None, help="write the JSON here (default: stdout)"
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="offline schema self-check of the trace exporter (no cluster)",
    )
    coordinator_addr(p)
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "migrate",
        help="drain a serving node's live streams into a handoff dir",
    )
    p.add_argument("node", help="node id of the serving engine to drain")
    p.add_argument(
        "--handoff-dir", required=True,
        help="directory the target engine polls (its DORA_MIGRATE_DIR)",
    )
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    coordinator_addr(p)
    p.set_defaults(fn=cmd_migrate)

    p = sub.add_parser(
        "profile",
        help="capture a deep device profile on a serving node",
    )
    p.add_argument("node", help="node id of the serving engine to profile")
    p.add_argument(
        "--seconds", type=float, default=5.0,
        help="capture duration before the node stops and reports (default 5)",
    )
    p.add_argument(
        "--stop", action="store_true",
        help="stop an in-flight capture early and fetch its artifact",
    )
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    coordinator_addr(p)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("logs", help="print a node's logs")
    p.add_argument("node")
    p.add_argument("--uuid", default=None)
    p.add_argument("--name", default=None)
    p.add_argument(
        "--level", default=None,
        choices=["trace", "debug", "info", "warn", "error"],
        help="only lines at or above this level (level-prefix parsed)",
    )
    coordinator_addr(p)
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("coordinator", help="run the control-plane coordinator")
    p.add_argument("--port", type=int, default=DORA_COORDINATOR_PORT_DEFAULT)
    p.add_argument(
        "--control-port", type=int, default=DORA_COORDINATOR_PORT_CONTROL_DEFAULT
    )
    p.add_argument("--quiet", action="store_true")
    p.set_defaults(fn=cmd_coordinator)

    p = sub.add_parser("daemon", help="run the data-plane daemon")
    p.add_argument(
        "--coordinator-addr",
        default=f"127.0.0.1:{DORA_COORDINATOR_PORT_DEFAULT}",
        help="coordinator daemon-register address",
    )
    p.add_argument("--machine-id", default="")
    p.add_argument("--run-dataflow", default=None, metavar="DATAFLOW_YAML",
                   help="standalone mode: run one dataflow and exit")
    p.add_argument("--local-comm", default=None, choices=["tcp", "uds", "shmem"],
                   help="node channel transport; default: the dataflow "
                        "YAML's communication.local, else tcp")
    p.set_defaults(fn=cmd_daemon)

    p = sub.add_parser("runtime", help="run the operator runtime (internal)")
    p.set_defaults(fn=cmd_runtime)

    p = sub.add_parser("new", help="create a node/operator/dataflow template")
    p.add_argument("kind", choices=["node", "operator", "dataflow"])
    p.add_argument("name")
    p.add_argument("--path", default=None)
    # Reference parity: --lang rust/python/c/cxx (cli main.rs:96-117);
    # rust has no toolchain here, the native tier is C/C++.
    p.add_argument(
        "--lang", choices=["python", "c", "c++"], default="python",
        help="scaffold language (c/c++ build against native/ headers)",
    )
    p.set_defaults(fn=cmd_new)

    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
