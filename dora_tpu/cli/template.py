"""`dora-tpu new` project templates.

Reference parity: binaries/cli/src/template/ (rust/python/c/c++ node,
operator, and dataflow scaffolds) — here Python node, JAX operator, and
dataflow YAML.
"""

from __future__ import annotations

from pathlib import Path

NODE_TEMPLATE = '''"""{name}: a dora-tpu node."""

from dora_tpu.node import Node


def main() -> None:
    with Node() as node:
        for event in node:
            if event["type"] == "INPUT":
                # process event["value"] (a pyarrow array) ...
                node.send_output("out", event["value"], event["metadata"])
            elif event["type"] == "STOP":
                break


if __name__ == "__main__":
    main()
'''

OPERATOR_TEMPLATE = '''"""{name}: a TPU-tier (JAX) dora-tpu operator.

Referenced from a dataflow YAML as:

    operator:
      jax: {name}/operator.py:make_operator
      inputs: {{x: some-node/out}}
      outputs: [y]
"""

import jax.numpy as jnp

from dora_tpu.tpu.api import JaxOperator


def make_operator() -> JaxOperator:
    def step(state, inputs):
        x = inputs["x"]
        return state, {{"y": x * 2.0}}

    return JaxOperator(step=step, init_state=())
'''

DATAFLOW_TEMPLATE = """nodes:
  - id: source
    path: module:dora_tpu.nodehub.pyarrow_sender
    outputs: [data]
    env: {{DATA: "[1, 2, 3]"}}

  - id: {name}
    path: {name}.py
    inputs:
      in: source/data
    outputs: [out]
"""


def create(kind: str, name: str, path: Path) -> int:
    if kind == "node":
        path.mkdir(parents=True, exist_ok=True)
        (path / f"{name}.py").write_text(NODE_TEMPLATE.format(name=name))
        (path / "dataflow.yml").write_text(DATAFLOW_TEMPLATE.format(name=name))
        print(f"created node project at {path}")
    elif kind == "operator":
        path.mkdir(parents=True, exist_ok=True)
        (path / "operator.py").write_text(OPERATOR_TEMPLATE.format(name=name))
        print(f"created operator at {path}")
    else:
        target = path if path.suffix else path / "dataflow.yml"
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(DATAFLOW_TEMPLATE.format(name="transform"))
        print(f"created dataflow at {target}")
    return 0
