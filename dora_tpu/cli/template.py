"""`dora-tpu new` project templates.

Reference parity: binaries/cli/src/template/ (rust/python/c/c++ node,
operator, and dataflow scaffolds, selected with ``--lang`` at
main.rs:96-117) — here Python node / JAX operator plus C and C++ node
and operator scaffolds that compile against the headers in ``native/``
via the dataflow's ``build:`` lines (the cpp-dataflow example pattern).
"""

from __future__ import annotations

from pathlib import Path


def _native_dir() -> Path:
    import dora_tpu

    return Path(dora_tpu.__file__).resolve().parent.parent / "native"

NODE_TEMPLATE = '''"""{name}: a dora-tpu node."""

from dora_tpu.node import Node


def main() -> None:
    with Node() as node:
        for event in node:
            if event["type"] == "INPUT":
                # process event["value"] (a pyarrow array) ...
                node.send_output("out", event["value"], event["metadata"])
            elif event["type"] == "STOP":
                break


if __name__ == "__main__":
    main()
'''

OPERATOR_TEMPLATE = '''"""{name}: a TPU-tier (JAX) dora-tpu operator.

Referenced from a dataflow YAML as:

    operator:
      jax: {name}/operator.py:make_operator
      inputs: {{x: some-node/out}}
      outputs: [y]
"""

import jax.numpy as jnp

from dora_tpu.tpu.api import JaxOperator


def make_operator() -> JaxOperator:
    def step(state, inputs):
        x = inputs["x"]
        return state, {{"y": x * 2.0}}

    return JaxOperator(step=step, init_state=())
'''

DATAFLOW_TEMPLATE = """nodes:
  - id: source
    path: module:dora_tpu.nodehub.pyarrow_sender
    outputs: [data]
    env: {{DATA: "[1, 2, 3]"}}

  - id: {name}
    path: {name}.py
    inputs:
      in: source/data
    outputs: [out]
"""


C_NODE_TEMPLATE = '''/* {name}: a dora-tpu node in C (echoes inputs). */
#include <stdio.h>
#include "dora_node_api.h"

int main(void) {{
  DoraContext* ctx = dora_init_from_env();
  if (!ctx) return 1;
  DoraEvent* event;
  while ((event = dora_next_event(ctx)) != NULL) {{
    DoraEventType type = dora_event_type(event);
    if (type == DORA_EVENT_STOP) {{
      dora_event_free(ctx, event);
      break;
    }}
    if (type == DORA_EVENT_INPUT) {{
      size_t len;
      const unsigned char* data = dora_event_data(event, &len);
      if (dora_send_output_enc(ctx, "out", data, len,
                               dora_event_encoding(event)) != 0) {{
        fprintf(stderr, "send failed: %s\\n", dora_last_error(ctx));
      }}
    }}
    dora_event_free(ctx, event);
  }}
  dora_close(ctx);
  return 0;
}}
'''

CXX_NODE_TEMPLATE = '''// {name}: a dora-tpu node in C++ (echoes inputs).
#include "dora_node_api.hpp"

int main() {{
  dora::Node node;
  while (auto event = node.next()) {{
    if (event.type() == DORA_EVENT_STOP) break;
    if (event.type() == DORA_EVENT_INPUT) {{
      node.send_output("out", event.data(), event.size(),
                       event.encoding().c_str());
    }}
  }}
  return 0;
}}
'''

C_OPERATOR_TEMPLATE = '''/* {name}: a dora-tpu operator in C (C ABI, dlopen-hosted).
 * extern "C" guard: the build line uses g++, which treats this file as
 * C++ — the runtime dlopens the unmangled symbol names. */
#include <stddef.h>
#include <stdlib.h>

#include "dora_operator_api.h"

typedef struct {{
  int count;
}} State;

#ifdef __cplusplus
extern "C" {{
#endif

void* dora_init_operator(void) {{
  State* s = (State*)calloc(1, sizeof(State));
  return s;
}}

void dora_drop_operator(void* state) {{ free(state); }}

int dora_on_event(void* state, const DoraOperatorEvent* event,
                  const DoraOperatorSendOutput* send_output) {{
  State* s = (State*)state;
  if (event->type == DORA_OP_EVENT_INPUT) {{
    s->count++;
    send_output->send(send_output->context, "out", event->data,
                      event->data_len, event->encoding);
  }}
  return DORA_OP_CONTINUE;
}}

#ifdef __cplusplus
}}
#endif
'''

CXX_OPERATOR_TEMPLATE = '''// {name}: a dora-tpu operator in C++ (RAII wrapper).
#include <string>

#include "dora_operator_api.hpp"

class {cls} : public dora::Operator {{
  int count_ = 0;

  // on_event (not on_input) so the input's encoding can be forwarded —
  // re-tagging an arrow-ipc payload as "raw" would corrupt it downstream.
  dora::Status on_event(const dora::Event& event,
                        dora::OutputSender& out) override {{
    if (event.type == DORA_OP_EVENT_INPUT) {{
      ++count_;
      out.send("out", event.data.data, event.data.len,
               std::string(event.encoding).c_str());
    }}
    return dora::Status::Continue;
  }}
}};

DORA_REGISTER_OPERATOR({cls})
'''

#: ``build:`` lines run under a shell (cli/main.py), so the native/
#: directory is resolved on the building machine via command
#: substitution — the scaffold stays valid when the checkout moves.
#: ``python3`` (overridable via DORA_PYTHON) rather than bare
#: ``python``, which many distros don't ship.
NATIVE_DIR_SH = '"$(${DORA_PYTHON:-python3} -m dora_tpu.cli.native_dir)"'

C_DATAFLOW_TEMPLATE = """nodes:
  - id: source
    path: module:dora_tpu.nodehub.pyarrow_sender
    outputs: [data]
    env: {{DATA: "[1, 2, 3]"}}

  - id: {name}
    path: ./{name}
    build: >
      g++ -O2 -std=c++17 -I {native} {name}.{ext}
      {native}/node_api.cpp {native}/shmem.cpp
      -o {name} -lrt -pthread
    inputs:
      in: source/data
    outputs: [out]
"""

NATIVE_OPERATOR_DATAFLOW_TEMPLATE = """nodes:
  - id: source
    path: module:dora_tpu.nodehub.pyarrow_sender
    outputs: [data]
    env: {{DATA: "[1, 2, 3]"}}

  - id: {name}
    operator:
      shared-library: {name}
      build: >
        g++ -O2 -shared -fPIC -std=c++17 -I {native}
        operator.{ext} -o lib{name}.so
      inputs:
        in: source/data
      outputs: [out]
"""


def create(kind: str, name: str, path: Path, lang: str = "python") -> int:
    native = NATIVE_DIR_SH
    if kind == "node":
        path.mkdir(parents=True, exist_ok=True)
        if lang == "python":
            (path / f"{name}.py").write_text(NODE_TEMPLATE.format(name=name))
            (path / "dataflow.yml").write_text(
                DATAFLOW_TEMPLATE.format(name=name)
            )
        else:
            ext = "c" if lang == "c" else "cpp"
            template = C_NODE_TEMPLATE if lang == "c" else CXX_NODE_TEMPLATE
            (path / f"{name}.{ext}").write_text(template.format(name=name))
            (path / "dataflow.yml").write_text(
                C_DATAFLOW_TEMPLATE.format(name=name, native=native, ext=ext)
            )
        print(f"created {lang} node project at {path}")
    elif kind == "operator":
        path.mkdir(parents=True, exist_ok=True)
        if lang == "python":
            (path / "operator.py").write_text(
                OPERATOR_TEMPLATE.format(name=name)
            )
        else:
            ext = "c" if lang == "c" else "cpp"
            cls = "".join(
                part.capitalize() for part in name.replace("-", "_").split("_")
            ) or "Op"
            template = (
                C_OPERATOR_TEMPLATE if lang == "c" else CXX_OPERATOR_TEMPLATE
            )
            (path / f"operator.{ext}").write_text(
                template.format(name=name, cls=cls)
            )
            (path / "dataflow.yml").write_text(
                NATIVE_OPERATOR_DATAFLOW_TEMPLATE.format(
                    name=name, native=native, ext=ext
                )
            )
        print(f"created {lang} operator at {path}")
    else:
        target = path if path.suffix else path / "dataflow.yml"
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(DATAFLOW_TEMPLATE.format(name="transform"))
        print(f"created dataflow at {target}")
    return 0
