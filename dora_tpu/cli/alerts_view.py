"""Render alert status for ``dora-tpu alerts`` and the `top` panel.

Pure formatting over one input — the merged alert status
(``dora_tpu.alerts.merge_alert_status`` / ``AlertEngine.status`` shape)
— so tests feed it dicts directly and the CLI stays a thin query loop.
"""

from __future__ import annotations

import time

from dora_tpu.alerts import active_alerts
from dora_tpu.cli.metrics_view import _table

_STATE_MARKS = {"firing": "!!", "pending": " ~", "ok": "  "}


def _age(since_unix: float, now: float | None = None) -> str:
    if not since_unix:
        return "-"  # instance observed but never transitioned
    now = time.time() if now is None else now
    s = max(0.0, now - since_unix)
    if s < 90:
        return f"{s:.0f}s"
    if s < 5400:
        return f"{s / 60:.0f}m"
    return f"{s / 3600:.1f}h"


def alert_rows(status: dict, now: float | None = None,
               active_only: bool = False) -> list[list[str]]:
    """Table rows (firing first) from a merged status."""
    rows = []
    for r in active_alerts(status):
        if active_only and r["state"] == "ok":
            continue
        value = r["value"]
        threshold = r["threshold"]
        rows.append([
            f"{_STATE_MARKS.get(r['state'], '  ')} {r['rule']}",
            r["instance"],
            r["state"],
            r["severity"],
            f"{value:g}" if value is not None else "-",
            f"{threshold:g}" if threshold is not None else "-",
            _age(r["since_unix"], now),
            str(r["incidents"]),
        ])
    return rows


_HEADER = ["ALERT", "INSTANCE", "STATE", "SEV", "VALUE", "THRESHOLD",
           "FOR", "INCIDENTS"]


def render_alerts(uuid: str, status: dict, now: float | None = None) -> str:
    firing = status.get("firing", 0)
    pending = status.get("pending", 0)
    transitions = status.get("transitions") or {}
    header = (
        f"dora-tpu alerts — dataflow {uuid}"
        f"   {firing} firing / {pending} pending"
        f"   (lifetime: {transitions.get('firing', 0)} fired, "
        f"{transitions.get('resolved', 0)} resolved)"
    )
    lines = [header, ""]
    rows = alert_rows(status, now)
    if rows:
        lines += _table(_HEADER, rows)
    else:
        lines += ["(no alert rules evaluated yet)"]
    return "\n".join(lines).rstrip() + "\n"


def render_alerts_panel(status: dict, now: float | None = None) -> list[str]:
    """The ALERTS section of `dora-tpu top`: active instances only, no
    header line (the dashboard provides its own framing)."""
    rows = alert_rows(status, now, active_only=True)
    if not rows:
        return []
    return [""] + _table(_HEADER, rows)
