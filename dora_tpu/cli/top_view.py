"""Render the live full-cluster dashboard for ``dora-tpu top``.

Pure formatting over two inputs — the merged point-in-time snapshot
(``dora_tpu.metrics.merge_snapshots`` output) and the merged history
(``dora_tpu.metrics_history.merge_history_snapshots`` output) — so tests
feed it dicts directly and the CLI stays a thin query loop.

Unlike ``metrics --watch``'s old two-snapshot diffing, every rate and
sparkline here comes from the daemon-side history ring: the first frame
already shows real rates, counter resets were handled server-side, and
the sparklines cover the ring's whole retention window, not just the
frames this CLI process happened to see.
"""

from __future__ import annotations

from dora_tpu.cli.metrics_view import (
    _fmt_bytes,
    _fmt_us,
    _sparkline,
    _table,
)
from dora_tpu.metrics_history import counter_series, gauge_series

#: sparkline cells (ring samples) shown per series
SPARK_POINTS = 40

#: serving-snapshot gauges of the device utilization plane (round 16)
_UTIL_KEYS = ("mfu", "device_busy_fraction", "hbm_used_bytes",
              "hbm_limit_bytes", "hbm_peak_bytes")


def _spark_of(values: list[float], peak: float | None = None) -> str:
    """Values -> sparkline normalized to their own peak (or ``peak``)."""
    if not values:
        return ""
    top = peak if peak else max(values)
    if top <= 0:
        return _sparkline([0.0] * len(values))
    return _sparkline([v / top for v in values])


def render_top(uuid: str, snap: dict, history: dict) -> str:
    rates = history.get("rates") or {}
    per_key = rates.get("per_key", {})
    pctl = history.get("percentiles") or {}
    samples = history.get("samples") or []

    machines = history.get("machines") or []
    header = f"dora-tpu top — dataflow {uuid}"
    if machines:
        header += f"   machines: {', '.join(m or '(local)' for m in machines)}"
    span = (
        (samples[-1]["t_ns"] - samples[0]["t_ns"]) / 1e9 if len(samples) > 1
        else 0.0
    )
    header += (
        f"\n  {len(samples)} samples / {span:.0f}s retained"
        f"   {rates.get('msgs_per_s', 0.0):.1f} msg/s"
    )
    respm = rates.get("respawns_per_min", 0.0)
    if respm:
        header += f"   {respm:.2f} respawns/min"
    dropped = history.get("dropped", 0)
    if dropped:
        header += f"   ring dropped {dropped}"
    resets = history.get("resets") or {}
    if resets:
        header += f"   {sum(resets.values())} counter resets"
    lines = [header, ""]

    # LINKS: totals from the snapshot, rates + sparkline from the ring.
    link_rows = []
    for key in sorted(snap.get("links", {})):
        v = snap["links"][key]
        series = counter_series(history, f"link:{key}:msgs", SPARK_POINTS)
        link_rows.append([
            key,
            str(v.get("msgs", 0)),
            _fmt_bytes(v.get("bytes", 0)),
            f"{per_key.get(f'link:{key}:msgs', 0.0):.1f}",
            f"{_fmt_bytes(per_key.get(f'link:{key}:bytes', 0.0))}/s",
            _spark_of(series),
        ])
    if link_rows:
        lines += _table(
            ["LINK", "MSGS", "BYTES", "MSG/S", "BYTES/S", "TREND"], link_rows
        ) + [""]
    else:
        lines += ["(no routed links yet)", ""]

    # QUEUES: live depth + depth sparkline + windowed latency.
    drops = snap.get("drops", {})
    depths = snap.get("queue_depth", {})
    latency = snap.get("latency_us", {})
    input_rows = []
    for key in sorted(set(drops) | set(depths) | set(latency)):
        h = latency.get(key, {})
        w = pctl.get(f"lat:{key}", {})
        series = gauge_series(history, f"queue:{key}", SPARK_POINTS)
        input_rows.append([
            key,
            str(depths.get(key, 0)),
            _spark_of(series),
            str(drops.get(key, 0)),
            _fmt_us(w.get("p50_us", h.get("p50_us"))),
            _fmt_us(w.get("p99_us", h.get("p99_us"))),
            str(h.get("count", 0)),
        ])
    if input_rows:
        lines += _table(
            ["INPUT", "DEPTH", "TREND", "DROPS", "P50/1m", "P99/1m",
             "DELIVERED"],
            input_rows,
        )

    # SERVING: tok/s from the ring's derived rates, TTFT over the last
    # minute, tok/s + page-occupancy sparklines from the series.
    serving = snap.get("serving", {})
    if serving:
        tokens_per_s = rates.get("tokens_per_s", {})
        serving_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            w = pctl.get(f"srv:{nid}:ttft_us", {})
            ttft = s.get("ttft_us", {})
            tps = tokens_per_s.get(nid)
            series = counter_series(
                history, f"srv:{nid}:decode_tokens", SPARK_POINTS
            )
            serving_rows.append([
                f"{nid} ({s.get('engine', '?')})",
                f"{s.get('slots_active', 0)}/{s.get('slots_total', 0)}",
                (
                    f"{s.get('used_pages', 0)}/{s.get('total_pages', 0)}"
                    if s.get("total_pages") else "-"
                ),
                str(s.get("backlog_depth", 0)),
                str(s.get("decode_tokens", 0)),
                f"{tps:.1f}" if tps is not None else "0.0",
                _spark_of(series),
                _fmt_us(w.get("p50_us", ttft.get("p50_us"))),
                _fmt_us(w.get("p99_us", ttft.get("p99_us"))),
                str(s.get("requests", 0)),
            ])
        lines += [""] + _table(
            ["SERVING", "SLOTS", "PAGES", "BACKLOG", "TOKENS", "TOK/S",
             "TREND", "TTFT P50/1m", "TTFT P99/1m", "REQS"],
            serving_rows,
        )
        for nid in sorted(serving):
            s = serving[nid]
            total = s.get("total_pages") or 0
            if not total:
                continue
            series = gauge_series(
                history, f"srv:{nid}:used_pages", SPARK_POINTS
            )
            lines += [
                f"  pages {nid} [{_spark_of(series, peak=total)}] "
                f"{s.get('used_pages', 0)}/{total} "
                f"peak {s.get('peak_used_pages', 0)}"
            ]

    # UTIL: device utilization plane (round 16) — MFU / busy / HBM
    # gauges from the live snapshot (falling back to the history's
    # derived util block), MFU sparkline from the ring. Nodes without
    # device gauges (pre-round-16 snapshots, monitor off) render
    # dashes or drop out entirely.
    if serving:
        hist_util = history.get("util") or {}
        util_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            u = {**hist_util.get(nid, {}), **{
                k: s[k] for k in _UTIL_KEYS if s.get(k) is not None
            }}
            if not u:
                continue
            mfu = u.get("mfu")
            busy = u.get("device_busy_fraction")
            used, limit = u.get("hbm_used_bytes"), u.get("hbm_limit_bytes")
            peak = u.get("hbm_peak_bytes")
            series = gauge_series(history, f"srv:{nid}:mfu", SPARK_POINTS)
            util_rows.append([
                nid,
                f"{mfu * 100:.1f}%" if mfu is not None else "-",
                f"{busy * 100:.0f}%" if busy is not None else "-",
                (
                    f"{_fmt_bytes(used)}/{_fmt_bytes(limit)}"
                    if used is not None and limit is not None else "-"
                ),
                _fmt_bytes(peak) if peak is not None else "-",
                _spark_of(series, peak=1.0),
            ])
        if util_rows:
            lines += [""] + _table(
                ["UTIL", "MFU", "BUSY", "HBM", "HBM PEAK", "MFU TREND"],
                util_rows,
            )

    # RECOVERY: counters + respawn rate from the ring.
    recovery = snap.get("recovery") or {}
    respawns = recovery.get("respawns") or {}
    replayed = recovery.get("replayed_inputs") or {}
    if respawns or replayed:
        rec_rows = []
        for nid in sorted(set(respawns) | set(replayed)):
            rate = per_key.get(f"respawn:{nid}", 0.0) * 60.0
            rec_rows.append([
                nid,
                str(respawns.get(nid, 0)),
                f"{rate:.2f}",
                str(replayed.get(nid, 0)),
            ])
        lines += [""] + _table(
            ["RECOVERY", "RESPAWNS", "RESPAWNS/MIN", "REPLAYED"], rec_rows
        )

    # SLO burn: the budget fraction consumed per window, plus a
    # violation timeline (one cell per ring sample, ▇ = violating).
    slo = history.get("slo") or snap.get("slo") or {}
    if slo:
        slo_rows = []
        for nid in sorted(slo):
            entry = slo[nid]
            targets = entry.get("targets", {})
            timeline = [
                1.0 if (s.get("slo") and nid in s["slo"]) else 0.0
                for s in samples[-SPARK_POINTS:]
            ]
            last = entry.get("last") or {}
            slo_rows.append([
                nid,
                ",".join(f"{k}={v:g}" for k, v in sorted(targets.items())),
                f"{entry.get('burn_1m', 0.0) * 100:.0f}%",
                f"{entry.get('burn_10m', 0.0) * 100:.0f}%",
                str(entry.get("violations", 0)),
                _sparkline(timeline),
                ",".join(f"{k}={v:g}" for k, v in sorted(last.items()))
                or "-",
            ])
        lines += [""] + _table(
            ["SLO", "TARGETS", "BURN 1M", "BURN 10M", "VIOLATIONS",
             "TIMELINE", "LAST"],
            slo_rows,
        )

    # FLEET: per-replica engine digests (free-stream capacity, page
    # occupancy, prefix-cache footprint, digest age) — the same gauge
    # block `dora-tpu fleet` and prom export. Absent on pre-fleet
    # snapshots, so the panel simply doesn't render there.
    fleet = snap.get("fleet") or {}
    if fleet:
        from dora_tpu.cli.fleet_view import render_fleet_panel

        lines += render_fleet_panel(fleet)

    # ALERTS: active (pending/firing) instances from the merged
    # snapshot's alerts block — evaluated daemon-side by the alert
    # engine, so this panel agrees with `dora-tpu alerts` and prom.
    alerts = snap.get("alerts") or {}
    if alerts.get("rules"):
        from dora_tpu.cli.alerts_view import render_alerts_panel

        lines += render_alerts_panel(alerts)
    return "\n".join(lines).rstrip() + "\n"
