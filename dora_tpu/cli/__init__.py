"""The dora-tpu command-line interface.

Reference parity: binaries/cli — `dora {new,build,check,graph,up,start,
stop,logs,list,destroy,daemon,coordinator,runtime}` (src/main.rs:55-228).
Like the reference, one binary embeds every role: `dora-tpu daemon` and
`dora-tpu coordinator` run the data/control planes, so a single installed
entry point can bring up a whole cluster.
"""
