"""Synchronous control-port client (CLI <-> coordinator).

Reference parity: the communication-layer request-reply TCP client
(libraries/communication-layer/request-reply) as used by
binaries/cli/src/main.rs:656-660.
"""

from __future__ import annotations

import socket
from typing import Any

from dora_tpu.clock import HLC
from dora_tpu.core.topics import DORA_COORDINATOR_PORT_CONTROL_DEFAULT
from dora_tpu.message import coordinator as cm
from dora_tpu.message.serde import decode_timestamped, encode_timestamped
from dora_tpu.transport.framing import recv_frame, send_frame


class ControlConnection:
    def __init__(self, addr: str | None = None, timeout: float = 60.0):
        addr = addr or f"127.0.0.1:{DORA_COORDINATOR_PORT_CONTROL_DEFAULT}"
        host, _, port = addr.rpartition(":")
        self._clock = HLC()
        self.sock = socket.create_connection((host, int(port)), timeout=5)
        self.sock.settimeout(timeout)

    def request(self, msg: Any) -> Any:
        send_frame(self.sock, encode_timestamped(msg, self._clock))
        reply = decode_timestamped(recv_frame(self.sock), self._clock).inner
        if isinstance(reply, cm.Error):
            raise RuntimeError(reply.message)
        return reply

    def stream(self):
        """After a LogSubscribe request: yield pushed messages."""
        while True:
            yield decode_timestamped(recv_frame(self.sock), self._clock).inner

    def send_only(self, msg: Any) -> None:
        send_frame(self.sock, encode_timestamped(msg, self._clock))

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect(addr: str | None = None) -> ControlConnection:
    try:
        return ControlConnection(addr)
    except OSError as e:
        raise SystemExit(
            f"cannot connect to coordinator at {addr or 'localhost'}: {e}\n"
            f"hint: run `dora-tpu up` first"
        ) from e
