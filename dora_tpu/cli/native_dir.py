"""Print the directory holding the bundled C/C++ headers and sources.

Generated project scaffolds reference this at build time
(``-I "$(python -m dora_tpu.cli.native_dir)"``) so a dataflow created by
``dora-tpu new`` keeps building after the checkout moves or the package
is installed elsewhere — the path is resolved on the machine that runs
the build, never baked into the YAML.
"""

from dora_tpu.cli.template import _native_dir

if __name__ == "__main__":
    print(_native_dir())
