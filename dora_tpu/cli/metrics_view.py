"""Render an aggregated metrics snapshot (dora_tpu.metrics) as a
top-style text table for ``dora-tpu metrics [--watch]``.

Pure formatting — no I/O, no control-plane types — so tests can feed it
snapshots directly and the CLI stays a thin loop.
"""

from __future__ import annotations


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def _fmt_us(us: float | None) -> str:
    if us is None:
        return "-"
    if us < 1000:
        return f"{us:.0f}µs"
    if us < 1_000_000:
        return f"{us / 1000:.1f}ms"
    return f"{us / 1_000_000:.2f}s"


def _table(headers: list[str], rows: list[list[str]]) -> list[str]:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip()]
    for row in rows:
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        )
    return lines


def render_metrics(
    uuid: str,
    snap: dict,
    prev: dict | None = None,
    interval: float | None = None,
) -> str:
    """One screenful: header (fastroute ratio), per-link throughput table,
    per-input latency/backlog table. ``prev`` + ``interval`` (watch mode)
    turn counter deltas into msg/s / bytes/s rates."""
    fr = snap.get("fastroute", {})
    ratio = fr.get("hit_ratio")
    header = f"dataflow {uuid}"
    if ratio is not None:
        header += (
            f"   fastroute {ratio * 100:.1f}% "
            f"({fr.get('hits', 0)} hits / {fr.get('fallbacks', 0)} fallbacks)"
        )
    reasons = fr.get("fallback_reasons") or {}
    if reasons:
        listed = ", ".join(f"{k}={v}" for k, v in sorted(reasons.items()))
        header += f"\n  fallback reasons: {listed}"
    lines = [header, ""]

    prev_links = (prev or {}).get("links", {})
    link_rows = []
    for key in sorted(snap.get("links", {})):
        v = snap["links"][key]
        row = [key, str(v.get("msgs", 0)), _fmt_bytes(v.get("bytes", 0))]
        if interval:
            before = prev_links.get(key, {})
            rate = (v.get("msgs", 0) - before.get("msgs", 0)) / interval
            brate = (v.get("bytes", 0) - before.get("bytes", 0)) / interval
            row += [f"{rate:.1f}", f"{_fmt_bytes(brate)}/s"]
        link_rows.append(row)
    headers = ["LINK", "MSGS", "BYTES"]
    if interval:
        headers += ["MSG/S", "BYTES/S"]
    if link_rows:
        lines += _table(headers, link_rows) + [""]
    else:
        lines += ["(no routed links yet)", ""]

    drops = snap.get("drops", {})
    depths = snap.get("queue_depth", {})
    latency = snap.get("latency_us", {})
    input_keys = sorted(set(drops) | set(depths) | set(latency))
    input_rows = []
    for key in input_keys:
        h = latency.get(key, {})
        input_rows.append([
            key,
            str(depths.get(key, 0)),
            str(drops.get(key, 0)),
            _fmt_us(h.get("p50_us")),
            _fmt_us(h.get("p90_us")),
            _fmt_us(h.get("p99_us")),
            str(h.get("count", 0)),
        ])
    if input_rows:
        lines += _table(
            ["INPUT", "DEPTH", "DROPS", "P50", "P90", "P99", "DELIVERED"],
            input_rows,
        )

    serving = snap.get("serving", {})
    if serving:
        prev_serving = (prev or {}).get("serving", {})
        serving_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            ttft = s.get("ttft_us", {})
            gap = s.get("dispatch_gap_us", {})
            toks = s.get("decode_tokens", 0)
            if interval:
                before = prev_serving.get(nid, {})
                tps = f"{(toks - before.get('decode_tokens', 0)) / interval:.1f}"
            else:
                tps = "-"
            pages = (
                f"{s.get('free_pages', 0)}/{s.get('total_pages', 0)}"
                if s.get("total_pages")
                else "-"
            )
            tpd = s.get("tokens_per_dispatch")
            serving_rows.append([
                f"{nid} ({s.get('engine', '?')})",
                f"{s.get('slots_active', 0)}/{s.get('slots_total', 0)}",
                pages,
                str(s.get("backlog_depth", 0)),
                str(toks),
                tps,
                f"{tpd:.1f}" if tpd is not None else "-",
                _fmt_us(ttft.get("p50_us")),
                _fmt_us(ttft.get("p99_us")),
                _fmt_us(gap.get("p50_us")),
                _fmt_us(gap.get("p99_us")),
                str(s.get("requests", 0)),
            ])
        lines += [""] + _table(
            ["SERVING", "SLOTS", "PAGES", "BACKLOG", "TOKENS", "TOK/S",
             "TOK/DISP", "TTFT P50", "TTFT P99", "GAP P50", "GAP P99",
             "REQS"],
            serving_rows,
        )
    return "\n".join(lines).rstrip() + "\n"
