"""Render an aggregated metrics snapshot (dora_tpu.metrics) as a
top-style text table for ``dora-tpu metrics [--watch]``.

Pure formatting — no I/O, no control-plane types — so tests can feed it
snapshots directly and the CLI stays a thin loop.
"""

from __future__ import annotations


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def _fmt_us(us: float | None) -> str:
    if us is None:
        return "-"
    if us < 1000:
        return f"{us:.0f}µs"
    if us < 1_000_000:
        return f"{us / 1000:.1f}ms"
    return f"{us / 1_000_000:.2f}s"


_SPARK = " ▁▂▃▄▅▆▇█"

#: serving-snapshot keys that mark a node as carrying the round-16
#: device utilization plane (any present -> UTIL table renders)
_UTIL_KEYS = (
    "mfu", "device_busy_fraction", "hbm_used_bytes", "hbm_limit_bytes",
    "hbm_peak_bytes", "device_compute_ns", "host_dispatch_ns",
    "device_fetch_ns", "kv_dtype", "kv_pool_bytes", "kv_quant_err",
)


def _sparkline(fracs: list[float]) -> str:
    """0..1 fractions as block characters (page-occupancy history)."""
    top = len(_SPARK) - 1
    return "".join(
        _SPARK[round(min(max(f, 0.0), 1.0) * top)] for f in fracs
    )


def _rate(cur: int, before: int, dt: float) -> str:
    """Counter delta over ``dt`` seconds. A negative delta means the
    counter reset to zero (node restart / engine restore re-reporting
    from scratch) — the current value IS the progress since the reset,
    so rate that instead (mirrors the history ring's delta decoder;
    the old ``-`` rendering blanked every rate for a full watch tick
    after a respawn)."""
    delta = cur - before
    if delta < 0:
        delta = cur
    return f"{delta / dt:.1f}"


def _table(headers: list[str], rows: list[list[str]]) -> list[str]:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip()]
    for row in rows:
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        )
    return lines


def render_metrics(
    uuid: str,
    snap: dict,
    prev: dict | None = None,
    interval: float | None = None,
    history: list[dict] | None = None,
    rates: dict | None = None,
) -> str:
    """One screenful: header (fastroute ratio), per-link throughput table,
    per-input latency/backlog table. ``rates`` (the ``rates`` block of a
    merged QueryMetricsHistory reply) supplies server-side rates from the
    daemon history ring — the preferred watch-mode source: the first tick
    already has them and counter resets were handled in the ring.
    ``prev`` + ``interval`` are the legacy CLI-side fallback (no history
    ring on the daemon): counter deltas over the MEASURED wall time
    between the two snapshots, clamped to >= 1 ms (snapshots come from
    different daemons — a skewed or back-to-back pair must not explode a
    rate or divide by ~0). ``history`` (older snapshots, oldest first)
    draws the page-occupancy sparkline under the SERVING table."""
    fr = snap.get("fastroute", {})
    ratio = fr.get("hit_ratio")
    header = f"dataflow {uuid}"
    if ratio is not None:
        header += (
            f"   fastroute {ratio * 100:.1f}% "
            f"({fr.get('hits', 0)} hits / {fr.get('fallbacks', 0)} fallbacks)"
        )
    reasons = fr.get("fallback_reasons") or {}
    if reasons:
        listed = ", ".join(f"{k}={v}" for k, v in sorted(reasons.items()))
        header += f"\n  fallback reasons: {listed}"
    lines = [header, ""]

    dt = max(interval, 1e-3) if interval is not None else None
    per_key = (rates or {}).get("per_key", {})
    prev_links = (prev or {}).get("links", {})
    link_rows = []
    for key in sorted(snap.get("links", {})):
        v = snap["links"][key]
        row = [key, str(v.get("msgs", 0)), _fmt_bytes(v.get("bytes", 0))]
        if rates is not None:
            row.append(f"{per_key.get(f'link:{key}:msgs', 0.0):.1f}")
            row.append(
                f"{_fmt_bytes(per_key.get(f'link:{key}:bytes', 0.0))}/s"
            )
        elif dt:
            before = prev_links.get(key, {})
            row.append(_rate(v.get("msgs", 0), before.get("msgs", 0), dt))
            bdelta = v.get("bytes", 0) - before.get("bytes", 0)
            if bdelta < 0:  # counter reset: rate the fresh value
                bdelta = v.get("bytes", 0)
            row.append(f"{_fmt_bytes(bdelta / dt)}/s")
        link_rows.append(row)
    headers = ["LINK", "MSGS", "BYTES"]
    if rates is not None or dt:
        headers += ["MSG/S", "BYTES/S"]
    if link_rows:
        lines += _table(headers, link_rows) + [""]
    else:
        lines += ["(no routed links yet)", ""]

    drops = snap.get("drops", {})
    depths = snap.get("queue_depth", {})
    latency = snap.get("latency_us", {})
    input_keys = sorted(set(drops) | set(depths) | set(latency))
    input_rows = []
    for key in input_keys:
        h = latency.get(key, {})
        input_rows.append([
            key,
            str(depths.get(key, 0)),
            str(drops.get(key, 0)),
            _fmt_us(h.get("p50_us")),
            _fmt_us(h.get("p90_us")),
            _fmt_us(h.get("p99_us")),
            str(h.get("count", 0)),
        ])
    if input_rows:
        lines += _table(
            ["INPUT", "DEPTH", "DROPS", "P50", "P90", "P99", "DELIVERED"],
            input_rows,
        )

    serving = snap.get("serving", {})
    if serving:
        prev_serving = (prev or {}).get("serving", {})
        serving_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            ttft = s.get("ttft_us", {})
            gap = s.get("dispatch_gap_us", {})
            fetch = s.get("fetch_us", {})
            toks = s.get("decode_tokens", 0)
            if rates is not None:
                node_tps = (rates.get("tokens_per_s") or {}).get(nid)
                tps = f"{node_tps:.1f}" if node_tps is not None else "0.0"
            elif dt:
                before = prev_serving.get(nid, {})
                tps = _rate(toks, before.get("decode_tokens", 0), dt)
            else:
                tps = "-"
            pages = (
                f"{s.get('used_pages', 0)}/{s.get('total_pages', 0)}"
                if s.get("total_pages")
                else "-"
            )
            tpd = s.get("tokens_per_dispatch")
            # Draft acceptance rate (speculative decoding). Old
            # snapshots predate the field and spec-off engines never
            # draft: both render as a dash, per the PR-5 convention.
            acc = s.get("spec_acceptance")
            serving_rows.append([
                f"{nid} ({s.get('engine', '?')})",
                f"{s.get('slots_active', 0)}/{s.get('slots_total', 0)}",
                pages,
                str(s.get("backlog_depth", 0)),
                str(toks),
                tps,
                f"{tpd:.1f}" if tpd is not None else "-",
                f"{acc * 100:.0f}%" if acc is not None else "-",
                _fmt_us(ttft.get("p50_us")),
                _fmt_us(ttft.get("p99_us")),
                _fmt_us(gap.get("p50_us")),
                _fmt_us(gap.get("p99_us")),
                _fmt_us(fetch.get("p50_us")),
                str(s.get("compiles", 0)),
                str(s.get("requests", 0)),
            ])
        lines += [""] + _table(
            ["SERVING", "SLOTS", "PAGES", "BACKLOG", "TOKENS", "TOK/S",
             "TOK/DISP", "ACC%", "TTFT P50", "TTFT P99", "GAP P50",
             "GAP P99", "FETCH P50", "COMPILES", "REQS"],
            serving_rows,
        )
        # Page-occupancy sparkline: used/total over the watch history
        # (one cell per refresh, newest right), peak + fragmentation
        # alongside — the at-a-glance "is the pool the bottleneck".
        for nid in sorted(serving):
            s = serving[nid]
            total = s.get("total_pages") or 0
            if not total:
                continue
            fracs = []
            for old in (history or []):
                o = (old.get("serving") or {}).get(nid)
                if o and o.get("total_pages"):
                    fracs.append(
                        o.get("used_pages", 0) / o["total_pages"]
                    )
            fracs.append(s.get("used_pages", 0) / total)
            lines += [
                f"  pages {nid} [{_sparkline(fracs[-48:])}] "
                f"{s.get('used_pages', 0)}/{total} "
                f"peak {s.get('peak_used_pages', 0)} "
                f"contig {s.get('largest_contig_free', 0)}"
            ]

    # Traffic-shaping plane: per-class backlog depths plus the shed /
    # preempt / resume / retune counters. Like RECOVERY, the table only
    # appears once the QoS machinery has actually done something (or a
    # class backlog is non-empty) — an unshaped deployment stays clean.
    if serving:
        qos_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            depths = s.get("qos_depth") or {}
            active = (
                s.get("shed") or s.get("preempted") or s.get("resumed")
                or s.get("retunes") or any(depths.values())
            )
            if not active:
                continue
            qos_rows.append([
                nid,
                str(depths.get("interactive", 0)),
                str(depths.get("standard", 0)),
                str(depths.get("batch", 0)),
                str(s.get("shed", 0)),
                str(s.get("preempted", 0)),
                str(s.get("resumed", 0)),
                str(s.get("autotune_k", 0) or "-"),
                str(s.get("retunes", 0)),
            ])
        if qos_rows:
            lines += [""] + _table(
                ["QOS", "Q:INT", "Q:STD", "Q:BATCH", "SHED", "PREEMPT",
                 "RESUMED", "K", "RETUNES"],
                qos_rows,
            )

    # Shared-prefix cache plane: hit rate, cached/shared page footprint,
    # COW boundary copies, evictions. Only appears once the cache has
    # seen traffic — cache-off engines and old snapshots stay clean.
    if serving:
        prefix_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            lookups = s.get("prefix_hits", 0) + s.get("prefix_misses", 0)
            if not lookups and not s.get("prefix_cached_pages"):
                continue
            rate = s.get("prefix_hit_rate")
            prefix_rows.append([
                nid,
                f"{rate * 100:.0f}%" if rate is not None else "-",
                str(s.get("prefix_hits", 0)),
                str(s.get("prefix_misses", 0)),
                str(s.get("prefix_hit_tokens", 0)),
                str(s.get("prefix_cached_pages", 0)),
                str(s.get("prefix_shared_pages", 0)),
                str(s.get("prefix_cow_copies", 0)),
                str(s.get("prefix_evictions", 0)),
            ])
        if prefix_rows:
            lines += [""] + _table(
                ["PREFIX", "HIT%", "HITS", "MISS", "HIT TOK", "CACHED",
                 "SHARED", "COW", "EVICT"],
                prefix_rows,
            )

    # Multi-tenant LoRA plane: resident-adapter pool occupancy, churn
    # (loads/evictions), adapter HBM bytes, and per-tenant live-stream
    # pins. Only appears once an engine actually serves adapters —
    # single-tenant deployments and old snapshots stay clean.
    if serving:
        tenant_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            streams = s.get("adapter_streams") or {}
            if not s.get("lora_max_resident") and not streams:
                continue
            pinned = ", ".join(
                f"{name}:{n}" for name, n in sorted(streams.items())
            )
            tenant_rows.append([
                nid,
                f"{s.get('lora_resident', 0)}"
                f"/{s.get('lora_max_resident', 0)}",
                _fmt_bytes(s.get("lora_resident_bytes", 0)),
                str(s.get("lora_loads", 0)),
                str(s.get("lora_evictions", 0)),
                pinned or "-",
            ])
        if tenant_rows:
            lines += [""] + _table(
                ["TENANT", "RESIDENT", "BYTES", "LOADS", "EVICT",
                 "STREAMS"],
                tenant_rows,
            )

    # Device utilization plane (round 16): MFU / busy fraction / HBM
    # gauges plus the cumulative window-time attribution. The table
    # appears once any node ships device keys; individual unknown
    # gauges (CPU backend exposes no allocator stats, peak FLOPs
    # undetected) and whole pre-round-16 snapshots render dashes — the
    # PR-5 backward-compat contract.
    if serving:
        util_rows = []
        for nid in sorted(serving):
            s = serving[nid]
            if not any(k in s for k in _UTIL_KEYS):
                continue
            mfu = s.get("mfu")
            busy = s.get("device_busy_fraction")
            used, limit = s.get("hbm_used_bytes"), s.get("hbm_limit_bytes")
            peak = s.get("hbm_peak_bytes")
            hbm = (
                f"{_fmt_bytes(used)}/{_fmt_bytes(limit)}"
                if used is not None and limit is not None
                else "-"
            )
            pool = s.get("kv_pool_bytes")
            qerr = s.get("kv_quant_err")
            util_rows.append([
                nid,
                f"{mfu * 100:.1f}%" if mfu is not None else "-",
                f"{busy * 100:.0f}%" if busy is not None else "-",
                hbm,
                _fmt_bytes(peak) if peak is not None else "-",
                f"{s.get('device_compute_ns', 0) / 1e6:.0f}ms",
                f"{s.get('host_dispatch_ns', 0) / 1e6:.0f}ms",
                f"{s.get('device_fetch_ns', 0) / 1e6:.0f}ms",
                s.get("kv_dtype") or "-",
                _fmt_bytes(pool) if pool is not None else "-",
                f"{qerr * 100:.2f}%" if qerr is not None else "-",
            ])
        if util_rows:
            lines += [""] + _table(
                ["UTIL", "MFU", "BUSY", "HBM", "HBM PEAK", "DEV",
                 "DISP", "FETCH", "KV", "KV POOL", "QERR"],
                util_rows,
            )
            # MFU sparkline over the watch history (one cell per
            # refresh, newest right) — the at-a-glance "is the device
            # actually busy".
            for nid in sorted(serving):
                s = serving[nid]
                if s.get("mfu") is None:
                    continue
                fracs = []
                for old in (history or []):
                    o = (old.get("serving") or {}).get(nid)
                    if o and o.get("mfu") is not None:
                        fracs.append(o["mfu"])
                fracs.append(s["mfu"])
                lines += [
                    f"  mfu {nid} [{_sparkline(fracs[-48:])}] "
                    f"{s['mfu'] * 100:.1f}%"
                ]

    # Elastic-recovery plane: daemon-side respawn/replay counters merge
    # with serving-side checkpoint/migration counters by node id. The
    # table only appears once something recovered — steady state stays
    # clean.
    recovery = snap.get("recovery") or {}
    respawns = recovery.get("respawns") or {}
    replayed = recovery.get("replayed_inputs") or {}
    rec_nodes = set(respawns) | set(replayed)
    for nid, s in serving.items():
        if (s.get("checkpoints") or s.get("restored_streams")
                or s.get("migrated_out") or s.get("migrated_in")):
            rec_nodes.add(nid)
    if rec_nodes:
        rec_rows = []
        for nid in sorted(rec_nodes):
            s = serving.get(nid, {})
            age = s.get("checkpoint_age_s")
            rec_rows.append([
                nid,
                str(respawns.get(nid, 0)),
                str(replayed.get(nid, 0)),
                str(s.get("checkpoints", 0)),
                f"{age:.1f}s" if age is not None else "-",
                str(s.get("restored_streams", 0)),
                str(s.get("migrated_out", 0)),
                str(s.get("migrated_in", 0)),
            ])
        lines += [""] + _table(
            ["RECOVERY", "RESPAWNS", "REPLAYED", "CKPTS", "CKPT AGE",
             "RESTORED", "MIG OUT", "MIG IN"],
            rec_rows,
        )
    return "\n".join(lines).rstrip() + "\n"
