"""Render the cluster fleet view for ``dora-tpu fleet`` and the `top`
panel.

Pure formatting over two input shapes — the merged fleet view
(``dora_tpu.fleet.merge_fleet_snapshots`` output: full digests plus
``machine``/``age_s``) for the standalone command, and the daemon
metrics snapshot's ``fleet`` gauge block (``dora_tpu.fleet.
fleet_gauges`` output) for the ``top`` panel — so tests feed dicts
directly and the CLI stays a thin query loop. Pre-fleet snapshots (a
history recorded before round 21, a replica that never published)
render dashes, never crash — the SERVING-table backward-compat
convention.
"""

from __future__ import annotations

from dora_tpu.cli.metrics_view import _table


def _age(age_s) -> str:
    if age_s is None:
        return "-"
    s = float(age_s)
    if s < 90:
        return f"{s:.1f}s"
    if s < 5400:
        return f"{s / 60:.0f}m"
    return f"{s / 3600:.1f}h"


def _ratio(used, total) -> str:
    if used is None or not total:
        return "-"
    return f"{used}/{total}"


def fleet_rows(replicas: dict) -> list[list[str]]:
    """Table rows from the merged fleet view's ``replicas`` mapping,
    replica-id order (the same deterministic order score_placement
    falls back to)."""
    rows = []
    for rid in sorted(replicas):
        d = replicas[rid]
        cfg = "-"
        if d.get("fingerprint"):
            cfg = (
                f"K={d.get('window', 0)} spec={d.get('spec_k', 0)} "
                f"kv={d.get('kv_dtype', '?')} w{d.get('weight_bits', '?')}"
            )
        adapters = d.get("adapters") or []
        rows.append([
            rid,
            d.get("machine") or "(local)",
            str(d.get("model_id") or "-"),
            str(d.get("fingerprint") or "-")[:8],
            cfg,
            str(d.get("free_streams", "-")),
            _ratio(d.get("used_pages"), d.get("total_pages")),
            str(d.get("prefix_pages", 0) or 0),
            str(len(d.get("prefixes") or [])),
            ",".join(adapters) if adapters else "-",
            _age(d.get("age_s")),
        ])
    return rows


_HEADER = ["REPLICA", "MACHINE", "MODEL", "FPRINT", "CONFIG",
           "FREE STRM", "PAGES", "PFX PAGES", "PFX N", "ADAPTERS", "AGE"]


def render_fleet(uuid: str, fleet: dict) -> str:
    replicas = fleet.get("replicas") or {}
    machines = fleet.get("machines") or []
    header = (
        f"dora-tpu fleet — dataflow {uuid}"
        f"   {len(replicas)} replica(s)"
    )
    if machines:
        header += (
            f"   machines: {', '.join(m or '(local)' for m in machines)}"
        )
    lines = [header, ""]
    if replicas:
        lines += _table(_HEADER, fleet_rows(replicas))
        # Interchangeability at a glance: replicas sharing a config
        # fingerprint are valid placement alternatives for each other.
        by_fp: dict[str, list[str]] = {}
        for rid in sorted(replicas):
            fp = replicas[rid].get("fingerprint") or ""
            if fp:
                by_fp.setdefault(fp, []).append(rid)
        groups = [ids for ids in by_fp.values() if len(ids) > 1]
        if groups:
            lines += [""] + [
                f"interchangeable: {', '.join(ids)}" for ids in groups
            ]
    else:
        lines += ["(no engine digests published yet)"]
    return "\n".join(lines).rstrip() + "\n"


def render_fleet_panel(fleet_block: dict) -> list[str]:
    """The FLEET section of `dora-tpu top`, from the metrics snapshot's
    per-replica gauge block. Partial entries (pre-fleet history, mixed
    daemon versions) render dashes."""
    if not fleet_block:
        return []
    rows = []
    for nid in sorted(fleet_block):
        f = fleet_block[nid] or {}
        occ = f.get("occupancy")
        rows.append([
            nid,
            str(f.get("free_streams", "-")),
            _ratio(f.get("used_pages"), f.get("total_pages")),
            f"{occ * 100:.0f}%" if occ is not None else "-",
            str(f.get("prefix_pages", "-")),
            _age(f.get("digest_age_s")),
        ])
    return [""] + _table(
        ["FLEET", "FREE STRM", "PAGES", "OCC", "PFX PAGES", "DIGEST AGE"],
        rows,
    )
