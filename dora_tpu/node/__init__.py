"""The Python node API: ``dora_tpu.Node``.

Reference parity: apis/rust/node (DoraNode + EventStream + DropStream) and
apis/python/node (the `dora.Node` pyclass shape): construct from the
environment (spawned nodes) or by node id (dynamic nodes), iterate events,
``send_output`` with zero-copy shared memory for payloads ≥ 4 KiB.

Usage::

    from dora_tpu import Node

    node = Node()
    for event in node:
        if event["type"] == "INPUT":
            node.send_output("out", event["value"])
"""

from __future__ import annotations

import os
import socket
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
import time
import uuid
from typing import Any

from dora_tpu.clock import HLC
from dora_tpu.core.topics import (
    DORA_DAEMON_LOCAL_LISTEN_PORT_DEFAULT,
    ZERO_COPY_THRESHOLD,
)
from dora_tpu.message import daemon_to_node as d2n
from dora_tpu.message import node_to_daemon as n2d
from dora_tpu.message.common import (
    ENCODING_ARROW_IPC,
    ENCODING_RAW,
    InlineData,
    Metadata,
    SharedMemoryData,
    TypeInfo,
    new_drop_token,
)
from dora_tpu.message.serde import decode_timestamped, encode_timestamped
from dora_tpu.native import ShmemRegion
from dora_tpu.node.channels import DaemonChannel, DaemonError
from dora_tpu.node.events import Event, EventStream
from dora_tpu.transport.framing import recv_frame, send_frame

#: Max cached reusable shmem regions per node
#: (reference: apis/rust/node/src/node/mod.rs:365).
SHMEM_CACHE_REGIONS = 20

#: On close, wait this long for receivers to release our regions
#: (reference: mod.rs:405).
DROP_TOKEN_WAIT_S = 10.0


class _DropStream:
    """Background thread receiving released drop tokens (our regions that no
    receiver references anymore)."""

    def __init__(self, channel: DaemonChannel, on_tokens):
        self._channel = channel
        self._on_tokens = on_tokens
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="dora-drop-stream", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            while not self._closed.is_set():
                reply = self._channel.request(n2d.NextDropEvents())
                if not isinstance(reply, d2n.DropEvents) or not reply.drop_tokens:
                    break
                self._on_tokens(reply.drop_tokens)
        except Exception:
            pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._channel.interrupt()  # wake the thread if parked in recv
        except Exception:
            pass
        self._thread.join(timeout=2)
        try:
            self._channel.close()
        except Exception:
            pass


class Node:
    """One dataflow node (spawned by the daemon, or dynamic)."""

    def __init__(self, node_id: str | None = None, daemon_addr: str | None = None):
        from dora_tpu.telemetry import (
            FLIGHT,
            TRACING,
            install_flight_dump,
            install_stack_dump,
        )

        install_stack_dump()
        # Tracing implies the ring (FLIGHT.configure_from_env): the ring
        # is the trace storage the flusher ships to the daemon.
        TRACING.configure_from_env()
        FLIGHT.configure_from_env()
        if FLIGHT.enabled:
            install_flight_dump()
        self._flight = FLIGHT
        self._tracing = TRACING
        #: ring position already shipped to the daemon (ReportTrace)
        self._trace_cursor = 0
        #: FLIGHT.dropped already turned into trace_truncated events
        self._trace_dropped_sent = 0
        #: per-output published message/byte counters (node-local view;
        #: the daemon's metrics plane is authoritative for routed counts)
        self._send_counts: dict[str, list] = {}
        config = self._load_config(node_id, daemon_addr)
        self._config = config
        self.dataflow_id = config.dataflow_id
        self.node_id = config.node_id
        self._clock = HLC()
        comm = config.daemon_communication

        self._control = DaemonChannel.connect(
            comm, n2d.CHANNEL_CONTROL, config.dataflow_id, config.node_id, self._clock
        )

        # Sender-side shmem region bookkeeping.
        self._regions_lock = tracked_lock("node.regions")
        self._regions_in_use: dict[str, ShmemRegion] = {}  # token -> region
        self._regions_free: list[ShmemRegion] = []
        self._finished_unreported: list[str] = []
        #: token -> outstanding ack count (p2p fan-out; default 1)
        self._token_refs: dict[str, int] = {}
        #: receiver side: p2p-delivered token -> its edge server
        self._p2p_token_routes: dict[str, Any] = {}

        # Peer-to-peer edge data plane (node/p2p.py): create the edge
        # channel servers and announce them BEFORE subscribing, so the
        # daemon can pair edges at the barrier. Dynamic nodes attach
        # after the barrier and keep the daemon path.
        self._p2p = None
        if not config.dynamic and os.environ.get("DORA_P2P", "1") not in (
            "", "0"
        ):
            try:
                from dora_tpu.node.p2p import P2PEndpoint

                self._p2p = P2PEndpoint(self)
                self._control.request_ok(
                    n2d.P2PAnnounce(listeners=self._p2p.listeners)
                )
            except Exception:
                if self._p2p is not None:
                    self._p2p.close()
                self._p2p = None

        drop_channel = DaemonChannel.connect(
            comm, n2d.CHANNEL_DROP, config.dataflow_id, config.node_id, self._clock
        )
        drop_channel.request_ok(n2d.SubscribeDrop())
        self._drop_stream = _DropStream(drop_channel, self._reclaim_regions)

        # Opt-in output coalescing: buffer sub-threshold inline SendMessage
        # frames on the control channel and flush them as one socket write
        # once this many bytes are buffered (the flusher thread drains
        # stragglers after a short linger). 0 / unset = off: every output
        # goes out immediately.
        self._coalesce = int(os.environ.get("DORA_SEND_COALESCE", "0") or "0")

        # Flusher: receiver-side drop-token acks (queued by GC finalizers)
        # and coalesced output frames share one timer — both drain through
        # a single coalesced write on the control channel.
        self._ack_cond = threading.Condition()
        self._pending_acks: list[str] = []
        self._ack_closing = False
        self._ack_thread = threading.Thread(
            target=self._flush_loop, name="dora-flusher", daemon=True
        )
        self._ack_thread.start()

        events_channel = DaemonChannel.connect(
            comm, n2d.CHANNEL_EVENTS, config.dataflow_id, config.node_id, self._clock
        )
        # Blocks until every node of the dataflow subscribed (start barrier).
        events_channel.request_ok(n2d.Subscribe())
        self._events = EventStream(events_channel, on_ack=self._queue_ack)
        if self._p2p is not None:
            # Post-barrier: start serving inbound edges and learn which
            # outputs publish peer-to-peer.
            self._p2p.start(self._events)
            try:
                reply = self._control.request(n2d.P2PEdgesRequest())
                if isinstance(reply, d2n.P2PEdgesReply):
                    self._p2p.set_outbound(reply)
            except Exception:
                pass  # daemon predates p2p: everything routes normally

        self._closed = False

    # ------------------------------------------------------------------
    # bootstrap
    # ------------------------------------------------------------------

    @staticmethod
    def _load_config(node_id: str | None, daemon_addr: str | None) -> d2n.NodeConfig:
        from dora_tpu.daemon.spawn import NODE_CONFIG_ENV, decode_node_config

        raw = os.environ.get(NODE_CONFIG_ENV)
        if raw and node_id is None:
            return decode_node_config(raw)
        if node_id is None:
            raise RuntimeError(
                "Node() must be started by a daemon (DORA_NODE_CONFIG is not "
                "set); pass node_id=... for a dynamic node"
            )
        # Dynamic node: fetch the config from the daemon's local listen port
        # (reference: apis/rust/node/src/node/mod.rs:87-110).
        addr = daemon_addr or f"127.0.0.1:{DORA_DAEMON_LOCAL_LISTEN_PORT_DEFAULT}"
        host, _, port = addr.rpartition(":")
        clock = HLC()
        with socket.create_connection((host, int(port)), timeout=10) as sock:
            send_frame(
                sock, encode_timestamped(n2d.NodeConfigRequest(node_id=node_id), clock)
            )
            reply = decode_timestamped(recv_frame(sock), clock).inner
        if not isinstance(reply, d2n.NodeConfigReply):
            raise RuntimeError(f"unexpected reply {type(reply).__name__}")
        if reply.error:
            raise RuntimeError(f"dynamic node init failed: {reply.error}")
        return reply.node_config

    # ------------------------------------------------------------------
    # events
    # ------------------------------------------------------------------

    def recv(self, timeout: float | None = None) -> Event | None:
        """Next event; None when the stream ended or ``timeout`` expired."""
        return self._events.recv(timeout)

    def wake(self) -> None:
        """Unpark a parked :meth:`recv` with a ``{"type": "WAKE"}`` event
        (thread-safe; used by the runtime's pipelined serving loop)."""
        self._events.wake()

    @property
    def stream_ended(self) -> bool:
        return self._events.ended

    #: dora Python API compatibility alias.
    next = recv

    def __iter__(self):
        return iter(self._events)

    def __next__(self) -> Event:
        while True:
            event = self._events.recv()
            if event is None:
                raise StopIteration
            if event is self._events.WAKE:
                continue
            return event

    # ------------------------------------------------------------------
    # outputs
    # ------------------------------------------------------------------

    def send_output(
        self,
        output_id: str,
        data: Any = None,
        metadata: dict | None = None,
    ) -> None:
        """Publish one output. ``data`` may be a pyarrow array, numpy array,
        list, bytes, or None; payloads ≥ 4 KiB travel via shared memory."""
        if output_id not in self._config.run_config.outputs:
            raise DaemonError(
                f"node {self.node_id!r} has no output {output_id!r} "
                f"(declared: {self._config.run_config.outputs})"
            )
        params = dict(metadata or {})

        if data is None:
            type_info = TypeInfo(encoding=ENCODING_RAW, len=0)
            message_data: Any = None
        elif isinstance(data, (bytes, bytearray, memoryview)):
            raw = bytes(data)
            type_info = TypeInfo(encoding=ENCODING_RAW, len=len(raw))
            message_data = self._pack_payload_raw(raw)
        else:
            from dora_tpu.node.arrow import (
                ipc_max_size,
                ipc_serialize,
                ipc_serialize_into,
                to_arrow,
            )

            arr = to_arrow(data)
            max_size = ipc_max_size(arr)
            if max_size >= ZERO_COPY_THRESHOLD:
                region, token = self._alloc_region(max_size)
                written = ipc_serialize_into(arr, memoryview(region))
                message_data = SharedMemoryData(
                    shmem_id=region.name, len=written, drop_token=token
                )
                type_info = TypeInfo(encoding=ENCODING_ARROW_IPC, len=written)
            else:
                payload = ipc_serialize(arr)
                type_info = TypeInfo(encoding=ENCODING_ARROW_IPC, len=len(payload))
                message_data = InlineData(data=payload)

        self._publish(
            output_id,
            Metadata(type_info=type_info, parameters=params),
            message_data,
        )

    def _publish(self, output_id: str, metadata: Metadata, data: Any) -> None:
        """Route one output: peer-to-peer edges first (direct shmem
        exchange, ~32 µs), then the daemon SendMessage only when some
        receiver still needs it (non-p2p local, remote, or none).

        With tracing on, a child trace context (derived from any context
        the caller already put in the metadata, e.g. the runtime's
        on_event span) is injected so the daemon and receiver correlate,
        and the publish is recorded as a ``t_send`` span."""
        if not self._tracing.active:
            return self._publish_inner(output_id, metadata, data)
        from dora_tpu.telemetry import OTEL_CTX_KEY, child_context

        params = metadata.parameters
        ctx = child_context(str(params.get(OTEL_CTX_KEY, "")))
        params[OTEL_CTX_KEY] = ctx
        t0 = time.monotonic_ns()
        try:
            return self._publish_inner(output_id, metadata, data)
        finally:
            self._flight.record(
                "t_send", output_id, ctx, time.monotonic_ns() - t0
            )

    def _publish_inner(self, output_id: str, metadata: Metadata, data: Any) -> None:
        nbytes = metadata.type_info.len
        counts = self._send_counts.get(output_id)
        if counts is None:
            counts = self._send_counts[output_id] = [0, 0]
        counts[0] += 1
        counts[1] += nbytes
        if self._flight.enabled:
            self._flight.record("send", output_id, nbytes)
        if self._p2p is not None:
            if not self._p2p.publish(output_id, metadata, data):
                return
        msg = n2d.SendMessage(output_id=output_id, metadata=metadata, data=data)
        if self._coalesce and (data is None or isinstance(data, InlineData)):
            # Inline outputs only: shmem payloads carry drop-token
            # lifecycle and must not sit in a sender-side buffer.
            if self._control.queue(msg) >= self._coalesce:
                self._control.flush()
            else:
                with self._ack_cond:
                    self._ack_cond.notify()  # flusher drains after linger
            return
        self._control.request(msg)

    def flush(self) -> None:
        """Flush coalesced (buffered) outputs to the daemon now. No-op
        unless coalescing is enabled (``DORA_SEND_COALESCE``)."""
        self._control.flush()

    def report_serving(self, snapshot: dict) -> None:
        """Ship a serving-metrics snapshot (metrics.ServingMetrics.
        snapshot()) to the daemon, fire-and-forget on the control
        channel — the metrics plane's node-side entry point (serving
        nodes call this periodically; see nodehub/llm_server)."""
        self._control.queue(n2d.ReportServing(snapshot=dict(snapshot)))
        self._control.flush()

    def report_engine_state(self, digest) -> None:
        """Ship an engine-state digest (message.common.EngineStateDigest)
        to the daemon, fire-and-forget on the control channel — the
        fleet plane's node-side entry point (serving nodes call this on
        the DORA_FLEET_DIGEST_S cadence; see nodehub/llm_server)."""
        self._control.queue(n2d.ReportEngineState(digest=digest))
        self._control.flush()

    def report_profile(self, artifact: str, error: str | None = None) -> None:
        """Report a finished deep-capture's artifact path (or failure)
        to the daemon, fire-and-forget — it forwards to the
        coordinator's waiting StartProfile/StopProfile reply."""
        self._control.queue(n2d.ReportProfile(artifact=artifact, error=error))
        self._control.flush()

    def allocate_sample(self, size: int) -> "DataSample":
        """Allocate a writable sample backed by a shared-memory region
        (reference: allocate_data_sample + DataSample,
        apis/rust/node/src/node/mod.rs:303-319,434-503). Fill
        ``sample.view[:n]`` and publish with :meth:`send_sample` — the
        producer-side copy disappears entirely."""
        if size < ZERO_COPY_THRESHOLD:
            return DataSample(self, None, None, bytearray(size))
        region, token = self._alloc_region(size)
        return DataSample(self, region, token, None)

    def send_sample(
        self,
        output_id: str,
        sample: "DataSample",
        length: int,
        metadata: dict | None = None,
        encoding: str = ENCODING_RAW,
    ) -> None:
        """Publish a filled sample (no copy for shmem-backed samples)."""
        if output_id not in self._config.run_config.outputs:
            raise DaemonError(
                f"node {self.node_id!r} has no output {output_id!r}"
            )
        if sample._sent:
            raise DaemonError("sample was already sent")
        sample._sent = True
        if sample._region is not None:
            message_data: Any = SharedMemoryData(
                shmem_id=sample._region.name,
                len=length,
                drop_token=sample._token,
            )
        else:
            message_data = InlineData(data=bytes(sample._inline[:length]))
        self._publish(
            output_id,
            Metadata(
                type_info=TypeInfo(encoding=encoding, len=length),
                parameters=dict(metadata or {}),
            ),
            message_data,
        )

    def _pack_payload_raw(self, raw: bytes) -> Any:
        if len(raw) >= ZERO_COPY_THRESHOLD:
            region, token = self._alloc_region(len(raw))
            memoryview(region)[: len(raw)] = raw
            return SharedMemoryData(
                shmem_id=region.name, len=len(raw), drop_token=token
            )
        return InlineData(data=raw)

    # ------------------------------------------------------------------
    # shared-memory region cache (reference: mod.rs:303-371)
    # ------------------------------------------------------------------

    def _alloc_region(self, size: int) -> tuple[ShmemRegion, str]:
        token = new_drop_token()
        with self._regions_lock:
            for i, region in enumerate(self._regions_free):
                if region.size >= size:
                    del self._regions_free[i]
                    self._regions_in_use[token] = region
                    return region, token
        # Round up to reduce fragmentation across varying payload sizes.
        alloc = max(4096, 1 << (size - 1).bit_length())
        region = ShmemRegion.create(f"dtp-{uuid.uuid4().hex[:16]}", alloc)
        with self._regions_lock:
            self._regions_in_use[token] = region
        return region, token

    def _queue_ack(self, token: str) -> None:
        # p2p-delivered tokens ack straight back over their edge channel
        # (the sender owns the region; the daemon never saw the token).
        edge = self._p2p_token_routes.pop(token, None)
        if edge is not None:
            edge.queue_ack(token)
            return
        with self._ack_cond:
            self._pending_acks.append(token)
            self._ack_cond.notify()

    def _register_p2p_token(self, token: str, edge: Any) -> None:
        self._p2p_token_routes[token] = edge

    def _set_token_refs(self, token: str, refs: int) -> None:
        """Expected ack count before ``token``'s region can be reused
        (p2p fan-out: one per direct receiver, plus the daemon's)."""
        with self._regions_lock:
            if refs > 1:
                self._token_refs[token] = refs

    #: Flusher linger: after a wake, wait this long for a burst to
    #: accumulate before the coalesced write (only when coalescing is on).
    FLUSH_LINGER_S = 0.0002

    #: Trace plane: with tracing on the flusher's idle wait is bounded so
    #: flight-recorder ring growth ships to the daemon periodically (the
    #: ring would otherwise wrap and lose span records on busy nodes).
    TRACE_FLUSH_S = 1.0

    def _queue_trace_report(self) -> None:
        """Queue ring growth since the last report as a fire-and-forget
        ReportTrace (caller flushes the control channel). Ring wrap
        between flushes is not silent: the loss ships as a synthetic
        ``trace_truncated`` event (count in slot ``a``), so the export
        shows WHERE the gap sits on the timeline, and it rides the
        existing ReportTrace wire format unchanged."""
        events, self._trace_cursor = self._flight.events_since(
            self._trace_cursor
        )
        dropped = self._flight.dropped
        if dropped > self._trace_dropped_sent:
            lost = dropped - self._trace_dropped_sent
            self._trace_dropped_sent = dropped
            events = [
                (
                    time.monotonic_ns(), time.time_ns(),
                    "trace_truncated", lost, None, None,
                )
            ] + list(events)
        if events:
            self._control.queue(
                n2d.ReportTrace(events=[list(e) for e in events])
            )

    def _flush_loop(self) -> None:
        while True:
            with self._ack_cond:
                while (
                    not self._pending_acks
                    and self._control.buffered_bytes == 0
                    and not self._ack_closing
                ):
                    if self._tracing.active:
                        if not self._ack_cond.wait(self.TRACE_FLUSH_S):
                            break  # idle tick: ship ring growth
                    else:
                        self._ack_cond.wait()
                if (
                    self._ack_closing
                    and not self._pending_acks
                    and self._control.buffered_bytes == 0
                ):
                    return
            if self._coalesce and not self._ack_closing:
                time.sleep(self.FLUSH_LINGER_S)
            with self._ack_cond:
                tokens, self._pending_acks = self._pending_acks, []
            try:
                if tokens:
                    self._control.queue(n2d.ReportDropTokens(drop_tokens=tokens))
                if self._tracing.active:
                    self._queue_trace_report()
                self._control.flush()
            except Exception:
                return

    def _reclaim_regions(self, tokens: list[str]) -> None:
        with self._regions_lock:
            for token in tokens:
                refs = self._token_refs.get(token)
                if refs is not None and refs > 1:
                    self._token_refs[token] = refs - 1
                    continue
                self._token_refs.pop(token, None)
                region = self._regions_in_use.pop(token, None)
                if region is None:
                    continue
                if len(self._regions_free) < SHMEM_CACHE_REGIONS:
                    self._regions_free.append(region)
                else:
                    try:
                        region.close(unlink=True, force=True)
                    except Exception:
                        pass

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------

    def dataflow_descriptor(self) -> dict:
        return self._config.dataflow_descriptor

    def dataflow_id_str(self) -> str:
        return self.dataflow_id

    @property
    def config(self) -> d2n.NodeConfig:
        return self._config

    def close(self) -> None:
        """Report outputs done, wait for receivers to release our regions
        (≤ 10 s), tear down channels."""
        if self._closed:
            return
        self._closed = True
        # Surface straggler events so their finalizers queue acks, then let
        # the flusher drain before we report done.
        self._events.close()
        with self._ack_cond:
            self._ack_closing = True
            self._ack_cond.notify()
        self._ack_thread.join(timeout=2)
        try:
            if self._tracing.active:
                # Final ring shipment (covers the tail the periodic
                # flusher missed, incl. t_recv records from the event
                # drain above); OutputsDone flushes the queue first.
                self._queue_trace_report()
            self._control.request_ok(n2d.OutputsDone())
        except Exception:
            pass
        if self._p2p is not None:
            self._p2p.flush_acks()  # bring home receiver-side p2p acks
        deadline = time.monotonic() + DROP_TOKEN_WAIT_S
        last_flush = time.monotonic()
        while time.monotonic() < deadline:
            with self._regions_lock:
                if not self._regions_in_use:
                    break
            if self._p2p is not None and time.monotonic() - last_flush > 0.5:
                self._p2p.flush_acks()
                last_flush = time.monotonic()
            time.sleep(0.05)
        if self._p2p is not None:
            self._p2p.close()
        self._drop_stream.close()
        self._events.close()
        try:
            self._control.close()
        except Exception:
            pass
        with self._regions_lock:
            for region in list(self._regions_in_use.values()) + self._regions_free:
                try:
                    region.close(unlink=True, force=True)
                except Exception:
                    pass
            self._regions_in_use.clear()
            self._regions_free.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DataSample:
    """A writable payload buffer, shmem-backed when ≥ 4 KiB."""

    __slots__ = ("_node", "_region", "_token", "_inline", "_sent")

    def __init__(self, node, region, token, inline):
        self._node = node
        self._region = region
        self._token = token
        self._inline = inline
        self._sent = False

    @property
    def view(self) -> memoryview:
        """The writable bytes (do not hold slices past send)."""
        if self._region is not None:
            return memoryview(self._region)
        return memoryview(self._inline)

    def __len__(self) -> int:
        return self._region.size if self._region is not None else len(self._inline)


__all__ = ["Node", "Event", "DataSample", "DaemonError"]
