"""Node-side daemon channels (synchronous — nodes are synchronous by design).

Reference parity: apis/rust/node/src/daemon_connection/mod.rs — a
``DaemonChannel`` abstracts over TCP, UDS, and the native shared-memory
request-reply channel; every channel starts with a Register exchange.
"""

from __future__ import annotations

import socket
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
from typing import Any

from dora_tpu import PROTOCOL_VERSION
from dora_tpu.message import daemon_to_node as d2n
from dora_tpu.message import node_to_daemon as n2d
from dora_tpu.message.serde import decode_timestamped, encode_timestamped
from dora_tpu.native import Disconnected, ShmemChannel
from dora_tpu.telemetry import FLIGHT
from dora_tpu.transport.framing import recv_frame, send_frame, send_frames


class DaemonError(RuntimeError):
    """The daemon rejected a request."""


class _SocketTransport:
    def __init__(self, sock: socket.socket):
        self.sock = sock

    def send(self, payload: bytes) -> None:
        send_frame(self.sock, payload)

    def send_many(self, payloads: list[bytes]) -> None:
        send_frames(self.sock, payloads)

    def recv(self) -> bytes:
        return recv_frame(self.sock)

    def interrupt(self) -> None:
        """Wake any thread blocked in recv (socket stays closeable later)."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self) -> None:
        self.interrupt()
        self.sock.close()


class _ShmemTransport:
    def __init__(self, channel: ShmemChannel):
        self.channel = channel

    def send(self, payload: bytes) -> None:
        self.channel.send(payload)

    def send_many(self, payloads: list[bytes]) -> None:
        # The shmem channel is message-oriented (one slot per message), so
        # frames can't be joined — but draining the buffer in one locked
        # pass still amortizes the Python-level per-send overhead.
        for payload in payloads:
            self.channel.send(payload)

    def recv(self) -> bytes:
        data = self.channel.recv(timeout=None)
        if data is None:  # pragma: no cover - no-timeout recv returns data
            raise Disconnected("shmem channel closed")
        return data

    def interrupt(self) -> None:
        """Set the disconnect flag — wakes blocked recv with Disconnected
        WITHOUT freeing the native handle (freeing under a blocked recv is a
        use-after-free; call close() only after the blocked thread exited)."""
        self.channel.disconnect()

    def close(self) -> None:
        self.channel.disconnect()
        self.channel.close()


class DaemonChannel:
    """One registered request-reply channel to the daemon.

    Fire-and-forget messages (no reply expected) may be buffered with
    ``queue()`` and flushed as one coalesced transport write — one
    syscall for the whole batch on socket transports. ``request()``
    always flushes the buffer first, so the daemon observes the same
    message order as the un-coalesced path.
    """

    def __init__(self, transport, clock):
        self._transport = transport
        self._clock = clock
        # Held across transport send AND recv: request() IS the
        # request-reply serialization point for this channel, so
        # blocking under it is the contract, not a hazard.
        self._lock = tracked_lock("node.channels.daemon", allow_blocking=True)
        self._pending: list[bytes] = []
        self._pending_bytes = 0
        self.closed = False

    # -- construction -------------------------------------------------------

    @classmethod
    def connect(
        cls, comm: Any, channel_kind: str, dataflow_id: str, node_id: str, clock
    ) -> "DaemonChannel":
        if isinstance(comm, d2n.TcpCommunication):
            host, _, port = comm.socket_addr.rpartition(":")
            sock = socket.create_connection((host, int(port)))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            transport: Any = _SocketTransport(sock)
        elif isinstance(comm, d2n.UnixDomainCommunication):
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.connect(comm.socket_file)
            transport = _SocketTransport(sock)
        elif isinstance(comm, d2n.ShmemCommunication):
            region = {
                n2d.CHANNEL_CONTROL: comm.control_region_id,
                n2d.CHANNEL_EVENTS: comm.events_region_id,
                n2d.CHANNEL_DROP: comm.drop_region_id,
            }[channel_kind]
            transport = _ShmemTransport(ShmemChannel.open(region))
        else:
            raise ValueError(f"unknown daemon communication {comm!r}")
        channel = cls(transport, clock)
        reply = channel.request(
            n2d.Register(
                dataflow_id=dataflow_id,
                node_id=node_id,
                protocol_version=PROTOCOL_VERSION,
                channel=channel_kind,
            )
        )
        if isinstance(reply, d2n.ReplyResult) and reply.error:
            channel.close()
            raise DaemonError(f"register failed: {reply.error}")
        return channel

    # -- requests -----------------------------------------------------------

    def _flush_locked(self) -> None:
        if self._pending:
            pending, self._pending = self._pending, []
            nbytes, self._pending_bytes = self._pending_bytes, 0
            if FLIGHT.enabled:
                FLIGHT.record("coalesce_flush", len(pending), nbytes)
            self._transport.send_many(pending)

    def request(self, msg: Any) -> Any:
        """Send one request and (if the message type expects it) wait for the
        reply. Buffered fire-and-forget frames flush first (ordering)."""
        with self._lock:
            self._flush_locked()
            self._transport.send(encode_timestamped(msg, self._clock))
            if not n2d.expects_reply(msg):
                return None
            frame = self._transport.recv()
        return decode_timestamped(frame, self._clock).inner

    def queue(self, msg: Any) -> int:
        """Buffer a fire-and-forget message for a later coalesced flush.
        Returns the buffered byte count (caller decides when to flush)."""
        assert not n2d.expects_reply(msg), "only fire-and-forget can be queued"
        frame = encode_timestamped(msg, self._clock)
        with self._lock:
            self._pending.append(frame)
            self._pending_bytes += len(frame)
            return self._pending_bytes

    def flush(self) -> None:
        """Send every buffered frame in one coalesced transport write."""
        with self._lock:
            self._flush_locked()

    @property
    def buffered_bytes(self) -> int:
        return self._pending_bytes

    def request_ok(self, msg: Any) -> None:
        reply = self.request(msg)
        if isinstance(reply, d2n.ReplyResult) and reply.error:
            raise DaemonError(reply.error)

    def interrupt(self) -> None:
        """Phase 1 of shutdown: unblock any thread parked in recv."""
        self._transport.interrupt()

    def close(self) -> None:
        """Phase 2: free the transport. Must not race a blocked recv — call
        interrupt() and join the consuming thread first."""
        if not self.closed:
            self.closed = True
            try:
                self.flush()  # best-effort: don't strand buffered outputs
            except Exception:
                pass
            self._transport.close()
