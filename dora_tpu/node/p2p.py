"""Peer-to-peer edge data plane (TPU-build extension).

The reference routes every message through the daemon; the measured
cost here is ~0.5-0.9 ms p50 per hop chain (sender control channel →
daemon pump thread → asyncio routing → receiver event channel —
BENCHMARKS.md "Known gap"). This module moves the data plane of
eligible edges onto direct shared-memory channels between the two node
processes, keeping the daemon as the control plane:

* Each python node pre-creates one shmem channel pair (data + ack) per
  SENDER feeding it — grouping that sender's inputs so their relative
  order survives, exactly like the daemon's single per-receiver queue —
  and announces the names on its control channel BEFORE subscribing
  (``P2PAnnounce``): by the time any sender can learn a name, the
  channel exists, so there is no connect race.
* At barrier release the daemon pairs capable local endpoints per edge,
  excludes those edges from its own routing, and answers each sender's
  ``P2PEdgesRequest`` with the channel assignments.
* A send is one fire-and-forget futex-paced frame (~10 µs sender cost)
  — the same ``Timestamped(Input)`` the daemon would deliver; payloads
  ≥ 4 KiB still travel as shared-memory regions by name, zero-copy.
  The channel's one-outstanding-frame flow control is the only
  backpressure, so the sender never waits out the receiver's thread
  wake-ups. Drop-token acks return on the companion ack channel
  (separate because the futex channel's payload area is shared between
  its two directions), drained by a per-channel reader thread — region
  recycling flows sender←receiver without the daemon bookkeeping
  either.
* The receiver side enforces the YAML ``queue_size`` contract locally:
  each per-sender thread keeps a FIFO backlog with per-input
  drop-oldest (dropping an event releases its region via the same
  finalizer path as a consumed one) and merges into the node's event
  stream.

Timers, stdout-forwarding outputs, C/C++ clients, dynamic nodes, and
cross-machine edges keep the daemon path — eligibility is decided
per-edge by the daemon, so mixed dataflows just work. Kill switch:
``DORA_P2P=0`` (either side).
"""

from __future__ import annotations

import collections
import logging
import queue as queue_mod
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
import time
import uuid
from typing import Any

from dora_tpu.message import daemon_to_node as d2n
from dora_tpu.message.common import SharedMemoryData
from dora_tpu.message.serde import decode_timestamped, encode_timestamped
from dora_tpu.native import Disconnected, ShmemChannel

logger = logging.getLogger(__name__)

#: Edge channel capacity: control frames only (metadata + region ids;
#: big payloads ride regions), but inline payloads up to the 4 KiB
#: zero-copy threshold plus metadata must fit comfortably.
EDGE_CHANNEL_CAPACITY = 1 << 20

#: How long a sender retries opening an announced channel (the server
#: exists pre-announce; retries only cover fs visibility latency).
OPEN_RETRY_S = 5.0


def ack_name(channel_name: str) -> str:
    """The companion ack channel of a data channel (receiver->sender
    drop-token returns; separate channel because the futex channel's
    payload area is shared between its two directions)."""
    return channel_name + "-a"


class _EdgeServer:
    """All inbound edges from ONE sender: a shmem channel server plus a
    FIFO backlog with per-input drop-oldest. One channel per sender —
    not per input — so the cross-input event ORDER from a given sender
    is preserved exactly as the daemon's single per-receiver queue
    preserves it (phase-marker protocols depend on this)."""

    def __init__(self, endpoint: "P2PEndpoint", sender: str,
                 queue_sizes: dict[str, int], channel: ShmemChannel,
                 ack_channel: ShmemChannel):
        self.endpoint = endpoint
        self.sender = sender
        self.queue_sizes = {k: max(1, v) for k, v in queue_sizes.items()}
        self.channel = channel
        #: acks ride a SEPARATE channel: the futex channel's payload
        #: area is shared between directions (request-reply discipline),
        #: so pushing acks on the data channel's reverse direction would
        #: clobber in-flight data frames (measured: scattered losses).
        self.ack_channel = ack_channel
        self.backlog: collections.deque = collections.deque()  # (input, ev)
        self.counts: dict[str, int] = {}
        #: last time the channel was observed EMPTY (recv timed out) —
        #: the stream-end barrier uses this to know no frame is in
        #: flight inside the channel itself.
        self.last_idle = 0.0
        self._acks: list[str] = []
        self._acks_lock = tracked_lock("node.p2p.edge_acks")
        self.thread = threading.Thread(
            target=self._run, name=f"dora-p2p-{sender}", daemon=True
        )

    # -- ack routing (called from GC finalizers, arbitrary threads) ---------

    def queue_ack(self, token: str) -> None:
        with self._acks_lock:
            self._acks.append(token)

    def take_acks(self) -> list[str]:
        with self._acks_lock:
            acks, self._acks = self._acks, []
            return acks

    # -- receive loop -------------------------------------------------------

    def _drain(self) -> None:
        events = self.endpoint.events
        first = True
        while self.backlog:
            input_id, event = self.backlog[0]
            try:
                # Block briefly on the FIRST put: when the consumer is
                # the bottleneck this hands the event over the moment a
                # queue slot frees instead of sleeping out a recv tick
                # (the 10 ms poll capped a backlogged edge at ~200
                # events/s; the sender is flow-controlled to one
                # outstanding frame either way).
                if first:
                    events._queue.put(event, timeout=0.01)
                else:
                    events._queue.put_nowait(event)
            except queue_mod.Full:
                return
            first = False
            self.backlog.popleft()
            self.counts[input_id] -= 1

    def _append(self, input_id: str, event) -> None:
        """FIFO append with the daemon's per-input drop-oldest bound."""
        self.backlog.append((input_id, event))
        count = self.counts.get(input_id, 0) + 1
        self.counts[input_id] = count
        if count > self.queue_sizes.get(input_id, 1):
            for i, (iid, _ev) in enumerate(self.backlog):
                if iid == input_id:
                    # Releasing the event fires its finalizer, which
                    # acks its drop token back through us.
                    del self.backlog[i]
                    self.counts[input_id] -= 1
                    break

    def _push_acks(self) -> None:
        """Opportunistically push accumulated acks back to the sender
        (its ack-reader thread drains them). try_send: if the previous
        push is still unconsumed, keep the acks for the next chance."""
        with self._acks_lock:
            if not self._acks:
                return
            acks = list(self._acks)
        frame = encode_timestamped(
            d2n.DropEvents(drop_tokens=acks), self.endpoint.node._clock
        )
        try:
            if self.ack_channel.try_send(frame):
                with self._acks_lock:
                    del self._acks[: len(acks)]
        except Exception:
            pass

    def _run(self) -> None:
        node = self.endpoint.node
        events = self.endpoint.events
        while not self.endpoint.closed.is_set():
            self._drain()
            self._push_acks()
            try:
                frame = self.channel.recv(timeout=0.01 if self.backlog else 0.2)
            except Disconnected:
                break
            except Exception:
                break
            if frame is None:
                self.last_idle = time.monotonic()
                continue  # tick: drain backlog / flush acks
            try:
                inner = decode_timestamped(frame, node._clock).inner
                if isinstance(inner, d2n.Input):
                    data = inner.data
                    if isinstance(data, SharedMemoryData) and data.drop_token:
                        node._register_p2p_token(data.drop_token, self)
                    event = events._convert(inner)
                    if event is not None:
                        self._append(inner.id, event)
                # NextDropEvents frames are pure ack-flush pings.
            except Exception:
                logger.exception("p2p edges from %s: bad frame", self.sender)
        # Surface any undelivered backlog before exiting (stream-end
        # barrier in EventStream waits on us via backlog_empty).
        deadline = time.monotonic() + 2.0
        while self.backlog and time.monotonic() < deadline:
            self._drain()
            time.sleep(0.005)


class P2PEndpoint:
    """Per-node p2p state: inbound edge servers + outbound assignments."""

    def __init__(self, node: Any):
        self.node = node
        self.events: Any = None  # EventStream, attached post-subscribe
        self.closed = threading.Event()
        self.servers: dict[str, _EdgeServer] = {}
        self.listeners: dict[str, str] = {}
        #: output_id -> d2n.P2POutput
        self.outbound: dict[str, Any] = {}
        self._out_channels: dict[str, ShmemChannel] = {}
        self._out_lock = tracked_lock("node.p2p.out")
        self._readers: list[threading.Thread] = []
        # One channel per SENDER (grouping that sender's inputs): the
        # descriptor knows each input's source; the announce format
        # stays {input: channel}, so inputs sharing a sender simply
        # announce the same channel name.
        for sender, inputs in self._inputs_by_sender(node).items():
            name = f"dtp-p2p-{uuid.uuid4().hex[:16]}"
            try:
                channel = ShmemChannel.create(name, EDGE_CHANNEL_CAPACITY)
                ack_channel = ShmemChannel.create(ack_name(name), 1 << 16)
            except Exception:
                logger.exception("p2p: channel create failed; edges from "
                                 "%s fall back to daemon routing", sender)
                continue
            self.servers[sender] = _EdgeServer(
                self, sender, dict(inputs), channel, ack_channel
            )
            for input_id in inputs:
                self.listeners[input_id] = name

    @staticmethod
    def _inputs_by_sender(node) -> dict[str, dict[str, int]]:
        """{sender node id: {input id: queue size}} from the descriptor
        (timer inputs and fused-internal edges stay with the daemon)."""
        from dora_tpu.core.config import UserMapping
        from dora_tpu.core.descriptor import Descriptor

        try:
            desc = Descriptor.parse(node._config.dataflow_descriptor)
            me = desc.node(node._config.node_id)
            internal = me.fused_internal_inputs()
        except Exception:
            return {}
        out: dict[str, dict[str, int]] = {}
        for input_id, inp in me.inputs.items():
            if input_id in internal:
                continue
            if isinstance(inp.mapping, UserMapping):
                out.setdefault(str(inp.mapping.source), {})[str(input_id)] \
                    = inp.queue_size
        return out

    # -- lifecycle ----------------------------------------------------------

    def start(self, events) -> None:
        """Attach the event stream and start the edge threads (call after
        the start barrier, before the first event is consumed)."""
        self.events = events
        events.pre_end = self.backlog_barrier
        for server in self.servers.values():
            server.thread.start()

    def set_outbound(self, reply: Any) -> None:
        self.outbound = dict(reply.outputs or {})

    def backlog_empty(self) -> bool:
        return all(not s.backlog for s in self.servers.values())

    def backlog_barrier(self, timeout: float = 5.0) -> None:
        """Stream-end ordering: daemon-delivered AllInputsClosed must not
        overtake p2p events still in flight. Flow control bounds the
        exposure to ONE unconsumed frame per edge (a sender's send(n)
        returns only after frame n-1 was consumed), so the barrier
        waits until every edge thread has both an empty backlog and has
        observed an EMPTY channel (an idle recv tick) since the barrier
        began — then nothing can still be queued anywhere."""
        start = time.monotonic()
        deadline = start + timeout
        while time.monotonic() < deadline:
            settled = True
            for s in self.servers.values():
                if not s.thread.is_alive():
                    continue
                if s.backlog or s.last_idle <= start:
                    settled = False
                    break
            if settled:
                return
            time.sleep(0.005)

    # -- sender side --------------------------------------------------------

    def publish(self, output_id: str, metadata, data) -> bool:
        """Publish to this output's p2p edges. Returns True when the
        caller must STILL send the daemon SendMessage (non-p2p receivers
        exist), False when fully handled."""
        out = self.outbound.get(output_id)
        if out is None:
            return True
        token = (
            data.drop_token if isinstance(data, SharedMemoryData) else None
        )
        if token is not None:
            # One ack expected per p2p receiver, plus the daemon's if it
            # still routes this output anywhere.
            self.node._set_token_refs(
                token, len(out.edges) + (1 if out.daemon_route else 0)
            )
        for edge in out.edges:
            frame = encode_timestamped(
                d2n.Input(id=edge.input_id, metadata=metadata, data=data),
                self.node._clock,
            )
            try:
                self._send(edge, frame)
            except Disconnected:
                # Receiver is gone; the daemon's failure handling will
                # stop the dataflow — account the ack we will never get.
                logger.warning("p2p edge to %s/%s disconnected",
                               edge.receiver, edge.input_id)
                if token is not None:
                    self.node._reclaim_regions([token])
        return out.daemon_route

    def _send(self, edge, frame: bytes) -> None:
        """Fire-and-forget publish: the channel's per-direction flow
        control is the only backpressure (one outstanding frame — the
        daemon SendMessage discipline), so the sender never waits out
        the receiver's thread wake-ups. Acks flow back asynchronously
        on the reverse direction, drained by a per-channel reader."""
        # _out_lock guards only the channel-table bookkeeping; the send
        # happens OUTSIDE it. Holding it across channel.send() made the
        # ack-flush path serialize behind a receiver stuck in its flow-
        # control window (lockcheck: held-across-blocking). Callers are
        # single-sender per the node.send_output contract, so the bare
        # send needs no lock of its own.
        with self._out_lock:
            channel = self._out_channels.get(edge.channel)
            if channel is None:
                channel = self._open(edge.channel)
                self._out_channels[edge.channel] = channel
                acks = self._open(ack_name(edge.channel))
                self._out_channels[ack_name(edge.channel)] = acks
                reader = threading.Thread(
                    target=self._ack_reader, args=(acks,),
                    name=f"dora-p2p-acks-{edge.receiver}", daemon=True,
                )
                reader.start()
                self._readers.append(reader)
        channel.send(frame)

    def _ack_reader(self, channel: ShmemChannel) -> None:
        while not self.closed.is_set():
            try:
                frame = channel.recv(timeout=0.5)
            except Exception:
                return
            if frame is None:
                continue
            try:
                inner = decode_timestamped(frame, self.node._clock).inner
                if isinstance(inner, d2n.DropEvents) and inner.drop_tokens:
                    self.node._reclaim_regions(inner.drop_tokens)
            except Exception:
                continue

    @staticmethod
    def _open(name: str) -> ShmemChannel:
        deadline = time.monotonic() + OPEN_RETRY_S
        while True:
            try:
                return ShmemChannel.open(name)
            except Exception:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.01)

    def flush_acks(self) -> None:
        """Ping every outbound edge once so lingering receiver-side acks
        come home (close path: lets the region wait finish promptly —
        the acks arrive asynchronously via the readers)."""
        from dora_tpu.message import node_to_daemon as n2d

        for out in self.outbound.values():
            for edge in out.edges:
                frame = encode_timestamped(
                    n2d.NextDropEvents(), self.node._clock
                )
                try:
                    self._send(edge, frame)
                except Exception:
                    continue

    def close(self) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        for server in self.servers.values():
            try:
                server.channel.disconnect()
                server.ack_channel.disconnect()
            except Exception:
                pass
        for server in self.servers.values():
            if server.thread.ident is not None:
                server.thread.join(timeout=2)
            try:
                server.channel.close(unlink=True)
                server.ack_channel.close(unlink=True)
            except Exception:
                pass
        with self._out_lock:
            for channel in self._out_channels.values():
                try:
                    channel.disconnect()
                except Exception:
                    pass
        for reader in self._readers:
            reader.join(timeout=1)
        with self._out_lock:
            for channel in self._out_channels.values():
                try:
                    channel.close(unlink=False)
                except Exception:
                    pass
            self._out_channels.clear()
