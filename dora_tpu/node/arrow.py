"""Arrow payload (de)serialization for the data plane.

Design difference from the reference (apis/rust/node/src/node/arrow_utils.rs):
instead of a hand-rolled buffer-offset table we use standard **Arrow IPC
stream format**. pyarrow serializes an array *directly into* a mapped
shared-memory region (one producer-side copy, exactly like the reference)
and deserializes it **zero-copy** — the resulting arrays view the mapped
region; no receiver-side copy happens.
"""

from __future__ import annotations

from typing import Any

import pyarrow as pa

#: Field name used when wrapping a bare array into a record batch for IPC.
_FIELD = "data"

#: Room for the schema message + framing around the batch message.
_IPC_OVERHEAD = 1024


def to_arrow(data: Any) -> pa.Array:
    """Coerce user data to an Arrow array (numpy arrays zero-copy)."""
    if isinstance(data, pa.Array):
        return data
    if isinstance(data, pa.ChunkedArray):
        return data.combine_chunks()
    try:
        import numpy as np

        if isinstance(data, np.ndarray):
            if data.ndim != 1:
                data = data.ravel()
            return pa.array(data)
    except ImportError:  # pragma: no cover
        pass
    return pa.array(data)


def _as_batch(arr: pa.Array) -> pa.RecordBatch:
    return pa.record_batch([arr], names=[_FIELD])


def ipc_max_size(arr: pa.Array) -> int:
    """Upper bound on the IPC stream size for one array."""
    return pa.ipc.get_record_batch_size(_as_batch(arr)) + _IPC_OVERHEAD


def ipc_serialize(arr: pa.Array) -> bytes:
    sink = pa.BufferOutputStream()
    batch = _as_batch(arr)
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue().to_pybytes()


def ipc_serialize_into(arr: pa.Array, buf: memoryview) -> int:
    """Serialize directly into a writable buffer (a mapped shmem region);
    returns the number of bytes written."""
    batch = _as_batch(arr)
    sink = pa.FixedSizeBufferWriter(pa.py_buffer(buf))
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.tell()


def ipc_deserialize(buf: Any) -> pa.Array:
    """Zero-copy read of one array from an IPC stream (bytes or memoryview —
    the arrays keep the underlying buffer alive via pyarrow's foreign-buffer
    reference)."""
    reader = pa.ipc.open_stream(pa.py_buffer(buf))
    table = reader.read_all()
    column = table.column(0)
    if column.num_chunks == 1:
        return column.chunk(0)
    return column.combine_chunks()


def ipc_bytes_str(text: str) -> bytes:
    """One-line helper: a single utf8 string as an IPC payload (used by the
    daemon's ``send_stdout_as`` republishing)."""
    return ipc_serialize(pa.array([text]))
