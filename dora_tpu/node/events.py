"""Node-side events: the user-facing Event object and the stream thread.

Reference parity: apis/rust/node/src/event_stream — a background thread
runs the blocking NextEvent loop, reconstructs zero-copy Arrow views over
mapped shared-memory regions, and piggybacks drop-token acknowledgements
for events the user code has dropped.

The Python Event mirrors the reference's Python dict shape
(apis/python/operator/src/lib.rs PyEvent): ``event["type"]`` in
{"INPUT","INPUT_CLOSED","STOP","RELOAD","ERROR"}, plus ``id``, ``value``
(pyarrow array, zero-copy), ``metadata``.
"""

from __future__ import annotations

import queue as queue_mod
import threading

from dora_tpu.analysis.lockcheck import tracked_lock
import weakref
from typing import Any

from dora_tpu.message import daemon_to_node as d2n
from dora_tpu.message import node_to_daemon as n2d
from dora_tpu.message.common import (
    ENCODING_ARROW_IPC,
    InlineData,
    SharedMemoryData,
)
from dora_tpu.native import ShmemRegion
from dora_tpu.telemetry import FLIGHT, OTEL_CTX_KEY, TRACING

#: pump-internal marker: the daemon closed the stream (AllInputsClosed).
_END = object()


class Event:
    """One dataflow event. Dict-like for dora API compatibility."""

    __slots__ = ("type", "id", "value", "metadata", "error", "operator_id",
                 "_ack", "__weakref__")

    def __init__(self, type: str, id: str | None = None, value: Any = None,
                 metadata: dict | None = None, error: str | None = None,
                 operator_id: str | None = None):
        self.type = type
        self.id = id
        self.value = value
        self.metadata = metadata or {}
        self.error = error
        self.operator_id = operator_id
        self._ack = None

    def __getitem__(self, key: str) -> Any:
        try:
            return getattr(self, key)
        except AttributeError:
            raise KeyError(key) from None

    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def __contains__(self, key: str) -> bool:
        return hasattr(self, key) and getattr(self, key) is not None

    def __repr__(self) -> str:
        parts = [f"type={self.type!r}"]
        if self.id:
            parts.append(f"id={self.id!r}")
        if self.error:
            parts.append(f"error={self.error!r}")
        return f"Event({', '.join(parts)})"


class EventStream:
    """Background thread pumping the blocking NextEvent loop.

    ``on_ack(token)`` is called (from arbitrary threads — GC finalizers)
    when user code drops an event whose payload lives in shared memory; the
    Node flushes those acks to the daemon out-of-band via ReportDropTokens
    on the control channel (the NextEvent piggyback the reference uses
    would strand the final ack: the pump is already parked inside the next
    blocking NextEvent when the user drops the last event).
    """

    #: Local buffer bound. Small on purpose: while the consumer lags, the
    #: pump must STOP pulling so events back up in the *daemon's*
    #: per-input queues, where the YAML ``queue_size`` drop-oldest
    #: contract applies (reference: node_communication/mod.rs:320-359).
    #: An unbounded local buffer would absorb every event the instant it
    #: arrives and silently disable queue_size for fast producers.
    DEFAULT_MAX_QUEUE = 2

    def __init__(self, channel, on_ack=None, max_queue: int | None = None):
        self._channel = channel
        self._on_ack = on_ack
        #: optional callable run before the end-of-stream sentinel is
        #: queued (the p2p endpoint drains edge backlogs here so direct
        #: events cannot be overtaken by the daemon's AllInputsClosed)
        self.pre_end = None
        #: region cache is shared with p2p edge threads
        self._regions_guard = tracked_lock("node.events.regions")
        if max_queue is None:
            max_queue = self.DEFAULT_MAX_QUEUE
        self._queue: queue_mod.Queue = queue_mod.Queue(max_queue)
        self._pending_acks: list[str] = []
        self._acks_lock = tracked_lock("node.events.acks")
        self._closed = threading.Event()
        #: set by the pump once no further real events can arrive (the
        #: end-of-stream sentinel is queued or being queued)
        self._eos = threading.Event()
        #: shmem_id -> mapped region (kept mapped for the stream's lifetime;
        #: senders never reuse a region name after unlinking, so a cached
        #: mapping can never go stale)
        self._regions: dict[str, ShmemRegion] = {}
        self._thread = threading.Thread(
            target=self._run, name="dora-event-stream", daemon=True
        )
        self._thread.start()

    # -- user side ----------------------------------------------------------

    @property
    def ended(self) -> bool:
        """True once the stream closed (all inputs closed / daemon gone)
        and no real events remain to consume. Works for poll-only users
        that never call recv(): the queued end-of-stream sentinel does
        not count as a remaining event."""
        if self._closed.is_set() and self._queue.empty():
            return True
        if not self._eos.is_set():
            return False
        with self._queue.mutex:
            return all(
                item is None or item is self.WAKE
                for item in self._queue.queue
            )

    #: Sentinel queued by :meth:`wake`; surfaces from :meth:`recv` as a
    #: ``{"type": "WAKE"}`` event. Only the runtime's serving loop uses
    #: wake(), and it swallows the event — plain ``for event in node``
    #: users never see one.
    WAKE: Event = {"type": "WAKE"}

    def recv(self, timeout: float | None = None) -> Event | None:
        """Next event, or None when the stream ended (or timeout expired)."""
        if self._closed.is_set() and self._queue.empty():
            return None
        try:
            item = self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return None
        if item is None:
            self._closed.set()
            return None
        return item

    def wake(self) -> None:
        """Unpark a ``recv(None)`` parked on an empty queue (the runtime's
        pipelined serving loop calls this from a fetch-completion callback
        so finished tick outputs are emitted immediately instead of being
        polled for). Lossy by design: when the queue is full, recv is not
        parked — the wake would be redundant."""
        try:
            self._queue.put_nowait(self.WAKE)
        except queue_mod.Full:
            pass

    def __iter__(self):
        while True:
            event = self.recv()
            if event is None:
                return
            if event is self.WAKE:
                continue
            yield event

    def close(self) -> None:
        self._closed.set()
        try:
            self._channel.interrupt()  # wake the pump if parked in recv
        except Exception:
            pass
        self._thread.join(timeout=2)
        try:
            self._channel.close()
        except Exception:
            pass
        for region in self._regions.values():
            try:
                # Never force: user code may still hold zero-copy arrays into
                # the region; unmapping under them would segfault. Regions
                # with live views stay mapped until process exit.
                region.close(unlink=False)
            except Exception:
                pass
        self._regions.clear()

    # -- pump thread --------------------------------------------------------

    def _put(self, item) -> bool:
        """Blocking put that gives up when the stream closes (a full
        buffer must never wedge shutdown)."""
        while not self._closed.is_set():
            try:
                self._queue.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            while not self._closed.is_set():
                with self._acks_lock:
                    acks, self._pending_acks = self._pending_acks, []
                reply = self._channel.request(n2d.NextEvent(drop_tokens=acks))
                if not isinstance(reply, d2n.NextEvents) or not reply.events:
                    break
                ended = False
                for ts in reply.events:
                    event = self._convert(ts.inner)
                    if event is _END:
                        # End of stream: do NOT set _closed here — only the
                        # queued None sentinel may end the stream. Setting
                        # the flag from this thread disarmed the sentinel
                        # put below while the consumer was already parked
                        # inside queue.get(), deadlocking it (the round-2
                        # shmem "reply loss": the reply arrived fine; this
                        # handoff lost it).
                        ended = True
                        break
                    if event is not None and not self._put(event):
                        return
                if ended:
                    break
        except Exception as e:
            if not self._closed.is_set():
                self._put(Event(type="ERROR", error=str(e)))
        finally:
            if self.pre_end is not None:
                try:
                    self.pre_end()
                except Exception:
                    pass
            self._eos.set()  # no further real events after this point
            # The end-of-stream sentinel must land (recv blocks without
            # it); retry around a full buffer unless the consumer closed.
            while not self._closed.is_set():
                try:
                    self._queue.put(None, timeout=0.2)
                    break
                except queue_mod.Full:
                    continue

    def _convert(self, inner: Any) -> Event | None:
        if isinstance(inner, d2n.Input):
            value, token = self._reconstruct(inner)
            event = Event(
                type="INPUT",
                id=inner.id,
                value=value,
                metadata=dict(inner.metadata.parameters),
            )
            if TRACING.active:
                # Receiver end of the message span: the sender's context
                # rode here in the metadata (spliced verbatim through the
                # daemon's wire path).
                ctx = event.metadata.get(OTEL_CTX_KEY)
                if ctx:
                    FLIGHT.record("t_recv", inner.id, str(ctx), 0)
            if token is not None:
                # Ack when the user drops the event (CPython refcounting
                # makes this prompt); the sender then reuses the region.
                event._ack = weakref.finalize(
                    event, self._queue_ack, token
                )
            return event
        if isinstance(inner, d2n.InputClosed):
            return Event(type="INPUT_CLOSED", id=inner.id)
        if isinstance(inner, d2n.AllInputsClosed):
            return _END
        if isinstance(inner, d2n.Stop):
            return Event(type="STOP")
        if isinstance(inner, d2n.Reload):
            return Event(type="RELOAD", operator_id=inner.operator_id)
        if isinstance(inner, d2n.Migrate):
            return Event(
                type="MIGRATE", metadata={"handoff_dir": inner.handoff_dir}
            )
        if isinstance(inner, d2n.Profile):
            return Event(
                type="PROFILE",
                metadata={"action": inner.action, "seconds": inner.seconds},
            )
        return None

    def _queue_ack(self, token: str) -> None:
        if self._on_ack is not None:
            try:
                self._on_ack(token)
            except Exception:
                pass
            return
        with self._acks_lock:
            self._pending_acks.append(token)

    def _reconstruct(self, inner: d2n.Input) -> tuple[Any, str | None]:
        """Rebuild the payload value; zero-copy for shared-memory data."""
        from dora_tpu.node.arrow import ipc_deserialize

        data = inner.data
        encoding = inner.metadata.type_info.encoding
        if data is None:
            return None, None
        if isinstance(data, InlineData):
            raw: Any = data.data
            if encoding == ENCODING_ARROW_IPC:
                return ipc_deserialize(raw), None
            return raw, None
        assert isinstance(data, SharedMemoryData)
        with self._regions_guard:  # cache shared with p2p edge threads
            region = self._regions.get(data.shmem_id)
            if region is None:
                region = ShmemRegion.open(data.shmem_id)
                self._regions[data.shmem_id] = region
        view = memoryview(region)[: data.len]
        if encoding == ENCODING_ARROW_IPC:
            # The arrays hold the memoryview via pyarrow's foreign buffer,
            # which pins the region's export count until they are dropped.
            value: Any = ipc_deserialize(view)
        else:
            # Raw bytes: hand out the mapped view itself — zero-copy, like
            # the reference's Buffer::from_custom_allocation path. The view
            # pins the mapping; the drop token is acked when the event is
            # dropped.
            value = view
        return value, data.drop_token
