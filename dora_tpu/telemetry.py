"""Tracing and metrics.

Reference parity: libraries/extensions/telemetry — trace context is
carried in message metadata under the ``open_telemetry_context``
parameter, serialized as a ``k:v;`` string
(telemetry/tracing/src/telemetry.rs:35-70); the daemon/runtime propagate
it across process boundaries. Works standalone (pure string codec); when
the ``opentelemetry`` package is installed and OTLP env vars are set,
spans and system metrics export for real.
"""

from __future__ import annotations

import logging
import os
import time
from contextlib import contextmanager

OTEL_CTX_KEY = "open_telemetry_context"

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# context string codec (reference: serialize_context / deserialize_context)
# ---------------------------------------------------------------------------


def serialize_context(ctx: dict[str, str]) -> str:
    return "".join(f"{k}:{v};" for k, v in ctx.items())


def parse_otel_context(raw: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in raw.split(";"):
        if ":" in part:
            k, _, v = part.partition(":")
            out[k] = v
    return out


def inject_context(metadata: dict, ctx: str | dict) -> dict:
    """Attach a trace context to outgoing message metadata."""
    if isinstance(ctx, dict):
        ctx = serialize_context(ctx)
    if ctx:
        metadata[OTEL_CTX_KEY] = ctx
    return metadata


def extract_context(metadata: dict) -> dict[str, str]:
    return parse_otel_context(str(metadata.get(OTEL_CTX_KEY, "")))


# ---------------------------------------------------------------------------
# optional OpenTelemetry integration
# ---------------------------------------------------------------------------

_tracer = None


def set_up_tracing(name: str):
    """Configure logging and, if available + configured, OTLP tracing
    (reference: set_up_tracing_opts, tracing/src/lib.rs:22-65)."""
    level = os.environ.get("DORA_LOG", os.environ.get("RUST_LOG", "info")).upper()
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format=f"%(asctime)s {name} %(levelname)s %(name)s: %(message)s",
    )
    global _tracer
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT") or os.environ.get(
        "DORA_JAEGER_TRACING"
    )
    if not endpoint:
        return None
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        provider = TracerProvider(
            resource=Resource.create({"service.name": name})
        )
        provider.add_span_processor(
            BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
        )
        trace.set_tracer_provider(provider)
        _tracer = trace.get_tracer(name)
        return _tracer
    except ImportError:
        logger.warning("opentelemetry not installed; tracing is log-only")
        return None


@contextmanager
def span(name: str, parent_ctx: str = ""):
    """A span context manager that yields the serialized context to embed in
    outgoing metadata. Without the otel SDK (and with ``DORA_TRACING`` set)
    this synthesizes W3C-style traceparent ids so traces still correlate
    across processes; with tracing off it forwards the parent unchanged at
    zero cost."""
    if _tracer is None and os.environ.get("DORA_TRACING", "") in ("", "0"):
        yield parent_ctx
        return
    if _tracer is not None:
        from opentelemetry import trace as otrace
        from opentelemetry.trace.propagation.tracecontext import (
            TraceContextTextMapPropagator,
        )

        propagator = TraceContextTextMapPropagator()
        parent = propagator.extract(parse_otel_context(parent_ctx))
        with _tracer.start_as_current_span(name, context=parent):
            carrier: dict[str, str] = {}
            propagator.inject(carrier)
            yield serialize_context(carrier)
        return
    # Fallback: keep a coherent traceparent chain without the SDK.
    parent = parse_otel_context(parent_ctx).get("traceparent")
    if parent and parent.count("-") == 3:
        trace_id = parent.split("-")[1]
    else:
        trace_id = os.urandom(16).hex()
    span_id = os.urandom(8).hex()
    yield serialize_context({"traceparent": f"00-{trace_id}-{span_id}-01"})


# ---------------------------------------------------------------------------
# metrics (reference: dora-metrics, OTLP system metrics)
# ---------------------------------------------------------------------------


def init_metrics(name: str, interval_s: float = 10.0):
    """Per-process system metrics via OTLP when configured; otherwise a
    no-op handle with a .sample() you can call manually."""

    class _Sampler:
        def sample(self) -> dict:
            import resource

            usage = resource.getrusage(resource.RUSAGE_SELF)
            return {
                "max_rss_kb": usage.ru_maxrss,
                "user_s": usage.ru_utime,
                "system_s": usage.ru_stime,
                "time": time.time(),
            }

    return _Sampler()
