"""Tracing and metrics.

Reference parity: libraries/extensions/telemetry — trace context is
carried in message metadata under the ``open_telemetry_context``
parameter, serialized as a ``k:v;`` string
(telemetry/tracing/src/telemetry.rs:35-70); the daemon/runtime propagate
it across process boundaries. Works standalone (pure string codec); when
the ``opentelemetry`` package is installed and OTLP env vars are set,
spans and system metrics export for real.
"""

from __future__ import annotations

import logging
import os
import time
from contextlib import contextmanager

OTEL_CTX_KEY = "open_telemetry_context"

logger = logging.getLogger(__name__)


def otlp_endpoint() -> str | None:
    """Single resolution rule for the OTLP export endpoint, shared by
    tracing and metrics: ``OTEL_EXPORTER_OTLP_ENDPOINT`` wins, with
    ``DORA_JAEGER_TRACING`` (the reference's legacy spelling) as the
    fallback. Both exporters MUST use this helper so setting either
    variable lights up the whole telemetry export path."""
    return (
        os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
        or os.environ.get("DORA_JAEGER_TRACING")
        or None
    )


# ---------------------------------------------------------------------------
# flight recorder (hot-path forensics)
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Fixed-size, allocation-free ring of timestamped hot-path events.

    The message plane records route / enqueue / drop-oldest / coalesce
    flush / fastroute hit-or-fallback events here when enabled
    (``DORA_FLIGHT_RECORDER=1``; size via ``DORA_FLIGHT_RECORDER_SIZE``,
    default 4096). Slots are preallocated lists mutated in place, so the
    steady state allocates nothing; when disabled, :meth:`record` is a
    single attribute check and return, so the hot path pays ~0.

    Recording from several threads may interleave slot writes; the ring
    is a forensic tool, not an exact log, and an occasionally torn slot
    is an accepted trade for staying lock-free on the hot path. The ring
    is dumped on SIGUSR2 alongside the asyncio task dump (daemons) or
    via :func:`install_flight_dump` (nodes).
    """

    __slots__ = ("enabled", "_slots", "_size", "_idx")

    def __init__(self, size: int = 4096, enabled: bool = False):
        self._size = max(1, size)
        self._slots = [[0, "", None, None] for _ in range(self._size)]
        self._idx = 0
        self.enabled = enabled

    def configure_from_env(self) -> None:
        """Re-read the env knobs (daemons/nodes call this at startup, so
        a knob set after module import — e.g. a bench A/B leg — still
        takes effect in-process)."""
        self.enabled = os.environ.get("DORA_FLIGHT_RECORDER", "") not in ("", "0")
        size = int(os.environ.get("DORA_FLIGHT_RECORDER_SIZE", "0") or "0")
        if size > 0 and size != self._size:
            self._size = size
            self._slots = [[0, "", None, None] for _ in range(size)]
            self._idx = 0

    def record(self, kind: str, a=None, b=None) -> None:
        if not self.enabled:
            return
        slot = self._slots[self._idx % self._size]
        slot[0] = time.monotonic_ns()
        slot[1] = kind
        slot[2] = a
        slot[3] = b
        self._idx += 1

    def events(self) -> list[tuple]:
        """Recorded events, oldest first (filled slots only)."""
        n = min(self._idx, self._size)
        start = self._idx - n
        out = []
        for i in range(start, self._idx):
            t, kind, a, b = self._slots[i % self._size]
            out.append((t, kind, a, b))
        return out

    def clear(self) -> None:
        self._idx = 0
        for slot in self._slots:
            slot[0] = 0
            slot[1] = ""
            slot[2] = None
            slot[3] = None

    def dump(self, file=None) -> None:
        import sys

        file = file or sys.stderr
        events = self.events()
        print(
            f"--- flight recorder ({len(events)} events, "
            f"{self._idx} recorded total)",
            file=file,
        )
        for t, kind, a, b in events:
            extra = " ".join(str(x) for x in (a, b) if x is not None)
            print(f"  {t} {kind} {extra}".rstrip(), file=file)
        file.flush()


#: Process-wide recorder; env-configured at import, re-read by
#: Daemon()/Node() via configure_from_env so late env changes count.
FLIGHT = FlightRecorder(
    size=int(os.environ.get("DORA_FLIGHT_RECORDER_SIZE", "4096") or "4096"),
    enabled=os.environ.get("DORA_FLIGHT_RECORDER", "") not in ("", "0"),
)


def install_flight_dump() -> None:
    """`kill -USR2 <pid>` dumps the flight-recorder ring to stderr — the
    node-process counterpart of the daemon's task dump (nodes are
    synchronous; there is no asyncio loop to hang a handler on). Chains
    any pre-existing SIGUSR2 handler; no-op off the main thread or when
    DORA_NO_STACK_DUMP=1."""
    if os.environ.get("DORA_NO_STACK_DUMP"):
        return
    import signal

    try:
        previous = signal.getsignal(signal.SIGUSR2)

        def _handler(signum, frame):
            FLIGHT.dump()
            if callable(previous) and previous not in (
                signal.SIG_IGN,
                signal.SIG_DFL,
            ):
                previous(signum, frame)

        signal.signal(signal.SIGUSR2, _handler)
    except (ValueError, AttributeError, OSError):
        pass  # not the main thread / no SIGUSR2 on this platform


def install_stack_dump() -> None:
    """`kill -USR1 <pid>` dumps all Python stacks to stderr (the
    daemon-side log file) — a wedged node in a stuck dataflow can always
    be inspected post-hoc. Chains any pre-existing SIGUSR1 handler; opt
    out with DORA_NO_STACK_DUMP=1 (e.g. when the host app owns the
    signal entirely). Idempotent, process-level; called by Node() and
    the runtime entry point."""
    if os.environ.get("DORA_NO_STACK_DUMP"):
        return
    try:
        import faulthandler
        import signal

        faulthandler.register(signal.SIGUSR1, chain=True)
    except (ValueError, AttributeError, OSError):
        pass  # no SIGUSR1 on this platform / not callable here


def install_task_dump(loop) -> None:
    """`kill -USR2 <pid>` dumps every asyncio task's await stack to
    stderr — the counterpart of :func:`install_stack_dump` for coroutines
    (which faulthandler cannot see: a parked coroutine is not on any
    thread's stack). Used by the standalone daemon; forensics for wedged
    dataflows."""
    if os.environ.get("DORA_NO_STACK_DUMP"):
        return
    import signal
    import sys
    import traceback

    def _dump() -> None:
        import asyncio

        print(f"--- asyncio task dump ({len(asyncio.all_tasks(loop))} tasks)",
              file=sys.stderr)
        for task in asyncio.all_tasks(loop):
            print(f"task {task.get_name()}: {task}", file=sys.stderr)
            for frame in task.get_stack():
                traceback.print_stack(frame, limit=1, file=sys.stderr)
        FLIGHT.dump(sys.stderr)
        sys.stderr.flush()

    try:
        loop.add_signal_handler(signal.SIGUSR2, _dump)
    except (ValueError, NotImplementedError, OSError, RuntimeError):
        pass


def remove_task_dump(loop) -> None:
    """Unbind the SIGUSR2 handler (the loop is about to close; a later
    signal must not hit a dead loop's wakeup fd)."""
    import signal

    try:
        loop.remove_signal_handler(signal.SIGUSR2)
    except (ValueError, NotImplementedError, OSError, RuntimeError):
        pass


# ---------------------------------------------------------------------------
# context string codec (reference: serialize_context / deserialize_context)
# ---------------------------------------------------------------------------


def serialize_context(ctx: dict[str, str]) -> str:
    return "".join(f"{k}:{v};" for k, v in ctx.items())


def parse_otel_context(raw: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in raw.split(";"):
        if ":" in part:
            k, _, v = part.partition(":")
            out[k] = v
    return out


def inject_context(metadata: dict, ctx: str | dict) -> dict:
    """Attach a trace context to outgoing message metadata."""
    if isinstance(ctx, dict):
        ctx = serialize_context(ctx)
    if ctx:
        metadata[OTEL_CTX_KEY] = ctx
    return metadata


def extract_context(metadata: dict) -> dict[str, str]:
    return parse_otel_context(str(metadata.get(OTEL_CTX_KEY, "")))


# ---------------------------------------------------------------------------
# optional OpenTelemetry integration
# ---------------------------------------------------------------------------

_tracer = None


def set_up_tracing(name: str):
    """Configure logging and, if available + configured, OTLP tracing
    (reference: set_up_tracing_opts, tracing/src/lib.rs:22-65)."""
    level = os.environ.get("DORA_LOG", os.environ.get("RUST_LOG", "info")).upper()
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format=f"%(asctime)s {name} %(levelname)s %(name)s: %(message)s",
    )
    global _tracer
    endpoint = otlp_endpoint()
    if not endpoint:
        return None
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        provider = TracerProvider(
            resource=Resource.create({"service.name": name})
        )
        provider.add_span_processor(
            BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
        )
        trace.set_tracer_provider(provider)
        _tracer = trace.get_tracer(name)
        return _tracer
    except ImportError:
        logger.warning("opentelemetry not installed; tracing is log-only")
        return None


@contextmanager
def span(name: str, parent_ctx: str = ""):
    """A span context manager that yields the serialized context to embed in
    outgoing metadata. Without the otel SDK (and with ``DORA_TRACING`` set)
    this synthesizes W3C-style traceparent ids so traces still correlate
    across processes; with tracing off it forwards the parent unchanged at
    zero cost."""
    if _tracer is None and os.environ.get("DORA_TRACING", "") in ("", "0"):
        yield parent_ctx
        return
    if _tracer is not None:
        from opentelemetry import trace as otrace
        from opentelemetry.trace.propagation.tracecontext import (
            TraceContextTextMapPropagator,
        )

        propagator = TraceContextTextMapPropagator()
        parent = propagator.extract(parse_otel_context(parent_ctx))
        with _tracer.start_as_current_span(name, context=parent):
            carrier: dict[str, str] = {}
            propagator.inject(carrier)
            yield serialize_context(carrier)
        return
    # Fallback: keep a coherent traceparent chain without the SDK.
    parent = parse_otel_context(parent_ctx).get("traceparent")
    if parent and parent.count("-") == 3:
        trace_id = parent.split("-")[1]
    else:
        trace_id = os.urandom(16).hex()
    span_id = os.urandom(8).hex()
    yield serialize_context({"traceparent": f"00-{trace_id}-{span_id}-01"})


# ---------------------------------------------------------------------------
# metrics (reference: dora-metrics, OTLP system metrics)
# ---------------------------------------------------------------------------


class MetricsSampler:
    """Per-process system metrics (reference: dora-metrics exports
    process CPU/memory/disk through an OTLP meter,
    telemetry/metrics/src/lib.rs:25-49).

    ``sample()`` always works (resource/psutil, no SDK needed) — the
    daemon can log it or answer control-API queries with it. When the
    OpenTelemetry *SDK* is installed and ``OTEL_EXPORTER_OTLP_ENDPOINT``
    is set, the same samples also export periodically as OTLP gauges.
    """

    def __init__(self, name: str):
        self.name = name
        self.exporting = False
        self._proc = None
        self._cached: dict | None = None
        try:
            import psutil

            self._proc = psutil.Process()
            # Prime cpu_percent: psutil computes it from the delta since
            # the previous call, so the first interval=None reading is
            # garbage (0.0). Paying the baseline read here makes the
            # first sample() meaningful.
            self._proc.cpu_percent(interval=None)
        except Exception:
            self._proc = None

    def sample(self) -> dict:
        import resource

        usage = resource.getrusage(resource.RUSAGE_SELF)
        out = {
            "max_rss_kb": usage.ru_maxrss,
            "user_s": usage.ru_utime,
            "system_s": usage.ru_stime,
            "time": time.time(),
        }
        if self._proc is not None:
            with self._proc.oneshot():
                out["rss_bytes"] = self._proc.memory_info().rss
                # psutil needs real time between cpu_percent calls; the
                # previous call's timestamp provides it on every sample
                # after the first.
                out["cpu_percent"] = self._proc.cpu_percent(interval=None)
                out["threads"] = self._proc.num_threads()
        self._cached = out
        return out

    def sample_cached(self, max_age_s: float = 1.0) -> dict:
        """The last sample if it is fresh, else a new one — so several
        per-gauge OTLP callbacks in one export cycle share one reading
        (back-to-back cpu_percent calls would read garbage)."""
        if self._cached and time.time() - self._cached["time"] < max_age_s:
            return self._cached
        return self.sample()


def init_metrics(name: str, interval_s: float = 10.0) -> MetricsSampler:
    """System-metrics handle; wires periodic OTLP export when the otel SDK
    and an endpoint are both present, mirroring ``set_up_tracing``."""
    sampler = MetricsSampler(name)
    endpoint = otlp_endpoint()  # same resolution as set_up_tracing
    if not endpoint:
        return sampler
    try:
        from opentelemetry.exporter.otlp.proto.grpc.metric_exporter import (
            OTLPMetricExporter,
        )
        from opentelemetry.metrics import set_meter_provider
        from opentelemetry.sdk.metrics import MeterProvider
        from opentelemetry.sdk.metrics.export import (
            PeriodicExportingMetricReader,
        )
        from opentelemetry.sdk.resources import Resource

        reader = PeriodicExportingMetricReader(
            OTLPMetricExporter(endpoint=endpoint),
            export_interval_millis=interval_s * 1000,
        )
        provider = MeterProvider(
            resource=Resource.create({"service.name": name}),
            metric_readers=[reader],
        )
        set_meter_provider(provider)
        meter = provider.get_meter(name)

        def observe(key: str):
            def callback(_options):
                from opentelemetry.metrics import Observation

                # Cached: the three gauges of one export cycle must share
                # one reading (see MetricsSampler.sample_cached).
                value = sampler.sample_cached().get(key, 0.0)
                return [Observation(float(value))]

            return callback

        for key in ("rss_bytes", "cpu_percent", "max_rss_kb"):
            meter.create_observable_gauge(
                f"process.{key}", callbacks=[observe(key)]
            )
        sampler.exporting = True
    except ImportError:
        logger.warning(
            "opentelemetry SDK not installed; system metrics are local-only"
        )
    return sampler
